#!/usr/bin/env bash
# Smoke test of the serving ops plane: boot a query server against a tiny
# trained engine, then hit every operational endpoint from the OUTSIDE
# (curl over real HTTP, the way a probe/load balancer/scrape job would)
# and assert 200 + well-formed JSON / Prometheus text.
#
# Endpoints covered: /healthz /readyz /metrics /logs.json /slo.json
# /qos.json (plus one real /queries.json POST so logs, histograms and
# the SLO engine have live data to report, and a rapid-fire burst so
# admission control demonstrably sheds with 429 + Retry-After).
#
# Runs hermetically: memory storage, ephemeral port, CPU-pinned JAX.
# Exit 0 = all checks passed. Wired into tier-1 via
# tests/test_smoke_endpoints.py.
set -euo pipefail

cd "$(dirname "$0")/.."

WORKDIR="$(mktemp -d -t pio-tpu-smoke-XXXXXX)"
SERVER_PID=""
CHAOS_PID=""
cleanup() {
    [ -n "$SERVER_PID" ] && kill "$SERVER_PID" 2>/dev/null || true
    [ -n "$CHAOS_PID" ] && kill "$CHAOS_PID" 2>/dev/null || true
    [ -n "$SERVER_PID" ] && wait "$SERVER_PID" 2>/dev/null || true
    [ -n "$CHAOS_PID" ] && wait "$CHAOS_PID" 2>/dev/null || true
    rm -rf "$WORKDIR"
}
trap cleanup EXIT

export JAX_PLATFORMS=cpu
export PIO_TPU_HOME="$WORKDIR/home"
mkdir -p "$PIO_TPU_HOME"
PORT_FILE="$WORKDIR/port"

fail() { echo "FAIL: $*" >&2; exit 1; }

# ------------------------------------------------------------------- lint
# The project-native static analyzer must pass clean over the tree —
# cheapest check first, no server boot needed.
python -m pio_tpu.tools.cli lint pio_tpu tests \
    || fail "pio lint found violations"
echo "ok   pio lint clean"

# The hot-path contract is CI-enforced here: the three interprocedural
# rules must report zero findings on their own (not just be drowned in
# a clean aggregate), the seeded roots must all be discovered, and the
# effect fixpoint must stay within its latency budget on this host.
python -m pio_tpu.tools.cli lint pio_tpu tests --json \
    --rules hotpath-blocking,hotpath-zero-copy,shm-frame-layout \
    | python -c '
import json, sys
doc = json.load(sys.stdin)
assert doc["count"] == 0, f"hot-path/layout findings: {doc}"
' || fail "hot-path contract rules not clean"
echo "ok   hotpath-blocking / hotpath-zero-copy / shm-frame-layout clean"

python -m pio_tpu.tools.cli lint --dump-effects pio_tpu | python -c '
import json, sys
doc = json.load(sys.stdin)
roots = {r["function"].rsplit(".", 1)[-1] + ":" + r["marker"]
         for r in doc["roots"]}
need = {
    "query:hotpath",              # query-server request handler
    "_run:hotpath",               # _MicroBatcher dispatch / LaneDrainer
    "submit:hotpath",             # _MicroBatcher admission
    "dispatch_bucketed:hotpath",  # bucket executor
    "submit:zerocopy",            # lane submit path
    "pack_query_i8:zerocopy",     # int8 packed frame
    "unpack_query_i8:zerocopy",
    # ISSUE 13: evloop front + packed zero-copy wire
    "_serve_one:hotpath",         # evloop per-request pipeline
    "submit_packed:zerocopy",     # lane submit of a wire frame
    "_submit_payload:zerocopy",   # shared slot/doorbell path
    "packed_frame_ok:zerocopy",   # structural frame check
    "_query_packed:zerocopy",     # packed HTTP handler
    "_packed_view:zerocopy",      # socket-buffer slice helper
}
missing = need - roots
assert not missing, f"hot-path roots missing from --dump-effects: {missing}"
fams = doc["frames"]
for fam in ("lane-slot", "metrics-stripe", "pel2-record"):
    assert fams.get(fam, {}).get("verified"), f"frame family {fam}: {fams.get(fam)}"
' || fail "--dump-effects roots/frames incomplete"
echo "ok   dump-effects lists every seeded hot-path root + frame family"

python - <<'PY' || fail "effects+contracts exceeded the 10s lint budget"
import time
from pio_tpu.analysis.contracts import get_contracts
from pio_tpu.analysis.core import (
    Finding, LintContext, collect_files, parse_module,
)
from pio_tpu.analysis.effects import EffectAnalysis

mods = [m for m in (parse_module(p) for p in collect_files(["pio_tpu"]))
        if not isinstance(m, Finding)]
t0 = time.monotonic()
EffectAnalysis(mods)
get_contracts(mods, LintContext())
dt = time.monotonic() - t0
assert dt < 10.0, f"effects+contracts took {dt:.1f}s (budget 10s)"
print(f"     effects + contracts over {len(mods)} modules: {dt:.2f}s")
PY
echo "ok   effect fixpoint + contract extraction within budget"

# ------------------------------------------------ contract surfaces
# ISSUE 20: the contract-drift rules must be registered, clean on
# their own (not just drowned in a clean aggregate), and the dump
# inventory must cover the cross-process surface end to end.
python -m pio_tpu.tools.cli lint --list-rules | python -c '
import sys
have = {line.split()[0] for line in sys.stdin if line.strip()}
need = {"endpoint-drift", "header-drift", "knob-default-drift",
        "knob-doc-drift", "failpoint-coverage"}
missing = need - have
assert not missing, f"contract rules not registered: {missing}"
' || fail "contract rules missing from --list-rules"
echo "ok   all five contract-drift rules registered"

python -m pio_tpu.tools.cli lint pio_tpu tests --json \
    --rules endpoint-drift,header-drift,knob-default-drift,knob-doc-drift,failpoint-coverage \
    | python -c '
import json, sys
doc = json.load(sys.stdin)
assert doc["count"] == 0, f"contract-drift findings: {doc}"
' || fail "contract-drift rules not clean"
echo "ok   contract-drift rules clean over the tree"

python -m pio_tpu.tools.cli lint --dump-contracts pio_tpu tests \
    | python -c '
import json, sys
doc = json.load(sys.stdin)
eps = set(doc["endpoints"])
need = {"/fleet.json", "/train.json", "/device.json", "/stats.json",
        "/slo.json", "/qos.json", "/storage.json", "/rollout.json",
        "/queries.json", "/events.json", "/router.json"}
missing = need - eps
assert not missing, f"endpoints missing from --dump-contracts: {missing}"
fleet = doc["endpoints"]["/fleet.json"]
assert fleet["producers"] and fleet["keys"] and fleet["consumers"], \
    "/fleet.json inventory must carry producers, keys and consumers"
hdrs = set(doc["headers"])
for h in ("x-pio-priority", "x-pio-deadline-ms", "x-pio-trace"):
    assert h in hdrs, f"header {h} missing from --dump-contracts"
from pio_tpu.utils.knobs import KNOBS
knobs = doc["knobs"]
unlisted = set(KNOBS) - set(knobs)
assert not unlisted, f"registry knobs missing from dump: {unlisted}"
for name in KNOBS:
    assert "default" in knobs[name], f"{name} has no canonical default"
' || fail "--dump-contracts inventory incomplete"
echo "ok   dump-contracts inventories endpoints, headers + every knob"

# Boot: train the recommendation template on a tiny in-memory corpus,
# serve it with a declared SLO, publish the ephemeral port, then park.
python - "$PORT_FILE" <<'PY' &
import datetime as dt
import os
import signal
import sys

os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "MEM"
os.environ["PIO_STORAGE_SOURCES_MEM_TYPE"] = "memory"
os.environ["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "MEM"
os.environ["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "MEM"

import pio_tpu.templates  # noqa: F401  (registers the factory)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.server import create_query_server
from pio_tpu.storage import App, Storage
from pio_tpu.workflow import build_engine, run_train, variant_from_dict

app_id = Storage.get_meta_data_apps().insert(App(0, "smoke"))
le = Storage.get_levents()
t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
for u in range(8):
    for i in range(6):
        in_block = (u < 4) == (i < 3)
        le.insert(
            Event("rate", "user", f"u{u}", "item", f"i{i}",
                  properties={"rating": 5.0 if in_block else 1.0},
                  event_time=t0),
            app_id,
        )
variant = variant_from_dict({
    "id": "smoke-rec",
    "engineFactory": "templates.recommendation",
    "datasource": {"params": {"app_name": "smoke"}},
    "algorithms": [{"name": "als", "params": {
        "rank": 4, "num_iterations": 4, "lambda_": 0.1}}],
})
engine, ep = build_engine(variant)
run_train(engine, ep, variant, ctx=ComputeContext.local())
# qos: generous enough that the sequential checks never shed, small
# enough that the burst at the end reliably trips 429s; no stale cache
# (a cache hit would turn the asserted 429 into a degraded 200)
server, service = create_query_server(
    variant, host="127.0.0.1", port=0, ctx=ComputeContext.local(),
    slos=["p99=50ms:99.9", "availability=99.9"],
    qos="rps=2,burst=8",
)
server.start()
with open(sys.argv[1] + ".tmp", "w") as f:
    f.write(str(server.port))
os.rename(sys.argv[1] + ".tmp", sys.argv[1])  # atomic publish
signal.sigwait({signal.SIGTERM, signal.SIGINT})
server.stop()
PY
SERVER_PID=$!

echo "waiting for server to boot (train + deploy)..."
for _ in $(seq 1 240); do
    [ -s "$PORT_FILE" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "FAIL: server process died during boot" >&2; exit 1; }
    sleep 0.5
done
[ -s "$PORT_FILE" ] || { echo "FAIL: server never published its port" >&2; exit 1; }
PORT="$(cat "$PORT_FILE")"
BASE="http://127.0.0.1:$PORT"
echo "server up on :$PORT"

check_json() {  # 200 + parseable JSON
    local path="$1"
    curl -fsS --max-time 10 "$BASE$path" | python -m json.tool >/dev/null \
        || fail "$path did not return 200 + valid JSON"
    echo "ok   $path"
}

# live traffic first, so /logs.json, /metrics and /slo.json report a
# real request (not just empty rings)
curl -fsS --max-time 30 -X POST -H 'Content-Type: application/json' \
    -d '{"user": "u1", "num": 3}' "$BASE/queries.json" \
    | python -m json.tool >/dev/null || fail "/queries.json round trip"
echo "ok   /queries.json"

check_json /healthz
check_json /readyz
check_json /logs.json
check_json "/logs.json?level=info&n=50"
check_json /slo.json
check_json /traces.json
check_json /stats.json
check_json /qos.json

# /qos.json must reflect the deployed admission policy
curl -fsS --max-time 10 "$BASE/qos.json" | python -c '
import json, sys
body = json.load(sys.stdin)
assert body["enabled"] is True, body
assert body["policy"]["rps"] == 2, body["policy"]
assert "shed" in body and "bucket" in body, body
' || fail "/qos.json missing admission-control state"
echo "ok   /qos.json policy"

# /slo.json must carry both declared objectives with burn-rate fields
curl -fsS --max-time 10 "$BASE/slo.json" | python -c '
import json, sys
body = json.load(sys.stdin)
names = {s["name"] for s in body["slos"]}
assert {"latency_p99", "availability"} <= names, names
for s in body["slos"]:
    assert "burnRates" in s and "errorBudgetRemaining" in s, s
' || fail "/slo.json missing declared objectives"
echo "ok   /slo.json objectives"

# /metrics must be Prometheus text with the core families present
METRICS="$(curl -fsS --max-time 10 "$BASE/metrics")"
for family in \
    '# TYPE pio_tpu_queries_total counter' \
    '# TYPE pio_tpu_request_seconds histogram' \
    '# TYPE pio_tpu_slo_error_budget_remaining gauge' \
    '# TYPE pio_tpu_log_messages_total counter'; do
    grep -qF "$family" <<<"$METRICS" || fail "/metrics missing '$family'"
done
echo "ok   /metrics exposition"

# parameter validation: negative n must be a 400, not a silent default
STATUS="$(curl -s -o /dev/null -w '%{http_code}' --max-time 10 "$BASE/logs.json?n=-5")"
[ "$STATUS" = 400 ] || fail "/logs.json?n=-5 returned $STATUS, want 400"
echo "ok   /logs.json?n=-5 -> 400"

# ------------------------------------------------- latency attribution
# an X-Pio-Trace we send must be adopted verbatim and echoed back, and
# the adopted trace's full waterfall must be retrievable by id
HDR="$(curl -fsS --max-time 10 -D - -o /dev/null \
    -X POST -H 'Content-Type: application/json' \
    -H 'X-Pio-Trace: smoke-trace-1' \
    -d '{"user": "u1", "num": 3}' "$BASE/queries.json")" \
    || fail "traced /queries.json POST failed"
grep -qi '^X-Pio-Trace: smoke-trace-1' <<<"$HDR" \
    || fail "response did not echo the adopted trace id (headers: $HDR)"
curl -fsS --max-time 10 "$BASE/traces.json?id=smoke-trace-1" | python -c '
import json, sys
body = json.load(sys.stdin)
stages = {s["stage"] for t in body["traces"] for s in t["spans"]}
assert {"accept", "parse", "execute", "write"} <= stages, stages
' || fail "/traces.json?id= did not return the adopted trace's waterfall"
echo "ok   X-Pio-Trace adopted + waterfall retrievable by id"

# the hot-path budget must attribute (stage sum ≈ e2e): the declared
# bar is >=95% on the bench's steady-state load; this smoke run is a
# cold server, so warm the average over a few extra requests (a single
# cold request's scheduling noise can dominate its ~1 ms budget) and
# gate at 80% — enough to catch a stage that silently stopped reporting
for _ in 1 2 3 4 5 6; do
    curl -fsS --max-time 10 -o /dev/null -X POST \
        -H 'Content-Type: application/json' \
        -d '{"user": "u1", "num": 3}' "$BASE/queries.json" \
        || fail "hotpath warm-up POST failed"
done
sleep 0.3  # e2e lands in the post-write hook; let the last one settle
curl -fsS --max-time 10 "$BASE/debug/hotpath.json" | python -c '
import json, sys
body = json.load(sys.stdin)
assert body["requestCount"] >= 5, body
stages = {s["stage"] for s in body["stages"]}
assert {"accept", "admit", "parse", "queue", "execute", "serialize",
        "write"} <= stages, stages
frac = body["attributedFraction"]
assert frac is not None and frac >= 0.80, (
    f"hot-path stages attribute only {frac!r} of the e2e average "
    f"(want >= 0.80): {json.dumps(body, indent=1)[:2000]}")
' || fail "/debug/hotpath.json stage sum does not match e2e latency"
echo "ok   /debug/hotpath.json attributes >=80% of e2e latency"

# admission control: rapid-fire past the rps=2,burst=8 budget (LAST, so
# drained tokens can't starve the checks above) and require at least one
# 429 carrying a Retry-After hint
SHED_HEADERS="$WORKDIR/shed-headers"
GOT_429=0
for _ in $(seq 1 25); do
    STATUS="$(curl -s -o /dev/null -D "$SHED_HEADERS" -w '%{http_code}' \
        --max-time 10 -X POST -H 'Content-Type: application/json' \
        -d '{"user": "u1", "num": 3}' "$BASE/queries.json")"
    if [ "$STATUS" = 429 ]; then GOT_429=1; break; fi
done
[ "$GOT_429" = 1 ] || fail "burst of 25 queries never rate-limited (no 429)"
grep -qi '^Retry-After:' "$SHED_HEADERS" \
    || fail "429 response missing Retry-After header"
echo "ok   burst -> 429 + Retry-After"

# ...and the shed must be accounted on /qos.json and /metrics
curl -fsS --max-time 10 "$BASE/qos.json" | python -c '
import json, sys
body = json.load(sys.stdin)
assert body["shed"]["rate_limit"] >= 1, body["shed"]
' || fail "/qos.json did not count the rate_limit shed"
# capture, THEN grep: grep -q exits at first match and a direct pipe
# would hand curl a SIGPIPE (exit 23) under pipefail once the /metrics
# body outgrows the pipe buffer
SHED_METRICS="$(curl -fsS --max-time 10 "$BASE/metrics")"
grep -q 'pio_tpu_qos_shed_total{.*reason="rate_limit"' <<<"$SHED_METRICS" \
    || fail "/metrics missing pio_tpu_qos_shed_total rate_limit sample"
echo "ok   shed accounted in /qos.json + /metrics"

# ------------------------------------------------------------------ chaos
# Fault injection: boot an EVENT server over sqlite with a low-rate
# latency+error spec armed (10 ms latency on every group-commit flush,
# 10 % injected errors on the sqlite commit). Every POST must still come
# back 201 — group commit's solo retry plus the server's retrying()
# wrapper absorb the injected errors, so no 5xx may leak — and the
# injections must be visible on /faults.json and /metrics.
CHAOS_PORT_FILE="$WORKDIR/chaos-port"
CHAOS_KEY_FILE="$WORKDIR/chaos-key"

# Before arming the spec, cross-check its point names against the lint
# inventory of failpoint() call sites — a renamed point would otherwise
# silently arm nothing and the chaos stage would stop testing anything.
python -m pio_tpu.tools.cli lint --dump-failpoints pio_tpu | python -c '
import json, sys
inv = json.load(sys.stdin)["failpoints"]
wanted = ["groupcommit.flush.sqlite", "storage.sqlite.commit"]
for name in wanted:
    for fp in inv:
        point = fp["point"]
        # dynamic points carry their static f-string prefix
        if point == name or (fp["dynamic"] and name.startswith(point)):
            break
    else:
        raise SystemExit(
            f"chaos spec targets {name!r} but no failpoint() call site "
            f"matches it — inventory: {sorted(f['point'] for f in inv)}")
' || fail "chaos spec references a failpoint that no longer exists"
echo "ok   chaos spec failpoints exist in the lint inventory"
PIO_TPU_FAULTS='groupcommit.flush.sqlite=latency:10ms,storage.sqlite.commit=error:0.1' \
python - "$CHAOS_PORT_FILE" "$CHAOS_KEY_FILE" <<'PY' &
import os
import signal
import sys

os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "SQ"
os.environ["PIO_STORAGE_SOURCES_SQ_TYPE"] = "sqlite"
os.environ["PIO_STORAGE_SOURCES_SQ_PATH"] = os.path.join(
    os.environ["PIO_TPU_HOME"], "chaos.db")
os.environ["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "SQ"
os.environ["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "SQ"

from pio_tpu.server import create_event_server
from pio_tpu.storage import AccessKey, App, Storage

app_id = Storage.get_meta_data_apps().insert(App(0, "chaos"))
key = Storage.get_meta_data_access_keys().insert(AccessKey("", app_id))
server = create_event_server(host="127.0.0.1", port=0).start()
with open(sys.argv[2], "w") as f:
    f.write(key)
with open(sys.argv[1] + ".tmp", "w") as f:
    f.write(str(server.port))
os.rename(sys.argv[1] + ".tmp", sys.argv[1])  # atomic publish
signal.sigwait({signal.SIGTERM, signal.SIGINT})
server.stop()
PY
CHAOS_PID=$!

echo "waiting for chaos event server..."
for _ in $(seq 1 120); do
    [ -s "$CHAOS_PORT_FILE" ] && break
    kill -0 "$CHAOS_PID" 2>/dev/null || {
        echo "FAIL: chaos event server died during boot" >&2; exit 1; }
    sleep 0.5
done
[ -s "$CHAOS_PORT_FILE" ] || fail "chaos event server never published its port"
CBASE="http://127.0.0.1:$(cat "$CHAOS_PORT_FILE")"
CKEY="$(cat "$CHAOS_KEY_FILE")"
echo "chaos event server up, faults armed"

for i in $(seq 1 30); do
    STATUS="$(curl -s -o /dev/null -w '%{http_code}' --max-time 15 \
        -X POST -H 'Content-Type: application/json' \
        -d "{\"event\": \"chaos\", \"entityType\": \"user\",
             \"entityId\": \"u$i\", \"targetEntityType\": \"item\",
             \"targetEntityId\": \"i$i\",
             \"eventTime\": \"2026-03-01T10:00:00Z\"}" \
        "$CBASE/events.json?accessKey=$CKEY")"
    [ "$STATUS" = 201 ] \
        || fail "chaos POST $i returned $STATUS, want 201 (injected fault leaked past the retry layer)"
done
echo "ok   30/30 event POSTs -> 201 under injected faults"

# /faults.json must report the armed spec and at least one trigger (the
# latency rule fires on every group-commit flush, so >= 1 is guaranteed)
curl -fsS --max-time 10 "$CBASE/faults.json" | python -c '
import json, sys
body = json.load(sys.stdin)
assert body["enabled"] is True, body
assert sum(t["count"] for t in body["triggered"]) >= 1, body
' || fail "/faults.json missing armed spec / trigger counts"
CHAOS_METRICS="$(curl -fsS --max-time 10 "$CBASE/metrics")"
grep -q 'pio_tpu_fault_triggered_total{' <<<"$CHAOS_METRICS" \
    || fail "/metrics missing pio_tpu_fault_triggered_total sample"
echo "ok   injections visible on /faults.json + /metrics"

# ----------------------------------- chaos v2: partlog leader failover
# ISSUE 9: a 3-partition replicated event server at commit durability
# must lose ZERO acknowledged writes when its leader is SIGKILLed
# mid-ingest — a 201 is only sent after >= min_acks followers fsynced
# the record, so the longest-verified-prefix promotion serves every
# acked event. The drill also proves /storage.json reports the live
# topology and the partlog/repl metric families are present.
python -m pio_tpu.tools.cli lint --dump-failpoints pio_tpu | python -c '
import json, sys
inv = {f["point"] for f in json.load(sys.stdin)["failpoints"]}
need = {"partlog.append.before_write", "repl.send", "repl.ack"}
missing = need - inv
assert not missing, f"partlog/repl failpoints missing from inventory: {missing}"
' || fail "partlog/repl failpoints missing from --dump-failpoints"
echo "ok   partlog/repl failpoints in lint inventory"

FAILOVER_STAGE="$WORKDIR/failover_stage.py"
cat > "$FAILOVER_STAGE" <<'PY'
"""Smoke stage: partitioned-log leader failover under SIGKILL.

Boots two in-process follower replicas and an EVENT server subprocess
over a 3-partition ``partlog`` at ``commit`` durability (a 201 is sent
only after a follower fsynced the record). A writer thread ingests
continuously; once enough writes are acked the leader is SIGKILLed
mid-ingest, the followers are promoted by longest verified prefix, and
the promoted log must serve EVERY acked write — zero acked-write loss.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

WORKDIR = sys.argv[1]

from pio_tpu.storage.partlog import failover
from pio_tpu.storage.partlog.partitioned import PartitionedEventLog
from pio_tpu.storage.partlog.replication import FollowerServer

froot1 = os.path.join(WORKDIR, "failover-f1")
froot2 = os.path.join(WORKDIR, "failover-f2")
f1 = FollowerServer(froot1)
f2 = FollowerServer(froot2)

leader_root = os.path.join(WORKDIR, "failover-leader")
port_file = os.path.join(WORKDIR, "failover-port")
info_file = os.path.join(WORKDIR, "failover-info")

LEADER_SRC = r'''
import json, os, signal, sys
from pio_tpu.server import create_event_server
from pio_tpu.storage import AccessKey, App, Storage

app_id = Storage.get_meta_data_apps().insert(App(0, "failover"))
key = Storage.get_meta_data_access_keys().insert(AccessKey("", app_id))
server = create_event_server(host="127.0.0.1", port=0).start()
info_file, port_file = sys.argv[1], sys.argv[2]
with open(info_file, "w") as f:
    json.dump({"key": key, "app_id": app_id}, f)
with open(port_file + ".tmp", "w") as f:
    f.write(str(server.port))
os.rename(port_file + ".tmp", port_file)  # atomic publish
signal.sigwait({signal.SIGTERM, signal.SIGINT})
server.stop()
'''

env = dict(os.environ)
env.update({
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "PL",
    "PIO_STORAGE_SOURCES_PL_TYPE": "partlog",
    "PIO_STORAGE_SOURCES_PL_PATH": leader_root,
    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
    "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    "PIO_TPU_PARTLOG_PARTITIONS": "3",
    "PIO_TPU_PARTLOG_REPLICAS": f"127.0.0.1:{f1.port},127.0.0.1:{f2.port}",
    "PIO_TPU_DURABILITY": "commit",
})
proc = subprocess.Popen(
    [sys.executable, "-c", LEADER_SRC, info_file, port_file], env=env)


def _cleanup():
    # a failed assertion must not leave the leader (sigwait) or the
    # follower accept loops holding the stage open
    stop_writer.set()
    if proc.poll() is None:
        proc.kill()
        proc.wait()
    f1.stop()
    f2.stop()


deadline = time.time() + 60
while not os.path.exists(port_file):
    if proc.poll() is not None:
        raise SystemExit("leader event server died during boot")
    if time.time() > deadline:
        proc.kill()
        raise SystemExit("leader event server never published its port")
    time.sleep(0.2)
with open(port_file) as f:
    base = "http://127.0.0.1:" + f.read().strip()
with open(info_file) as f:
    info = json.load(f)


def get(path):
    with urllib.request.urlopen(base + path, timeout=10) as r:
        return r.read().decode("utf-8")


acked = set()
stop_writer = threading.Event()


def writer():
    i = 0
    while not stop_writer.is_set():
        i += 1
        body = json.dumps({
            "event": "chaos", "entityType": "user", "entityId": f"u{i}",
            "properties": {"seq": i},
            "eventTime": "2026-03-01T10:00:00Z",
        }).encode("utf-8")
        req = urllib.request.Request(
            base + "/events.json?accessKey=" + info["key"],
            data=body, headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=15) as r:
                if r.status == 201:
                    acked.add(f"u{i}")
        except Exception:
            return  # leader is gone: the in-flight write was never acked


t = threading.Thread(target=writer, daemon=True)
t.start()
try:
    deadline = time.time() + 60
    while len(acked) < 15:
        if time.time() > deadline:
            raise SystemExit(f"only {len(acked)} writes acked in 60s")
        time.sleep(0.05)

    # the outside view while the leader is up: topology + repl metrics
    topo = json.loads(get("/storage.json"))
    assert topo["backend"] == "partlog", topo
    assert topo["role"] == "leader" and topo["partitions"] == 3, topo
    assert len(topo["partition_detail"]) == 3, topo
    repl = topo["replication"]
    assert repl is not None and repl["min_acks"] >= 1, repl
    assert len(repl["followers"]) == 2, repl
    metrics = get("/metrics")
    for fam in ("pio_tpu_partlog_appends_total", "pio_tpu_repl_acks_total"):
        assert fam + "{" in metrics, f"/metrics missing {fam}"

    # mid-ingest SIGKILL: the writer thread is still posting
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()
    stop_writer.set()
    t.join(timeout=30)
    n_acked = len(acked)
finally:
    _cleanup()

promoted_root = os.path.join(WORKDIR, "failover-promoted")
report = failover.promote([froot1, froot2], promoted_root)
assert report["partitions"] == 3, report

log = PartitionedEventLog(promoted_root)
try:
    got = {e.entity_id for e in log.find(info["app_id"])}
finally:
    log.close()
lost = acked - got
assert not lost, (
    f"promoted follower lost {len(lost)} acked writes: {sorted(lost)[:5]}")
print(f"failover stage: {n_acked} acked writes, 0 lost after promotion "
      f"({len(got)} records served by the promoted root)")
PY
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" python "$FAILOVER_STAGE" "$WORKDIR" \
    || fail "partlog failover stage (acked-write loss / topology assertions)"
echo "ok   partlog failover: leader SIGKILLed mid-ingest, zero acked writes lost"

# -------------------------------------------------- pooled batch lane
# ISSUE 7: a pooled server with the shape-bucket cache warmed and the
# cross-worker batch lane armed must keep the micro-batcher engaged
# under concurrent load (mode != "off") and never retrace a bucket in
# steady state (the retrace counter stays flat across the timed
# window). The driver is a real temp FILE, not a heredoc on stdin:
# the pool's spawn context re-imports __main__ in every worker
# (__mp_main__), which needs an importable path — the module guards
# its body with __name__ == "__main__" so workers import it inertly.
POOL_STAGE="$WORKDIR/pool_stage.py"
cat > "$POOL_STAGE" <<'PY'
"""Smoke stage: pooled serving with shape buckets + the batch lane.

Boots a 2-worker SO_REUSEPORT pool (worker 0 designated device owner so
the lane arms), drives concurrent load, then asserts on the OUTSIDE
view (/metrics pool-wide sums, /stats.json):

- the bucket retrace counter is FLAT across the steady-state window
  (every batch shape was served by a warmed executable),
- the batch lane actually moved traffic (drained counter > 0),
- the micro-batcher did not latch off (``mode != "off"``).
"""
import datetime as dt
import json
import os
import threading
import time
import urllib.request


def _post(base, body, timeout=30):
    req = urllib.request.Request(
        base + "/queries.json",
        data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def _get(base, path, timeout=10):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.read().decode("utf-8")


def _counter_total(metrics_text, name):
    """Sum every sample of one counter family in Prometheus text (the
    scrape already sums worker stripes; this folds label cells)."""
    total = 0.0
    for line in metrics_text.splitlines():
        if line.startswith(name + "{") or line.startswith(name + " "):
            total += float(line.rsplit(" ", 1)[1])
    return total


def _drive(base, n_threads, n_each, retry=False):
    errs = []

    def run(t):
        for q in range(n_each):
            body = {"user": "u%d" % ((t * 31 + q) % 8), "num": 3}
            for attempt in range(40 if retry else 1):
                try:
                    got = _post(base, body)
                    assert "itemScores" in got, got
                    break
                except Exception as exc:  # 503 while a worker warms up
                    if not retry or attempt == 39:
                        errs.append(exc)
                        return
                    time.sleep(0.5)

    threads = [
        threading.Thread(target=run, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errs:
        raise SystemExit(f"pool load failed: {errs[:3]}")


def main():
    os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "SQ"
    os.environ["PIO_STORAGE_SOURCES_SQ_TYPE"] = "sqlite"
    os.environ["PIO_STORAGE_SOURCES_SQ_PATH"] = os.path.join(
        os.environ["PIO_TPU_HOME"], "pool.db")
    os.environ["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "SQ"
    os.environ["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "SQ"
    # batching on (the micro-batcher + the warmup sweep key off this);
    # a short ladder keeps the per-worker CPU warmup sweep quick
    os.environ["PIO_TPU_SERVE_MICROBATCH_US"] = "1500"
    os.environ["PIO_TPU_BUCKET_WARMUP"] = "1"
    os.environ["PIO_TPU_BATCH_BUCKETS"] = "1,2,4,8"

    import pio_tpu.templates  # noqa: F401  (registers the factory)
    from pio_tpu.controller import ComputeContext
    from pio_tpu.data import Event
    from pio_tpu.server.worker_pool import ServingPool
    from pio_tpu.storage import App, Storage
    from pio_tpu.workflow import build_engine, run_train, variant_from_dict

    app_id = Storage.get_meta_data_apps().insert(App(0, "smoke-pool"))
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    for u in range(8):
        for i in range(6):
            in_block = (u < 4) == (i < 3)
            le.insert(
                Event("rate", "user", f"u{u}", "item", f"i{i}",
                      properties={"rating": 5.0 if in_block else 1.0},
                      event_time=t0),
                app_id,
            )
    variant = variant_from_dict({
        "id": "smoke-pool-rec",
        "engineFactory": "templates.recommendation",
        "datasource": {"params": {"app_name": "smoke-pool"}},
        "algorithms": [{"name": "als", "params": {
            "rank": 4, "num_iterations": 4, "lambda_": 0.1}}],
    })
    engine, ep = build_engine(variant)
    run_train(engine, ep, variant, ctx=ComputeContext.local())

    pool = ServingPool(
        variant, host="127.0.0.1", port=0, n_workers=2,
        device_worker=True,
    )
    pool.start()
    try:
        pool.wait_ready(timeout=240.0)
        base = f"http://127.0.0.1:{pool.port}"
        # settle round: /readyz only vouches for the worker the kernel
        # happened to pick, so retry 503s until BOTH workers are
        # deployed + warmed; any cold compile (first num=3 top-k) lands
        # here, outside the timed window
        _drive(base, 8, 5, retry=True)
        retrace_before = _counter_total(
            _get(base, "/metrics"), "pio_tpu_bucket_retrace_total")
        # steady state: 16 concurrent clients across both workers
        _drive(base, 16, 10)
        metrics = _get(base, "/metrics")
        retrace_after = _counter_total(
            metrics, "pio_tpu_bucket_retrace_total")
        assert retrace_after == retrace_before, (
            f"bucket retraces moved {retrace_before} -> {retrace_after} "
            f"under steady-state load: a batch shape escaped the "
            f"warmed ladder")
        drained = _counter_total(
            metrics, "pio_tpu_batchlane_drained_total")
        assert drained >= 1, (
            f"batch lane never drained a request (drained={drained}); "
            f"pool queries are not aggregating")
        # the micro-batcher must not have latched off; sample stats over
        # several connections (the kernel picks the answering worker)
        modes = {}
        for _ in range(12):
            st = json.loads(_get(base, "/stats.json"))
            mb = st.get("microbatch")
            if mb is not None:
                modes[st.get("worker")] = mb["mode"]
        assert modes, "no worker reported micro-batch stats"
        assert "off" not in modes.values(), (
            f"micro-batcher latched off under pooled load: {modes}")
        print(f"pool stage: modes={modes} drained={int(drained)} "
              f"retraces={int(retrace_after)}")
    finally:
        pool.stop()


if __name__ == "__main__":
    main()
PY
# PYTHONPATH: the driver lives in $WORKDIR, so sys.path[0] is /tmp —
# point it (and the spawned pool workers, which inherit the env) at
# this checkout
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" python "$POOL_STAGE" \
    || fail "pooled batch-lane stage (mode/retrace/lane assertions)"
echo "ok   pooled serving: micro-batcher engaged, retraces flat, lane drained"

# ------------------------------------------ device-resident serving
# ISSUE 8: the resident-scorer failpoints must be dump-visible (a chaos
# spec targeting them must arm something), then a classification server
# with residency forced on and the int8 query wire must serve a steady
# window where the h2d counter grows by AT MOST the int8 payload per
# request (1 byte/feature — the params never re-ship), the bucket
# retrace counter stays flat, and the donation hit rate holds >= 0.95.
python -m pio_tpu.tools.cli lint --dump-failpoints pio_tpu | python -c '
import json, sys
inv = {f["point"] for f in json.load(sys.stdin)["failpoints"]}
need = {"scorer.h2d.ship", "scorer.donate.dispatch"}
missing = need - inv
assert not missing, f"resident failpoints missing from inventory: {missing}"
' || fail "scorer.h2d/scorer.donate failpoints missing from --dump-failpoints"
echo "ok   scorer.h2d/scorer.donate failpoints in lint inventory"

python - <<'PY' || fail "device-resident stage (h2d/retrace/donation assertions)"
"""Smoke stage: device-resident serving on the int8 query wire.

Boots a classification server with ``PIO_TPU_DEVICE_RESIDENT=1`` and
``PIO_TPU_SERVE_WIRE=int8``, warms it, then drives a steady window and
asserts from the OUTSIDE view (/metrics, /stats.json) that the wire is
actually thin: h2d bytes grow by <= 1 byte/feature/request, zero
retraces, donation hit rate >= 0.95, and every prediction is right.
"""
import datetime as dt
import json
import os
import urllib.request

os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "MEM"
os.environ["PIO_STORAGE_SOURCES_MEM_TYPE"] = "memory"
os.environ["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "MEM"
os.environ["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "MEM"
os.environ["PIO_TPU_DEVICE_RESIDENT"] = "1"
os.environ["PIO_TPU_SERVE_WIRE"] = "int8"
os.environ["PIO_TPU_BUCKET_WARMUP"] = "1"
os.environ["PIO_TPU_BATCH_BUCKETS"] = "1,2,4"

import pio_tpu.templates  # noqa: F401  (registers the factory)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.server import create_query_server
from pio_tpu.storage import App, Storage
from pio_tpu.workflow import build_engine, run_train, variant_from_dict

app_id = Storage.get_meta_data_apps().insert(App(0, "smoke-res"))
le = Storage.get_levents()
t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
PLANS = ("basic", "premium", "pro")
n = 0
for hot, plan in enumerate(PLANS):
    for _ in range(8):
        props = {f"attr{j}": (7 if j == hot else 1) for j in range(3)}
        props["plan"] = plan
        le.insert(
            Event("$set", "user", f"u{n}", properties=props,
                  event_time=t0 + dt.timedelta(minutes=n)),
            app_id,
        )
        n += 1
variant = variant_from_dict({
    "id": "smoke-resident",
    "engineFactory": "templates.classification",
    "datasource": {"params": {"app_name": "smoke-res"}},
    "algorithms": [{"name": "logreg", "params": {}}],
})
engine, ep = build_engine(variant)
ctx = ComputeContext.local()
run_train(engine, ep, variant, ctx=ctx)
server, _service = create_query_server(
    variant, host="127.0.0.1", port=0, ctx=ctx
)
server.start()
try:
    base = f"http://127.0.0.1:{server.port}"

    def post(body):
        req = urllib.request.Request(
            base + "/queries.json",
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read().decode("utf-8"))

    def get(path):
        with urllib.request.urlopen(base + path, timeout=10) as r:
            return r.read().decode("utf-8")

    def counter(text, name):
        total = 0.0
        for line in text.splitlines():
            if line.startswith(name + "{") or line.startswith(name + " "):
                total += float(line.rsplit(" ", 1)[1])
        return total

    got = post({"attrs": [9.0, 1.0, 1.0]})  # warm route + wire
    assert got.get("label") == "basic", got
    m0 = get("/metrics")
    h2d0 = counter(m0, "pio_tpu_serving_h2d_bytes_total")
    retr0 = counter(m0, "pio_tpu_bucket_retrace_total")
    N, D = 40, 3
    for q in range(N):
        hot = q % 3
        got = post({"attrs": [9.0 if j == hot else 1.0 for j in range(3)]})
        assert got.get("label") == PLANS[hot], (q, got)
    m1 = get("/metrics")
    h2d = counter(m1, "pio_tpu_serving_h2d_bytes_total") - h2d0
    retr = counter(m1, "pio_tpu_bucket_retrace_total") - retr0
    assert 0 < h2d <= N * D, (
        f"h2d grew {h2d} bytes over {N} requests on the int8 wire "
        f"(want (0, {N * D}]: 1 byte/feature, params never re-ship)")
    assert retr == 0, f"bucket retraces moved by {retr} in steady state"
    res = json.loads(get("/stats.json"))["residency"]
    assert res["enabled"] and res["paramBytes"] > 0, res
    sc = res["scorers"][0]
    assert sc["wire"] == "int8", sc
    assert sc["donation"]["hitRate"] >= 0.95, sc["donation"]
    print(f"resident stage: h2d={int(h2d)}B/{N} reqs retraces={int(retr)} "
          f"donationHitRate={sc['donation']['hitRate']}")
finally:
    server.stop()
PY
echo "ok   device-resident serving: int8 wire thin, retraces flat, donations hit"

# ------------------------------------------------ device telemetry plane
# ISSUE 17: the devicewatch failpoints must be dump-visible, then a
# resident server's /device.json must book real ledger bytes against
# the budget, hold the compile-attribution counters FLAT over a steady
# window AND across a hot swap (while the generation bumps), release
# bytes on scorer retirement (peak survives), and a dashboard pointed
# at the server must render /devices.html from one scrape.
python -m pio_tpu.tools.cli lint --dump-failpoints pio_tpu | python -c '
import json, sys
inv = {f["point"] for f in json.load(sys.stdin)["failpoints"]}
need = {"devicewatch.sample", "devicewatch.payload"}
missing = need - inv
assert not missing, f"devicewatch failpoints missing from inventory: {missing}"
' || fail "devicewatch failpoints missing from --dump-failpoints"
echo "ok   devicewatch failpoints in lint inventory"

python - <<'PY' || fail "device telemetry stage (bytes/compile/generation assertions)"
"""Smoke stage: the device telemetry plane over a deploy -> steady ->
hot-swap -> retire walk, asserted from the OUTSIDE view (/device.json,
/metrics, /devices.html)."""
import datetime as dt
import json
import os
import urllib.request

os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "MEM"
os.environ["PIO_STORAGE_SOURCES_MEM_TYPE"] = "memory"
os.environ["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "MEM"
os.environ["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "MEM"
os.environ["PIO_TPU_DEVICE_RESIDENT"] = "1"
os.environ["PIO_TPU_BUCKET_WARMUP"] = "1"
os.environ["PIO_TPU_BATCH_BUCKETS"] = "1,2,4"
os.environ["PIO_TPU_DEVICE_BUDGET_BYTES"] = str(64 * 1024 * 1024)
os.environ["PIO_TPU_DEVICEWATCH_INTERVAL_S"] = "0.2"

import pio_tpu.templates  # noqa: F401  (registers the factory)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.server import create_query_server
from pio_tpu.server.dashboard import create_dashboard
from pio_tpu.storage import App, Storage
from pio_tpu.workflow import build_engine, run_train, variant_from_dict

app_id = Storage.get_meta_data_apps().insert(App(0, "smoke-dev"))
le = Storage.get_levents()
t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
PLANS = ("basic", "premium", "pro")
n = 0
for hot, plan in enumerate(PLANS):
    for _ in range(8):
        props = {f"attr{j}": (7 if j == hot else 1) for j in range(3)}
        props["plan"] = plan
        le.insert(
            Event("$set", "user", f"u{n}", properties=props,
                  event_time=t0 + dt.timedelta(minutes=n)),
            app_id,
        )
        n += 1
variant = variant_from_dict({
    "id": "smoke-devwatch",
    "engineFactory": "templates.classification",
    "datasource": {"params": {"app_name": "smoke-dev"}},
    "algorithms": [{"name": "logreg", "params": {}}],
})
engine, ep = build_engine(variant)
ctx = ComputeContext.local()
run_train(engine, ep, variant, ctx=ctx)
server, service = create_query_server(
    variant, host="127.0.0.1", port=0, ctx=ctx
)
server.start()
dash = None
try:
    base = f"http://127.0.0.1:{server.port}"

    def post(body):
        req = urllib.request.Request(
            base + "/queries.json",
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read().decode("utf-8"))

    def get(path, b=None):
        with urllib.request.urlopen((b or base) + path, timeout=10) as r:
            return r.read().decode("utf-8")

    d0 = json.loads(get("/device.json"))
    assert d0["generation"] == 1, d0["generation"]
    assert d0["ledger"]["totalBytes"] > 0, "deploy booked no ledger bytes"
    assert d0["ledger"]["byCategory"]["resident"] > 0, d0["ledger"]
    assert d0["budgetBytes"] == 64 * 1024 * 1024, d0["budgetBytes"]
    assert 0 < d0["headroomBytes"] < d0["budgetBytes"], d0["headroomBytes"]
    sites = d0["compiles"]["sites"]
    assert sites["bucket_warmup"]["count"] == 3, sites
    c0 = d0["compiles"]["total"]

    # steady window: compile counters must not move
    for q in range(30):
        hot = q % 3
        got = post({"attrs": [9.0 if j == hot else 1.0 for j in range(3)]})
        assert got.get("label") == PLANS[hot], (q, got)
    d1 = json.loads(get("/device.json"))
    assert d1["compiles"]["total"] == c0, (
        f"compiles moved {c0} -> {d1['compiles']['total']} in steady state")

    # hot swap: generation bumps, the re-warm over the unchanged bucket
    # ladder hits the global jit cache and must NOT be recounted
    service._load(None)
    d2 = json.loads(get("/device.json"))
    assert d2["generation"] == 2, d2["generation"]
    assert d2["compiles"]["total"] == c0, (
        f"hot-swap re-warm recounted compiles: {c0} -> "
        f"{d2['compiles']['total']}")
    assert d2["ledger"]["byCategory"]["resident"] > 0, d2["ledger"]
    live_bytes = d2["ledger"]["totalBytes"]

    # retire: resident + donated bytes fall to zero, the peak survives
    for sc in list(service._resident):
        sc.retire()
    d3 = json.loads(get("/device.json"))
    cats = d3["ledger"]["byCategory"]
    assert cats.get("resident", 0) == 0, cats
    assert cats.get("donated", 0) == 0, cats
    assert d3["ledger"]["totalBytes"] < live_bytes
    peak = d3["devices"][0]["peakBytes"]
    assert peak >= live_bytes, (peak, live_bytes)

    m = get("/metrics")
    for fam in ("pio_tpu_device_bytes_in_use", "pio_tpu_device_peak_bytes",
                "pio_tpu_device_budget_headroom_bytes",
                "pio_tpu_xla_compile_total"):
        assert fam in m, f"{fam} missing from /metrics"

    # dashboard renders the plane from one scrape
    dash = create_dashboard(host="127.0.0.1", port=0, query_url=base)
    dash.start()
    page = get("/devices.html", b=f"http://127.0.0.1:{dash.port}")
    assert "scrape failed" not in page, page[:400]
    assert "bucket_warmup" in page and "HBM (MiB)" in page, page[:400]
    print(f"device stage: ledger {live_bytes}B live -> "
          f"{d3['ledger']['totalBytes']}B retired, peak {peak}B, "
          f"compiles {c0} flat across steady+swap, gen 1->2")
finally:
    if dash is not None:
        dash.stop()
    server.stop()
PY
echo "ok   device telemetry: bytes rise/fall, compiles flat, /devices.html renders"

# ------------------------------------------------ evloop HTTP front
# ISSUE 13: the selector-based front must hold the threaded baseline
# on pooled keep-alive load (bench.py serving.evfront records the
# >=1.5x headline), keep /debug/hotpath.json attribution >= 95%, and
# the packed int8 wire must take the zero-copy fast path with exact
# JSON parity.
EVFRONT_STAGE="$WORKDIR/evfront_stage.py"
cat > "$EVFRONT_STAGE" <<'PY'
"""Smoke stage: the evloop HTTP front + packed int8 wire vs threaded.

Boots the SAME trained classification engine behind both fronts
(``PIO_TPU_HTTP_FRONT``) and drives each with a multiplexed raw-socket
client over 16 keep-alive connections — the threaded baseline serves
the JSON wire, the evloop front serves the packed int8 wire (the
deployment the tentpole ships). Asserts from the OUTSIDE view:

- evloop QPS >= the threaded baseline (bench.py ``serving.evfront``
  records the real >=1.5x headline; this gate catches a regression),
- /debug/hotpath.json ``attributedFraction`` >= 0.95 on the evloop
  front under steady-state load,
- a packed ``application/x-pio-query-i8`` POST answers byte-for-byte
  parity with the JSON wire and takes the zero-copy fast path
  (``pio_tpu_http_parse_fastpath_total`` moves).
"""
import datetime as dt
import json
import os
import selectors
import socket
import time

os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "MEM"
os.environ["PIO_STORAGE_SOURCES_MEM_TYPE"] = "memory"
os.environ["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "MEM"
os.environ["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "MEM"
os.environ["PIO_TPU_DEVICE_RESIDENT"] = "1"
os.environ["PIO_TPU_SERVE_WIRE"] = "int8"
os.environ["PIO_TPU_BUCKET_WARMUP"] = "1"
os.environ["PIO_TPU_BATCH_BUCKETS"] = "1,2,4"

import pio_tpu.templates  # noqa: F401  (registers the factory)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.server import create_query_server
from pio_tpu.server.http import PACKED_QUERY_CONTENT_TYPE
from pio_tpu.storage import App, Storage
from pio_tpu.workflow import build_engine, run_train, variant_from_dict

app_id = Storage.get_meta_data_apps().insert(App(0, "smoke-evfront"))
le = Storage.get_levents()
t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
PLANS = ("basic", "premium", "pro")
n = 0
for hot, plan in enumerate(PLANS):
    for _ in range(8):
        props = {f"attr{j}": (7 if j == hot else 1) for j in range(3)}
        props["plan"] = plan
        le.insert(
            Event("$set", "user", f"u{n}", properties=props,
                  event_time=t0 + dt.timedelta(minutes=n)),
            app_id,
        )
        n += 1
variant = variant_from_dict({
    "id": "smoke-evfront",
    "engineFactory": "templates.classification",
    "datasource": {"params": {"app_name": "smoke-evfront"}},
    "algorithms": [{"name": "logreg", "params": {}}],
})
engine, ep = build_engine(variant)
ctx = ComputeContext.local()
run_train(engine, ep, variant, ctx=ctx)


def mk_req(payload, ctype):
    return (b"POST /queries.json HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: " + ctype.encode("latin-1") + b"\r\n"
            b"Content-Length: " + str(len(payload)).encode() +
            b"\r\n\r\n" + payload)


def _count_responses(buf, on_body=None):
    """Pop complete Content-Length-framed responses off ``buf``."""
    got = 0
    while True:
        he = buf.find(b"\r\n\r\n")
        if he < 0:
            return got
        cl = 0
        for hline in bytes(buf[:he]).lower().split(b"\r\n"):
            if hline.startswith(b"content-length:"):
                cl = int(hline.split(b":", 1)[1])
        if len(buf) < he + 4 + cl:
            return got
        if on_body is not None:
            on_body(bytes(buf[he + 4:he + 4 + cl]))
        del buf[:he + 4 + cl]
        got += 1


def drive(port, req, n_conns, total):
    """One outstanding request per keep-alive connection, multiplexed
    in ONE client thread (a thread-per-connection client would cost
    more GIL time than either server front under test)."""
    sel = selectors.DefaultSelector()
    socks = []
    for _ in range(n_conns):
        s = socket.create_connection(("127.0.0.1", port))
        s.setblocking(False)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        socks.append(s)
        sel.register(s, selectors.EVENT_READ, bytearray())
    sent = done = 0
    start = time.monotonic()
    for s in socks:
        s.sendall(req)
        sent += 1
    while done < total:
        for key, _ in sel.select(10):
            s, buf = key.fileobj, key.data
            chunk = s.recv(65536)
            if not chunk:
                raise SystemExit("server closed a keep-alive connection")
            buf += chunk
            for _ in range(_count_responses(buf)):
                done += 1
                if sent < total:
                    s.sendall(req)
                    sent += 1
    took = time.monotonic() - start
    for s in socks:
        sel.unregister(s)
        s.close()
    return total / took


def one(port, method, path, payload=None, ctype=None):
    s = socket.create_connection(("127.0.0.1", port))
    if payload is None:
        s.sendall(f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
                  f"Connection: close\r\n\r\n".encode())
    else:
        s.sendall(mk_req(payload, ctype))
    buf = bytearray()
    while True:
        chunk = s.recv(65536)
        if not chunk:
            break
        buf += chunk
        out = []
        if _count_responses(buf, out.append):
            s.close()
            return out[0]
    s.close()
    raise SystemExit(f"no complete response for {method} {path}")


body = {"attrs": [9.0, 1.0, 1.0]}
json_payload = json.dumps(body).encode("utf-8")
qps = {}
for front, wire in (("threaded", "json"), ("evloop", "packed")):
    os.environ["PIO_TPU_HTTP_FRONT"] = front
    server, svc = create_query_server(
        variant, host="127.0.0.1", port=0, ctx=ctx
    )
    server.start()
    try:
        req = mk_req(json_payload, "application/json") if wire == "json" \
            else mk_req(svc.pack_query_body(body), PACKED_QUERY_CONTENT_TYPE)
        drive(server.port, req, 4, 64)  # settle: cold scheduling noise
        # best-of-2: a single window on a shared 1-core host is noisy
        qps[front] = max(drive(server.port, req, 16, 600) for _ in (0, 1))
        if front != "evloop":
            continue
        out_json = one(server.port, "POST", "/queries.json",
                       json_payload, "application/json")
        out_packed = one(server.port, "POST", "/queries.json",
                         svc.pack_query_body(body),
                         PACKED_QUERY_CONTENT_TYPE)
        assert json.loads(out_packed) == json.loads(out_json), (
            out_packed, out_json)
        assert json.loads(out_packed).get("label") == "basic", out_packed
        metrics = one(server.port, "GET", "/metrics").decode("utf-8")
        fast = sum(
            float(line.rsplit(" ", 1)[1])
            for line in metrics.splitlines()
            if line.startswith("pio_tpu_http_parse_fastpath_total"))
        assert fast >= 600, (
            f"packed load did not take the parse fast path (sum={fast})")
        hp = json.loads(one(server.port, "GET", "/debug/hotpath.json"))
        frac = hp.get("attributedFraction")
        assert hp["requestCount"] >= 600, hp["requestCount"]
        assert frac is not None and frac >= 0.95, (
            f"evloop attribution {frac} < 0.95 over "
            f"{hp['requestCount']} requests "
            f"(residual {hp.get('residualMsPerRequest')} ms/req)")
    finally:
        server.stop()

# 5% scheduler-noise floor: best-of-2 windows on a shared host still
# land within a few percent of each other run to run, and a genuine
# evloop regression shows up far past that
assert qps["evloop"] >= 0.95 * qps["threaded"], (
    f"evloop front (packed wire) lost to the threaded baseline: "
    f"{qps['evloop']:.0f} vs {qps['threaded']:.0f} qps")
print(f"evfront stage: threaded-json={qps['threaded']:.0f}qps "
      f"evloop-packed={qps['evloop']:.0f}qps "
      f"speedup={qps['evloop'] / qps['threaded']:.2f}x")
PY
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" python "$EVFRONT_STAGE" \
    || fail "evloop front stage (qps/attribution/packed-parity assertions)"
echo "ok   evloop front: qps holds threaded baseline, attribution >= 95%, packed fastpath parity"

# --------------------------------------------- mesh-sharded serving
# ISSUE 10: the shard.* failpoints must be dump-visible, then a
# recommendation server on a simulated 8-device mesh with
# PIO_TPU_MESH_SERVE=1 (and sharded persistence on) must report a
# populated /stats.json "sharding" block, answer a steady window with
# the retrace counter flat, and agree with the host-scored reference.
python -m pio_tpu.tools.cli lint --dump-failpoints pio_tpu | python -c '
import json, sys
inv = {f["point"] for f in json.load(sys.stdin)["failpoints"]}
need = {"shard.place", "shard.reshard"}
missing = need - inv
assert not missing, f"shard failpoints missing from inventory: {missing}"
' || fail "shard.place/shard.reshard failpoints missing from --dump-failpoints"
echo "ok   shard.place/shard.reshard failpoints in lint inventory"

python - <<'PY' || fail "mesh-sharded stage (sharding block/retrace/parity assertions)"
"""Smoke stage: mesh-sharded serving via the partition-rule registry.

Trains ALS with sharded persistence on, serves it over a simulated
8-device CPU mesh with PIO_TPU_MESH_SERVE=1, and asserts from the
outside: the /stats.json sharding block names the mesh and the placed
model, rankings match the host-scored reference exactly, and the bucket
retrace counter stays flat across the steady-state window.
"""
import datetime as dt
import json
import os
import urllib.request

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "MEM"
os.environ["PIO_STORAGE_SOURCES_MEM_TYPE"] = "memory"
os.environ["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "MEM"
os.environ["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "MEM"
os.environ["PIO_TPU_SHARDED_PERSIST"] = "1"
os.environ["PIO_TPU_MESH_SERVE"] = "1"
os.environ["PIO_TPU_BUCKET_WARMUP"] = "1"
os.environ["PIO_TPU_BATCH_BUCKETS"] = "1,2,4"

import pio_tpu.templates  # noqa: F401  (registers the factory)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.server import create_query_server
from pio_tpu.storage import App, Storage
from pio_tpu.templates.recommendation import Query
from pio_tpu.workflow import (
    build_engine, load_models_for_instance, run_train, variant_from_dict,
)

app_id = Storage.get_meta_data_apps().insert(App(0, "smoke-shard"))
le = Storage.get_levents()
t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
for u in range(12):
    for i in range(8):
        in_block = (u < 6) == (i < 4)
        le.insert(
            Event("rate", "user", f"u{u}", "item", f"i{i}",
                  properties={"rating": 5.0 if in_block else 1.0},
                  event_time=t0 + dt.timedelta(minutes=u * 60 + i)),
            app_id,
        )
variant = variant_from_dict({
    "id": "smoke-sharded",
    "engineFactory": "templates.recommendation",
    "datasource": {"params": {"app_name": "smoke-shard"}},
    "algorithms": [{"name": "als", "params": {
        "rank": 6, "num_iterations": 8, "lambda_": 0.05, "seed": 1}}],
})
engine, ep = build_engine(variant)
ctx = ComputeContext.create(seed=0)
n_dev = ctx.num_devices
assert n_dev == 8, f"expected the simulated 8-device mesh, got {n_dev}"
iid = run_train(engine, ep, variant, ctx=ctx)

# the sharded-persist artifacts must actually exist (blob is stripped)
ms = Storage.get_model_data_models()
assert ms.get(iid + ".shards") is not None, "shard manifest missing"

# headline constraint: a per-device budget the WHOLE model does not fit
# in (480 B of factors, 64 B/chip budget) — serving must only be
# possible sharded over the mesh
from pio_tpu.ops.topn import DeviceTopNScorer
from pio_tpu.parallel.partition import DeviceBudgetExceeded

os.environ["PIO_TPU_DEVICE_BUDGET_BYTES"] = "64"
probe = load_models_for_instance(iid, engine, ep, ctx)[0]
rows, cols = probe.factors.user_factors, probe.factors.item_factors
assert rows.nbytes + cols.nbytes > 64, "model unexpectedly fits one chip"
try:
    DeviceTopNScorer(rows, cols, prefer_device=True)
except DeviceBudgetExceeded:
    pass
else:
    raise AssertionError("single-chip placement ignored the budget")

# host-scored reference: the same instance through the direct predict
# path on host numpy — pin host mode so warmup never attempts a
# single-chip placement (the 64 B budget is still in force)
models = load_models_for_instance(iid, engine, ep, ctx)
serving = engine.make_serving(ep)
os.environ["PIO_TPU_SERVE_DEVICE"] = "host"
pairs = engine.algorithms_with_models(ep, models)
os.environ.pop("PIO_TPU_SERVE_DEVICE", None)
def host_ref(user, num):
    q = Query(user=user, num=num)
    preds = [algo.predict(m, q) for algo, m in pairs]
    return [s.item for s in serving.serve(q, preds).item_scores]

server, _service = create_query_server(
    variant, host="127.0.0.1", port=0, ctx=ctx
)
server.start()
try:
    base = f"http://127.0.0.1:{server.port}"

    def post(body):
        req = urllib.request.Request(
            base + "/queries.json",
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read().decode("utf-8"))

    def get(path):
        with urllib.request.urlopen(base + path, timeout=10) as r:
            return r.read().decode("utf-8")

    def counter(text, name):
        total = 0.0
        for line in text.splitlines():
            if line.startswith(name + "{") or line.startswith(name + " "):
                total += float(line.rsplit(" ", 1)[1])
        return total

    sh = json.loads(get("/stats.json"))["sharding"]
    assert sh["enabled"] and sh["meshDevices"] == 8, sh
    assert sh["models"] and sh["models"][0]["nDevices"] == 8, sh
    placed = counter(get("/metrics"), "pio_tpu_shard_bytes_placed_total")
    assert placed == sh["models"][0]["totalBytes"], (placed, sh)

    got = post({"user": "u0", "num": 4})  # warm route
    assert [s["item"] for s in got["itemScores"]] == host_ref("u0", 4), got
    m0 = get("/metrics")
    retr0 = counter(m0, "pio_tpu_bucket_retrace_total")
    N = 40
    for q in range(N):
        user = f"u{q % 12}"
        got = post({"user": user, "num": 4})
        assert [s["item"] for s in got["itemScores"]] == host_ref(user, 4), (
            user, got)
    retr = counter(get("/metrics"), "pio_tpu_bucket_retrace_total") - retr0
    assert retr == 0, f"bucket retraces moved by {retr} in steady state"
    print(f"sharded stage: mesh={sh['models'][0]['meshShape']} "
          f"placed={int(placed)}B retraces={int(retr)} parity exact over "
          f"{N} requests")
finally:
    server.stop()
PY
echo "ok   mesh-sharded serving: sharding block populated, retraces flat, host parity"

# --------------------------------------------- streamed sharded training
# ISSUE 14: the stream.* failpoints must be dump-visible, then a
# two-tower engine whose params exceed a tiny per-chip budget must (a)
# refuse single-chip placement, (b) train mesh-sharded with the epoch
# STREAMING through parallel/stream.py (the h2d counter moves), (c)
# persist sharded, and (d) deploy on the mesh answering at exact parity
# with the host-scored reference.
python -m pio_tpu.tools.cli lint --dump-failpoints pio_tpu | python -c '
import json, sys
inv = {f["point"] for f in json.load(sys.stdin)["failpoints"]}
need = {"stream.encode", "stream.put", "stream.dispatch"}
missing = need - inv
assert not missing, f"stream failpoints missing from inventory: {missing}"
' || fail "stream.* failpoints missing from --dump-failpoints"
echo "ok   stream.encode/put/dispatch failpoints in lint inventory"

python - <<'PY' || fail "streamed-training stage (budget/stream/persist/parity assertions)"
"""Smoke stage: streamed sharded training end to end.

Budget arithmetic at this scale: the two-tower params are 1792 B
unsharded, ~930 B/device sharded over model=2, and the staged epoch id
arrays are 768 B — so a 1200 B/chip budget rejects single-chip
placement, fits the sharded tables, and forces the auto feed to stream
batch spans (params + staged epoch would be ~1700 B).
"""
import datetime as dt
import json
import os
import urllib.request

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "MEM"
os.environ["PIO_STORAGE_SOURCES_MEM_TYPE"] = "memory"
os.environ["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "MEM"
os.environ["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "MEM"
os.environ["PIO_TPU_SHARDED_PERSIST"] = "1"
os.environ["PIO_TPU_MESH_SERVE"] = "1"

import numpy as np

import pio_tpu.templates  # noqa: F401  (registers the factory)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.server import create_query_server
from pio_tpu.storage import App, Storage
from pio_tpu.templates.recommendation import Query
from pio_tpu.workflow import (
    build_engine, load_models_for_instance, run_train, variant_from_dict,
)

app_id = Storage.get_meta_data_apps().insert(App(0, "smoke-stream"))
le = Storage.get_levents()
t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
for u in range(12):
    for i in range(8):
        in_block = (u < 6) == (i < 4)
        le.insert(
            Event("rate", "user", f"u{u}", "item", f"i{i}",
                  properties={"rating": 5.0 if in_block else 1.0},
                  event_time=t0 + dt.timedelta(minutes=u * 60 + i)),
            app_id,
        )
variant = variant_from_dict({
    "id": "smoke-streamed",
    "engineFactory": "templates.twotower",
    "datasource": {"params": {"app_name": "smoke-stream"}},
    "algorithms": [{"name": "twotower", "params": {
        "embed_dim": 8, "hidden": 8, "out_dim": 8, "steps": 30,
        "batch_size": 16, "model_parallel": 2, "seed": 1}}],
})
engine, ep = build_engine(variant)
ctx = ComputeContext.create(seed=0)
assert ctx.num_devices == 8, f"expected 8 simulated devices, got {ctx.num_devices}"

os.environ["PIO_TPU_DEVICE_BUDGET_BYTES"] = "1200"

# (a) single-chip placement must refuse the budget
from pio_tpu.models.two_tower import TwoTowerConfig, train_two_tower
from pio_tpu.parallel.partition import DeviceBudgetExceeded

rng = np.random.default_rng(0)
cfg = TwoTowerConfig(embed_dim=8, hidden=8, out_dim=8, steps=30,
                     batch_size=16, seed=1)
try:
    train_two_tower(None, rng.integers(0, 12, 96).astype(np.int32),
                    rng.integers(0, 8, 96).astype(np.int32), 12, 8, cfg)
except DeviceBudgetExceeded:
    pass
else:
    raise AssertionError("single-chip placement ignored the budget")

# (b) mesh training streams: the feed's h2d counter must move
from pio_tpu.parallel.stream import _H2D_BYTES

h2d0 = _H2D_BYTES.value()
iid = run_train(engine, ep, variant, ctx=ctx)
h2d = _H2D_BYTES.value() - h2d0
assert h2d > 0, "training under budget did not stream (h2d counter flat)"

# (c) sharded persist artifacts exist (blob is shard-stripped)
ms = Storage.get_model_data_models()
assert ms.get(iid + ".shards") is not None, "shard manifest missing"

# (d) mesh deploy answers at exact parity with the host reference
models = load_models_for_instance(iid, engine, ep, ctx)
serving = engine.make_serving(ep)
os.environ["PIO_TPU_SERVE_DEVICE"] = "host"
pairs = engine.algorithms_with_models(ep, models)
os.environ.pop("PIO_TPU_SERVE_DEVICE", None)

def host_ref(user, num):
    q = Query(user=user, num=num)
    preds = [algo.predict(m, q) for algo, m in pairs]
    return [s.item for s in serving.serve(q, preds).item_scores]

server, _service = create_query_server(
    variant, host="127.0.0.1", port=0, ctx=ctx
)
server.start()
try:
    base = f"http://127.0.0.1:{server.port}"

    def post(body):
        req = urllib.request.Request(
            base + "/queries.json",
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read().decode("utf-8"))

    for q in range(24):
        user = f"u{q % 12}"
        got = post({"user": user, "num": 4})
        assert [s["item"] for s in got["itemScores"]] == host_ref(user, 4), (
            user, got)
    print(f"streamed stage: h2d={int(h2d)}B streamed through the feed, "
          f"sharded persist + mesh deploy, parity exact over 24 requests")
finally:
    server.stop()
PY
echo "ok   streamed sharded training: budget refusal, streamed feed, sharded persist, serve parity"

# -------------------------------------------------- fleet federation
# ISSUE 11: the fleet telemetry plane. Three live members — a
# replicated-partlog event leader (subprocess), its follower's status
# sidecar, and a dashboard — federate into one fleetd whose
# /fleet.json must report them all up with non-null replication lag;
# killing the follower must flip it to down within two scrape
# intervals while the federated counters keep the last-seen snapshot
# in the sums.
FLEET_STAGE="$WORKDIR/fleet_stage.py"
cat > "$FLEET_STAGE" <<'PY'
"""Smoke stage: cross-host metric federation + cluster status."""
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

WORKDIR = sys.argv[1]

from pio_tpu.server.dashboard import create_dashboard
from pio_tpu.server.fleetd import (
    create_fleet_server, create_follower_status_server,
)
from pio_tpu.storage.partlog.replication import FollowerServer

froot = os.path.join(WORKDIR, "fleet-follower")
follower = FollowerServer(froot)

leader_root = os.path.join(WORKDIR, "fleet-leader")
port_file = os.path.join(WORKDIR, "fleet-port")
info_file = os.path.join(WORKDIR, "fleet-info")

LEADER_SRC = r'''
import json, os, signal, sys
from pio_tpu.server import create_event_server
from pio_tpu.storage import AccessKey, App, Storage

app_id = Storage.get_meta_data_apps().insert(App(0, "fleet"))
key = Storage.get_meta_data_access_keys().insert(AccessKey("", app_id))
server = create_event_server(host="127.0.0.1", port=0).start()
info_file, port_file = sys.argv[1], sys.argv[2]
with open(info_file, "w") as f:
    json.dump({"key": key}, f)
with open(port_file + ".tmp", "w") as f:
    f.write(str(server.port))
os.rename(port_file + ".tmp", port_file)
signal.sigwait({signal.SIGTERM, signal.SIGINT})
server.stop()
'''

env = dict(os.environ)
env.update({
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "PL",
    "PIO_STORAGE_SOURCES_PL_TYPE": "partlog",
    "PIO_STORAGE_SOURCES_PL_PATH": leader_root,
    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
    "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    "PIO_TPU_PARTLOG_PARTITIONS": "2",
    "PIO_TPU_PARTLOG_REPLICAS": f"127.0.0.1:{follower.port}",
    # batch durability: the follower mirrors asynchronously, so the
    # leader keeps acking (and counters keep summing) after we kill it
    "PIO_TPU_DURABILITY": "batch",
})
proc = subprocess.Popen(
    [sys.executable, "-c", LEADER_SRC, info_file, port_file], env=env)

servers = []


def cleanup():
    for s in servers:
        try:
            s.stop()
        except Exception:
            pass
    if proc.poll() is None:
        proc.kill()
        proc.wait()
    try:
        follower.stop()
    except Exception:
        pass


try:
    deadline = time.time() + 60
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            raise SystemExit("event leader died during boot")
        if time.time() > deadline:
            raise SystemExit("event leader never published its port")
        time.sleep(0.2)
    with open(port_file) as f:
        leader = "127.0.0.1:" + f.read().strip()
    with open(info_file) as f:
        key = json.load(f)["key"]

    sidecar = create_follower_status_server(
        follower, host="127.0.0.1", port=0).start()
    servers.append(sidecar)
    dash = create_dashboard(host="127.0.0.1", port=0)
    dash.start()
    servers.append(dash)

    def post(n):
        for i in range(n):
            body = json.dumps({
                "event": "fleet", "entityType": "user",
                "entityId": f"u{i}", "properties": {"seq": i},
                "eventTime": "2026-03-01T10:00:00Z",
            }).encode("utf-8")
            req = urllib.request.Request(
                f"http://{leader}/events.json?accessKey=" + key,
                data=body, headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=15) as r:
                assert r.status == 201, r.status

    post(8)
    # async replication: wait until the follower acked every committed
    # byte so the lag the fleet reports is concrete (and zero)
    deadline = time.time() + 30
    while time.time() < deadline:
        with urllib.request.urlopen(
                f"http://{leader}/storage.json", timeout=10) as r:
            topo = json.loads(r.read().decode("utf-8"))
        committed = {str(p["partition"]): p["committed_bytes"]
                     for p in topo["partition_detail"]}
        acked = (topo["replication"] or {}).get("min_acked") or {}
        if sum(committed.values()) > 0 and all(
                acked.get(k) == v for k, v in committed.items()):
            break
        time.sleep(0.1)
    else:
        raise SystemExit(f"follower never caught up: {topo}")

    members = ",".join([
        leader,
        f"127.0.0.1:{sidecar.port}",
        f"127.0.0.1:{dash.port}",
    ])
    fleetd = create_fleet_server(members, host="127.0.0.1", port=0,
                                 interval_s=0.3)
    fleetd.start()
    servers.append(fleetd)
    agg = fleetd.service.agg
    furl = f"http://127.0.0.1:{fleetd.port}"

    def get(url, path):
        with urllib.request.urlopen(url + path, timeout=10) as r:
            return r.status, r.read().decode("utf-8")

    # readiness gates on the first full scrape pass
    try:
        status, _ = get(furl, "/readyz")
    except urllib.error.HTTPError as e:
        status = e.code
    assert status == 503, f"fleetd ready before any scrape ({status})"
    agg.start()
    deadline = time.time() + 30
    while agg.passes < 1 and time.time() < deadline:
        time.sleep(0.05)
    assert get(furl, "/readyz")[0] == 200, "fleetd never became ready"

    pay = json.loads(get(furl, "/fleet.json")[1])
    assert pay["fleet"]["members"] == 3, pay["fleet"]
    assert pay["fleet"]["up"] == 3, pay["fleet"]
    roles = {m["member"]: m["role"] for m in pay["members"]}
    assert roles[leader] == "leader", roles
    assert roles[f"127.0.0.1:{sidecar.port}"] == "follower", roles

    # replication lag is concrete numbers, not nulls
    lead = pay["partlog"]["leaders"][0]
    assert len(lead["partitionDetail"]) == 2, lead
    total_committed = 0
    for p in lead["partitionDetail"]:
        total_committed += p["committedBytes"]
        fol = p["followers"][0]
        assert fol["ackedBytes"] is not None, p
        assert fol["lagBytes"] is not None, p
    assert total_committed > 0, lead

    # federated /metrics: every member's families, member-labeled, and
    # counter sums matching the leader's own scrape
    fed = get(furl, "/metrics")[1]
    for needle in (
        f'pio_tpu_events_ingested_total{{', f'pio_tpu_member="{leader}"',
        f'pio_tpu_repl_follower_position_bytes{{partition="0",'
        f'pio_tpu_member="127.0.0.1:{sidecar.port}"}}',
        f'pio_tpu_fleet_member_up{{member="{leader}"}} 1',
    ):
        assert needle in fed, f"federated scrape missing {needle!r}"
    own = get(f"http://{leader}", "/metrics")[1]
    own_ingested = sum(
        float(line.rsplit(" ", 1)[1])
        for line in own.splitlines()
        if line.startswith("pio_tpu_events_ingested_total{"))
    fed_ingested = sum(
        float(line.rsplit(" ", 1)[1])
        for line in fed.splitlines()
        if line.startswith("pio_tpu_events_ingested_total{")
        and f'pio_tpu_member="{leader}"' in line)
    assert fed_ingested == own_ingested >= 8, (fed_ingested, own_ingested)

    # SIGKILL the follower's surfaces: down within two scrape
    # intervals, last-seen snapshot retained in the federation
    agg.stale_after_s = 0.3
    agg.down_after_s = 0.6  # = two scrape intervals
    sidecar.stop()
    servers.remove(sidecar)
    follower.stop()
    # poll for BOTH: the dead follower marked down AND the live leader
    # seen up in the same payload (with stale_after == interval the
    # leader legitimately reads "stale" between scrapes, so a
    # single-instant assert on its status races the scrape loop)
    deadline = time.time() + 30
    while time.time() < deadline:
        pay = json.loads(get(furl, "/fleet.json")[1])
        by = {m["member"]: m["status"] for m in pay["members"]}
        if by[f"127.0.0.1:{sidecar.port}"] == "down" \
                and by[leader] == "up":
            break
        time.sleep(0.1)
    else:
        raise SystemExit(
            f"follower never down with leader up in one payload: {by}")

    post(4)  # live members keep counting while one is dark
    time.sleep(1.0)  # > one scrape interval
    fed2 = get(furl, "/metrics")[1]
    assert (f'pio_tpu_fleet_member_up'
            f'{{member="127.0.0.1:{sidecar.port}"}} 0') in fed2, "up!=0"
    assert (f'pio_tpu_repl_follower_position_bytes{{partition="0",'
            f'pio_tpu_member="127.0.0.1:{sidecar.port}"}}') in fed2, (
        "dead member's snapshot vanished from the federation")
    fed2_ingested = sum(
        float(line.rsplit(" ", 1)[1])
        for line in fed2.splitlines()
        if line.startswith("pio_tpu_events_ingested_total{")
        and f'pio_tpu_member="{leader}"' in line)
    assert fed2_ingested >= own_ingested + 4, (fed2_ingested, own_ingested)

    print(f"fleet stage: 3 members federated, "
          f"committed={int(total_committed)}B lag reported, follower "
          f"down in <2 intervals, sums {int(fed_ingested)} -> "
          f"{int(fed2_ingested)} with snapshot retained")
finally:
    cleanup()
PY
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" python "$FLEET_STAGE" "$WORKDIR" \
    || fail "fleet federation stage (liveness/lag/federated-sum assertions)"
echo "ok   fleet federation: 3 members, lag reported, follower death detected, sums retained"

# ------------------------------------------------ bench history gate
# ISSUE 16 satellite: the bench ledger's regression flags fail the
# pipeline loudly. --check-history only reads BENCH_HISTORY.jsonl (no
# benchmark run, no throwaway home) and exits nonzero when the last two
# comparable rows regress past the threshold.
python bench.py --check-history \
    || fail "bench history regression (bench.py --check-history)"
echo "ok   bench history: no unexplained regression in the ledger"

# --------------------------------------------- training telemetry plane
# ISSUE 16: live /train.json progress from REAL `pio train` CLI runs —
# monotonically advancing step/epoch and a non-empty loss window while
# the run is in flight; a fleetd that shows the trainer member up
# during the run and down after its exit; and the run ledger, where a
# second run slowed by an injected feed-latency failpoint must be
# flagged by `pio runs --diff`.
TRAIN_STAGE="$WORKDIR/train_stage.py"
cat > "$TRAIN_STAGE" <<'PY'
"""Smoke stage: training telemetry plane end to end."""
import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

WORKDIR = sys.argv[1]

# sqlite storage shared between the seeding parent and the CLI children
os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "SQ"
os.environ["PIO_STORAGE_SOURCES_SQ_TYPE"] = "sqlite"
os.environ["PIO_STORAGE_SOURCES_SQ_PATH"] = os.path.join(
    WORKDIR, "train_stage.db")
os.environ["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "SQ"
os.environ["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "SQ"
# small stream chunks: many feed puts -> many failpoint hits, and the
# step counter advances chunk by chunk while we poll
os.environ["PIO_TPU_TRAIN_STREAM_MB"] = "0.02"

import datetime as dt

from pio_tpu.data import Event
from pio_tpu.storage import App, Storage

app_id = Storage.get_meta_data_apps().insert(App(0, "twsmoke"))
le = Storage.get_levents()
t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
for u in range(24):
    for i in range(12):
        if (u < 12) == (i < 6):
            le.insert(Event("rate", "user", f"u{u}", "item", f"i{i}",
                            properties={"rating": 5.0}, event_time=t0),
                      app_id)

engine_json = os.path.join(WORKDIR, "twsmoke-engine.json")
with open(engine_json, "w") as f:
    json.dump({
        "id": "twsmoke",
        "engineFactory": "templates.twotower",
        "datasource": {"params": {"app_name": "twsmoke"}},
        "algorithms": [{"name": "twotower", "params": {
            "embed_dim": 8, "hidden": 16, "out_dim": 8,
            "steps": 120, "batch_size": 256, "stream": "on"}}],
    }, f)


def run_train(faults, watch=False):
    """One `pio train` CLI run; with watch, poll /train.json live and
    track the trainer member through a fleetd."""
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "pio_tpu", "train",
         "--engine-json", engine_json, "--status-port", "0",
         "--faults", faults],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=dict(os.environ),
    )
    port = None
    deadline = time.time() + 120
    lines = []
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line)
        m = re.search(r"status sidecar on 127\.0\.0\.1:(\d+)", line)
        if m:
            port = int(m.group(1))
            break
    assert port, f"sidecar port never printed: {''.join(lines)}"
    # drain the rest of stdout so the child never blocks on the pipe
    t = threading.Thread(
        target=lambda: lines.extend(iter(proc.stdout.readline, "")),
        daemon=True)
    t.start()
    samples = []
    fleetd = None
    try:
        if watch:
            from pio_tpu.server.fleetd import create_fleet_server

            fleetd = create_fleet_server(
                f"127.0.0.1:{port}", host="127.0.0.1", port=0,
                interval_s=0.2)
            fleetd.start()
            fleetd.service.agg.start()
        seen_up = False
        while proc.poll() is None:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/train.json",
                        timeout=5) as r:
                    samples.append(json.loads(r.read().decode("utf-8")))
            except (urllib.error.URLError, OSError):
                pass  # before the run activates / after it ends
            if (watch and not seen_up and samples
                    and samples[-1].get("step", 0) > 0):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{fleetd.port}/fleet.json",
                        timeout=5) as r:
                    fp = json.loads(r.read().decode("utf-8"))
                me = fp["members"][0]
                if me["role"] == "trainer" and me["status"] == "up":
                    assert me["training"]["runId"], me
                    seen_up = True
            time.sleep(0.02)
        proc.wait(timeout=120)
        assert proc.returncode == 0, (
            f"pio train failed ({proc.returncode}): {''.join(lines)}")
        if watch:
            assert seen_up, "fleetd never saw the trainer member up"
            # the sidecar died with its run: down within a few scrapes
            agg = fleetd.service.agg
            agg.stale_after_s = 0.2
            agg.down_after_s = 0.4
            deadline = time.time() + 30
            while time.time() < deadline:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{fleetd.port}/fleet.json",
                        timeout=5) as r:
                    fp = json.loads(r.read().decode("utf-8"))
                if fp["members"][0]["status"] == "down":
                    break
                time.sleep(0.1)
            else:
                raise SystemExit(
                    f"trainer member never marked down: {fp['members']}")
            assert fp["members"][0]["role"] == "trainer", fp["members"]
    finally:
        if proc.poll() is None:
            proc.kill()
        if fleetd is not None:
            fleetd.service.agg.stop()
            fleetd.stop()
    return samples


samples = run_train("stream.put=latency:30ms", watch=True)
steps = [s["step"] for s in samples]
assert steps, "no /train.json samples during the run"
assert steps == sorted(steps), f"step went backwards: {steps}"
assert max(steps) > 0, f"step never advanced: {steps}"
assert len(set(s for s in steps if s > 0)) >= 2, (
    f"step did not advance chunk by chunk: {steps}")
epochs = [s["epoch"] for s in samples if s["epoch"] is not None]
assert epochs == sorted(epochs), f"epoch went backwards: {epochs}"
with_loss = [s for s in samples if s["step"] > 0]
assert with_loss and with_loss[-1]["lossWindow"], (
    "loss window empty while steps advanced")
assert any(s["stream"]["streamed"] for s in with_loss), "feed not streamed"

# run 2: same engine, feed slowed 10x by the injected failpoint
run_train("stream.put=latency:300ms")

diff = subprocess.run(
    [sys.executable, "-m", "pio_tpu", "runs",
     "--engine-json", engine_json, "--diff"],
    capture_output=True, text=True, env=dict(os.environ), timeout=120,
)
assert diff.returncode == 1, (
    f"pio runs --diff did not flag the slowed run:\n{diff.stdout}\n"
    f"{diff.stderr}")
assert "REGRESSION" in diff.stdout, diff.stdout
assert "train_seconds" in diff.stderr, diff.stderr

listing = subprocess.run(
    [sys.executable, "-m", "pio_tpu", "runs",
     "--engine-json", engine_json],
    capture_output=True, text=True, env=dict(os.environ), timeout=120,
)
assert listing.returncode == 0, listing.stderr
assert listing.stdout.count("COMPLETED") == 2, listing.stdout

n_steps = [s for s in steps if s > 0]
print(f"train stage: {len(samples)} live polls, step walked "
      f"{n_steps[0]} -> {n_steps[-1]}/120 monotonically, loss window "
      f"{len(with_loss[-1]['lossWindow'])} entries, trainer member "
      f"up->down in fleetd, `pio runs --diff` flagged the slowed run")
PY
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" python "$TRAIN_STAGE" "$WORKDIR" \
    || fail "training telemetry stage (progress/ledger/fleet assertions)"
echo "ok   training telemetry: live /train.json progress, fleetd trainer tracking, runs-ledger regression flagged"

# ------------------------------------------------ serving fabric router
# ISSUE 18: the router failpoints must be dump-visible, then the chaos
# drill — two REAL serving members over shared sqlite model storage
# with a routerd front tier fanning steady threaded load; SIGKILL
# member 1 mid-load. Every request must still be answered 200 (zero
# non-inflight 5xx: the router forces the dead member out of the ring
# on the first transport error and retries on member 2), /router.json
# must show the remap within two scrape intervals, and the
# pio_tpu_router_* families must account the traffic.
python -m pio_tpu.tools.cli lint --dump-failpoints pio_tpu | python -c '
import json, sys
inv = {f["point"] for f in json.load(sys.stdin)["failpoints"]}
need = {"router.pick", "router.forward", "router.verify"}
missing = need - inv
assert not missing, f"router failpoints missing from inventory: {missing}"
' || fail "router.pick/forward/verify failpoints missing from --dump-failpoints"
echo "ok   router failpoints in lint inventory"

ROUTER_STAGE="$WORKDIR/router_stage.py"
cat > "$ROUTER_STAGE" <<'PY'
"""Smoke stage: serving-fabric failover under SIGKILL.

Trains the tiny recommendation engine once into sqlite, boots TWO real
query-server subprocesses over that shared model store, fronts them
with an in-process routerd (fast 0.3 s scrape), then drives steady
threaded load through the router while member 1 is SIGKILLed
mid-flight. The bar, same as the partlog drill: zero non-inflight 5xx
— the router's one-shot retry plus passive forced-down must absorb the
kill invisibly — and the outside view (/router.json, /metrics) must
show member 1 leaving the ring and member 2 absorbing its keyspace.
"""
import datetime as dt
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

WORKDIR = sys.argv[1]

os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "SQ"
os.environ["PIO_STORAGE_SOURCES_SQ_TYPE"] = "sqlite"
os.environ["PIO_STORAGE_SOURCES_SQ_PATH"] = os.path.join(
    WORKDIR, "router.db")
os.environ["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "SQ"
os.environ["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "SQ"

import pio_tpu.templates  # noqa: F401  (registers the factory)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.storage import App, Storage
from pio_tpu.workflow import build_engine, run_train, variant_from_dict

VARIANT = {
    "id": "smoke-router-rec",
    "engineFactory": "templates.recommendation",
    "datasource": {"params": {"app_name": "smoke-router"}},
    "algorithms": [{"name": "als", "params": {
        "rank": 4, "num_iterations": 4, "lambda_": 0.1}}],
}

app_id = Storage.get_meta_data_apps().insert(App(0, "smoke-router"))
le = Storage.get_levents()
t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
for u in range(8):
    for i in range(6):
        in_block = (u < 4) == (i < 3)
        le.insert(
            Event("rate", "user", f"u{u}", "item", f"i{i}",
                  properties={"rating": 5.0 if in_block else 1.0},
                  event_time=t0),
            app_id,
        )
variant = variant_from_dict(VARIANT)
engine, ep = build_engine(variant)
run_train(engine, ep, variant, ctx=ComputeContext.local())

variant_file = os.path.join(WORKDIR, "router-variant.json")
with open(variant_file, "w") as f:
    json.dump(VARIANT, f)

MEMBER_SRC = r'''
import json, os, signal, sys
from pio_tpu.server import create_query_server
from pio_tpu.workflow import variant_from_dict

with open(sys.argv[1]) as f:
    variant = variant_from_dict(json.load(f))
server, _service = create_query_server(variant, host="127.0.0.1", port=0)
server.start()
with open(sys.argv[2] + ".tmp", "w") as f:
    f.write(str(server.port))
os.rename(sys.argv[2] + ".tmp", sys.argv[2])  # atomic publish
signal.sigwait({signal.SIGTERM, signal.SIGINT})
server.stop()
'''

port_files = [os.path.join(WORKDIR, f"router-m{i}-port") for i in (1, 2)]
members = [
    subprocess.Popen(
        [sys.executable, "-c", MEMBER_SRC, variant_file, pf],
        env=dict(os.environ))
    for pf in port_files
]
router_server = None
stop_load = threading.Event()


def _cleanup():
    stop_load.set()
    for p in members:
        if p.poll() is None:
            p.kill()
            p.wait()
    if router_server is not None:
        router_server.service.stop()
        router_server.stop()


def _wait_ready(base, deadline):
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(base + "/readyz", timeout=5) as r:
                if r.status == 200:
                    return
        except Exception:
            pass
        time.sleep(0.2)
    raise SystemExit(f"{base} never became ready")


try:
    deadline = time.time() + 120
    ports = []
    for pf, p in zip(port_files, members):
        while not os.path.exists(pf):
            if p.poll() is not None:
                raise SystemExit("serving member died during boot")
            if time.time() > deadline:
                raise SystemExit("serving member never published its port")
            time.sleep(0.2)
        with open(pf) as f:
            ports.append(int(f.read().strip()))
    for port in ports:
        _wait_ready(f"http://127.0.0.1:{port}", deadline)

    from pio_tpu.server.routerd import create_router_server

    targets = [
        (f"m{i + 1}", f"http://127.0.0.1:{port}")
        for i, port in enumerate(ports)
    ]
    router_server = create_router_server(
        targets, host="127.0.0.1", port=0, partitions=2, interval_s=0.3,
    ).start()
    router_server.service.start()
    rbase = f"http://127.0.0.1:{router_server.port}"
    _wait_ready(rbase, time.time() + 30)

    statuses = []
    lock = threading.Lock()

    def load(t):
        i = 0
        while not stop_load.is_set():
            i += 1
            body = json.dumps(
                {"user": f"u{(t * 31 + i) % 8}", "num": 3}
            ).encode("utf-8")
            req = urllib.request.Request(
                rbase + "/queries.json", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=20) as r:
                    ok = r.status == 200 and b"itemScores" in r.read()
                    code = r.status if ok else -1
            except urllib.error.HTTPError as e:
                code = e.code
            except Exception as e:
                code = f"{type(e).__name__}"
            with lock:
                statuses.append(code)

    threads = [
        threading.Thread(target=load, args=(t,), daemon=True)
        for t in range(4)
    ]
    for t in threads:
        t.start()

    deadline = time.time() + 60
    while True:
        with lock:
            n = len(statuses)
        if n >= 20:
            break
        if time.time() > deadline:
            raise SystemExit(f"only {n} routed requests in 60s")
        time.sleep(0.05)

    # mid-load SIGKILL: member 1 vanishes with its keyspace
    os.kill(members[0].pid, signal.SIGKILL)
    members[0].wait()
    killed_at = time.time()
    time.sleep(2.0)  # keep the load running across the failover
    stop_load.set()
    for t in threads:
        t.join(timeout=30)

    bad = [s for s in statuses if s != 200]
    assert not bad, (
        f"{len(bad)}/{len(statuses)} routed requests failed across the "
        f"SIGKILL: {bad[:5]} (want zero non-inflight 5xx)")

    # the ring must have remapped within ~2 scrape intervals; allow
    # generous wall-clock slack for the assertion poll itself
    snap = None
    deadline = killed_at + 15
    while time.time() < deadline:
        with urllib.request.urlopen(rbase + "/router.json", timeout=5) as r:
            snap = json.loads(r.read().decode("utf-8"))
        if snap["ring"]["routable"] == ["m2"]:
            break
        time.sleep(0.1)
    else:
        raise SystemExit(f"m1 never left the ring: {snap['members']}")
    by_member = {m["member"]: m for m in snap["members"]}
    assert by_member["m1"]["errors"] >= 1, by_member["m1"]
    assert by_member["m2"]["forwarded"] >= 1, by_member["m2"]
    assert snap["ring"]["partitions"] == 2, snap["ring"]

    with urllib.request.urlopen(rbase + "/metrics", timeout=5) as r:
        metrics = r.read().decode("utf-8")
    for fam in ("pio_tpu_router_forwarded_total{",
                "pio_tpu_router_forward_errors_total{",
                "pio_tpu_router_member_routable{",
                "pio_tpu_router_pick_seconds_bucket{",
                "pio_tpu_router_ring_size 1"):
        assert fam in metrics, f"/metrics missing {fam}"

    print(f"router stage: {len(statuses)} routed requests, 0 failed "
          f"across SIGKILL of m1; m2 absorbed "
          f"{by_member['m2']['forwarded']} forwards "
          f"({by_member['m2']['retried']} retries)")
finally:
    _cleanup()
PY
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" python "$ROUTER_STAGE" "$WORKDIR" \
    || fail "serving fabric router stage (failover/ring/metrics assertions)"
echo "ok   serving fabric: member SIGKILLed mid-load, zero failed requests, ring remapped to the survivor"

# ------------------------------------------------ progressive rollout
# ISSUE 19: the rollout failpoints must be dump-visible, then the
# progressive-delivery chaos drill — a clean candidate must walk
# shadow -> canary -> promoted on its own (ring generation flipping
# exactly once per member, only on a verified 200, shadow mirroring
# adding no measurable incumbent p50), and a candidate SIGKILLed
# mid-canary must be auto-rolled-back by the judge with the incumbent
# restored byte-identically and zero interactive 5xx throughout.
python -m pio_tpu.tools.cli lint --dump-failpoints pio_tpu | python -c '
import json, sys
inv = {f["point"] for f in json.load(sys.stdin)["failpoints"]}
need = {"rollout.mirror", "rollout.judge", "rollout.promote",
        "rollout.rollback"}
missing = need - inv
assert not missing, f"rollout failpoints missing from inventory: {missing}"
' || fail "rollout.* failpoints missing from --dump-failpoints"
echo "ok   rollout failpoints in lint inventory"

ROLLOUT_STAGE="$WORKDIR/rollout_stage.py"
cat > "$ROLLOUT_STAGE" <<'PY'
"""Smoke stage: progressive delivery — auto-promote and auto-rollback.

Trains one incumbent and two candidate instances of the tiny
recommendation engine into shared sqlite (fixed training seed, so a
clean candidate answers byte-identically to the incumbent), boots two
incumbent members plus two candidate members as real query-server
subprocesses, fronts the incumbents with an in-process routerd, and
drives steady threaded load the whole time.  Drill one: POST /rollout
with a clean candidate and let the controller walk shadow -> canary ->
promoted unattended; the member generation must flip exactly once per
member and only on a verified 200, and the shadow window's client p50
must sit inside the pre-rollout noise floor (mirroring is off the
relay path).  Drill two: start a second rollout, SIGKILL the candidate
mid-canary; the judge must see the scrape go dark, auto-rollback,
leave the incumbent members untouched (same instance, same generation,
same manifest sha set), and no client request may fail in either
drill.
"""
import datetime as dt
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

WORKDIR = sys.argv[1]

os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "SQ"
os.environ["PIO_STORAGE_SOURCES_SQ_TYPE"] = "sqlite"
os.environ["PIO_STORAGE_SOURCES_SQ_PATH"] = os.path.join(
    WORKDIR, "rollout.db")
os.environ["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "SQ"
os.environ["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "SQ"

import pio_tpu.templates  # noqa: F401  (registers the factory)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.storage import App, Storage
from pio_tpu.workflow import build_engine, run_train, variant_from_dict

VARIANT = {
    "id": "smoke-rollout-rec",
    "engineFactory": "templates.recommendation",
    "datasource": {"params": {"app_name": "smoke-rollout"}},
    "algorithms": [{"name": "als", "params": {
        "rank": 4, "num_iterations": 4, "lambda_": 0.1}}],
}

app_id = Storage.get_meta_data_apps().insert(App(0, "smoke-rollout"))
le = Storage.get_levents()
t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
for u in range(8):
    for i in range(6):
        in_block = (u < 4) == (i < 3)
        le.insert(
            Event("rate", "user", f"u{u}", "item", f"i{i}",
                  properties={"rating": 5.0 if in_block else 1.0},
                  event_time=t0),
            app_id,
        )
variant = variant_from_dict(VARIANT)
iids = []
for _ in range(3):
    engine, ep = build_engine(variant)
    iids.append(run_train(engine, ep, variant, ctx=ComputeContext.local()))
INC, CAND1, CAND2 = iids

variant_file = os.path.join(WORKDIR, "rollout-variant.json")
with open(variant_file, "w") as f:
    json.dump(VARIANT, f)

MEMBER_SRC = r'''
import json, os, signal, sys
from pio_tpu.server import create_query_server
from pio_tpu.workflow import variant_from_dict

with open(sys.argv[1]) as f:
    variant = variant_from_dict(json.load(f))
server, _service = create_query_server(
    variant, host="127.0.0.1", port=0, instance_id=sys.argv[3])
server.start()
with open(sys.argv[2] + ".tmp", "w") as f:
    f.write(str(server.port))
os.rename(sys.argv[2] + ".tmp", sys.argv[2])  # atomic publish
signal.sigwait({signal.SIGTERM, signal.SIGINT})
server.stop()
'''

# m1/m2 are the incumbent ring; c1/c2 boot on the incumbent instance
# and only ever serve a candidate through the verified deploy path
names = ("m1", "m2", "c1", "c2")
port_files = {n: os.path.join(WORKDIR, f"rollout-{n}-port") for n in names}
procs = {
    n: subprocess.Popen(
        [sys.executable, "-c", MEMBER_SRC, variant_file, port_files[n], INC],
        env=dict(os.environ))
    for n in names
}
router_server = None
stop_load = threading.Event()


def _cleanup():
    stop_load.set()
    for p in procs.values():
        if p.poll() is None:
            p.kill()
            p.wait()
    if router_server is not None:
        router_server.service.stop()
        router_server.stop()


def _wait_ready(base, deadline):
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(base + "/readyz", timeout=5) as r:
                if r.status == 200:
                    return
        except Exception:
            pass
        time.sleep(0.2)
    raise SystemExit(f"{base} never became ready")


try:
    deadline = time.time() + 180
    ports = {}
    for n in names:
        pf, p = port_files[n], procs[n]
        while not os.path.exists(pf):
            if p.poll() is not None:
                raise SystemExit(f"member {n} died during boot")
            if time.time() > deadline:
                raise SystemExit(f"member {n} never published its port")
            time.sleep(0.2)
        with open(pf) as f:
            ports[n] = int(f.read().strip())
    for n in names:
        _wait_ready(f"http://127.0.0.1:{ports[n]}", deadline)

    from pio_tpu.server.routerd import create_router_server

    targets = [(n, f"http://127.0.0.1:{ports[n]}") for n in ("m1", "m2")]
    router_server = create_router_server(
        targets, host="127.0.0.1", port=0, partitions=2, interval_s=0.3,
    ).start()
    router_server.service.start()
    rbase = f"http://127.0.0.1:{router_server.port}"
    _wait_ready(rbase, time.time() + 30)

    records = []  # (done_at, elapsed_s, status)
    lock = threading.Lock()

    def load(t):
        i = 0
        while not stop_load.is_set():
            i += 1
            body = json.dumps(
                {"user": f"u{(t * 31 + i) % 8}", "num": 3}
            ).encode("utf-8")
            req = urllib.request.Request(
                rbase + "/queries.json", data=body,
                headers={"Content-Type": "application/json"})
            t1 = time.time()
            try:
                with urllib.request.urlopen(req, timeout=20) as r:
                    ok = r.status == 200 and b"itemScores" in r.read()
                    code = r.status if ok else -1
            except urllib.error.HTTPError as e:
                code = e.code
            except Exception as e:
                code = f"{type(e).__name__}"
            with lock:
                records.append((time.time(), time.time() - t1, code))

    threads = [
        threading.Thread(target=load, args=(t,), daemon=True)
        for t in range(3)
    ]
    for t in threads:
        t.start()

    def rollout_json():
        with urllib.request.urlopen(rbase + "/rollout.json", timeout=5) as r:
            return json.loads(r.read().decode("utf-8"))

    def deploy_report(name):
        url = f"http://127.0.0.1:{ports[name]}/deploy.json"
        with urllib.request.urlopen(url, timeout=5) as r:
            return json.loads(r.read().decode("utf-8"))

    def post_rollout(payload):
        req = urllib.request.Request(
            rbase + "/rollout", data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 202, f"POST /rollout answered {r.status}"

    def wait_stage(want, timeout_s):
        deadline = time.time() + timeout_s
        snap = None
        while time.time() < deadline:
            snap = rollout_json()
            if snap["stage"] == want:
                return snap
            if snap["stage"] in ("failed", "rolled_back") \
                    and want not in ("failed", "rolled_back"):
                raise SystemExit(
                    f"rollout hit {snap['stage']} while waiting for "
                    f"{want}: {snap['trail']}")
            time.sleep(0.1)
        raise SystemExit(
            f"rollout never reached {want} "
            f"(stuck at {snap and snap['stage']}): {snap and snap['trail']}")

    def p50(rows):
        xs = sorted(rows)
        return xs[len(xs) // 2]

    # warm-up / baseline window: steady traffic with no rollout running
    deadline = time.time() + 60
    while True:
        with lock:
            n = len(records)
        if n >= 30:
            break
        if time.time() > deadline:
            raise SystemExit(f"only {n} routed requests in 60s")
        time.sleep(0.05)

    gen_before = {n: deploy_report(n) for n in ("m1", "m2", "c1")}
    for n, rep in gen_before.items():
        assert rep["engineInstanceId"] == INC, (n, rep)

    # ---- drill one: a clean candidate must auto-promote ----------------
    rollout_started = time.time()
    post_rollout({
        "engineInstanceId": CAND1,
        "targets": f"127.0.0.1:{ports['c1']}",
        "by": "smoke",
        "shadowRate": 1.0, "shadowMinSamples": 8, "shadowHoldSeconds": 1.5,
        "mismatchLimit": 0.2, "scoreTolerance": 0.25,
        "canaryFraction": 0.5, "canaryHoldSeconds": 0.5,
        "canaryMinRequests": 5, "judgeIntervalSeconds": 0.25,
    })
    snap = wait_stage("promoted", 150)
    signals = [e["signal"] for e in snap["trail"]]
    assert signals == ["start", "candidate_verified", "shadow_clean",
                       "canary_clean", "all_verified"], snap["trail"]
    assert snap["stageCode"] == 5, snap["stageCode"]
    assert snap["incumbentInstance"] == INC, snap["incumbentInstance"]
    assert snap["shadow"]["samples"] >= 8, snap["shadow"]
    assert snap["shadow"]["mismatches"] == 0, snap["shadow"]
    assert snap["canary"]["requests"] >= 5, snap["canary"]
    assert snap["judge"]["ticks"] >= 1, snap["judge"]

    # generation flipped exactly once per member, only on a verified 200
    for n in ("m1", "m2", "c1"):
        rep = deploy_report(n)
        assert rep["engineInstanceId"] == CAND1, (n, rep)
        assert rep["generation"] == gen_before[n]["generation"] + 1, (
            n, gen_before[n]["generation"], rep["generation"])

    # shadow mirroring must not move the incumbent's client p50: compare
    # the shadow-stage window against the pre-rollout baseline (generous
    # noise floor — the mirror thread is off the relay path entirely)
    by_stage = {e["to"]: e["at"] for e in snap["trail"]}
    with lock:
        done = list(records)
    base_rows = [el for at, el, c in done
                 if c == 200 and at < rollout_started]
    shadow_rows = [el for at, el, c in done
                   if c == 200 and by_stage["shadow"] <= at
                   < by_stage["canary"]]
    assert len(base_rows) >= 10 and len(shadow_rows) >= 5, (
        len(base_rows), len(shadow_rows))
    base_p50, shadow_p50 = p50(base_rows), p50(shadow_rows)
    assert shadow_p50 <= base_p50 * 3 + 0.08, (
        f"shadow mirroring moved the incumbent p50: baseline "
        f"{base_p50 * 1e3:.1f}ms -> shadow {shadow_p50 * 1e3:.1f}ms")

    # ---- drill two: SIGKILL the candidate mid-canary -------------------
    base2 = {n: deploy_report(n) for n in ("m1", "m2")}
    post_rollout({
        "engineInstanceId": CAND2,
        "targets": f"127.0.0.1:{ports['c2']}",
        "by": "smoke",
        "shadowRate": 1.0, "shadowMinSamples": 5, "shadowHoldSeconds": 0.2,
        "mismatchLimit": 0.2, "scoreTolerance": 0.25,
        "canaryFraction": 0.5, "canaryHoldSeconds": 120.0,
        "canaryMinRequests": 1000000, "judgeIntervalSeconds": 0.25,
        "downAfterFailures": 3,
    })
    wait_stage("canary", 90)
    time.sleep(0.6)  # let the canary keyspace take real traffic
    os.kill(procs["c2"].pid, signal.SIGKILL)
    procs["c2"].wait()
    killed_at = time.time()
    snap2 = wait_stage("rolled_back", 30)

    trail2 = snap2["trail"]
    back = [e for e in trail2 if e["to"] == "rolling_back"]
    assert back and back[0]["signal"] == "candidate_unreachable", trail2
    assert back[0]["at"] - killed_at < 15, (
        f"rollback took {back[0]['at'] - killed_at:.1f}s after the kill")
    assert trail2[-1]["signal"] == "incumbent_restored", trail2
    assert snap2["incumbentInstance"] == CAND1, snap2["incumbentInstance"]

    # the incumbent ring must be byte-identically where the rollout
    # found it: same instance, same swap generation, same sha set
    for n in ("m1", "m2"):
        rep = deploy_report(n)
        assert rep == base2[n], (n, base2[n], rep)

    stop_load.set()
    for t in threads:
        t.join(timeout=30)

    bad = [r for r in records if r[2] != 200]
    assert not bad, (
        f"{len(bad)}/{len(records)} client requests failed across the "
        f"two rollout drills: {bad[:5]} (want zero interactive non-200)")

    with urllib.request.urlopen(rbase + "/metrics", timeout=5) as r:
        metrics = r.read().decode("utf-8")
    for fam in ("pio_tpu_rollout_stage",
                "pio_tpu_rollout_transitions_total{",
                "pio_tpu_rollout_mirrored_total{",
                "pio_tpu_rollout_shadow_samples_total{",
                "pio_tpu_rollout_judge_total{"):
        assert fam in metrics, f"/metrics missing {fam}"

    print(f"rollout stage: clean candidate promoted "
          f"({snap['shadow']['samples']} shadow samples, "
          f"{snap['canary']['requests']} canaried, p50 "
          f"{base_p50 * 1e3:.1f}ms -> {shadow_p50 * 1e3:.1f}ms), "
          f"SIGKILLed candidate rolled back in "
          f"{back[0]['at'] - killed_at:.1f}s, "
          f"{len(records)} client requests, 0 failed")
finally:
    _cleanup()
PY
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" python "$ROLLOUT_STAGE" "$WORKDIR" \
    || fail "progressive rollout stage (promote/rollback/trail assertions)"
echo "ok   progressive delivery: clean candidate auto-promoted, SIGKILLed candidate auto-rolled-back, zero failed requests"

echo "smoke OK"
