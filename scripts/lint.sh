#!/usr/bin/env bash
# Run the project-native static analyzer over the tree (or over the
# paths given as arguments). Exit 0 = clean, 1 = findings, 2 = usage.
#
#   scripts/lint.sh                 # whole tree (pio_tpu + tests)
#   scripts/lint.sh pio_tpu/qos     # one subtree
#   scripts/lint.sh --json          # machine-readable findings
#
# Flags are passed through to `pio lint` (--json, --rules ID[,ID...],
# --list-rules, --dump-failpoints).
set -euo pipefail

cd "$(dirname "$0")/.."

args=("$@")
have_path=0
for a in "${args[@]:-}"; do
    case "$a" in
        --*) ;;
        "") ;;
        *) have_path=1 ;;
    esac
done
if [ "$have_path" = 0 ]; then
    args+=(pio_tpu tests)
fi

exec python -m pio_tpu.tools.cli lint "${args[@]}"
