#!/usr/bin/env bash
# Run the project-native static analyzer. Exit 0 = clean, 1 = findings,
# 2 = usage.
#
# Default is the fast path: findings only for files changed vs HEAD
# (`pio lint --changed`), with the whole tree still loaded so the
# interprocedural rules see full call-graph / frame-family context.
#
#   scripts/lint.sh                 # changed files vs HEAD (fast)
#   scripts/lint.sh --all           # whole tree (pio_tpu + tests)
#   scripts/lint.sh pio_tpu/qos     # one subtree (implies full lint)
#   scripts/lint.sh --json          # machine-readable findings
#
# Other flags pass through to `pio lint` (--rules ID[,ID...],
# --list-rules, --base REV, --dump-failpoints, --dump-callgraph,
# --dump-effects, --dump-contracts).
#
# The changed-files fast path includes docs/*.md: the knob table in
# docs/operations.md is a linted contract surface (knob-doc-drift), so
# a docs-only diff still re-lints contracts instead of short-circuiting.
set -euo pipefail

cd "$(dirname "$0")/.."

args=()
have_path=0
all=0
for a in "$@"; do
    case "$a" in
        --all) all=1 ;;
        --*) args+=("$a") ;;
        "") ;;
        *) have_path=1; args+=("$a") ;;
    esac
done
if [ "$have_path" = 0 ]; then
    args+=(pio_tpu tests)
    if [ "$all" = 0 ]; then
        args+=(--changed)
    fi
fi

exec python -m pio_tpu.tools.cli lint "${args[@]}"
