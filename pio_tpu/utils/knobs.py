"""Canonical ``PIO_TPU_*`` configuration-knob registry.

Every environment knob the server reads is declared here exactly once —
name, parse kind, default, and the one-line doc that feeds the generated
"Configuration knobs" table in docs/operations.md. Readers go through
:func:`knob_int` / :func:`knob_float` / :func:`knob_str` (or
:func:`knob_raw` where *unset vs set* is significant), which pull the
default and positivity constraint from the declaration — so two modules
can never again disagree about what an unset knob means.

``pio lint`` enforces the discipline both ways: ``knob-default-drift``
flags any literal ``os.environ[...]`` / ``env_int(...)`` read of a
``PIO_TPU_*`` name that bypasses this registry or disagrees with it,
and ``knob-doc-drift`` keeps the docs table and this file in lockstep.
``pio lint --dump-contracts`` emits the whole inventory as JSON.

Parse discipline matches :mod:`pio_tpu.utils.envutil`: numeric knobs
warn and fall back to the declared default on garbage instead of
crashing at import time. String knobs are returned verbatim (callers
own ``strip()``/``lower()`` normalisation — several are tri-state flags
like ``auto``/``host``/``0`` where exact semantics live at the call
site).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from pio_tpu.utils import envutil


@dataclass(frozen=True)
class Knob:
    """One declared configuration knob."""

    name: str
    kind: str                      # "int" | "float" | "str"
    default: object                # the value an unset env means
    doc: str
    positive: bool = False         # numeric knobs: reject <= 0 values

    def default_repr(self) -> str:
        """The default as it appears in the docs table cell."""
        return "(empty)" if self.default == "" else str(self.default)


_DECLARATIONS: Tuple[Knob, ...] = (
    # -- serving fronts / HTTP plumbing ---------------------------------
    Knob("PIO_TPU_HTTP_FRONT", "str", "threaded",
         "HTTP front implementation: `threaded` or `evloop`"),
    Knob("PIO_TPU_HTTP_BACKLOG", "int", 128, "listen(2) backlog for "
         "both fronts", positive=True),
    Knob("PIO_TPU_HTTP_IDLE_TIMEOUT_S", "float", 30.0,
         "idle keep-alive connection timeout, seconds", positive=True),
    Knob("PIO_TPU_HTTP_MAX_PIPELINE", "int", 16,
         "max pipelined requests parsed per evloop read burst",
         positive=True),
    Knob("PIO_TPU_MAX_BODY_MB", "float", 4096.0,
         "hard cap on any request body, MB", positive=True),
    Knob("PIO_TPU_MAX_JSON_BODY_MB", "float", 64.0,
         "cap on JSON request bodies, MB", positive=True),
    Knob("PIO_TPU_SSL_CERTFILE", "str", "",
         "TLS certificate path; unset serves plaintext"),
    Knob("PIO_TPU_SSL_KEYFILE", "str", "",
         "TLS private-key path (defaults to the certfile)"),
    # -- query serving ---------------------------------------------------
    Knob("PIO_TPU_SERVE_DEVICE", "str", "auto",
         "scoring placement: `auto`, `host`, or `device`"),
    Knob("PIO_TPU_SERVE_WIRE", "str", "auto",
         "serve-path wire encoding override"),
    Knob("PIO_TPU_DEVICE_RESIDENT", "str", "auto",
         "pin model params device-resident: `auto`/`1`/`0`"),
    Knob("PIO_TPU_MESH_SERVE", "str", "0",
         "serve through the worker mesh instead of in-process"),
    Knob("PIO_TPU_SERVE_MICROBATCH_US", "float", 0.0,
         "micro-batching window, microseconds; 0 disables"),
    Knob("PIO_TPU_SERVE_MICROBATCH_ADAPTIVE", "str", "1",
         "`0` pins the micro-batch window instead of adapting it"),
    Knob("PIO_TPU_BATCH_LANE", "str", "1",
         "`0` disables the shared-memory batch lane to mesh workers"),
    Knob("PIO_TPU_BATCH_BUCKETS", "str", "",
         "comma-separated batch-size bucket ladder override"),
    Knob("PIO_TPU_BUCKET_WARMUP", "str", "",
         "`1`/`0` force or forbid bucket warm-up compilation"),
    Knob("PIO_TPU_LANE_SLOTS", "int", 64,
         "batch-lane slots per worker", positive=True),
    Knob("PIO_TPU_LANE_SLOT_BYTES", "int", 16384,
         "payload bytes per batch-lane slot", positive=True),
    Knob("PIO_TPU_LANE_TIMEOUT_S", "float", 0.25,
         "batch-lane reply wait before falling back to HTTP",
         positive=True),
    Knob("PIO_TPU_MB_REPROBE_S", "float", 30.0,
         "seconds between micro-batch mode reprobes"),
    Knob("PIO_TPU_HEARTBEAT_MAX_AGE_S", "float", 30.0,
         "worker heartbeat age before the pool restarts it",
         positive=True),
    # -- SLO / QoS / degrade ---------------------------------------------
    Knob("PIO_TPU_SLO", "str", "",
         "SLO spec, e.g. `p99:200ms,availability:0.999`"),
    Knob("PIO_TPU_QOS", "str", "",
         "QoS admission spec (class weights and shed policy)"),
    Knob("PIO_TPU_SLOW_TRACE_MS", "float", 0.0,
         "emit a trace for requests slower than this; 0 disables"),
    # -- observability ---------------------------------------------------
    Knob("PIO_TPU_LOG_JSON", "str", "",
         "`1` renders console logs as JSON lines"),
    Knob("PIO_TPU_LOG_RING", "int", 512,
         "in-memory log ring capacity backing /logs.json"),
    Knob("PIO_TPU_PROFILE", "str", "",
         "directory for device profiler traces; unset disables"),
    Knob("PIO_TPU_PROFILE_EXECUTIONS", "int", 8,
         "executions captured per profile burst", positive=True),
    Knob("PIO_TPU_DEVICEWATCH", "str", "1",
         "`0` disables the device telemetry sampler"),
    Knob("PIO_TPU_DEVICEWATCH_INTERVAL_S", "float", 2.0,
         "device sampler period, seconds"),
    Knob("PIO_TPU_DEVICE_BUDGET_BYTES", "int", 0,
         "per-chip HBM budget; 0 means the library default"),
    Knob("PIO_TPU_FLEET_TARGETS", "str", "",
         "comma-separated `name=host:port` members to scrape"),
    Knob("PIO_TPU_FLEET_INTERVAL_S", "float", 5.0,
         "fleet scrape period, seconds", positive=True),
    Knob("PIO_TPU_TRAIN_STATUS_PORT", "int", 0,
         "port for the training status endpoint; 0 disables"),
    Knob("PIO_TPU_TRAIN_STATUS_URL", "str", "",
         "dashboard override for the training status URL"),
    # -- training / models -----------------------------------------------
    Knob("PIO_TPU_TRAIN_STREAM_MB", "float", 64.0,
         "streamed training-batch chunk size, MB; <= 0 disables"),
    Knob("PIO_TPU_ALS_STREAM_MB", "float", 8.0,
         "streamed ALS edge-shipment chunk size, MB; <= 0 disables"),
    Knob("PIO_TPU_LOGREG_STREAM_MB", "float", 8.0,
         "streamed logreg feature chunk size, MB; <= 0 disables"),
    Knob("PIO_TPU_ALS_ITEM_WIRE", "str", "auto",
         "ALS sharded item-factor wire encoding override"),
    Knob("PIO_TPU_ALS_MESH_WIRE", "str", "auto",
         "ALS mesh edge wire encoding override"),
    Knob("PIO_TPU_EMBED_PALLAS_OVER_MB", "float", 2048.0,
         "embedding table size above which the Pallas kernel is used"),
    Knob("PIO_TPU_EVAL_APP", "str", "",
         "default app name for template evaluation runs"),
    Knob("PIO_TPU_NO_NATIVE", "str", "",
         "any value disables the native (graft) fast paths"),
    # -- distributed -----------------------------------------------------
    Knob("PIO_TPU_COORDINATOR", "str", "",
         "multi-process coordinator `host:port`; unset = single host"),
    Knob("PIO_TPU_NUM_PROCESSES", "str", "",
         "world size for multi-process init; unset = single process"),
    Knob("PIO_TPU_PROCESS_ID", "str", "",
         "this process's rank for multi-process init"),
    # -- storage / durability --------------------------------------------
    Knob("PIO_TPU_HOME", "str", "",
         "state directory root; unset means `~/.pio_tpu`"),
    Knob("PIO_TPU_DURABILITY", "str", "batch",
         "event-log durability mode: `commit`, `batch`, or `os`"),
    Knob("PIO_TPU_SHARDED_PERSIST", "str", "0",
         "`1` persists model shards from every process"),
    Knob("PIO_TPU_BLOB_ACCESS_KEY", "str", "",
         "access key for the blob storage backend"),
    Knob("PIO_TPU_PARTLOG_PARTITIONS", "int", 4,
         "partitioned-log partition count", positive=True),
    Knob("PIO_TPU_PARTLOG_SEGMENT_BYTES", "int", 4 * 1024 * 1024,
         "partitioned-log segment roll size, bytes", positive=True),
    Knob("PIO_TPU_PARTLOG_REPLICAS", "str", "",
         "comma-separated follower `host:port` replica addresses"),
    Knob("PIO_TPU_REPL_MIN_ACKS", "int", 1,
         "follower acks required per append (1 when replicas are "
         "configured, else 0)", positive=False),
    Knob("PIO_TPU_REPL_ACK_TIMEOUT_S", "float", 2.0,
         "replication ack wait, seconds", positive=True),
    Knob("PIO_TPU_REPL_CONNECT_DEADLINE_S", "float", 10.0,
         "replication connect retry deadline, seconds", positive=True),
    # -- router / rollout ------------------------------------------------
    Knob("PIO_TPU_ROUTER_BURN_LIMIT", "float", 2.0,
         "SLO burn rate above which the router sheds a member",
         positive=True),
    Knob("PIO_TPU_ROUTER_LAG_SOFT_BYTES", "float", 64.0 * 1024 * 1024,
         "replication lag where router scoring starts to penalise",
         positive=True),
    Knob("PIO_TPU_ROUTER_HEDGE_MS", "float", 0.0,
         "hedged second request delay, milliseconds; 0 disables"),
    # -- faults / plugins / debug ----------------------------------------
    Knob("PIO_TPU_FAULTS", "str", "",
         "failpoint spec, e.g. `router.pick=error:0.1`"),
    Knob("PIO_TPU_PLUGINS", "str", "",
         "comma-separated plugin modules imported at server start"),
    Knob("PIO_TPU_DEBUG_SYNC", "str", "",
         "`1`/`raise`/`log` arms the instrumented lock runtime"),
)

#: name -> declaration; THE canonical knob inventory
KNOBS: Dict[str, Knob] = {k.name: k for k in _DECLARATIONS}


def get(name: str) -> Knob:
    """The declaration for ``name`` (KeyError when unregistered)."""
    return KNOBS[name]


def all_knobs() -> Tuple[Knob, ...]:
    """Every declaration, sorted by name."""
    return tuple(sorted(_DECLARATIONS, key=lambda k: k.name))


def _lookup(name: str, kind: str, fallback) -> Optional[Knob]:
    k = KNOBS.get(name)
    if k is None:
        if fallback is None:
            raise KeyError(f"unregistered knob {name!r} (declare it in "
                           f"pio_tpu/utils/knobs.py)")
        return None
    if k.kind != kind:
        raise TypeError(f"knob {name} is declared {k.kind}, read as {kind}")
    return k


def knob_int(name: str, fallback: Optional[int] = None) -> int:
    """Registry-backed :func:`envutil.env_int`. ``fallback`` applies
    only to *unregistered* names (scratch knobs in tests)."""
    k = _lookup(name, "int", fallback)
    if k is None:
        return envutil.env_int(name, int(fallback))
    return envutil.env_int(name, int(k.default), positive=k.positive)


def knob_float(name: str, fallback: Optional[float] = None) -> float:
    """Registry-backed :func:`envutil.env_float`."""
    k = _lookup(name, "float", fallback)
    if k is None:
        return envutil.env_float(name, float(fallback))
    return envutil.env_float(name, float(k.default), positive=k.positive)


def knob_str(name: str, fallback: Optional[str] = None) -> str:
    """String knob read: the raw env value, or the declared default
    when unset. No normalisation — tri-state flags keep their call-site
    semantics."""
    k = _lookup(name, "str", fallback)
    default = fallback if k is None else k.default
    raw = os.environ.get(name)
    return str(default) if raw is None else raw


def knob_raw(name: str) -> Optional[str]:
    """The raw env value or ``None`` — for knobs where *unset* is
    semantically different from any set value (e.g. distributed init
    and TLS config). The name must still be registered."""
    get(name)
    return os.environ.get(name)


#: markers bounding the generated table in docs/operations.md
TABLE_BEGIN = "<!-- knob-table:begin -->"
TABLE_END = "<!-- knob-table:end -->"


def markdown_table() -> str:
    """The docs/operations.md "Configuration knobs" table body —
    regenerate with ``python -m pio_tpu.utils.knobs``. The
    ``knob-doc-drift`` lint rule asserts the doc matches."""
    lines = ["| Knob | Type | Default | Description |",
             "| --- | --- | --- | --- |"]
    for k in all_knobs():
        lines.append(
            f"| `{k.name}` | {k.kind} | `{k.default_repr()}` | {k.doc} |"
        )
    return "\n".join(lines)


if __name__ == "__main__":  # pragma: no cover - doc regeneration helper
    print(markdown_table())
