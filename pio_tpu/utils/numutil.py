"""Small numeric helpers shared across models/ops."""

from __future__ import annotations


def round_up(x: int, mult: int) -> int:
    """Smallest multiple of ``mult`` ≥ ``x``."""
    return -(-x // mult) * mult


def n_stream_chunks(n_bytes: int, env_var: str, default: str = "8",
                    cap: int = 8) -> int:
    """Chunk count for a streamed host→device shipment — the sizing
    rule lives with the executor (``parallel/stream.py``); this wrapper
    keeps the historical import path for the model trainers. Lazy
    import: numutil must stay importable without the parallel package
    (and its obs registration) on the path."""
    from pio_tpu.parallel.stream import n_stream_chunks as impl

    return impl(n_bytes, env_var, default=default, cap=cap)
