"""Small numeric helpers shared across models/ops."""

from __future__ import annotations


def round_up(x: int, mult: int) -> int:
    """Smallest multiple of ``mult`` ≥ ``x``."""
    return -(-x // mult) * mult
