"""Small numeric helpers shared across models/ops."""

from __future__ import annotations

from pio_tpu.utils.envutil import env_float


def round_up(x: int, mult: int) -> int:
    """Smallest multiple of ``mult`` ≥ ``x``."""
    return -(-x // mult) * mult


def n_stream_chunks(n_bytes: int, env_var: str, default: str = "8",
                    cap: int = 8) -> int:
    """Chunk count for a streamed host→device shipment: ``ceil(bytes /
    chunk_mb)`` capped at ``cap``; 1 (streaming off) when the env knob
    is ≤ 0. Shared by the ALS single-device/mesh wires and the logreg
    feature wire so the threshold semantics can't drift."""
    mb = env_float(env_var, float(default))
    if mb <= 0:
        return 1
    return int(min(cap, -(-n_bytes // max(1, int(mb * 2 ** 20)))))
