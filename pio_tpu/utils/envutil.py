"""Hardened ``PIO_TPU_*`` environment parsing.

Every numeric knob in the tree goes through these helpers (enforced by
the ``env-hardening`` lint rule): a typo'd value must degrade to the
documented default with a warning, not kill a server at import time.
NaN is always rejected; ``positive=True`` additionally rejects values
``<= 0`` (body caps, ages, rates — where zero/negative would reject or
break everything).
"""

from __future__ import annotations

import os
import warnings


def _warn(name: str, raw: str, default, why: str) -> None:
    warnings.warn(
        f"{name}={raw!r} {why}; using default {default:g}",
        RuntimeWarning,
        stacklevel=3,
    )


def env_float(name: str, default: float, *, positive: bool = False) -> float:
    """Float env knob with warn-and-default semantics."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        v = float(raw)
    except (TypeError, ValueError):
        _warn(name, raw, default, "is not a number")
        return default
    if v != v:  # NaN compares unequal to itself
        _warn(name, raw, default, "is NaN")
        return default
    if positive and v <= 0:
        _warn(name, raw, default, "must be a positive number")
        return default
    return v


def env_int(name: str, default: int, *, positive: bool = False) -> int:
    """Integer env knob with warn-and-default semantics."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        v = int(raw)
    except (TypeError, ValueError):
        _warn(name, raw, default, "is not an integer")
        return default
    if positive and v <= 0:
        _warn(name, raw, default, "must be a positive integer")
        return default
    return v
