"""Shared utilities."""

from pio_tpu.utils.timeutil import EPOCH, from_micros, to_micros

__all__ = ["EPOCH", "from_micros", "to_micros"]
