"""Epoch-microsecond conversions — single source of truth.

Integer arithmetic only: float ``total_seconds()*1e6`` truncates 1us low for
large (post-2038) and pre-1970 timestamps, which after Event's millisecond
truncation corrupts stored times by a full millisecond on round-trip.
"""

from __future__ import annotations

import datetime as _dt

EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
_US = _dt.timedelta(microseconds=1)


def to_micros(t: _dt.datetime) -> int:
    return (t - EPOCH) // _US


def from_micros(us: int) -> _dt.datetime:
    return EPOCH + _dt.timedelta(microseconds=int(us))
