"""Typed component params — the engine.json binding layer.

Rebuild of the reference's ``controller/Params.scala`` + the
``workflow/JsonExtractor.scala`` reflection machinery (UNVERIFIED paths; see
SURVEY.md). Where the reference reflects Scala case-class constructors from
Json4s ASTs, we bind JSON objects to Python dataclasses with explicit
validation: unknown keys are rejected (same behavior the reference gets from
strict extraction), missing keys fall back to dataclass defaults, and a
missing required key is an error naming the field.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import re
import typing
from typing import Any, Mapping, Optional, Type, TypeVar

#: camelCase → snake_case boundary (see params_from_dict wire parity)
_SNAKE_RE = re.compile(r"(?<=[a-z0-9])([A-Z])")


@functools.lru_cache(maxsize=None)
def _hints_of(cls: type) -> Mapping[str, Any]:
    """Per-class cache of ``get_type_hints`` — it re-evaluates forward
    references (compile() per annotation) on every call, and query
    binding runs once per serving request."""
    return typing.get_type_hints(cls)

P = TypeVar("P", bound="Params")


class ParamsError(ValueError):
    """Raised when engine.json params don't bind to a Params dataclass."""


@dataclasses.dataclass(frozen=True)
class Params:
    """Base class for component parameters (reference ``trait Params``).

    Subclass as a frozen dataclass:

        @dataclasses.dataclass(frozen=True)
        class ALSParams(Params):
            rank: int = 10
            num_iterations: int = 10
            reg: float = 0.01
    """


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    """No parameters (reference ``EmptyParams``)."""


def _check_field_type(name: str, value: Any, ftype: Any) -> Any:
    """Best-effort runtime check/coercion for common JSON-able field types."""
    origin = typing.get_origin(ftype)
    if ftype is Any or origin is not None and origin is not list:
        return value  # Optional/Union/Dict etc. — accept as-is
    if ftype is float and isinstance(value, int) and not isinstance(value, bool):
        return float(value)
    if isinstance(ftype, type):
        if ftype is int and isinstance(value, bool):
            raise ParamsError(f"param {name!r}: got bool, expected int")
        if origin is None and not isinstance(value, ftype):
            raise ParamsError(
                f"param {name!r}: got {type(value).__name__}, "
                f"expected {ftype.__name__}"
            )
    return value


def params_from_dict(cls: Type[P], d: Optional[Mapping[str, Any]]) -> P:
    """Bind a JSON object to a Params dataclass (strict about unknown keys)."""
    if d is not None and not isinstance(d, Mapping):
        raise ParamsError(
            f"{cls.__name__}: params must be a JSON object, "
            f"got {type(d).__name__}"
        )
    d = dict(d or {})
    if not dataclasses.is_dataclass(cls):
        raise ParamsError(f"{cls.__name__} must be a dataclass")
    hints = _hints_of(cls)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    # reference wire parity: queries and engine.json use camelCase keys
    # ("whiteList", "numIterations"); fields here are snake_case. Accept
    # both spellings; a key that matches a field exactly wins.
    for key in list(d):
        if key in fields:
            continue
        snake = _SNAKE_RE.sub(r"_\1", key).lower()
        if snake not in fields and snake + "_" in fields:
            # Python-keyword collisions: the reference's "lambda" binds to
            # a lambda_ field (same for any keyword-named wire param)
            snake = snake + "_"
        if snake in fields:
            if snake in d:
                raise ParamsError(
                    f"{cls.__name__}: both {key!r} and {snake!r} given"
                )
            d[snake] = d.pop(key)
    unknown = set(d) - set(fields)
    if unknown:
        raise ParamsError(
            f"{cls.__name__}: unknown params {sorted(unknown)}; "
            f"known: {sorted(fields)}"
        )
    kwargs = {}
    for name, f in fields.items():
        if name in d:
            kwargs[name] = _check_field_type(name, d[name], hints.get(name))
        elif (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING  # type: ignore[misc]
        ):
            raise ParamsError(f"{cls.__name__}: missing required param {name!r}")
    try:
        return cls(**kwargs)  # type: ignore[return-value]
    except (TypeError, ValueError) as e:
        raise ParamsError(f"{cls.__name__}: {e}") from None


def params_to_dict(p: Params) -> dict:
    return dataclasses.asdict(p)


def params_to_json(p: Params) -> str:
    return json.dumps(params_to_dict(p), sort_keys=True)
