"""Engine — binds DASE components + params; orchestrates train/eval.

Rebuild of the reference's ``controller/Engine.scala`` +
``controller/EngineFactory.scala`` (UNVERIFIED paths; see SURVEY.md). Key
differences from the reference, by design:

- No JVM reflection: engine factories register by name in a process registry
  (``@register_engine``) or resolve as ``"module.path:attribute"`` — the
  ``engineFactory`` field of ``engine.json`` accepts either.
- ``train`` returns plain Python model objects; model persistence happens in
  the workflow layer (pickle blob ≙ reference Kryo blob, or
  ``PersistentModel`` opt-out).
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

from pio_tpu.controller.components import (
    Algorithm,
    DataSource,
    Preparator,
    SanityCheck,
    Serving,
)
from pio_tpu.controller.params import (
    EmptyParams,
    Params,
    ParamsError,
    params_from_dict,
    params_to_dict,
)
from pio_tpu.parallel.context import ComputeContext

log = logging.getLogger("pio_tpu.engine")


def serve_fold(serving, algorithms, models, qa):
    """One eval fold's query loop: supplement → per-algo batch predict →
    serve.

    Shared by :meth:`Engine.eval` and the FastEval path so serving
    semantics can't diverge. Dispatches through ``batch_predict`` (whose
    default is a predict loop) so algorithms with a vectorized override —
    one device matmul per fold, constraint snapshots once per call — get
    it during evaluation too, not just `pio batchpredict`.
    Returns [(query, prediction, actual)].
    """
    supplemented = [(serving.supplement(q), actual) for q, actual in qa]
    indexed = [(i, q) for i, (q, _a) in enumerate(supplemented)]
    per_algo = [
        dict(algo.batch_predict(model, indexed))
        for algo, model in zip(algorithms, models)
    ]
    return [
        (q, serving.serve(q, [preds[i] for preds in per_algo]), actual)
        for i, (q, actual) in enumerate(supplemented)
    ]


@dataclasses.dataclass(frozen=True)
class EngineParams:
    """Per-run parameter bundle (reference ``EngineParams``)."""

    data_source_params: Params = EmptyParams()
    preparator_params: Params = EmptyParams()
    algorithm_params_list: Tuple[Tuple[str, Params], ...] = ()
    serving_params: Params = EmptyParams()


class Engine:
    """Binds DASE component classes (reference ``Engine[TD,EI,PD,Q,P,A]``).

    ``algorithm_class_map`` maps algorithm names (as referenced from
    engine.json's ``algorithms[].name``) to Algorithm classes.
    """

    def __init__(
        self,
        data_source_class: Type[DataSource],
        preparator_class: Type[Preparator],
        algorithm_class_map: Dict[str, Type[Algorithm]],
        serving_class: Type[Serving],
    ):
        self.data_source_class = data_source_class
        self.preparator_class = preparator_class
        self.algorithm_class_map = dict(algorithm_class_map)
        self.serving_class = serving_class

    # -- params binding (reference jValueToEngineParams) ---------------------
    def params_from_variant(self, variant: Dict[str, Any]) -> EngineParams:
        """Bind an engine.json variant dict to typed EngineParams."""

        def section(name: str) -> Optional[dict]:
            v = variant.get(name)
            if v is None:
                return None
            if not isinstance(v, dict):
                raise ParamsError(f"engine.json {name!r} must be an object")
            return v.get("params", {})

        ds = params_from_dict(
            self.data_source_class.params_class, section("datasource")
        )
        prep = params_from_dict(
            self.preparator_class.params_class, section("preparator")
        )
        serv = params_from_dict(self.serving_class.params_class, section("serving"))

        algos: List[Tuple[str, Params]] = []
        for entry in variant.get("algorithms", []):
            name = entry.get("name")
            if name not in self.algorithm_class_map:
                raise ParamsError(
                    f"unknown algorithm {name!r}; engine declares "
                    f"{sorted(self.algorithm_class_map)}"
                )
            algos.append(
                (
                    name,
                    params_from_dict(
                        self.algorithm_class_map[name].params_class,
                        entry.get("params", {}),
                    ),
                )
            )
        if not algos:
            # default: every declared algorithm with default params
            algos = [
                (name, cls.params_class())
                for name, cls in self.algorithm_class_map.items()
            ]
        return EngineParams(
            data_source_params=ds,
            preparator_params=prep,
            algorithm_params_list=tuple(algos),
            serving_params=serv,
        )

    # -- instantiation (reference Doer.apply) --------------------------------
    def _algorithms(self, engine_params: EngineParams) -> List[Algorithm]:
        return [
            self.algorithm_class_map[name](params)
            for name, params in engine_params.algorithm_params_list
        ]

    # -- train (reference object Engine.train) -------------------------------
    def train(
        self,
        ctx: ComputeContext,
        engine_params: EngineParams,
        skip_sanity_check: bool = False,
        stop_after_read: bool = False,
        stop_after_prepare: bool = False,
        timings: Optional[dict] = None,
    ) -> List[Any]:
        """Run DataSource -> Preparator -> each Algorithm; return models.

        ``timings``, when given, is filled with per-phase wall seconds
        (``read``, ``prepare``, ``train:<i>_<algo>``) — the rebuild's
        answer to the reference's Spark-UI stage view (SURVEY.md §5
        tracing).

        When a trace is open (run_train's TRAIN_TRACER), each phase runs
        inside a LIVE span named with the dot convention (``read``,
        ``prepare``, ``train.<i>_<algo>``) — its log records carry
        ``(trace_id, span)`` so ``/logs.json?trace_id=`` reassembles the
        run, and the trainwatch recorder's ``phase`` field follows along
        for ``/train.json``. The ``timings`` keys keep their historical
        colon form (instance env ``phase_train:<i>_<algo>`` is an API).
        """
        import contextlib as _ctxlib

        from pio_tpu.obs import active_trace, monotonic_s, trainwatch

        def _phase(name, fn):
            span_name = name.replace(":", ".")
            trainwatch.set_phase(span_name)
            tr = active_trace()
            span_cm = (
                tr.span(span_name) if tr is not None
                else _ctxlib.nullcontext()
            )
            t0 = monotonic_s()
            with span_cm:
                out = fn()
            dur = round(monotonic_s() - t0, 3)
            if timings is not None:
                timings[name] = dur
            trainwatch_rec = trainwatch.active_recorder()
            if trainwatch_rec is not None:
                trainwatch_rec.set_phase_seconds(span_name, dur)
            return out

        data_source = self.data_source_class(engine_params.data_source_params)
        td = _phase("read", lambda: data_source.read_training(ctx))
        if not skip_sanity_check and isinstance(td, SanityCheck):
            td.sanity_check()
        if stop_after_read:
            log.info("stopping after read_training (stop_after_read)")
            return []
        preparator = self.preparator_class(engine_params.preparator_params)
        pd = _phase("prepare", lambda: preparator.prepare(ctx, td))
        if not skip_sanity_check and isinstance(pd, SanityCheck):
            pd.sanity_check()
        if stop_after_prepare:
            log.info("stopping after prepare (stop_after_prepare)")
            return []
        models = []
        algo_names = [n for n, _ in engine_params.algorithm_params_list]
        for i, algo in enumerate(self._algorithms(engine_params)):
            algo_ctx = ctx
            manager = None
            if (
                getattr(ctx, "checkpoint_base", None)
                and getattr(ctx, "checkpoint_every", 0) > 0
            ):
                import dataclasses as _dc
                import os as _os

                from pio_tpu.workflow.checkpoint import CheckpointManager

                # per-algorithm subdir: two algorithms in one engine must
                # never restore each other's snapshots
                manager = CheckpointManager(
                    _os.path.join(
                        ctx.checkpoint_base, f"algo{i}_{algo_names[i]}"
                    )
                )
                algo_ctx = _dc.replace(ctx, checkpoint=manager)
            try:
                # index-prefixed like the checkpoint subdirs: two algos
                # with the same name must not overwrite each other
                models.append(
                    _phase(
                        f"train:{i}_{algo_names[i]}",
                        lambda: algo.train(algo_ctx, pd),
                    )
                )
            finally:
                if manager is not None:
                    manager.close()
        return models

    # -- eval (reference object Engine.eval) ---------------------------------
    def eval(
        self, ctx: ComputeContext, engine_params: EngineParams
    ) -> List[Tuple[Any, Any, List[Tuple[Any, Any, Any]]]]:
        """Returns per-fold: (evalInfo, query-prediction-actual triples).

        Shape parity with the reference's
        ``Seq[(EI, RDD[(Q, P, A)])]`` (fold-level lazy evaluation replaced
        by eager lists).
        """
        data_source = self.data_source_class(engine_params.data_source_params)
        preparator = self.preparator_class(engine_params.preparator_params)
        serving = self.serving_class(engine_params.serving_params)
        algorithms = self._algorithms(engine_params)

        results = []
        for td, eval_info, qa in data_source.read_eval(ctx):
            pd = preparator.prepare(ctx, td)
            models = [algo.train(ctx, pd) for algo in algorithms]
            results.append((eval_info, serve_fold(serving, algorithms, models, qa)))
        return results

    # -- deploy prep (reference Engine.prepareDeploy) ------------------------
    def make_serving(self, engine_params: EngineParams) -> Serving:
        return self.serving_class(engine_params.serving_params)

    def algorithms_with_models(
        self, engine_params: EngineParams, models: Sequence[Any]
    ) -> List[Tuple[Algorithm, Any]]:
        algos = self._algorithms(engine_params)
        if len(algos) != len(models):
            raise ValueError(
                f"{len(algos)} algorithms but {len(models)} models"
            )
        # serving prep (reference Engine.prepareDeploy): one-time device
        # upload / jitted-scorer build per (algorithm, model) pair
        return [
            (a, a.prepare_for_serving(m)) for a, m in zip(algos, models)
        ]


class SimpleEngine(Engine):
    """Single-algorithm engine with identity prep + first serving
    (reference ``SimpleEngine``)."""

    def __init__(self, data_source_class, algorithm_class):
        from pio_tpu.controller.components import FirstServing, IdentityPreparator

        super().__init__(
            data_source_class,
            IdentityPreparator,
            {"default": algorithm_class},
            FirstServing,
        )


# -------------------------------------------------------------- registry
EngineFactory = Callable[[], Engine]

_ENGINE_REGISTRY: Dict[str, EngineFactory] = {}


def register_engine(name: str):
    """Decorator registering an engine factory under a stable name
    (the TPU-native replacement for the reference's reflective
    ``engineFactory`` class lookup)."""

    def deco(factory: EngineFactory) -> EngineFactory:
        _ENGINE_REGISTRY[name] = factory
        return factory

    return deco


def engine_factory_names() -> List[str]:
    return sorted(_ENGINE_REGISTRY)


def get_engine_factory(name: str) -> EngineFactory:
    """Resolve a factory: registry name first, then ``module:attr`` import."""
    if name in _ENGINE_REGISTRY:
        return _ENGINE_REGISTRY[name]
    if ":" in name:
        mod_name, _, attr = name.partition(":")
        try:
            mod = importlib.import_module(mod_name)
        except ImportError as e:
            raise ParamsError(f"cannot import engine factory {name!r}: {e}") from None
        fn = getattr(mod, attr, None)
        if fn is None:
            raise ParamsError(f"{mod_name!r} has no attribute {attr!r}")
        return fn
    # Final attempt: importing a module may register the name as a side
    # effect. Try the name itself, its parent package, and both prefixed
    # with "pio_tpu." (bundled templates register e.g.
    # "templates.recommendation" but live at pio_tpu.templates.*).
    if "." in name:
        candidates = [name, name.rsplit(".", 1)[0]]
        candidates += [f"pio_tpu.{c}" for c in candidates]
        for mod_name in candidates:
            try:
                importlib.import_module(mod_name)
            except ImportError:
                continue
            if name in _ENGINE_REGISTRY:
                return _ENGINE_REGISTRY[name]
    # Bare names ("recommendation"): the bundled template gallery
    # registers them on import — load it before giving up, so CLI
    # entrypoints work without the caller pre-importing pio_tpu.templates.
    try:
        importlib.import_module("pio_tpu.templates")
    except ImportError:
        pass
    if name in _ENGINE_REGISTRY:
        return _ENGINE_REGISTRY[name]
    raise ParamsError(
        f"engine factory {name!r} not registered; known: {engine_factory_names()}"
    )
