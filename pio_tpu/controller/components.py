"""DASE component ABCs: DataSource, Preparator, Algorithm, Serving.

Rebuild of the reference's ``core/src/main/scala/o/a/p/controller/
{PDataSource,LDataSource,PPreparator,LPreparator,PAlgorithm,P2LAlgorithm,
LAlgorithm,LServing}.scala`` (UNVERIFIED paths; see SURVEY.md).

The reference splits every component into P (distributed, RDD-based) and L
(local) variants because Spark makes distribution a type-level concern. Under
JAX the split collapses: a component receives a
:class:`~pio_tpu.parallel.context.ComputeContext` and the SAME code runs on
one device or a pod mesh — sharding is a data annotation, not a class
hierarchy. We keep ``PAlgorithm``/``P2LAlgorithm``/``LAlgorithm`` as aliases
so reference users find familiar names; all mean :class:`Algorithm`.

Every component is constructed with its Params instance (reference
``Doer.apply``): ``cls(params)``.
"""

from __future__ import annotations

import abc
from typing import Any, Generic, List, Optional, Sequence, Tuple, TypeVar

from pio_tpu.controller.params import EmptyParams, Params
from pio_tpu.parallel.context import ComputeContext

TD = TypeVar("TD")  # training data
EI = TypeVar("EI")  # evaluation info
PD = TypeVar("PD")  # prepared data
Q = TypeVar("Q")  # query
P = TypeVar("P")  # prediction
A = TypeVar("A")  # actual (ground truth)
M = TypeVar("M")  # model


class Component(abc.ABC):
    """Base: holds the params it was constructed with (reference AbstractDoer).

    Inherits ABC so ``@abc.abstractmethod`` on subclasses is actually
    enforced at instantiation time.
    """

    params_class: type = EmptyParams

    def __init__(self, params: Optional[Params] = None):
        self.params = params if params is not None else self.params_class()


class SanityCheck(abc.ABC):
    """Opt-in hook called on TD/PD after read/prepare
    (reference ``controller/SanityCheck.scala``)."""

    @abc.abstractmethod
    def sanity_check(self) -> None:
        """Raise if the data is unusable (e.g. empty training set)."""


class DataSource(Component, Generic[TD, EI, Q, A]):
    """Reads training/eval data from the event store
    (reference ``PDataSource.readTraining(sc)`` / ``readEval``)."""

    @abc.abstractmethod
    def read_training(self, ctx: ComputeContext) -> TD: ...

    def read_eval(
        self, ctx: ComputeContext
    ) -> List[Tuple[TD, EI, List[Tuple[Q, A]]]]:
        """Eval folds: (trainingData, evalInfo, [(query, actual)]).

        Default: no eval data (reference's default throws on eval use;
        returning [] makes ``eval`` a clean no-op instead).
        """
        return []


class Preparator(Component, Generic[TD, PD]):
    """TD -> PD feature preparation (reference ``PPreparator.prepare``)."""

    @abc.abstractmethod
    def prepare(self, ctx: ComputeContext, training_data: TD) -> PD: ...


class IdentityPreparator(Preparator[TD, TD]):
    """PD == TD passthrough (reference ``IdentityPreparator``)."""

    def prepare(self, ctx: ComputeContext, training_data: TD) -> TD:
        return training_data


class Algorithm(Component, Generic[PD, M, Q, P]):
    """Train a model; answer queries (reference ``PAlgorithm``/``LAlgorithm``).

    ``train`` typically builds sharded arrays from PD and runs a pjit
    program over ``ctx.mesh``; ``predict`` runs a (cached-jit) device
    computation per query; ``batch_predict`` vectorizes offline scoring
    (reference ``batchPredict`` used by ``pio batchpredict``).
    """

    @abc.abstractmethod
    def train(self, ctx: ComputeContext, prepared_data: PD) -> M: ...

    @abc.abstractmethod
    def predict(self, model: M, query: Q) -> P: ...

    def batch_predict(self, model: M, queries: Sequence[Tuple[int, Q]]) -> List[Tuple[int, P]]:
        """Default: loop predict. Override with a vectorized device program."""
        return [(i, self.predict(model, q)) for i, q in queries]

    def prepare_for_serving(self, model: M) -> M:
        """One-time serving prep at deploy/load (the model-side half of the
        reference's ``Engine.prepareDeploy``): upload factor tables to the
        accelerator, build jitted scorers. Runs for both the query server
        and batch predict (``Engine.algorithms_with_models``). Default:
        return the model unchanged."""
        return model

    def warmup_query(self, model: M) -> Optional[Q]:
        """A representative query the serving layer can replicate to warm
        its shape-bucket executables at deploy (see
        ``pio_tpu/server/bucketcache.py``). Return None (the default) to
        opt out — buckets then compile lazily on first live dispatch,
        counted as retraces."""
        return None

    def resident_scorer(self, model: M):
        """Build a device-resident scorer for ``model`` (a
        ``pio_tpu.server.residency.ResidentLinearScorer`` or compatible:
        ``bind``/``prealloc``/``retire``/``to_dict``), or None (the
        default) when this template has no resident serving path. The
        query server calls this at deploy/hot-swap — behind the swap
        lock, generation-bumped with the shape-bucket cache — and
        attaches the result to the model, so ``predict``/
        ``batch_predict`` implementations that honor it serve from
        device-placed params instead of the host mirror."""
        return None


# Reference-parity aliases (see module docstring): the P/L/P2L distinction is
# a Spark artifact; on a mesh all algorithms are "distributed".
PAlgorithm = Algorithm
P2LAlgorithm = Algorithm
LAlgorithm = Algorithm
PDataSource = DataSource
LDataSource = DataSource
PPreparator = Preparator
LPreparator = Preparator


class PersistentModel(abc.ABC):
    """Opt-in custom model persistence (reference ``PersistentModel`` /
    ``PersistentModelLoader``). Models not implementing this are stored as a
    pickled blob in the Models store (reference: Kryo blob)."""

    @abc.abstractmethod
    def save(self, instance_id: str, params: Params, ctx: ComputeContext) -> bool:
        """Persist; return True if handled (False -> fall back to blob)."""

    @classmethod
    @abc.abstractmethod
    def load(cls, instance_id: str, params: Params, ctx: ComputeContext) -> "PersistentModel":
        ...


class Serving(Component, Generic[Q, P]):
    """Combine per-algorithm predictions into one response
    (reference ``LServing.serve``)."""

    @abc.abstractmethod
    def serve(self, query: Q, predictions: List[P]) -> P: ...

    def supplement(self, query: Q) -> Q:
        """Hook to enrich the query before algorithms see it
        (reference ``LServing.supplementBase``)."""
        return query


class FirstServing(Serving[Q, P]):
    """Returns the first algorithm's prediction (reference ``FirstServing``)."""

    def serve(self, query: Q, predictions: List[P]) -> P:
        return predictions[0]


class AverageServing(Serving[Q, float]):
    """Numeric mean of predictions (reference ``LAverageServing``)."""

    def serve(self, query: Q, predictions: List[float]) -> float:
        return sum(predictions) / len(predictions)


LServing = Serving
LFirstServing = FirstServing
LAverageServing = AverageServing
