"""Evaluation framework: Evaluation, EngineParamsGenerator, MetricEvaluator,
FastEval memoization.

Rebuild of the reference's ``controller/Evaluation.scala``,
``EngineParamsGenerator.scala``, ``MetricEvaluator.scala`` and
``FastEvalEngine.scala`` (UNVERIFIED paths; see SURVEY.md).

The reference's FastEvalEngine memoizes DataSource/Preparator/Algorithm
outputs across engine-params sharing a prefix so a hyper-parameter sweep
doesn't re-read or re-prepare identical stages. :class:`FastEvalCache`
replicates that: stage outputs are cached keyed by the serialized params
prefix — change only algorithm params and the sweep reuses TD/PD; change
only serving params and it reuses trained models too.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from pio_tpu.controller.components import Serving
from pio_tpu.controller.engine import Engine, EngineParams, serve_fold
from pio_tpu.controller.metrics import Metric
from pio_tpu.controller.params import params_to_dict
from pio_tpu.parallel.context import ComputeContext

log = logging.getLogger("pio_tpu.evaluation")


class EngineParamsGenerator:
    """Declares the params list a sweep evaluates
    (reference ``EngineParamsGenerator``)."""

    def __init__(self, engine_params_list: Sequence[EngineParams]):
        if not engine_params_list:
            raise ValueError("engine_params_list must not be empty")
        self.engine_params_list = list(engine_params_list)


class Evaluation:
    """Binds an engine + metric(s) (reference ``trait Evaluation``).

    ``engine_params_generator`` pairs the sweep definition with the
    evaluation (reference ``Evaluation with EngineParamsGenerator``
    mix-in); the CLI ``eval`` verb reads it when no generator is passed
    explicitly.
    """

    def __init__(
        self,
        engine: Engine,
        metric: Metric,
        other_metrics: Sequence[Metric] = (),
        engine_params_generator: Optional[EngineParamsGenerator] = None,
    ):
        self.engine = engine
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.engine_params_generator = engine_params_generator


@dataclasses.dataclass
class MetricScores:
    """Scores for one engine-params candidate
    (reference ``MetricScores`` in MetricEvaluator)."""

    engine_params: EngineParams
    score: float
    other_scores: List[float]


@dataclasses.dataclass
class MetricEvaluatorResult:
    """Sweep outcome (reference ``MetricEvaluatorResult``)."""

    best_engine_params: EngineParams
    best_score: float
    best_index: int
    metric_header: str
    other_metric_headers: List[str]
    engine_params_scores: List[MetricScores]

    def to_json(self) -> str:
        def ep_dict(ep: EngineParams) -> dict:
            return {
                "dataSourceParams": params_to_dict(ep.data_source_params),
                "preparatorParams": params_to_dict(ep.preparator_params),
                "algorithmParamsList": [
                    {"name": n, "params": params_to_dict(p)}
                    for n, p in ep.algorithm_params_list
                ],
                "servingParams": params_to_dict(ep.serving_params),
            }

        def safe(x: float):
            # json.dumps would emit the invalid literal `NaN` otherwise
            return x if math.isfinite(x) else None

        return json.dumps(
            {
                "metricHeader": self.metric_header,
                "otherMetricHeaders": self.other_metric_headers,
                "bestScore": safe(self.best_score),
                "bestIndex": self.best_index,
                "bestEngineParams": ep_dict(self.best_engine_params),
                "engineParamsScores": [
                    {
                        "engineParams": ep_dict(s.engine_params),
                        "score": safe(s.score),
                        "otherScores": [safe(x) for x in s.other_scores],
                    }
                    for s in self.engine_params_scores
                ],
            },
            indent=2,
        )


class FastEvalCache:
    """Prefix-memoized stage outputs (reference ``FastEvalEngineWorkflow``).

    Keys (mirroring the reference's ``DataSourcePrefix`` /
    ``PreparatorPrefix`` / ``AlgorithmsPrefix``):
      - data-source stage:   serialized data_source_params
      - preparator stage:    + preparator_params
      - algorithms stage:    + algorithm_params_list
    """

    def __init__(self):
        self.data_source: Dict[str, Any] = {}
        self.preparator: Dict[str, Any] = {}
        self.algorithms: Dict[str, Any] = {}
        self.hits = 0
        self.misses = 0

    @staticmethod
    def ds_key(ep: EngineParams) -> str:
        return json.dumps(params_to_dict(ep.data_source_params), sort_keys=True)

    @classmethod
    def prep_key(cls, ep: EngineParams) -> str:
        return cls.ds_key(ep) + "|" + json.dumps(
            params_to_dict(ep.preparator_params), sort_keys=True
        )

    @classmethod
    def algo_key(cls, ep: EngineParams) -> str:
        return cls.prep_key(ep) + "|" + json.dumps(
            [(n, params_to_dict(p)) for n, p in ep.algorithm_params_list],
            sort_keys=True,
        )

    def get_or(self, cache: Dict[str, Any], key: str, compute):
        if key in cache:
            self.hits += 1
            return cache[key]
        self.misses += 1
        cache[key] = compute()
        return cache[key]


def _fast_eval(
    engine: Engine, ctx: ComputeContext, ep: EngineParams, cache: FastEvalCache
):
    """Engine.eval with FastEval stage memoization."""
    data_source = engine.data_source_class(ep.data_source_params)

    eval_folds = cache.get_or(
        cache.data_source, cache.ds_key(ep), lambda: data_source.read_eval(ctx)
    )

    def compute_prepared():
        preparator = engine.preparator_class(ep.preparator_params)
        return [
            (preparator.prepare(ctx, td), eval_info, qa)
            for td, eval_info, qa in eval_folds
        ]

    prepared = cache.get_or(cache.preparator, cache.prep_key(ep), compute_prepared)

    def compute_models():
        algorithms = [
            engine.algorithm_class_map[name](params)
            for name, params in ep.algorithm_params_list
        ]
        return [
            (algorithms, [algo.train(ctx, pd) for algo in algorithms], eval_info, qa)
            for pd, eval_info, qa in prepared
        ]

    trained = cache.get_or(cache.algorithms, cache.algo_key(ep), compute_models)

    serving = engine.serving_class(ep.serving_params)
    return [
        (eval_info, serve_fold(serving, algorithms, models, qa))
        for algorithms, models, eval_info, qa in trained
    ]


class MetricEvaluator:
    """Scores each candidate params, picks the best
    (reference ``MetricEvaluator.evaluateBase``)."""

    def __init__(self, metric: Metric, other_metrics: Sequence[Metric] = ()):
        self.metric = metric
        self.other_metrics = list(other_metrics)

    def evaluate(
        self,
        ctx: ComputeContext,
        engine: Engine,
        engine_params_list: Sequence[EngineParams],
        fast_eval: bool = True,
    ) -> MetricEvaluatorResult:
        if not engine_params_list:
            raise ValueError("engine_params_list must not be empty")
        cache = FastEvalCache() if fast_eval else None
        scores: List[MetricScores] = []
        for i, ep in enumerate(engine_params_list):
            if cache is not None:
                eval_data = _fast_eval(engine, ctx, ep, cache)
            else:
                eval_data = engine.eval(ctx, ep)
            score = self.metric.calculate(eval_data)
            others = [m.calculate(eval_data) for m in self.other_metrics]
            log.info(
                "params[%d]: %s = %s", i, self.metric.header, score
            )
            scores.append(MetricScores(ep, score, others))

        # NaN scores (empty/unscorable folds) can never win: a NaN at index
        # 0 would otherwise stick because compare() returns 0 for NaN.
        best_i = None
        for i in range(len(scores)):
            if math.isnan(scores[i].score):
                continue
            if best_i is None or self.metric.compare(
                scores[i].score, scores[best_i].score
            ) > 0:
                best_i = i
        if best_i is None:
            raise ValueError(
                "every candidate scored NaN - no fold produced a scorable "
                "(query, prediction, actual) triple"
            )
        if cache is not None:
            log.info(
                "FastEval cache: %d hits / %d misses", cache.hits, cache.misses
            )
        return MetricEvaluatorResult(
            best_engine_params=scores[best_i].engine_params,
            best_score=scores[best_i].score,
            best_index=best_i,
            metric_header=self.metric.header,
            other_metric_headers=[m.header for m in self.other_metrics],
            engine_params_scores=scores,
        )
