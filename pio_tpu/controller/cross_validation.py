"""K-fold cross-validation split — rebuild of the reference's e2 eval helper.

Reference: ``e2/src/main/scala/o/a/p/e2/evaluation/CommonHelperFunctions.scala``
(``splitData``; UNVERIFIED path, see SURVEY.md §2.5): split an indexed dataset
into k folds, where fold i's test set is every element whose index ≡ i (mod k)
and its training set is everything else — then hand both to user-supplied
constructors.

Used by template ``read_eval`` implementations to produce the
``[(training_data, eval_info, [(query, actual)])]`` folds the Evaluation
framework consumes.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple, TypeVar

D = TypeVar("D")
TD = TypeVar("TD")
Q = TypeVar("Q")
A = TypeVar("A")


def split_data(
    k: int,
    data: Sequence[D],
    to_training_data: Callable[[List[D]], TD],
    to_query_actual: Callable[[D], Tuple[Q, A]],
) -> List[Tuple[TD, dict, List[Tuple[Q, A]]]]:
    """Deterministic k-fold split by element index.

    Returns one ``(training_data, eval_info, [(query, actual)])`` triple per
    fold; ``eval_info`` is ``{"fold": i}``.
    """
    if k <= 1:
        raise ValueError("k-fold cross-validation needs k >= 2")
    folds = []
    for fold in range(k):
        train = [d for i, d in enumerate(data) if i % k != fold]
        test = [d for i, d in enumerate(data) if i % k == fold]
        folds.append(
            (
                to_training_data(train),
                {"fold": fold},
                [to_query_actual(d) for d in test],
            )
        )
    return folds
