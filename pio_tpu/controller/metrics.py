"""Evaluation metrics (reference ``controller/Metric.scala``, UNVERIFIED path).

A Metric folds the evaluation data set — ``[(eval_info, [(q, p, a)])]`` —
into one comparable result. Where the reference computes per-fold averages
with RDD aggregations, these run as host-side folds (eval sets are modest)
or vectorized numpy; algorithm-side batch scoring already happened on device.
"""

from __future__ import annotations

import abc
import math
from typing import Any, Generic, List, Optional, Sequence, Tuple, TypeVar

EI = TypeVar("EI")
Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")

#: one fold: (eval_info, [(query, prediction, actual)])
EvalDataSet = Sequence[Tuple[EI, Sequence[Tuple[Q, P, A]]]]


class Metric(abc.ABC, Generic[EI, Q, P, A]):
    """Base metric; higher is better unless ``higher_is_better`` says not."""

    higher_is_better: bool = True

    @abc.abstractmethod
    def calculate(self, eval_data_set: EvalDataSet) -> float: ...

    @property
    def header(self) -> str:
        return type(self).__name__

    def compare(self, r0: float, r1: float) -> int:
        """sign(r0 - r1) respecting direction (reference ``Metric.compare``)."""
        delta = (r0 - r1) if self.higher_is_better else (r1 - r0)
        return (delta > 0) - (delta < 0)


class AverageMetric(Metric[EI, Q, P, A]):
    """Mean of a per-(Q,P,A) score over all folds (reference ``AverageMetric``)."""

    @abc.abstractmethod
    def calculate_one(self, query: Q, prediction: P, actual: A) -> float: ...

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        total, n = 0.0, 0
        for _, qpa in eval_data_set:
            for q, p, a in qpa:
                total += self.calculate_one(q, p, a)
                n += 1
        return total / n if n else float("nan")


class OptionAverageMetric(Metric[EI, Q, P, A]):
    """Like AverageMetric but ``None`` scores are excluded from the mean
    (reference ``OptionAverageMetric``)."""

    @abc.abstractmethod
    def calculate_one(self, query: Q, prediction: P, actual: A) -> Optional[float]: ...

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        total, n = 0.0, 0
        for _, qpa in eval_data_set:
            for q, p, a in qpa:
                s = self.calculate_one(q, p, a)
                if s is not None:
                    total += s
                    n += 1
        return total / n if n else float("nan")


class SumMetric(Metric[EI, Q, P, A]):
    """Sum of per-(Q,P,A) scores (reference ``SumMetric``)."""

    @abc.abstractmethod
    def calculate_one(self, query: Q, prediction: P, actual: A) -> float: ...

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        return sum(
            self.calculate_one(q, p, a)
            for _, qpa in eval_data_set
            for q, p, a in qpa
        )


class StdevMetric(Metric[EI, Q, P, A]):
    """Population stdev of per-(Q,P,A) scores (reference ``StdevMetric``)."""

    @abc.abstractmethod
    def calculate_one(self, query: Q, prediction: P, actual: A) -> float: ...

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        xs = [
            self.calculate_one(q, p, a)
            for _, qpa in eval_data_set
            for q, p, a in qpa
        ]
        if not xs:
            return float("nan")
        mean = sum(xs) / len(xs)
        return math.sqrt(sum((x - mean) ** 2 for x in xs) / len(xs))


class ZeroMetric(Metric[EI, Q, P, A]):
    """Always 0 — placeholder (reference ``ZeroMetric``)."""

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        return 0.0
