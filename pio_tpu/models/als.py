"""ALS matrix factorization — TPU-native replacement for Spark MLlib ALS.

The reference's Recommendation/Similar-Product templates call
``org.apache.spark.mllib.recommendation.ALS.train`` / ``trainImplicit``
(reference: examples/scala-parallel-recommendation ALSAlgorithm.scala,
UNVERIFIED path; see SURVEY.md). MLlib's ALS block-partitions the rating
matrix into in/out-link blocks and shuffles factor updates between executors
every half-iteration. This module is the TPU-first re-design:

- Ratings are a COO edge list (user_idx, item_idx, rating) — dense int32/f32
  arrays, statically shaped, sharded over the mesh ``data`` axis.
- One half-iteration (e.g. the user update) is::

      A_u = Σ_{i ∈ R(u)} q_i q_iᵀ + λI        b_u = Σ_i r_ui q_i
      p_u = A_u⁻¹ b_u

  computed as a chunked ``lax.scan`` of per-edge outer products reduced with
  ``segment_sum`` (no ragged gathers, no data-dependent shapes — XLA sees a
  fixed [chunk, K, K] window every step).
- Cross-device combine is ``psum_scatter`` (reduce-scatter) over the
  entity dimension: each device sums partial normal equations from its edge
  shard, receives 1/D of the entities, solves its slice with a batched
  ``jnp.linalg.solve``, and ``all_gather``s the factors back. This replaces
  MLlib's shuffle with two ICI collectives per half-step — the
  scaling-book recipe for data-parallel normal equations.
- Implicit feedback (Hu-Koren-style): confidence c = 1 + α·r, preference 1;
  the shared ``QᵀQ`` gram term is one MXU matmul, and only the
  ``(c-1) q qᵀ`` correction rides the segment-sum path.

Hot-loop FLOPs (edge outer products N·K², batched solves E·K³) both map to
the MXU via batched matmul/LU; HBM traffic is bounded by the chunk size.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import numpy as np

from pio_tpu.parallel.context import ComputeContext


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    rank: int = 10
    iterations: int = 10
    reg: float = 0.1
    implicit: bool = False
    alpha: float = 40.0
    #: edges per scan chunk — bounds the [chunk, K, K] HBM intermediate
    edges_per_chunk: int = 1 << 17
    seed: int = 0


@dataclasses.dataclass
class ALSFactors:
    """Trained factors (host numpy; replicated on device during training)."""

    user_factors: np.ndarray  # [n_users, rank]
    item_factors: np.ndarray  # [n_items, rank]


def _pad_edges(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    rating: np.ndarray,
    n_shards: int,
    chunk: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]:
    """Pad the edge list so each shard holds an equal whole number of chunks.

    Padding edges carry mask 0 and point at entity 0 — they contribute
    exactly zero to the normal equations.
    """
    n = len(user_idx)
    per_shard = -(-n // (n_shards * chunk)) * chunk
    n_pad = per_shard * n_shards
    u = np.zeros(n_pad, dtype=np.int32)
    i = np.zeros(n_pad, dtype=np.int32)
    r = np.zeros(n_pad, dtype=np.float32)
    m = np.zeros(n_pad, dtype=np.float32)
    u[:n], i[:n], r[:n], m[:n] = user_idx, item_idx, rating, 1.0
    return u, i, r, m, n_pad


def _round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def train_als(
    ctx: ComputeContext,
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    rating: np.ndarray,
    n_users: int,
    n_items: int,
    config: ALSConfig = ALSConfig(),
) -> ALSFactors:
    """Train ALS over the context's mesh (or a single device).

    Entity counts are padded to mesh multiples; factor rows beyond the true
    counts are dropped on the way out.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    if len(user_idx) == 0:
        raise ValueError("ALS needs at least one rating")

    mesh = ctx.mesh
    axis = ctx.batch_axis
    n_shards = mesh.shape[axis] if mesh is not None else 1
    K = config.rank
    chunk = min(config.edges_per_chunk, _round_up(len(user_idx), 256))

    u_host, i_host, r_host, m_host, n_pad = _pad_edges(
        np.asarray(user_idx, np.int32),
        np.asarray(item_idx, np.int32),
        np.asarray(rating, np.float32),
        n_shards,
        chunk,
    )
    U_pad = _round_up(max(n_users, 1), n_shards)
    I_pad = _round_up(max(n_items, 1), n_shards)

    key = jax.random.PRNGKey(config.seed)
    ku, ki = jax.random.split(key)
    # MLlib-style init: small random factors; scale keeps AᵀA well-conditioned.
    P0 = jax.random.normal(ku, (U_pad, K), jnp.float32) * 0.01
    Q0 = jax.random.normal(ki, (I_pad, K), jnp.float32) * 0.01

    lam = jnp.float32(config.reg)
    alpha = jnp.float32(config.alpha)
    implicit = config.implicit
    eye = jnp.eye(K, dtype=jnp.float32)

    def partial_normal_eq(edges, factors, n_entities, varying_axis=None):
        """Chunked scan: Σ w·q qᵀ and Σ rhs·q per entity (one shard's edges)."""
        ent_idx, other_idx, r, m = edges

        def chunk_step(carry, ch):
            A, b = carry
            e_idx, o_idx, r_c, m_c = ch
            q = factors[o_idx]  # [chunk, K] gather of the fixed factor side
            if implicit:
                # confidence c = 1 + α r; correction weight (c-1)·mask
                w = alpha * r_c * m_c
                rhs = (1.0 + alpha * r_c) * m_c  # c · preference(=1)
            else:
                w = m_c
                rhs = r_c * m_c
            outer = jnp.einsum("ck,cl->ckl", q, q) * w[:, None, None]
            A = A + jax.ops.segment_sum(outer, e_idx, num_segments=n_entities)
            b = b + jax.ops.segment_sum(q * rhs[:, None], e_idx, num_segments=n_entities)
            return (A, b), None

        n_chunks = ent_idx.shape[0] // chunk
        chunks = tuple(
            x.reshape(n_chunks, chunk, *x.shape[1:])
            for x in (ent_idx, other_idx, r, m)
        )
        A0 = jnp.zeros((n_entities, K, K), jnp.float32)
        b0 = jnp.zeros((n_entities, K), jnp.float32)
        if varying_axis is not None:
            # Inside shard_map the carry becomes device-varying after the
            # first chunk; mark the zeros accordingly so scan types match.
            A0 = jax.lax.pcast(A0, (varying_axis,), to="varying")
            b0 = jax.lax.pcast(b0, (varying_axis,), to="varying")
        (A, b), _ = jax.lax.scan(chunk_step, (A0, b0), chunks)
        return A, b

    def solve_block(A, b, gram):
        """Regularized batched solve on a block of entities."""
        A = A + lam * eye[None, :, :]
        if implicit:
            A = A + gram[None, :, :]
        return jnp.linalg.solve(A, b[:, :, None])[:, :, 0]

    if mesh is not None and n_shards > 1:
        edge_spec = (P(axis), P(axis), P(axis), P(axis))

        def half_step_sharded(ent_idx, other_idx, r, m, factors, n_entities):
            """shard_map body: edge-parallel accumulate -> reduce-scatter ->
            local solve -> all-gather (the MLlib-shuffle replacement)."""

            def body(ent_idx, other_idx, r, m, factors):
                A, b = partial_normal_eq(
                    (ent_idx, other_idx, r, m), factors, n_entities,
                    varying_axis=axis,
                )
                # reduce-scatter the normal equations over the entity dim:
                # each device ends up owning n_entities/D rows, fully summed.
                A = jax.lax.psum_scatter(A, axis, scatter_dimension=0, tiled=True)
                b = jax.lax.psum_scatter(b, axis, scatter_dimension=0, tiled=True)
                gram = (
                    jnp.einsum("ik,il->kl", factors, factors)
                    if implicit
                    else jnp.zeros((K, K), jnp.float32)
                )
                new_local = solve_block(A, b, gram)  # [n/D, K]
                return jax.lax.all_gather(new_local, axis, axis=0, tiled=True)

            # check_vma=False: after the tiled all_gather every device holds
            # identical factors, but the varying-axis type system can't
            # infer that replication statically.
            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=edge_spec + (P(),),
                out_specs=P(),
                check_vma=False,
            )(ent_idx, other_idx, r, m, factors)
    else:

        def half_step_sharded(ent_idx, other_idx, r, m, factors, n_entities):
            A, b = partial_normal_eq((ent_idx, other_idx, r, m), factors, n_entities)
            gram = (
                jnp.einsum("ik,il->kl", factors, factors)
                if implicit
                else jnp.zeros((K, K), jnp.float32)
            )
            return solve_block(A, b, gram)

    @functools.partial(jax.jit, static_argnames=())
    def run(u, i, r, m, P_init, Q_init):
        def iteration(_, PQ):
            P_f, Q_f = PQ
            P_f = half_step_sharded(u, i, r, m, Q_f, U_pad)
            Q_f = half_step_sharded(i, u, r, m, P_f, I_pad)
            return (P_f, Q_f)

        return jax.lax.fori_loop(0, config.iterations, iteration, (P_init, Q_init))

    if mesh is not None:
        edge_sharding = NamedSharding(mesh, P(axis))
        rep = NamedSharding(mesh, P())
        put_e = lambda x: jax.device_put(x, edge_sharding)
        put_r = lambda x: jax.device_put(x, rep)
    else:
        put_e = put_r = jnp.asarray

    P_f, Q_f = run(
        put_e(u_host), put_e(i_host), put_e(r_host), put_e(m_host),
        put_r(P0), put_r(Q0),
    )
    return ALSFactors(
        user_factors=np.asarray(jax.device_get(P_f))[:n_users],
        item_factors=np.asarray(jax.device_get(Q_f))[:n_items],
    )


def predict_scores(
    user_factors: np.ndarray, item_factors: np.ndarray, user: int
) -> np.ndarray:
    """Scores of every item for one user (host-side; serving keeps factors
    on device — see the recommendation template)."""
    return user_factors[user] @ item_factors.T


def top_n(
    scores: np.ndarray, n: int, exclude: Optional[np.ndarray] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Top-n item indices + scores, optionally excluding seen items."""
    s = scores.copy()
    if exclude is not None and len(exclude):
        s[exclude] = -np.inf
    n = min(n, len(s))
    idx = np.argpartition(-s, n - 1)[:n] if n < len(s) else np.argsort(-s)
    idx = idx[np.argsort(-s[idx])]
    return idx, s[idx]
