"""ALS matrix factorization — TPU-native replacement for Spark MLlib ALS.

The reference's Recommendation/Similar-Product templates call
``org.apache.spark.mllib.recommendation.ALS.train`` / ``trainImplicit``
(reference: examples/scala-parallel-recommendation ALSAlgorithm.scala,
UNVERIFIED path; see SURVEY.md). MLlib's ALS block-partitions the rating
matrix into in/out-link blocks and shuffles factor updates between executors
every half-iteration. This module is the TPU-first re-design:

- Host-side, the COO rating list is packed ONCE per orientation (by-user and
  by-item) into **fixed-width dense blocks**: edges sorted by entity, each
  entity's adjacency split into ``[block_width]`` slices, padded slots
  carrying weight 0. Static shapes, no ragged gathers.
- One half-iteration (e.g. the user update) is::

      A_u = Σ_{i ∈ R(u)} q_i q_iᵀ + λI        b_u = Σ_i r_ui q_i
      p_u = A_u⁻¹ b_u

  computed per block as one **batched MXU matmul**
  (``einsum('bwk,bwl->bkl')`` over ``[blocks, width, K]`` gathered factors)
  followed by a ``segment_sum`` of the ~E/width block partials onto entities
  with ``indices_are_sorted=True`` — the scatter is over blocks, not edges,
  so the VPU-hostile part shrinks by the block width while the FLOPs ride
  the systolic array.
- Cross-device combine is ``psum_scatter`` (reduce-scatter) over the entity
  dimension: each device sums partial normal equations from its block shard,
  receives 1/D of the entities, solves its slice with a batched
  ``jnp.linalg.solve``, and ``all_gather``s the factors back. Two ICI
  collectives per half-step replace MLlib's shuffle — the scaling-book
  recipe for data-parallel normal equations.
- Implicit feedback (Hu-Koren-style): confidence c = 1 + α·r, preference 1;
  the shared ``QᵀQ`` gram term is one MXU matmul, and only the
  ``(c-1) q qᵀ`` correction rides the blocked path.

The jitted trainer is cached per (mesh, static config) so repeated
``train_als`` calls — serving retrains, evaluation sweeps, benchmarks —
recompile only on shape changes.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
from pio_tpu.utils import knobs
from pio_tpu.obs import monotonic_s, trainwatch
from typing import Optional, Tuple

import numpy as np

from pio_tpu.utils.numutil import (
    n_stream_chunks as _n_stream_chunks,
    round_up as _round_up,
)

from pio_tpu.parallel.context import ComputeContext


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    rank: int = 10
    iterations: int = 10
    reg: float = 0.1
    implicit: bool = False
    alpha: float = 40.0
    #: edges per dense block; None → power of two near half the mean degree
    #: (bounds padding waste at ~width/2 per entity)
    block_width: Optional[int] = None
    #: blocks per scan step — bounds the [chunk, width, K] HBM intermediate
    blocks_per_chunk: int = 4096
    #: dtype for the factor gather + normal-equation matmuls. "auto"
    #: picks bfloat16 on accelerator backends — the MXU's native rate,
    #: halving the gather bandwidth — and float32 on CPU, where bf16 is
    #: emulated (no rate or bandwidth win) and its table rounding only
    #: compounds across iterations. Explicit "bfloat16" / "float32"
    #: override; accumulation and the solves stay float32 either way.
    matmul_dtype: str = "auto"
    #: per-entity K×K solver: "auto" uses exact Cholesky for small entity
    #: counts and switches to Jacobi-preconditioned CG (matmul-only, rides
    #: the MXU) above ~32k entities, where XLA's batched factorizations
    #: serialize badly on TPU (LU at MovieLens-25M user count: ~780 ms per
    #: half-step; CG: ~90 ms). Explicit "cg" / "cholesky" / "lu" override.
    solver: str = "auto"
    seed: int = 0


@dataclasses.dataclass
class ALSFactors:
    """Trained factors (host numpy; replicated on device during training)."""

    user_factors: np.ndarray  # [n_users, rank]
    item_factors: np.ndarray  # [n_items, rank]




def _native_packer():
    """The C++ packer (pio_tpu/native/als_pack.cpp), or None when no
    toolchain is available (tests cover both paths)."""
    if knobs.knob_str("PIO_TPU_NO_NATIVE"):
        return None
    try:
        from pio_tpu.native import als_pack_lib

        return als_pack_lib()
    except Exception:  # NativeUnavailable, or a broken toolchain
        return None


def _ptr(a: np.ndarray, dtype, ctype):
    """C pointer to a's buffer. Asserts rather than converts: a silent
    ascontiguousarray copy would send native WRITES into a discarded
    temporary (these helpers are used for output buffers too)."""
    import ctypes

    assert a.dtype == dtype and a.flags.c_contiguous, (a.dtype, a.flags)
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _i32p(a: np.ndarray):
    import ctypes

    return _ptr(a, np.int32, ctypes.c_int32)


def _i64p(a: np.ndarray):
    import ctypes

    return _ptr(a, np.int64, ctypes.c_int64)


def _f32p(a: np.ndarray):
    import ctypes

    return _ptr(a, np.float32, ctypes.c_float)


def _auto_width(n_edges: int, n_entities: int) -> int:
    # Narrow blocks: padding waste (≈ width/2 per entity) costs real
    # host→device bytes, which dominate over the extra scatter rows on the
    # tunneled/PCIe link (measured optimum 16-64 at MovieLens scales).
    mean_deg = max(1.0, n_edges / max(1, n_entities))
    w = 1 << int(np.ceil(np.log2(max(8.0, mean_deg / 4))))
    return int(min(64, max(16, w)))


def _pack_blocks(
    ent_idx: np.ndarray,
    other_idx: np.ndarray,
    rating: np.ndarray,
    n_entities: int,
    width: int,
    pad_blocks_to: int,
    counts: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack a COO edge list into dense [n_blocks, width] CSR-style blocks.

    Returns (block_ent [S], block_other [S,W], block_rating [S,W]);
    ``block_ent`` ascending so downstream segment sums take the
    sorted-indices fast path. Padded slots carry ``other = -1`` — the
    validity mask is derived on device from the sign, so no separate mask
    array rides the host→device link.
    """
    order = np.argsort(ent_idx, kind="stable")
    e = ent_idx[order]
    if counts is None:
        counts = np.bincount(e, minlength=n_entities)
    blocks_per_ent = -(-counts // width)  # zero for empty entities
    n_blocks = int(blocks_per_ent.sum())
    S = max(pad_blocks_to, _round_up(max(n_blocks, 1), pad_blocks_to))

    block_start = np.zeros(n_entities + 1, dtype=np.int64)
    np.cumsum(blocks_per_ent, out=block_start[1:])
    edge_start = np.zeros(n_entities + 1, dtype=np.int64)
    np.cumsum(counts, out=edge_start[1:])

    # position of each (sorted) edge within its entity's adjacency
    pos = np.arange(len(e), dtype=np.int64) - edge_start[e]
    flat = (block_start[e] + pos // width) * width + pos % width

    block_other = np.full(S * width, -1, dtype=np.int32)
    block_rating = np.zeros(S * width, dtype=np.float32)
    block_other[flat] = other_idx[order]
    block_rating[flat] = rating[order]

    # padding blocks target the LAST entity (masked out) to keep ids
    # ascending for the segment-sum sorted fast path
    block_ent = np.full(S, n_entities - 1, dtype=np.int32)
    reps = np.repeat(np.arange(n_entities, dtype=np.int32), blocks_per_ent)
    block_ent[: len(reps)] = reps
    return (
        block_ent,
        block_other.reshape(S, width),
        block_rating.reshape(S, width),
    )


def _resolve_matmul_dtype(matmul_dtype: str) -> str:
    """``"auto"`` → bfloat16 where the MXU pays for it, float32 on CPU
    (emulated bf16: same FLOP rate, strictly more rounding)."""
    if matmul_dtype != "auto":
        return matmul_dtype
    import jax

    return "float32" if jax.default_backend() == "cpu" else "bfloat16"


def _make_math(reg: float, implicit: bool, alpha: float,
               matmul_dtype: str, solver: str, rating_wire: str = "f32",
               item_wire: str = "planes"):
    """Shared jittable ALS math: blocked normal-equation accumulation, the
    batched solvers, and the wire decode. Closed over the static config and
    used by BOTH the monolithic trainer (:func:`_build_trainer`) and the
    streamed trainer (:func:`_build_stream_trainer`) so the two paths
    cannot drift apart numerically."""
    import types

    import jax
    import jax.numpy as jnp

    lam = jnp.float32(reg)
    alpha_f = jnp.float32(alpha)
    mm_dtype = jnp.dtype(matmul_dtype)

    def partial_normal_eq(block_ent, block_other, block_r, factors,
                          n_entities, chunk, varying_axis=None):
        """Blocked scan: Σ w·q qᵀ and Σ rhs·q per entity (one shard)."""
        K = factors.shape[1]
        # cast ONCE per half-step: the scan then gathers from the low-
        # precision table (half the HBM traffic) and the einsums hit the
        # MXU at its native bf16 rate; accumulation stays f32 below
        factors_mm = factors.astype(mm_dtype)

        def chunk_step(carry, ch):
            A, b = carry
            ent, other, r_c = ch
            # padded slots are other == -1; validity derives from the sign
            m_c = (other >= 0).astype(jnp.float32)
            q = factors_mm[jnp.maximum(other, 0)]  # [chunk, W, K] gather
            if implicit:
                # confidence c = 1 + α r; correction weight (c-1)·mask
                w = alpha_f * r_c * m_c
                rhs = (1.0 + alpha_f * r_c) * m_c  # c · preference(=1)
            else:
                w = m_c
                rhs = r_c * m_c
            # batched MXU matmul: [chunk, K, W] @ [chunk, W, K], f32 acc
            A_blk = jnp.einsum(
                "cwk,cwl->ckl", q * w[:, :, None].astype(mm_dtype), q,
                preferred_element_type=jnp.float32,
            )
            b_blk = jnp.einsum(
                "cwk,cw->ck", q, rhs.astype(mm_dtype),
                preferred_element_type=jnp.float32,
            )
            A = A + jax.ops.segment_sum(
                A_blk, ent, num_segments=n_entities, indices_are_sorted=True
            )
            b = b + jax.ops.segment_sum(
                b_blk, ent, num_segments=n_entities, indices_are_sorted=True
            )
            return (A, b), None

        S = block_ent.shape[0]
        n_chunks = S // chunk
        chunks = tuple(
            x.reshape(n_chunks, chunk, *x.shape[1:])
            for x in (block_ent, block_other, block_r)
        )
        A0 = jnp.zeros((n_entities, K, K), jnp.float32)
        b0 = jnp.zeros((n_entities, K), jnp.float32)
        if varying_axis is not None:
            # Inside shard_map the carry becomes device-varying after the
            # first chunk; mark the zeros accordingly so scan types match.
            from pio_tpu.parallel.compat import pcast

            A0 = pcast(A0, (varying_axis,), to="varying")
            b0 = pcast(b0, (varying_axis,), to="varying")
        (A, b), _ = jax.lax.scan(chunk_step, (A0, b0), chunks)
        return A, b

    def _cg_solve(A, b):
        """Batched Jacobi-preconditioned CG — matmul-only, so it rides the
        MXU instead of XLA's serialized batched factorizations (measured
        ~8× faster than LU at MovieLens-25M entity counts). A is SPD
        (normal equations + λI); K+8 iterations ≥ the Krylov dimension
        with margin for f32 rounding on ill-conditioned systems."""
        K = b.shape[1]
        inv_d = 1.0 / jnp.diagonal(A, axis1=1, axis2=2)
        x = b * inv_d
        r = b - jnp.einsum("nkl,nl->nk", A, x)
        z = r * inv_d
        p = z
        rz = (r * z).sum(-1)

        def body(_, st):
            x, r, p, rz = st
            Ap = jnp.einsum("nkl,nl->nk", A, p)
            denom = (p * Ap).sum(-1)
            alpha_c = rz / jnp.where(denom != 0, denom, 1.0)
            x = x + alpha_c[:, None] * p
            r = r - alpha_c[:, None] * Ap
            z = r * inv_d
            rz2 = (r * z).sum(-1)
            beta = rz2 / jnp.where(rz != 0, rz, 1.0)
            p = z + beta[:, None] * p
            return (x, r, p, rz2)

        x, *_ = jax.lax.fori_loop(0, K + 8, body, (x, r, p, rz))
        return x

    def solve_block(A, b, gram):
        """Regularized batched solve on a block of entities."""
        K = b.shape[1]
        A = A + lam * jnp.eye(K, dtype=jnp.float32)[None, :, :]
        if implicit:
            A = A + gram[None, :, :]
        # "auto": exact Cholesky while it's cheap, CG at the batch sizes
        # where XLA's TPU factorizations serialize (A.shape[0] is static
        # at trace time, so this is a compile-time branch)
        if solver not in ("auto", "cg", "cholesky", "lu"):
            raise ValueError(
                f"unknown ALS solver {solver!r}; use auto/cg/cholesky/lu"
            )
        eff = solver
        if eff == "auto":
            eff = "cg" if A.shape[0] > 32768 else "cholesky"
        if eff == "cg":
            return _cg_solve(A, b)
        if eff == "cholesky":
            L = jnp.linalg.cholesky(A)
            y = jax.scipy.linalg.solve_triangular(
                L, b[:, :, None], lower=True
            )
            x = jax.scipy.linalg.solve_triangular(
                jnp.swapaxes(L, 1, 2), y, lower=False
            )
            return x[:, :, 0]
        return jnp.linalg.solve(A, b[:, :, None])[:, :, 0]

    def gram_of(factors):
        if implicit:
            return jnp.einsum("ik,il->kl", factors, factors)
        return jnp.zeros((factors.shape[1], factors.shape[1]), jnp.float32)

    def half_local(blocks, factors, n_entities, chunk):
        """One single-device half-step from a blocked layout."""
        A, b = partial_normal_eq(*blocks, factors, n_entities, chunk)
        return solve_block(A, b, gram_of(factors))

    def decode_items(i_lo, i_hi, ovf_idx=None, ovf_val=None, counts=None):
        """Wire → int32 item ids.

        ``planes``: uint16 low plane + optional uint8 high plane.
        ``delta12``: 12-bit gaps over the (user, item)-sorted adjacency —
        ``i_lo`` u8 low byte, ``i_hi`` nibble-packed high 4 bits (2
        edges/byte), plus a sparse overflow list (``delta >> 12`` in
        ``ovf_val``). Ids reconstruct as a segmented cumsum: global
        uint32 cumsum of deltas minus each user's prefix (gathered at
        segment starts from ``counts``) — wraparound-exact because every
        true id < 2^16.
        """
        if item_wire == "delta12":
            E = i_lo.shape[0]
            lo = i_lo.astype(jnp.uint32)
            hi = jnp.stack(
                [i_hi & 0xF, i_hi >> 4], axis=1
            ).reshape(-1)[:E].astype(jnp.uint32)
            delta = lo | (hi << 8)
            delta = delta.at[ovf_idx].add(
                ovf_val.astype(jnp.uint32) << 12
            )
            G = jnp.cumsum(delta, dtype=jnp.uint32)
            cnt = counts.astype(jnp.int32)
            es = jnp.concatenate(
                [jnp.zeros(1, jnp.int32), jnp.cumsum(cnt)]
            )[:-1]
            g_prev = jnp.where(es > 0, G[jnp.maximum(es - 1, 0)], 0)
            offs = jnp.repeat(g_prev, cnt, total_repeat_length=E)
            return (G - offs).astype(jnp.int32)
        i32 = i_lo.astype(jnp.int32)
        if i_hi.shape[0]:
            i32 = i32 | (i_hi.astype(jnp.int32) << 16)
        return i32

    def decode_ratings(r, n_edges):
        """Wire → float32 ratings per the static ``rating_wire`` kind:
        ``u4`` nibble-packed half-star codes (2 edges/byte), ``u8``
        half-star codes, ``f16``/``f32`` raw floats."""
        if rating_wire == "u4":
            lo = (r & 0xF).astype(jnp.float32)
            hi = (r >> 4).astype(jnp.float32)
            pairs = jnp.stack([lo, hi], axis=1).reshape(-1)
            return pairs[:n_edges] * jnp.float32(0.5)
        if rating_wire == "u8":
            return r.astype(jnp.float32) * jnp.float32(0.5)
        return r.astype(jnp.float32)

    return types.SimpleNamespace(
        partial_normal_eq=partial_normal_eq,
        solve_block=solve_block,
        gram_of=gram_of,
        half_local=half_local,
        decode_items=decode_items,
        decode_ratings=decode_ratings,
    )


@functools.lru_cache(maxsize=32)
def _build_trainer(mesh, axis: str, iterations: int, reg: float,
                   implicit: bool, alpha: float,
                   chunk_user: int, chunk_item: int,
                   matmul_dtype: str = "bfloat16", solver: str = "cg",
                   packed_shapes=None, rank: int = 0,
                   U_pad: int = 0, I_pad: int = 0,
                   rating_wire: str = "f32", item_wire: str = "planes",
                   mesh_wire_lens=None):
    """Jitted ALS trainer for one (mesh, static-config) combination.

    The returned function takes the two packed-block layouts + initial
    factors; shapes specialize inside jax.jit's own cache.
    """
    import jax
    import jax.numpy as jnp

    math = _make_math(reg, implicit, alpha, matmul_dtype, solver,
                      rating_wire, item_wire)
    partial_normal_eq = math.partial_normal_eq
    solve_block = math.solve_block
    gram_of = math.gram_of

    if mesh is not None and mesh.shape[axis] > 1:
        from jax.sharding import PartitionSpec as P

        from pio_tpu.parallel.compat import shard_map

        blk_spec = (P(axis), P(axis), P(axis))

        def half_step(ent, other, r, factors, n_entities, chunk):
            """shard_map body: block-parallel accumulate → reduce-scatter →
            local solve → all-gather (the MLlib-shuffle replacement)."""

            def body(ent, other, r, factors):
                A, b = partial_normal_eq(
                    ent, other, r, factors, n_entities, chunk,
                    varying_axis=axis,
                )
                # reduce-scatter the normal equations over the entity dim:
                # each device ends up owning n_entities/D rows, fully summed.
                A = jax.lax.psum_scatter(A, axis, scatter_dimension=0, tiled=True)
                b = jax.lax.psum_scatter(b, axis, scatter_dimension=0, tiled=True)
                new_local = solve_block(A, b, gram_of(factors))  # [n/D, K]
                return jax.lax.all_gather(new_local, axis, axis=0, tiled=True)

            # check_vma=False: after the tiled all_gather every device holds
            # identical factors, but the varying-axis type system can't
            # infer that replication statically.
            return shard_map(
                body,
                mesh=mesh,
                in_specs=blk_spec + (P(),),
                out_specs=P(),
                check_vma=False,
            )(ent, other, r, factors)
    else:

        def half_step(ent, other, r, factors, n_entities, chunk):
            A, b = partial_normal_eq(
                ent, other, r, factors, n_entities, chunk
            )
            return solve_block(A, b, gram_of(factors))

    def run_body(by_user, by_item, seed):
        # factor init on device, inside the one compiled program:
        # MLlib-style |N(0,1)|/√rank — POSITIVE entries matched to the
        # nonnegative ratings. A tiny symmetric init (±0.01) makes the
        # first reg-dominated half-step collapse every factor onto one
        # direction, and ALS (monotone) then converges inside that
        # rank-deficient basin on some seeds
        ku, ki = jax.random.split(jax.random.PRNGKey(seed))
        scale = jnp.float32(rank) ** -0.5
        P_init = jnp.abs(jax.random.normal(ku, (U_pad, rank), jnp.float32)) * scale
        Q_init = jnp.abs(jax.random.normal(ki, (I_pad, rank), jnp.float32)) * scale

        def iteration(_, PQ):
            P_f, Q_f = PQ
            P_f = half_step(*by_user, Q_f, U_pad, chunk_user)
            Q_f = half_step(*by_item, P_f, I_pad, chunk_item)
            return (P_f, Q_f)

        return jax.lax.fori_loop(0, iterations, iteration, (P_init, Q_init))

    if packed_shapes is None:
        return jax.jit(run_body)

    # COO variant (single-device): ship the edge list ONCE, pre-sorted by
    # (user, item) on the host (native two-pass sort), and build BOTH
    # blocked layouts on device inside the same jit dispatch. Sorting
    # host-side means the per-edge USER ids never cross the wire at all —
    # one per-user counts array replaces them and the device rebuilds the
    # id column with a single repeat. Items ship as 12-bit adjacency gaps
    # (delta12) or uint16 planes, ratings as 4-bit half-star codes —
    # ~2 B/edge total vs 12 B raw COO (measured 175 MB → ~50 MB at
    # MovieLens-25M); on a tunneled/slow host↔device link the transfer is
    # the training bottleneck, so wire bytes are throughput.
    su, wu, si, wi = packed_shapes

    @jax.jit
    def run_packed(counts_u, counts_i, i_lo, i_hi, ovf_idx, ovf_val, r,
                   seed):
        # wire decode (all static dispatch on the wire kinds):
        #   items: uint16 plane (+uint8 high plane < 2^24), or 12-bit
        #   deltas over the item-sorted adjacency + sparse overflow
        #   ratings: u4 nibble-packed half-star codes (2 edges/byte) when
        #   every code ≤ 15, u8 codes, else fp16/f32 raw
        if mesh is not None and mesh_wire_lens is not None:
            # mesh compact wire: edge arrays arrived SHARDED over the
            # mesh axis (host link crossed once) as one or more CHUNKS
            # per array (PIO_TPU_ALS_STREAM_MB — chunked puts pipeline
            # the per-device transfers); re-replicate each chunk over
            # ICI here, drop its shard-divisibility padding, and splice
            # the stream back together — the decode's cumsum needs the
            # whole stream on every device. Chunking never re-encodes:
            # concat(trimmed chunks) is byte-identical to the
            # monolithic array.
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(mesh, P())
            lens_lo, lens_hi, lens_r = mesh_wire_lens

            def gather_cat(chunks, lens):
                parts = [
                    jax.lax.with_sharding_constraint(c, repl)[:n]
                    for c, n in zip(chunks, lens)
                ]
                return parts[0] if len(parts) == 1 \
                    else jnp.concatenate(parts)

            i_lo = gather_cat(i_lo, lens_lo)
            i_hi = gather_cat(i_hi, lens_hi)
            r = gather_cat(r, lens_r)
        E = i_lo.shape[0]
        i32 = math.decode_items(i_lo, i_hi, ovf_idx, ovf_val, counts_u)
        r32 = math.decode_ratings(r, E)
        u32 = jnp.repeat(
            jnp.arange(U_pad, dtype=jnp.int32), counts_u,
            total_repeat_length=E,
        )
        # both degree histograms ride the wire (0.9 MB total) — the
        # on-device bincount is a 25M-edge scatter-add, the host count is
        # a pass the native packer already made
        by_user = device_pack(u32, i32, r32, U_pad, wu, su,
                              assume_sorted=True, counts=counts_u)
        by_item = device_pack(i32, u32, r32, I_pad, wi, si,
                              counts=counts_i)
        return run_body(by_user, by_item, seed)

    return run_packed


@functools.lru_cache(maxsize=16)
def _build_stream_trainer(iterations: int, reg: float, implicit: bool,
                          alpha: float, matmul_dtype: str, solver: str,
                          rank: int, U_pad: int, I_pad: int,
                          w_user: int, w_item: int, S_item: int,
                          chunk_stream: int, chunk_item: int,
                          rating_wire: str, item_wire: str,
                          chunk_spec: tuple):
    """Double-buffered single-device trainer: the wire arrays arrive in
    ``len(chunk_spec)`` slices and each slice's by-user block pack + its
    contribution to iteration 1's user-side normal equations run WHILE the
    next slice is still crossing the host↔device link (the queued
    ``device_put``s ride the transfer stream; each chunk program only waits
    on its own inputs). ``chunk_spec`` is a tuple of per-chunk
    ``(S_c, pad_entity, first_user)``: the chunk's static padded block
    count, the entity its padding blocks alias (the chunk's LAST user,
    which keeps the concatenated block layout globally ascending for the
    segment-sum sorted fast path), and the first user present (the sliced
    local-counts offset).

    The finalize program concatenates the chunk-local block layouts into
    the full by-user layout (no repack), solves P1 from the streamed
    normal equations, packs the item side, and runs the remaining
    iterations. Numerically this differs from the monolithic path only in
    iteration-1 accumulation grouping (float reduction order)."""
    import jax
    import jax.numpy as jnp

    math = _make_math(reg, implicit, alpha, matmul_dtype, solver,
                      rating_wire, item_wire)

    def _lc_full(local_counts, u0_c):
        """Expand a chunk's sliced local-counts span to full U_pad."""
        return jax.lax.dynamic_update_slice(
            jnp.zeros(U_pad, jnp.int32),
            local_counts.astype(jnp.int32), (u0_c,),
        )

    @jax.jit
    def init(seed):
        # same key split as run_body: ku (P_init) is unused — the first
        # half-step overwrites P — so only Q0 must match the monolithic
        # trainer's draw
        ku, ki = jax.random.split(jax.random.PRNGKey(seed))
        del ku
        Q0 = jnp.abs(
            jax.random.normal(ki, (I_pad, rank), jnp.float32)
        ) * (jnp.float32(rank) ** -0.5)
        A0 = jnp.zeros((U_pad, rank, rank), jnp.float32)
        b0 = jnp.zeros((U_pad, rank), jnp.float32)
        return Q0, A0, b0

    def _make_accum(S_c: int, pad_c: int, u0_c: int):
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def accum(A, b, Q0, local_counts, i_lo, i_hi, ovf_idx, ovf_val, r):
            E_c = i_lo.shape[0]
            # local_counts arrives sliced to the chunk's present-user span
            # [u0_c, pad_c] (ships span·4 B instead of U_pad·4 B per
            # chunk); expand to full length on device
            lc = _lc_full(local_counts, u0_c)
            i32 = math.decode_items(i_lo, i_hi, ovf_idx, ovf_val, lc)
            r32 = math.decode_ratings(r, E_c)
            blocks = device_pack(
                None, i32, r32, U_pad, w_user, S_c,
                assume_sorted=True, counts=lc, pad_entity=pad_c,
            )
            dA, db = math.partial_normal_eq(
                *blocks, Q0, U_pad, chunk_stream
            )
            return A + dA, b + db, blocks

        return accum

    accums = tuple(_make_accum(*spec) for spec in chunk_spec)

    @jax.jit
    def finalize(A, b, Q0, counts_u, counts_i, user_blocks, wire_chunks,
                 lc_slices):
        # full by-user layout = concat of the chunk-local packs (padding
        # aliases each chunk's last user, so ids stay ascending)
        by_user = tuple(
            jnp.concatenate([blk[k] for blk in user_blocks])
            for k in range(3)
        )
        # item side needs the full COO: re-decode the (device-resident)
        # wire chunks — elementwise, cheap; the delta item wire is
        # chunk-segmented, so each chunk decodes against its own
        # local-counts span
        i32 = jnp.concatenate([
            math.decode_items(
                lo, hi, ovf_i, ovf_v, _lc_full(lc, chunk_spec[c][2])
            )
            for c, ((lo, hi, ovf_i, ovf_v, _r), lc)
            in enumerate(zip(wire_chunks, lc_slices))
        ])
        r32 = jnp.concatenate(
            [math.decode_ratings(r, lo.shape[0])
             for lo, hi, ovf_i, ovf_v, r in wire_chunks]
        )
        E = i32.shape[0]
        u32 = jnp.repeat(
            jnp.arange(U_pad, dtype=jnp.int32), counts_u,
            total_repeat_length=E,
        )
        by_item = device_pack(i32, u32, r32, I_pad, w_item, S_item,
                              counts=counts_i)
        # iteration 1: user half is already accumulated (streamed)
        P = math.solve_block(A, b, math.gram_of(Q0))
        Q = math.half_local(by_item, P, I_pad, chunk_item)

        def iteration(_, PQ):
            P, Q = PQ
            P = math.half_local(by_user, Q, U_pad, chunk_stream)
            Q = math.half_local(by_item, P, I_pad, chunk_item)
            return (P, Q)

        return jax.lax.fori_loop(0, iterations - 1, iteration, (P, Q))

    return init, accums, finalize


def device_pack(ent, oth, rat, n_entities: int, width: int, S: int,
                assume_sorted: bool = False, counts=None,
                pad_entity=None):
    """On-device COO→blocked-CSR packing (traceable; jnp throughout).

    Layout is bit-identical to the host packers (_pack_blocks /
    native als_pack_fill) — enforced by tests/test_als.py
    ``test_device_pack_matches_host_packers``. ``S``, ``width``, and
    ``n_entities`` are static. ``assume_sorted`` skips the stable argsort
    when the caller guarantees ``ent`` is already ascending (the
    counts-rebuilt user column is sorted by construction).

    Formulated as pure GATHERS: every [S, W] slot computes which edge (if
    any) it holds — block's entity via searchsorted over the block prefix
    sum, position within the entity's adjacency from the block offset —
    and gathers it, composing through the argsort permutation when the
    input isn't pre-sorted. The scatter formulation (`.at[flat].set` over
    the S·W slot space) measured ~3.2 s per 25M edges on v5e where the
    gathers take ~0.3 s: scatters serialize on TPU, gathers tile.

    ``pad_entity`` redirects the padding blocks' (masked) entity id —
    the streamed trainer points them at a chunk's LAST present entity so
    concatenated chunk layouts stay globally ascending. Only valid when
    no real block belongs to an entity beyond it. ``ent`` may be ``None``
    when ``counts`` is supplied with ``assume_sorted`` (it is unused).
    """
    import jax.numpy as jnp

    if counts is None:
        counts = jnp.bincount(ent, length=n_entities)  # order-free
    else:
        counts = counts.astype(jnp.int32)  # caller-supplied (wire input)
    blocks = -(-counts // width)
    zero = jnp.zeros(1, counts.dtype)
    block_start = jnp.concatenate([zero, jnp.cumsum(blocks)])
    edge_start = jnp.concatenate([zero, jnp.cumsum(counts)])

    # per block: owning entity (padding blocks → pad_entity, masked out)
    pad_tgt = (n_entities - 1) if pad_entity is None else pad_entity
    bids = jnp.searchsorted(block_start[1:], jnp.arange(S), side="right")
    block_ent = jnp.minimum(bids, pad_tgt).astype(jnp.int32)

    # per slot: position within the entity's adjacency, then edge index
    blk_in_ent = jnp.arange(S) - block_start[block_ent]  # [S]
    pos = blk_in_ent[:, None] * width + jnp.arange(width)[None, :]
    valid = pos < counts[block_ent][:, None]  # [S, W]
    src = jnp.where(valid, edge_start[block_ent][:, None] + pos, 0)
    if not assume_sorted:
        # compose through the stable sort permutation: one fused gather
        src = jnp.argsort(ent, stable=True)[src]
    block_other = jnp.where(valid, oth[src], jnp.int32(-1))
    block_rating = jnp.where(valid, rat[src], jnp.float32(0.0))
    return block_ent, block_other, block_rating


def _run_streamed(config: "ALSConfig", rank: int, U_pad: int, I_pad: int,
                  w_user: int, w_item: int, S_item: int, chunk_item: int,
                  counts_u: np.ndarray, counts_i: np.ndarray,
                  i_sorted: np.ndarray, r_ship: np.ndarray,
                  rating_wire: str, item_wire: str,
                  n_stream: int, seed, stats: Optional[dict]):
    """Dispatch the double-buffered single-device training run.

    Slices the (user, item)-sorted edges into ``n_stream`` spans, encodes
    each span's item wire CHUNK-LOCALLY (the delta wire restarts each
    user's gap chain at the chunk boundary — a straddling user's first
    in-chunk edge ships its absolute id, so chunks decode independently
    against their local counts), queues every span's ``device_put`` up
    front (async — they drain on the transfer stream in order), then
    chains the per-chunk accumulate programs: chunk k's pack +
    normal-equation accumulation executes while chunk k+1 is still
    crossing the link. With ``stats`` the phases are serialized (block
    between h2d and compute) to measure them — overlap off. Chunk
    boundaries are even so nibble-packed planes split on byte boundaries.
    """
    import jax

    E = i_sorted.shape[0]
    edge_start = np.zeros(U_pad + 1, np.int64)
    np.cumsum(counts_u, out=edge_start[1:])
    bounds = [min(E, (E * c // n_stream) // 2 * 2)
              for c in range(n_stream)] + [E]
    spans = [(bounds[c], bounds[c + 1]) for c in range(n_stream)
             if bounds[c + 1] > bounds[c]]

    local_slices, n_blocks, chunk_spec = [], [], []
    for e0, e1 in spans:
        lc = np.diff(np.clip(edge_start, e0, e1))
        u0 = int(np.searchsorted(edge_start, e0, side="right")) - 1
        pad_c = int(np.searchsorted(edge_start, e1 - 1, side="right")) - 1
        local_slices.append(
            np.ascontiguousarray(lc[u0:pad_c + 1], np.int32)
        )
        n_blocks.append(int((-(-lc // w_user)).sum()))
        chunk_spec.append([0, pad_c, u0])  # S_c filled below
    chunk_stream = min(
        config.blocks_per_chunk,
        _round_up(max(1, -(-sum(n_blocks) // len(spans))), 8),
    )
    for spec, nb in zip(chunk_spec, n_blocks):
        spec[0] = _round_up(max(nb, 1), chunk_stream)

    init, accums, finalize = _build_stream_trainer(
        config.iterations, float(config.reg), bool(config.implicit),
        float(config.alpha), _resolve_matmul_dtype(str(config.matmul_dtype)), str(config.solver),
        rank, U_pad, I_pad, w_user, w_item, S_item,
        chunk_stream, chunk_item, rating_wire, item_wire,
        tuple(tuple(s) for s in chunk_spec),
    )

    def _encode_chunk(e0, e1, lc):
        if item_wire == "delta12":
            d_lo, d_hi, ovf_idx, ovf_val, _ = _encode_items_delta(
                i_sorted[e0:e1], lc
            )
        else:
            d_lo, d_hi = _planes(i_sorted[e0:e1], I_pad)
            ovf_idx = np.zeros(0, np.int32)
            ovf_val = np.zeros(0, np.uint8)
        r_c = (r_ship[e0 // 2:(e1 + 1) // 2] if rating_wire == "u4"
               else r_ship[e0:e1])
        return d_lo, d_hi, ovf_idx, ovf_val, r_c

    # the shared streamed-feed executor (parallel/stream.py) runs the
    # encode → queued-put → chained-dispatch loop; ALS retains the wire
    # chunks (finalize re-decodes them for the item side) so it rides
    # the queue-ahead mode (lookahead=0), and maps the executor's
    # encode phase onto its historical ``pack_s`` stats key
    from pio_tpu.parallel.stream import stream_feed

    def encode(chunk):
        (e0, e1), lc = chunk
        return (*_encode_chunk(e0, e1, lc), lc)

    def put(host, _idx):
        *wire, lc = host
        return tuple(jax.device_put(a) for a in wire), jax.device_put(lc)

    extra = {}

    def put_extra():
        extra["cu"] = jax.device_put(counts_u.astype(np.int32))
        extra["ci"] = jax.device_put(
            np.ascontiguousarray(counts_i, np.int32)
        )
        return extra["cu"], extra["ci"]

    def init_carry():
        Q0, A, b = init(seed)
        return Q0, A, b, ()

    def dispatch(carry, dev, c):
        Q0, A, b, user_blocks = carry
        wire, lc = dev
        A, b, blk = accums[c](A, b, Q0, lc, *wire)
        # chunk progress for the telemetry plane: ALS has no per-step
        # loss (normal equations), so progress is edges accumulated
        e0, e1 = spans[c]
        trainwatch.record_steps(0, examples=e1 - e0)
        return Q0, A, b, user_blocks + (blk,)

    def fin(carry, devs):
        Q0, A, b, user_blocks = carry
        return finalize(A, b, Q0, extra["cu"], extra["ci"], user_blocks,
                        tuple(d[0] for d in devs),
                        tuple(d[1] for d in devs))

    return stream_feed(
        list(zip(spans, local_slices)),
        encode=encode, put=put, put_extra=put_extra,
        init_carry=init_carry, dispatch=dispatch, finalize=fin,
        stats=stats, encode_stat_key="pack_s",
    )


def _nibble_pack(codes: np.ndarray) -> np.ndarray:
    """Pack uint8 codes ≤ 15 two-per-byte: byte k = edge 2k (low nibble)
    | edge 2k+1 (high nibble). Mirrors ``decode_ratings('u4')``."""
    n = len(codes)
    if n % 2:
        codes = np.concatenate([codes, np.zeros(1, np.uint8)])
    pair = codes.reshape(-1, 2)
    return (pair[:, 0] | (pair[:, 1] << 4)).astype(np.uint8)


def _planes(idx: np.ndarray, n_pad: int):
    """(low, high) item wire planes: uint16 alone below 2^16, uint16 +
    uint8 high plane below 2^24 (3 B/id instead of 4), raw int32 beyond.
    The empty high plane means "unused"."""
    none = np.zeros(0, np.uint8)
    if n_pad < 65536:
        return idx.astype(np.uint16), none
    if n_pad < (1 << 24):
        return (
            (idx & 0xFFFF).astype(np.uint16),
            (idx >> 16).astype(np.uint8),
        )
    return idx, none


def _u8p(a: np.ndarray):
    import ctypes

    return _ptr(a, np.uint8, ctypes.c_uint8)


def _np_deltas(ids: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Per-edge gap to the previous same-segment id (first edge of each
    segment gaps from 0). Numpy reference for the native delta encoder."""
    E = len(ids)
    cnt = counts[counts > 0].astype(np.int64)
    starts = np.zeros(len(cnt), np.int64)
    np.cumsum(cnt[:-1], out=starts[1:])
    prev = np.empty(E, np.int32)
    prev[0] = 0
    prev[1:] = ids[:-1]
    prev[starts] = 0
    return ids.astype(np.int32) - prev


def _delta_wire_size(
    ids: np.ndarray, counts: np.ndarray
) -> Optional[Tuple[int, int]]:
    """``(wire_bytes, n_ovf)`` for the delta12 encoding WITHOUT
    materializing it (one count pass), or None when the encoding is
    inapplicable (ids not segment-sorted, or a gap ≥ 2^16)."""
    E = len(ids)
    if E == 0:
        return 0, 0
    native = _native_packer()
    if native is not None:
        cnt64 = np.ascontiguousarray(counts, np.int64)
        n_ovf = int(native.als_delta_count(
            _i32p(ids), _i64p(cnt64), len(cnt64)
        ))
        if n_ovf < 0:
            return None
    else:
        delta = _np_deltas(ids, counts)
        if len(delta) and (
            int(delta.min()) < 0 or int(delta.max()) >= 65536
        ):
            return None
        n_ovf = int((delta > 0xFFF).sum())
    return E + (E + 1) // 2 + 5 * n_ovf, n_ovf


def _encode_items_delta(ids: np.ndarray, counts: np.ndarray,
                        n_ovf: Optional[int] = None):
    """12-bit delta item wire over a (user, item)-sorted edge slice.

    ``counts`` segments ``ids`` into per-user runs (zero entries allowed;
    nonzero entries must sum to ``len(ids)``). Each edge ships the gap to
    the previous item of the same user (the first edge of a run ships its
    absolute id) as u8 low byte + nibble-packed high 4 bits — 1.5 B/edge
    — plus a sparse overflow list carrying ``delta >> 12`` for the rare
    gaps ≥ 4096. Exact for any id space < 2^16 (see
    ``_make_math.decode_items``). Native single-pass encoder when the
    toolchain is available; the numpy path is the format's reference.
    Returns ``(d_lo, d_hi, ovf_idx i32, ovf_val u8, wire_bytes)``.
    """
    E = len(ids)
    if E == 0:
        z8 = np.zeros(0, np.uint8)
        return z8, z8, np.zeros(0, np.int32), z8, 0
    native = _native_packer()
    if native is not None:
        cnt64 = np.ascontiguousarray(counts, np.int64)
        if n_ovf is None:  # caller may pass _delta_wire_size's count
            n_ovf = int(native.als_delta_count(
                _i32p(ids), _i64p(cnt64), len(cnt64)
            ))
        if n_ovf >= 0:
            d_lo = np.empty(E, np.uint8)
            d_hi = np.zeros((E + 1) // 2, np.uint8)
            ovf_idx = np.empty(n_ovf, np.int32)
            ovf_val = np.empty(n_ovf, np.uint8)
            native.als_delta_fill(
                _i32p(ids), _i64p(cnt64), len(cnt64), E,
                _u8p(d_lo), _u8p(d_hi), _i32p(ovf_idx), _u8p(ovf_val),
            )
            bytes_ = (d_lo.nbytes + d_hi.nbytes + ovf_idx.nbytes
                      + ovf_val.nbytes)
            return d_lo, d_hi, ovf_idx, ovf_val, bytes_
    delta = _np_deltas(ids, counts)
    ovf = np.nonzero(delta > 0xFFF)[0]
    d_lo = (delta & 0xFF).astype(np.uint8)
    d_hi = _nibble_pack(((delta >> 8) & 0xF).astype(np.uint8))
    ovf_idx = ovf.astype(np.int32)
    ovf_val = (delta[ovf] >> 12).astype(np.uint8)
    bytes_ = d_lo.nbytes + d_hi.nbytes + ovf_idx.nbytes + ovf_val.nbytes
    return d_lo, d_hi, ovf_idx, ovf_val, bytes_


def _encode_ratings(r_sorted: np.ndarray) -> Tuple[np.ndarray, str]:
    """Choose the densest lossless rating wire format.

    Returns ``(wire array, kind)`` where kind ∈ {u4, u8, f16, f32}:
    nibble-packed half-star codes (2 edges/byte — MovieLens's 0.5..5.0
    grid and implicit r=1 both qualify), byte codes to 127.5 stars, fp16
    when that cast is exact, else raw f32. The decode lives in
    ``_make_math.decode_ratings``; every kind round-trips exactly. The
    grid check + byte coding is one fused native pass when available
    (the numpy pipeline was ~10% of the whole host pack)."""
    native = _native_packer()
    if native is not None and r_sorted.size:
        codes = np.empty(r_sorted.size, np.uint8)
        mx = native.als_rating_codes(
            _f32p(r_sorted), r_sorted.size, _u8p(codes)
        )
        if mx >= 0:
            if mx <= 15:
                return _nibble_pack(codes), "u4"
            return codes, "u8"
    else:
        r2 = r_sorted * np.float32(2.0)
        if r2.size and np.all(r2 == np.round(r2)) \
                and float(r2.min()) >= 0.0:
            if float(r2.max()) <= 15.0:
                return _nibble_pack(r2.astype(np.uint8)), "u4"
            if float(r2.max()) <= 255.0:
                return r2.astype(np.uint8), "u8"
    r16 = r_sorted.astype(np.float16)
    if np.array_equal(r16.astype(np.float32), r_sorted):
        return r16, "f16"
    return r_sorted, "f32"


def _sort_edges_by_user(user_idx, item_idx, rating, n_edges, U_pad,
                        counts_u):
    """(user, item)-sorted item/rating columns: native two-pass sort
    (counting sort by user + per-adjacency stable item sort) with a numpy
    lexsort fallback. Item-sorted adjacencies are what make the delta
    item wire dense AND improve factor-gather locality on device; ALS
    itself is order-invariant within a user."""
    native = _native_packer()
    if native is not None:
        i_sorted = np.empty(n_edges, np.int32)
        r_sorted = np.empty(n_edges, np.float32)
        native.als_sort_by_entity(
            _i32p(user_idx), _i32p(item_idx), _f32p(rating),
            n_edges, U_pad, _i64p(counts_u),
            _i32p(i_sorted), _f32p(r_sorted),
        )
        rc = native.als_sort_within_entity(
            _i32p(i_sorted), _f32p(r_sorted), U_pad, _i64p(counts_u)
        )
        if rc != 0:  # a single entity with ≥2^32 edges: the radix
            # sorter's 32-bit cursors would wrap, so it refuses
            # wholesale. Training is order-invariant so this is safe,
            # but the delta wire then won't apply (negative gaps →
            # planes fallback) — say so instead of silently diverging
            # from the numpy lexsort path.
            import logging

            logging.getLogger("pio_tpu.als").warning(
                "within-user item sort skipped (an entity exceeds "
                "2^24 edges); item wire falls back to planes"
            )
    else:
        order = np.lexsort((item_idx, user_idx))
        i_sorted = np.ascontiguousarray(item_idx[order])
        r_sorted = np.ascontiguousarray(rating[order])
    return i_sorted, r_sorted


def _choose_item_wire(i_sorted, counts_u, I_pad, n_edges):
    """Pick the denser lossless item wire: uint16/24/32 planes vs 12-bit
    deltas over the (user, item)-sorted adjacency, sized by a count-only
    pass (PIO_TPU_ALS_ITEM_WIRE overrides: auto/delta12/planes).
    Returns (item_wire, n_ovf, edge_item_bytes)."""
    item_env = knobs.knob_str("PIO_TPU_ALS_ITEM_WIRE")
    plane_width = 2 if I_pad < 65536 else (3 if I_pad < 2 ** 24 else 4)
    n_ovf = None
    delta_bytes = None
    if I_pad < 65536 and item_env in ("auto", "delta12"):
        sized = _delta_wire_size(i_sorted, counts_u)
        if sized is not None:
            delta_bytes, n_ovf = sized
            if item_env == "delta12" or delta_bytes < 2 * n_edges:
                return "delta12", n_ovf, delta_bytes
    return "planes", n_ovf, plane_width * n_edges


def _run_mesh_compact(config, mesh, axis, n_shards, user_idx, item_idx,
                      rating, n_edges, U_pad, I_pad, w_user, w_item,
                      counts_layout, trainer, seed, stats):
    """Multi-shard training over the COMPACT edge wire.

    The host link (PCIe on a TPU VM, a tunnel here) is the slow hop and
    ICI the fast one, so the wire crosses the host link exactly once:
    every edge-indexed array ships SHARDED over the mesh axis (each
    device receives 1/n of ~2 B/edge), and the jitted trainer
    re-replicates them with an all-gather that rides ICI before the
    on-device dual blocked-layout construction (``device_pack``). The
    constructed block arrays come out sharded by block index — the
    layout the shard_map half-steps consume — so block CONTENT never
    needed host-side shard routing at all (the round-3 design note in
    docs/parallelism.md). Bit-identical to the host-packed blocked-f32
    path by the device_pack parity guarantee."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    t0 = monotonic_s()
    counts_u, chunk_user, S_u = counts_layout(user_idx, w_user, U_pad)
    counts_i, chunk_item, S_i = counts_layout(item_idx, w_item, I_pad)
    if S_u * w_user >= 2 ** 31 or S_i * w_item >= 2 ** 31:
        raise ValueError(
            "edge set too large for int32 block addressing; raise "
            "block width or shard the edge set first"
        )
    counts_u = np.ascontiguousarray(counts_u, np.int64)
    i_sorted, r_sorted = _sort_edges_by_user(
        user_idx, item_idx, rating, n_edges, U_pad, counts_u
    )
    r_ship, rating_wire = _encode_ratings(r_sorted)
    item_wire, n_ovf, item_bytes = _choose_item_wire(
        i_sorted, counts_u, I_pad, n_edges
    )
    if item_wire == "delta12":
        i_ship, i_hi, ovf_idx, ovf_val, _ = _encode_items_delta(
            i_sorted, counts_u, n_ovf=n_ovf
        )
    else:
        i_ship, i_hi = _planes(i_sorted, I_pad)
        ovf_idx = np.zeros(0, np.int32)
        ovf_val = np.zeros(0, np.uint8)
    # chunked shipment (the single-device stream discipline applied to
    # the sharded puts): slice each ENCODED array into ≤8 spans so the
    # per-device transfers of span k+1 pipeline behind span k instead of
    # one monolithic put per array serializing the whole h2d. Slicing
    # happens after encoding, so the wire BYTES are unchanged — the
    # trainer splices the trimmed spans back together before decoding.
    edge_bytes = item_bytes + r_ship.nbytes
    n_stream = _n_stream_chunks(edge_bytes, "PIO_TPU_ALS_STREAM_MB")

    def spans_of(a):
        if n_stream == 1 or len(a) == 0:
            return [a]
        bounds = [len(a) * c // n_stream for c in range(n_stream + 1)]
        return [a[s:e] for s, e in zip(bounds[:-1], bounds[1:]) if e > s]

    lo_spans = spans_of(i_ship)
    hi_spans = spans_of(i_hi)
    r_spans = spans_of(r_ship)

    if stats is not None:
        stats["pack_s"] = monotonic_s() - t0
        stats["wire_bytes"] = (
            item_bytes + r_ship.nbytes + 4 * (U_pad + I_pad)
        )
        stats["encoding"] = f"{rating_wire}+{item_wire}"
        stats["n_stream"] = max(len(lo_spans), len(r_spans))

    run = trainer(
        chunk_user, chunk_item, (S_u, w_user, S_i, w_item),
        rating_wire, item_wire,
        mesh_wire_lens=(
            tuple(len(s) for s in lo_spans),
            tuple(len(s) for s in hi_spans),
            tuple(len(s) for s in r_spans),
        ),
    )
    shard1 = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())

    def pad_to_shards(a):
        p = (-len(a)) % n_shards
        return np.concatenate([a, np.zeros(p, a.dtype)]) if p else a

    t0 = monotonic_s()
    small = (
        jax.device_put(counts_u.astype(np.int32), repl),
        jax.device_put(np.ascontiguousarray(counts_i, np.int32), repl),
        jax.device_put(ovf_idx, repl),
        jax.device_put(ovf_val, repl),
    )
    # interleave the arrays' spans so early spans of every array are in
    # flight together; per-span timings land in stats on profiled runs
    lo_dev: list = []
    hi_dev: list = []
    r_dev: list = []
    chunk_ts = []
    for parts in itertools.zip_longest(lo_spans, hi_spans, r_spans):
        tc = monotonic_s()
        group = []
        for part, dev in zip(parts, (lo_dev, hi_dev, r_dev)):
            if part is not None:
                dev.append(jax.device_put(pad_to_shards(part), shard1))
                group.append(dev[-1])
        if stats is not None:
            jax.block_until_ready(group)
            chunk_ts.append(round(monotonic_s() - tc, 3))
    args = (*small[:2], tuple(lo_dev), tuple(hi_dev), *small[2:],
            tuple(r_dev))
    if stats is not None:
        jax.block_until_ready(args)
        stats["h2d_s"] = monotonic_s() - t0
        stats["h2d_chunk_s"] = chunk_ts
        t0 = monotonic_s()
        P_f, Q_f = run(*args, seed)
        jax.block_until_ready((P_f, Q_f))
        stats["device_s"] = monotonic_s() - t0
    else:
        P_f, Q_f = run(*args, seed)
    return P_f, Q_f


def train_als(
    ctx: ComputeContext,
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    rating: np.ndarray,
    n_users: int,
    n_items: int,
    config: ALSConfig = ALSConfig(),
    stats: Optional[dict] = None,
) -> ALSFactors:
    """Train ALS over the context's mesh (or a single device).

    Entity counts are padded to mesh multiples; factor rows beyond the true
    counts are dropped on the way out.

    ``stats``, when a dict, is filled with a per-phase breakdown —
    ``{pack_s, wire_bytes, encoding, n_stream, h2d_s, device_s}`` — by
    BLOCKING between the host-pack / host→device / device-compute phases.
    That serialization disables the streamed path's transfer/compute
    overlap, so pass ``stats`` only on profiling runs, not timed ones.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    if len(user_idx) == 0:
        raise ValueError("ALS needs at least one rating")

    mesh = ctx.mesh
    axis = ctx.batch_axis
    n_shards = mesh.shape[axis] if mesh is not None else 1
    K = config.rank
    n_edges = len(user_idx)

    user_idx = np.asarray(user_idx, np.int32)
    item_idx = np.asarray(item_idx, np.int32)
    rating = np.asarray(rating, np.float32)

    U_pad = _round_up(max(n_users, 1), n_shards)
    I_pad = _round_up(max(n_items, 1), n_shards)

    # telemetry window: ALS "steps" are the alternating solve iterations
    # (no per-step loss — normal equations); edges count as examples
    trainwatch.begin_algo(
        "als", total_steps=int(config.iterations),
        per_device_bytes=(U_pad + I_pad) * K * 4 // max(1, n_shards),
    )
    edges_recorded = False

    w_user = config.block_width or _auto_width(n_edges, n_users)
    w_item = config.block_width or _auto_width(n_edges, n_items)

    def _counts_layout(ent, width, n_entities):
        """counts + (chunk, padded block count S) for one side."""
        native = _native_packer()
        if native is not None:
            counts = np.zeros(n_entities, np.int64)
            n_blocks = int(native.als_pack_count(
                _i32p(ent), len(ent), n_entities, width, _i64p(counts)
            ))
            if n_blocks < 0:
                raise ValueError("entity index out of range")
        else:
            counts = np.bincount(ent, minlength=n_entities)
            n_blocks = int((-(-counts // width)).sum())
        per_shard = max(1, -(-n_blocks // n_shards))
        chunk = min(config.blocks_per_chunk, _round_up(per_shard, 8))
        pad_to = n_shards * chunk
        # single home for the padded block count — the numpy packer is
        # handed S directly so both paths cannot drift apart
        S = max(pad_to, _round_up(max(n_blocks, 1), pad_to))
        return counts, chunk, S

    def _layout(ent, other, rat, width, n_entities):
        """Host-packed blocks (the multi-shard path; single-device packs
        on device instead — see _build_trainer's COO variant)."""
        native = _native_packer()
        counts, chunk, S = _counts_layout(ent, width, n_entities)
        if native is not None:
            block_ent = np.empty(S, np.int32)
            block_other = np.empty(S * width, np.int32)
            block_rating = np.empty(S * width, np.float32)
            native.als_pack_fill(
                _i32p(ent), _i32p(other), _f32p(rat), len(ent),
                n_entities, width, _i64p(counts), S,
                _i32p(block_ent), _i32p(block_other), _f32p(block_rating),
            )
            blocks = (
                block_ent,
                block_other.reshape(S, width),
                block_rating.reshape(S, width),
            )
        else:
            blocks = _pack_blocks(
                ent, other, rat, n_entities, width, S, counts=counts
            )
            assert blocks[0].shape[0] == S
        return blocks, chunk

    seed = np.uint32(config.seed)

    def _trainer(chunk_user, chunk_item, packed_shapes, rating_wire="f32",
                 item_wire="planes", mesh_wire_lens=None):
        # one call site for the long positional signature so the mesh and
        # single-device branches can never drift apart
        return _build_trainer(
            mesh, axis, config.iterations, float(config.reg),
            bool(config.implicit), float(config.alpha),
            chunk_user, chunk_item,
            _resolve_matmul_dtype(str(config.matmul_dtype)), str(config.solver),
            packed_shapes, K, U_pad, I_pad, rating_wire, item_wire,
            mesh_wire_lens,
        )

    if n_shards > 1:
        # wire policy: "compact" (default) ships the single-device delta/
        # plane+code wire — each device receives 1/n of it over the host
        # link (PCIe/DCN, the slow hop) and the jitted trainer re-
        # replicates it over ICI (fast) before the on-device dual blocked-
        # layout construction, whose sharded outputs feed the shard_map
        # half-steps. "blocked" keeps the host-packed f32 block shipment
        # (~16× the bytes/edge) — retained as the equality reference.
        mesh_wire = knobs.knob_str("PIO_TPU_ALS_MESH_WIRE")
        if mesh_wire in ("auto", "compact"):
            P_f, Q_f = _run_mesh_compact(
                config, mesh, axis, n_shards, user_idx, item_idx, rating,
                n_edges, U_pad, I_pad, w_user, w_item, _counts_layout,
                _trainer, seed, stats,
            )
        else:
            t0 = monotonic_s()
            # canonical (user, item) edge order BEFORE packing: block
            # content becomes input-order-invariant and bit-identical to
            # the compact path's on-device construction (which composes
            # through a stable sort of the same canonical stream)
            cu0 = np.ascontiguousarray(
                np.bincount(user_idx, minlength=U_pad), np.int64
            )
            i_srt, r_srt = _sort_edges_by_user(
                user_idx, item_idx, rating, n_edges, U_pad, cu0
            )
            u_srt = np.repeat(
                np.arange(U_pad, dtype=np.int32), cu0
            )
            by_user, chunk_user = _layout(
                u_srt, i_srt, r_srt, w_user, U_pad
            )
            by_item, chunk_item = _layout(
                i_srt, u_srt, r_srt, w_item, I_pad
            )
            run = _trainer(chunk_user, chunk_item, None)
            blk = NamedSharding(mesh, P(axis))
            blk2 = NamedSharding(mesh, P(axis, None))
            put_blocks = lambda t: (
                jax.device_put(t[0], blk),
                jax.device_put(t[1], blk2),
                jax.device_put(t[2], blk2),
            )
            if stats is not None:
                stats["pack_s"] = monotonic_s() - t0
                stats["wire_bytes"] = sum(
                    a.nbytes for t in (by_user, by_item) for a in t
                )
                stats["encoding"] = "blocked-f32"
                stats["n_stream"] = 1
                t0 = monotonic_s()
                u_dev, i_dev = put_blocks(by_user), put_blocks(by_item)
                jax.block_until_ready((u_dev, i_dev))
                stats["h2d_s"] = monotonic_s() - t0
                t0 = monotonic_s()
                P_f, Q_f = run(u_dev, i_dev, seed)
                jax.block_until_ready((P_f, Q_f))
                stats["device_s"] = monotonic_s() - t0
            else:
                P_f, Q_f = run(
                    put_blocks(by_user), put_blocks(by_item), seed
                )
    else:
        # Single-device path: ship the COO edges pre-sorted by user (see
        # _build_trainer's COO variant for the wire format) and let the
        # jitted trainer build both blocked layouts on device. Crucial on
        # hosts where the device link is slow or shares a core with the
        # process (the tunneled-TPU case). Above a wire-size threshold the
        # shipment is STREAMED in chunks overlapped with the chunk packs +
        # iteration-1 accumulation (_build_stream_trainer).
        t0 = monotonic_s()
        counts_u, chunk_user, S_u = _counts_layout(user_idx, w_user, U_pad)
        counts_i, chunk_item, S_i = _counts_layout(item_idx, w_item, I_pad)
        if S_u * w_user >= 2 ** 31 or S_i * w_item >= 2 ** 31:
            raise ValueError(
                "edge set too large for int32 block addressing; "
                "use a multi-device mesh"
            )

        counts_u = np.ascontiguousarray(counts_u, np.int64)
        i_sorted, r_sorted = _sort_edges_by_user(
            user_idx, item_idx, rating, n_edges, U_pad, counts_u
        )
        r_ship, rating_wire = _encode_ratings(r_sorted)
        # item wire sized by a count-only pass so nothing is materialized
        # before the stream/monolithic split
        item_wire, n_ovf, item_bytes = _choose_item_wire(
            i_sorted, counts_u, I_pad, n_edges
        )
        use_delta = item_wire == "delta12"
        edge_bytes = item_bytes + r_ship.nbytes
        if stats is not None:
            stats["pack_s"] = monotonic_s() - t0
            stats["wire_bytes"] = (
                edge_bytes + 4 * (U_pad + I_pad)  # + the two count arrays
            )
            stats["encoding"] = f"{rating_wire}+{item_wire}"

        # stream threshold: chunked double-buffered shipment once the edge
        # wire exceeds ~one chunk (default 8 MiB); tiny runs keep the
        # single-dispatch path. <= 0 disables streaming entirely.
        n_stream = _n_stream_chunks(edge_bytes, "PIO_TPU_ALS_STREAM_MB")
        if config.iterations < 1:
            # the streamed trainer fuses iteration 1's user half-step into
            # the chunk accumulation, so it can't express "0 iterations";
            # route those runs through the monolithic path
            n_stream = 1
        if stats is not None:
            stats["n_stream"] = max(1, n_stream)
        if n_stream > 1:
            trainwatch.set_stream(True, n_stream)
            edges_recorded = True  # _run_streamed records per chunk
            P_f, Q_f = _run_streamed(
                config, K, U_pad, I_pad, w_user, w_item, S_i, chunk_item,
                counts_u, counts_i, i_sorted, r_ship, rating_wire,
                item_wire, n_stream, seed, stats,
            )
        else:
            if use_delta:
                i_ship, i_hi, ovf_idx, ovf_val, _ = _encode_items_delta(
                    i_sorted, counts_u, n_ovf=n_ovf
                )
            else:
                i_ship, i_hi = _planes(i_sorted, I_pad)
                ovf_idx = np.zeros(0, np.int32)
                ovf_val = np.zeros(0, np.uint8)
            run = _trainer(
                chunk_user, chunk_item, (S_u, w_user, S_i, w_item),
                rating_wire, item_wire,
            )
            args = (
                counts_u.astype(np.int32),
                np.ascontiguousarray(counts_i, np.int32),
                i_ship, i_hi, ovf_idx, ovf_val, r_ship,
            )
            if stats is not None:
                t0 = monotonic_s()
                args = tuple(jax.device_put(a) for a in args)
                jax.block_until_ready(args)
                stats["h2d_s"] = monotonic_s() - t0
                t0 = monotonic_s()
                P_f, Q_f = run(*args, seed)
                jax.block_until_ready((P_f, Q_f))
                stats["device_s"] = monotonic_s() - t0
            else:
                P_f, Q_f = run(*args, seed)

    P_f, Q_f = jax.device_get((P_f, Q_f))
    trainwatch.record_steps(
        int(config.iterations),
        examples=0 if edges_recorded else n_edges,
    )
    return ALSFactors(
        user_factors=np.asarray(P_f)[:n_users],
        item_factors=np.asarray(Q_f)[:n_items],
    )


def predict_scores(
    user_factors: np.ndarray, item_factors: np.ndarray, user: int
) -> np.ndarray:
    """Scores of every item for one user (host-side; serving keeps factors
    on device — see the recommendation template)."""
    return user_factors[user] @ item_factors.T


def top_n(
    scores: np.ndarray, n: int, exclude: Optional[np.ndarray] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Top-n item indices + scores, optionally excluding seen items."""
    s = scores.copy()
    if exclude is not None and len(exclude):
        s[exclude] = -np.inf
    n = min(n, len(s))
    idx = np.argpartition(-s, n - 1)[:n] if n < len(s) else np.argsort(-s)
    idx = idx[np.argsort(-s[idx])]
    return idx, s[idx]
