"""ALS matrix factorization — TPU-native replacement for Spark MLlib ALS.

The reference's Recommendation/Similar-Product templates call
``org.apache.spark.mllib.recommendation.ALS.train`` / ``trainImplicit``
(reference: examples/scala-parallel-recommendation ALSAlgorithm.scala,
UNVERIFIED path; see SURVEY.md). MLlib's ALS block-partitions the rating
matrix into in/out-link blocks and shuffles factor updates between executors
every half-iteration. This module is the TPU-first re-design:

- Host-side, the COO rating list is packed ONCE per orientation (by-user and
  by-item) into **fixed-width dense blocks**: edges sorted by entity, each
  entity's adjacency split into ``[block_width]`` slices, padded slots
  carrying weight 0. Static shapes, no ragged gathers.
- One half-iteration (e.g. the user update) is::

      A_u = Σ_{i ∈ R(u)} q_i q_iᵀ + λI        b_u = Σ_i r_ui q_i
      p_u = A_u⁻¹ b_u

  computed per block as one **batched MXU matmul**
  (``einsum('bwk,bwl->bkl')`` over ``[blocks, width, K]`` gathered factors)
  followed by a ``segment_sum`` of the ~E/width block partials onto entities
  with ``indices_are_sorted=True`` — the scatter is over blocks, not edges,
  so the VPU-hostile part shrinks by the block width while the FLOPs ride
  the systolic array.
- Cross-device combine is ``psum_scatter`` (reduce-scatter) over the entity
  dimension: each device sums partial normal equations from its block shard,
  receives 1/D of the entities, solves its slice with a batched
  ``jnp.linalg.solve``, and ``all_gather``s the factors back. Two ICI
  collectives per half-step replace MLlib's shuffle — the scaling-book
  recipe for data-parallel normal equations.
- Implicit feedback (Hu-Koren-style): confidence c = 1 + α·r, preference 1;
  the shared ``QᵀQ`` gram term is one MXU matmul, and only the
  ``(c-1) q qᵀ`` correction rides the blocked path.

The jitted trainer is cached per (mesh, static config) so repeated
``train_als`` calls — serving retrains, evaluation sweeps, benchmarks —
recompile only on shape changes.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional, Tuple

import numpy as np

from pio_tpu.utils.numutil import round_up as _round_up

from pio_tpu.parallel.context import ComputeContext


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    rank: int = 10
    iterations: int = 10
    reg: float = 0.1
    implicit: bool = False
    alpha: float = 40.0
    #: edges per dense block; None → power of two near half the mean degree
    #: (bounds padding waste at ~width/2 per entity)
    block_width: Optional[int] = None
    #: blocks per scan step — bounds the [chunk, width, K] HBM intermediate
    blocks_per_chunk: int = 4096
    #: dtype for the factor gather + normal-equation matmuls ("bfloat16"
    #: or "float32"). bf16 is the MXU's native rate and halves the gather
    #: bandwidth; accumulation and the solves stay float32 either way.
    matmul_dtype: str = "bfloat16"
    #: per-entity K×K solver: "auto" uses exact Cholesky for small entity
    #: counts and switches to Jacobi-preconditioned CG (matmul-only, rides
    #: the MXU) above ~32k entities, where XLA's batched factorizations
    #: serialize badly on TPU (LU at MovieLens-25M user count: ~780 ms per
    #: half-step; CG: ~90 ms). Explicit "cg" / "cholesky" / "lu" override.
    solver: str = "auto"
    seed: int = 0


@dataclasses.dataclass
class ALSFactors:
    """Trained factors (host numpy; replicated on device during training)."""

    user_factors: np.ndarray  # [n_users, rank]
    item_factors: np.ndarray  # [n_items, rank]




def _native_packer():
    """The C++ packer (pio_tpu/native/als_pack.cpp), or None when no
    toolchain is available (tests cover both paths)."""
    if os.environ.get("PIO_TPU_NO_NATIVE"):
        return None
    try:
        from pio_tpu.native import als_pack_lib

        return als_pack_lib()
    except Exception:  # NativeUnavailable, or a broken toolchain
        return None


def _ptr(a: np.ndarray, dtype, ctype):
    """C pointer to a's buffer. Asserts rather than converts: a silent
    ascontiguousarray copy would send native WRITES into a discarded
    temporary (these helpers are used for output buffers too)."""
    import ctypes

    assert a.dtype == dtype and a.flags.c_contiguous, (a.dtype, a.flags)
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _i32p(a: np.ndarray):
    import ctypes

    return _ptr(a, np.int32, ctypes.c_int32)


def _i64p(a: np.ndarray):
    import ctypes

    return _ptr(a, np.int64, ctypes.c_int64)


def _f32p(a: np.ndarray):
    import ctypes

    return _ptr(a, np.float32, ctypes.c_float)


def _auto_width(n_edges: int, n_entities: int) -> int:
    # Narrow blocks: padding waste (≈ width/2 per entity) costs real
    # host→device bytes, which dominate over the extra scatter rows on the
    # tunneled/PCIe link (measured optimum 16-64 at MovieLens scales).
    mean_deg = max(1.0, n_edges / max(1, n_entities))
    w = 1 << int(np.ceil(np.log2(max(8.0, mean_deg / 4))))
    return int(min(64, max(16, w)))


def _pack_blocks(
    ent_idx: np.ndarray,
    other_idx: np.ndarray,
    rating: np.ndarray,
    n_entities: int,
    width: int,
    pad_blocks_to: int,
    counts: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack a COO edge list into dense [n_blocks, width] CSR-style blocks.

    Returns (block_ent [S], block_other [S,W], block_rating [S,W]);
    ``block_ent`` ascending so downstream segment sums take the
    sorted-indices fast path. Padded slots carry ``other = -1`` — the
    validity mask is derived on device from the sign, so no separate mask
    array rides the host→device link.
    """
    order = np.argsort(ent_idx, kind="stable")
    e = ent_idx[order]
    if counts is None:
        counts = np.bincount(e, minlength=n_entities)
    blocks_per_ent = -(-counts // width)  # zero for empty entities
    n_blocks = int(blocks_per_ent.sum())
    S = max(pad_blocks_to, _round_up(max(n_blocks, 1), pad_blocks_to))

    block_start = np.zeros(n_entities + 1, dtype=np.int64)
    np.cumsum(blocks_per_ent, out=block_start[1:])
    edge_start = np.zeros(n_entities + 1, dtype=np.int64)
    np.cumsum(counts, out=edge_start[1:])

    # position of each (sorted) edge within its entity's adjacency
    pos = np.arange(len(e), dtype=np.int64) - edge_start[e]
    flat = (block_start[e] + pos // width) * width + pos % width

    block_other = np.full(S * width, -1, dtype=np.int32)
    block_rating = np.zeros(S * width, dtype=np.float32)
    block_other[flat] = other_idx[order]
    block_rating[flat] = rating[order]

    # padding blocks target the LAST entity (masked out) to keep ids
    # ascending for the segment-sum sorted fast path
    block_ent = np.full(S, n_entities - 1, dtype=np.int32)
    reps = np.repeat(np.arange(n_entities, dtype=np.int32), blocks_per_ent)
    block_ent[: len(reps)] = reps
    return (
        block_ent,
        block_other.reshape(S, width),
        block_rating.reshape(S, width),
    )


@functools.lru_cache(maxsize=32)
def _build_trainer(mesh, axis: str, iterations: int, reg: float,
                   implicit: bool, alpha: float,
                   chunk_user: int, chunk_item: int,
                   matmul_dtype: str = "bfloat16", solver: str = "cg",
                   packed_shapes=None, rank: int = 0,
                   U_pad: int = 0, I_pad: int = 0):
    """Jitted ALS trainer for one (mesh, static-config) combination.

    The returned function takes the two packed-block layouts + initial
    factors; shapes specialize inside jax.jit's own cache.
    """
    import jax
    import jax.numpy as jnp

    lam = jnp.float32(reg)
    alpha_f = jnp.float32(alpha)
    mm_dtype = jnp.dtype(matmul_dtype)

    def partial_normal_eq(block_ent, block_other, block_r, factors,
                          n_entities, chunk, varying_axis=None):
        """Blocked scan: Σ w·q qᵀ and Σ rhs·q per entity (one shard)."""
        K = factors.shape[1]
        # cast ONCE per half-step: the scan then gathers from the low-
        # precision table (half the HBM traffic) and the einsums hit the
        # MXU at its native bf16 rate; accumulation stays f32 below
        factors_mm = factors.astype(mm_dtype)

        def chunk_step(carry, ch):
            A, b = carry
            ent, other, r_c = ch
            # padded slots are other == -1; validity derives from the sign
            m_c = (other >= 0).astype(jnp.float32)
            q = factors_mm[jnp.maximum(other, 0)]  # [chunk, W, K] gather
            if implicit:
                # confidence c = 1 + α r; correction weight (c-1)·mask
                w = alpha_f * r_c * m_c
                rhs = (1.0 + alpha_f * r_c) * m_c  # c · preference(=1)
            else:
                w = m_c
                rhs = r_c * m_c
            # batched MXU matmul: [chunk, K, W] @ [chunk, W, K], f32 acc
            A_blk = jnp.einsum(
                "cwk,cwl->ckl", q * w[:, :, None].astype(mm_dtype), q,
                preferred_element_type=jnp.float32,
            )
            b_blk = jnp.einsum(
                "cwk,cw->ck", q, rhs.astype(mm_dtype),
                preferred_element_type=jnp.float32,
            )
            A = A + jax.ops.segment_sum(
                A_blk, ent, num_segments=n_entities, indices_are_sorted=True
            )
            b = b + jax.ops.segment_sum(
                b_blk, ent, num_segments=n_entities, indices_are_sorted=True
            )
            return (A, b), None

        S = block_ent.shape[0]
        n_chunks = S // chunk
        chunks = tuple(
            x.reshape(n_chunks, chunk, *x.shape[1:])
            for x in (block_ent, block_other, block_r)
        )
        A0 = jnp.zeros((n_entities, K, K), jnp.float32)
        b0 = jnp.zeros((n_entities, K), jnp.float32)
        if varying_axis is not None:
            # Inside shard_map the carry becomes device-varying after the
            # first chunk; mark the zeros accordingly so scan types match.
            A0 = jax.lax.pcast(A0, (varying_axis,), to="varying")
            b0 = jax.lax.pcast(b0, (varying_axis,), to="varying")
        (A, b), _ = jax.lax.scan(chunk_step, (A0, b0), chunks)
        return A, b

    def _cg_solve(A, b):
        """Batched Jacobi-preconditioned CG — matmul-only, so it rides the
        MXU instead of XLA's serialized batched factorizations (measured
        ~8× faster than LU at MovieLens-25M entity counts). A is SPD
        (normal equations + λI); K+8 iterations ≥ the Krylov dimension
        with margin for f32 rounding on ill-conditioned systems."""
        K = b.shape[1]
        inv_d = 1.0 / jnp.diagonal(A, axis1=1, axis2=2)
        x = b * inv_d
        r = b - jnp.einsum("nkl,nl->nk", A, x)
        z = r * inv_d
        p = z
        rz = (r * z).sum(-1)

        def body(_, st):
            x, r, p, rz = st
            Ap = jnp.einsum("nkl,nl->nk", A, p)
            denom = (p * Ap).sum(-1)
            alpha_c = rz / jnp.where(denom != 0, denom, 1.0)
            x = x + alpha_c[:, None] * p
            r = r - alpha_c[:, None] * Ap
            z = r * inv_d
            rz2 = (r * z).sum(-1)
            beta = rz2 / jnp.where(rz != 0, rz, 1.0)
            p = z + beta[:, None] * p
            return (x, r, p, rz2)

        x, *_ = jax.lax.fori_loop(0, K + 8, body, (x, r, p, rz))
        return x

    def solve_block(A, b, gram):
        """Regularized batched solve on a block of entities."""
        K = b.shape[1]
        A = A + lam * jnp.eye(K, dtype=jnp.float32)[None, :, :]
        if implicit:
            A = A + gram[None, :, :]
        # "auto": exact Cholesky while it's cheap, CG at the batch sizes
        # where XLA's TPU factorizations serialize (A.shape[0] is static
        # at trace time, so this is a compile-time branch)
        if solver not in ("auto", "cg", "cholesky", "lu"):
            raise ValueError(
                f"unknown ALS solver {solver!r}; use auto/cg/cholesky/lu"
            )
        eff = solver
        if eff == "auto":
            eff = "cg" if A.shape[0] > 32768 else "cholesky"
        if eff == "cg":
            return _cg_solve(A, b)
        if eff == "cholesky":
            L = jnp.linalg.cholesky(A)
            y = jax.scipy.linalg.solve_triangular(
                L, b[:, :, None], lower=True
            )
            x = jax.scipy.linalg.solve_triangular(
                jnp.swapaxes(L, 1, 2), y, lower=False
            )
            return x[:, :, 0]
        return jnp.linalg.solve(A, b[:, :, None])[:, :, 0]

    def gram_of(factors):
        if implicit:
            return jnp.einsum("ik,il->kl", factors, factors)
        return jnp.zeros((factors.shape[1], factors.shape[1]), jnp.float32)

    if mesh is not None and mesh.shape[axis] > 1:
        from jax.sharding import PartitionSpec as P

        blk_spec = (P(axis), P(axis), P(axis))

        def half_step(ent, other, r, factors, n_entities, chunk):
            """shard_map body: block-parallel accumulate → reduce-scatter →
            local solve → all-gather (the MLlib-shuffle replacement)."""

            def body(ent, other, r, factors):
                A, b = partial_normal_eq(
                    ent, other, r, factors, n_entities, chunk,
                    varying_axis=axis,
                )
                # reduce-scatter the normal equations over the entity dim:
                # each device ends up owning n_entities/D rows, fully summed.
                A = jax.lax.psum_scatter(A, axis, scatter_dimension=0, tiled=True)
                b = jax.lax.psum_scatter(b, axis, scatter_dimension=0, tiled=True)
                new_local = solve_block(A, b, gram_of(factors))  # [n/D, K]
                return jax.lax.all_gather(new_local, axis, axis=0, tiled=True)

            # check_vma=False: after the tiled all_gather every device holds
            # identical factors, but the varying-axis type system can't
            # infer that replication statically.
            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=blk_spec + (P(),),
                out_specs=P(),
                check_vma=False,
            )(ent, other, r, factors)
    else:

        def half_step(ent, other, r, factors, n_entities, chunk):
            A, b = partial_normal_eq(
                ent, other, r, factors, n_entities, chunk
            )
            return solve_block(A, b, gram_of(factors))

    def run_body(by_user, by_item, seed):
        # factor init on device, inside the one compiled program:
        # MLlib-style small random factors keep AᵀA well-conditioned
        ku, ki = jax.random.split(jax.random.PRNGKey(seed))
        P_init = jax.random.normal(ku, (U_pad, rank), jnp.float32) * 0.01
        Q_init = jax.random.normal(ki, (I_pad, rank), jnp.float32) * 0.01

        def iteration(_, PQ):
            P_f, Q_f = PQ
            P_f = half_step(*by_user, Q_f, U_pad, chunk_user)
            Q_f = half_step(*by_item, P_f, I_pad, chunk_item)
            return (P_f, Q_f)

        return jax.lax.fori_loop(0, iterations, iteration, (P_init, Q_init))

    if packed_shapes is None:
        return jax.jit(run_body)

    # COO variant (single-device): ship the edge list ONCE, pre-sorted by
    # user on the host (native counting sort), and build BOTH blocked
    # layouts on device inside the same jit dispatch. Sorting host-side
    # means the per-edge USER ids never cross the wire at all — one
    # per-user counts array replaces them and the device rebuilds the id
    # column with a single repeat. With uint16 item planes and uint8
    # half-star rating codes the wire cost is ~3 B/edge (vs 12 B raw COO);
    # on a tunneled/slow host↔device link the transfer is the training
    # bottleneck, so wire bytes are throughput (measured: 175 MB → 66 MB
    # at MovieLens-25M).
    su, wu, si, wi = packed_shapes

    @jax.jit
    def run_packed(counts_u, counts_i, i_lo, i_hi, r, seed):
        # wire decode (all static dtype dispatch):
        #   item ids < 2^16 arrive uint16; < 2^24 as uint16 low plane +
        #   uint8 high plane (i_hi; zero-size when unused)
        #   ratings: uint8 = half-star code (2× the value), else fp16
        #   when that cast was lossless, else f32
        i32 = i_lo.astype(jnp.int32)
        if i_hi.shape[0]:
            i32 = i32 | (i_hi.astype(jnp.int32) << 16)
        if r.dtype == jnp.uint8:
            r32 = r.astype(jnp.float32) * jnp.float32(0.5)
        else:
            r32 = r.astype(jnp.float32)
        E = i_lo.shape[0]
        u32 = jnp.repeat(
            jnp.arange(U_pad, dtype=jnp.int32), counts_u,
            total_repeat_length=E,
        )
        # both degree histograms ride the wire (0.9 MB total) — the
        # on-device bincount is a 25M-edge scatter-add, the host count is
        # a pass the native packer already made
        by_user = device_pack(u32, i32, r32, U_pad, wu, su,
                              assume_sorted=True, counts=counts_u)
        by_item = device_pack(i32, u32, r32, I_pad, wi, si,
                              counts=counts_i)
        return run_body(by_user, by_item, seed)

    return run_packed


def device_pack(ent, oth, rat, n_entities: int, width: int, S: int,
                assume_sorted: bool = False, counts=None):
    """On-device COO→blocked-CSR packing (traceable; jnp throughout).

    Layout is bit-identical to the host packers (_pack_blocks /
    native als_pack_fill) — enforced by tests/test_als.py
    ``test_device_pack_matches_host_packers``. ``S``, ``width``, and
    ``n_entities`` are static. ``assume_sorted`` skips the stable argsort
    when the caller guarantees ``ent`` is already ascending (the
    counts-rebuilt user column is sorted by construction).

    Formulated as pure GATHERS: every [S, W] slot computes which edge (if
    any) it holds — block's entity via searchsorted over the block prefix
    sum, position within the entity's adjacency from the block offset —
    and gathers it, composing through the argsort permutation when the
    input isn't pre-sorted. The scatter formulation (`.at[flat].set` over
    the S·W slot space) measured ~3.2 s per 25M edges on v5e where the
    gathers take ~0.3 s: scatters serialize on TPU, gathers tile.
    """
    import jax.numpy as jnp

    if counts is None:
        counts = jnp.bincount(ent, length=n_entities)  # order-free
    else:
        counts = counts.astype(jnp.int32)  # caller-supplied (wire input)
    blocks = -(-counts // width)
    zero = jnp.zeros(1, counts.dtype)
    block_start = jnp.concatenate([zero, jnp.cumsum(blocks)])
    edge_start = jnp.concatenate([zero, jnp.cumsum(counts)])

    # per block: owning entity (padding blocks → last entity, masked out)
    bids = jnp.searchsorted(block_start[1:], jnp.arange(S), side="right")
    block_ent = jnp.minimum(bids, n_entities - 1).astype(jnp.int32)

    # per slot: position within the entity's adjacency, then edge index
    blk_in_ent = jnp.arange(S) - block_start[block_ent]  # [S]
    pos = blk_in_ent[:, None] * width + jnp.arange(width)[None, :]
    valid = pos < counts[block_ent][:, None]  # [S, W]
    src = jnp.where(valid, edge_start[block_ent][:, None] + pos, 0)
    if not assume_sorted:
        # compose through the stable sort permutation: one fused gather
        src = jnp.argsort(ent, stable=True)[src]
    block_other = jnp.where(valid, oth[src], jnp.int32(-1))
    block_rating = jnp.where(valid, rat[src], jnp.float32(0.0))
    return block_ent, block_other, block_rating


def train_als(
    ctx: ComputeContext,
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    rating: np.ndarray,
    n_users: int,
    n_items: int,
    config: ALSConfig = ALSConfig(),
) -> ALSFactors:
    """Train ALS over the context's mesh (or a single device).

    Entity counts are padded to mesh multiples; factor rows beyond the true
    counts are dropped on the way out.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    if len(user_idx) == 0:
        raise ValueError("ALS needs at least one rating")

    mesh = ctx.mesh
    axis = ctx.batch_axis
    n_shards = mesh.shape[axis] if mesh is not None else 1
    K = config.rank
    n_edges = len(user_idx)

    user_idx = np.asarray(user_idx, np.int32)
    item_idx = np.asarray(item_idx, np.int32)
    rating = np.asarray(rating, np.float32)

    U_pad = _round_up(max(n_users, 1), n_shards)
    I_pad = _round_up(max(n_items, 1), n_shards)

    w_user = config.block_width or _auto_width(n_edges, n_users)
    w_item = config.block_width or _auto_width(n_edges, n_items)

    def _counts_layout(ent, width, n_entities):
        """counts + (chunk, padded block count S) for one side."""
        native = _native_packer()
        if native is not None:
            counts = np.zeros(n_entities, np.int64)
            n_blocks = int(native.als_pack_count(
                _i32p(ent), len(ent), n_entities, width, _i64p(counts)
            ))
            if n_blocks < 0:
                raise ValueError("entity index out of range")
        else:
            counts = np.bincount(ent, minlength=n_entities)
            n_blocks = int((-(-counts // width)).sum())
        per_shard = max(1, -(-n_blocks // n_shards))
        chunk = min(config.blocks_per_chunk, _round_up(per_shard, 8))
        pad_to = n_shards * chunk
        # single home for the padded block count — the numpy packer is
        # handed S directly so both paths cannot drift apart
        S = max(pad_to, _round_up(max(n_blocks, 1), pad_to))
        return counts, chunk, S

    def _layout(ent, other, width, n_entities):
        """Host-packed blocks (the multi-shard path; single-device packs
        on device instead — see _build_trainer's COO variant)."""
        native = _native_packer()
        counts, chunk, S = _counts_layout(ent, width, n_entities)
        if native is not None:
            block_ent = np.empty(S, np.int32)
            block_other = np.empty(S * width, np.int32)
            block_rating = np.empty(S * width, np.float32)
            native.als_pack_fill(
                _i32p(ent), _i32p(other), _f32p(rating), len(ent),
                n_entities, width, _i64p(counts), S,
                _i32p(block_ent), _i32p(block_other), _f32p(block_rating),
            )
            blocks = (
                block_ent,
                block_other.reshape(S, width),
                block_rating.reshape(S, width),
            )
        else:
            blocks = _pack_blocks(
                ent, other, rating, n_entities, width, S, counts=counts
            )
            assert blocks[0].shape[0] == S
        return blocks, chunk

    seed = np.uint32(config.seed)

    def _trainer(chunk_user, chunk_item, packed_shapes):
        # one call site for the long positional signature so the mesh and
        # single-device branches can never drift apart
        return _build_trainer(
            mesh, axis, config.iterations, float(config.reg),
            bool(config.implicit), float(config.alpha),
            chunk_user, chunk_item,
            str(config.matmul_dtype), str(config.solver),
            packed_shapes, K, U_pad, I_pad,
        )

    if n_shards > 1:
        by_user, chunk_user = _layout(user_idx, item_idx, w_user, U_pad)
        by_item, chunk_item = _layout(item_idx, user_idx, w_item, I_pad)
        run = _trainer(chunk_user, chunk_item, None)
        blk = NamedSharding(mesh, P(axis))
        blk2 = NamedSharding(mesh, P(axis, None))
        put_blocks = lambda t: (
            jax.device_put(t[0], blk),
            jax.device_put(t[1], blk2),
            jax.device_put(t[2], blk2),
        )
        P_f, Q_f = run(put_blocks(by_user), put_blocks(by_item), seed)
    else:
        # Single-device path: ship the COO edges pre-sorted by user (see
        # _build_trainer's COO variant for the wire format) and let the
        # jitted trainer build both blocked layouts on device. Crucial on
        # hosts where the device link is slow or shares a core with the
        # process (the tunneled-TPU case).
        counts_u, chunk_user, S_u = _counts_layout(user_idx, w_user, U_pad)
        counts_i, chunk_item, S_i = _counts_layout(item_idx, w_item, I_pad)
        if S_u * w_user >= 2 ** 31 or S_i * w_item >= 2 ** 31:
            raise ValueError(
                "edge set too large for int32 block addressing; "
                "use a multi-device mesh"
            )
        run = _trainer(chunk_user, chunk_item, (S_u, w_user, S_i, w_item))

        # stable sort by user: native counting sort, numpy argsort fallback
        counts_u = np.ascontiguousarray(counts_u, np.int64)
        native = _native_packer()
        if native is not None:
            i_sorted = np.empty(n_edges, np.int32)
            r_sorted = np.empty(n_edges, np.float32)
            native.als_sort_by_entity(
                _i32p(user_idx), _i32p(item_idx), _f32p(rating),
                n_edges, U_pad, _i64p(counts_u),
                _i32p(i_sorted), _f32p(r_sorted),
            )
        else:
            order = np.argsort(user_idx, kind="stable")
            i_sorted = item_idx[order]
            r_sorted = rating[order]

        def _planes(idx, n_pad):
            """(low, high) wire encoding: uint16 alone below 2^16, uint16
            + uint8 high plane below 2^24 (3 B/id instead of 4), raw int32
            beyond. The empty high plane means "unused"."""
            none = np.zeros(0, np.uint8)
            if n_pad < 65536:
                return idx.astype(np.uint16), none
            if n_pad < (1 << 24):
                return (
                    (idx & 0xFFFF).astype(np.uint16),
                    (idx >> 16).astype(np.uint8),
                )
            return idx, none

        i_ship, i_hi = _planes(i_sorted, I_pad)
        # ratings: uint8 half-star codes when the grid allows (MovieLens's
        # 0.5..5.0 stars and implicit r=1 both do), else fp16 when
        # lossless, else f32
        r2 = r_sorted * np.float32(2.0)
        if (
            r2.size == 0
            or (
                np.all(r2 == np.round(r2))
                and r2.min() >= 0.0
                and r2.max() <= 255.0
            )
        ):
            r_ship = r2.astype(np.uint8)
        else:
            r16 = r_sorted.astype(np.float16)
            r_ship = r16 if np.array_equal(
                r16.astype(np.float32), r_sorted
            ) else r_sorted
        P_f, Q_f = run(
            counts_u.astype(np.int32),
            np.ascontiguousarray(counts_i, np.int32),
            i_ship, i_hi, r_ship, seed,
        )

    P_f, Q_f = jax.device_get((P_f, Q_f))
    return ALSFactors(
        user_factors=np.asarray(P_f)[:n_users],
        item_factors=np.asarray(Q_f)[:n_items],
    )


def predict_scores(
    user_factors: np.ndarray, item_factors: np.ndarray, user: int
) -> np.ndarray:
    """Scores of every item for one user (host-side; serving keeps factors
    on device — see the recommendation template)."""
    return user_factors[user] @ item_factors.T


def top_n(
    scores: np.ndarray, n: int, exclude: Optional[np.ndarray] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Top-n item indices + scores, optionally excluding seen items."""
    s = scores.copy()
    if exclude is not None and len(exclude):
        s[exclude] = -np.inf
    n = min(n, len(s))
    idx = np.argpartition(-s, n - 1)[:n] if n < len(s) else np.argsort(-s)
    idx = idx[np.argsort(-s[idx])]
    return idx, s[idx]
