"""Binary one-hot vectorizer — TPU-native rebuild of the reference e2 helper.

Reference: ``e2/src/main/scala/o/a/p/e2/engine/BinaryVectorizer.scala``
(UNVERIFIED path; see SURVEY.md §2.5) — learns a ``(field, value) → index``
map from property maps restricted to selected fields, then turns a property
map into a binary feature vector.

TPU-first notes: the learned index is a plain dict (host side); vectorized
encoding of a *batch* of property maps produces a dense ``[B, D]`` float32
matrix ready to shard over the mesh ``data`` axis — downstream models
(logreg, NB) consume it directly as MXU matmul input.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Sequence, Tuple


@dataclasses.dataclass
class BinaryVectorizer:
    """(field, value) → dense one-hot index."""

    index: Dict[Tuple[str, str], int]
    fields: Tuple[str, ...]

    @property
    def dim(self) -> int:
        return len(self.index)

    @classmethod
    def fit(
        cls,
        maps: Sequence[Mapping[str, str]],
        fields: Sequence[str],
    ) -> "BinaryVectorizer":
        """Learn the index from observed (field, value) pairs.

        ≙ reference ``BinaryVectorizer.apply(RDD[HashMap], properties)``.
        Insertion order is deterministic (first-seen), so vectors are stable
        across runs for identical input order.
        """
        fset = tuple(fields)
        index: Dict[Tuple[str, str], int] = {}
        for m in maps:
            for f in fset:
                if f in m:
                    key = (f, str(m[f]))
                    if key not in index:
                        index[key] = len(index)
        return cls(index=index, fields=fset)

    def to_vector(self, m: Mapping[str, str]) -> List[float]:
        """One property map → binary vector (list of 0.0/1.0)."""
        vec = [0.0] * len(self.index)
        for f in self.fields:
            if f in m:
                i = self.index.get((f, str(m[f])))
                if i is not None:
                    vec[i] = 1.0
        return vec

    def to_matrix(self, maps: Sequence[Mapping[str, str]]):
        """Batch encode → np.float32 [B, D] (input to sharded models)."""
        import numpy as np

        out = np.zeros((len(maps), len(self.index)), np.float32)
        for b, m in enumerate(maps):
            for f in self.fields:
                if f in m:
                    i = self.index.get((f, str(m[f])))
                    if i is not None:
                        out[b, i] = 1.0
        return out
