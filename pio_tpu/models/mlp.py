"""Sparse-input MLP classifier — the text-classification training substrate.

The reference's text pipeline trains MLlib NaiveBayes / LogisticRegression
on Spark-side sparse TF-IDF vectors (upstream text-classification template —
UNVERIFIED; SURVEY.md §2.5). The TPU-first redesign is a small MLP whose
first layer consumes the document **as a bag**: hidden activations are
``relu(embedding_bag(W_in, ids, tfidf) + b)`` — the Pallas streamed
sparse×dense matmul (pio_tpu/ops/embedding.py) — followed by a dense
softmax head on the MXU.

Parallelism: examples (bags) are sharded over the mesh ``data`` axis;
parameters are replicated. The loss mean over the sharded batch is where
XLA inserts the gradient ``psum`` over ICI (≙ Spark ``treeAggregate``).
The whole Adam loop is one compiled ``lax.scan`` — zero host round-trips.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    hidden: int = 128
    iterations: int = 200
    learning_rate: float = 1e-2
    reg: float = 0.0  # L2 on the dense head
    seed: int = 0


@dataclasses.dataclass
class MLPModel:
    """Trained sparse-input MLP (host numpy copies of the params)."""

    w_in: np.ndarray  # [V, H] embedding/input layer
    b_in: np.ndarray  # [H]
    w_out: np.ndarray  # [H, C]
    b_out: np.ndarray  # [C]
    n_classes: int
    #: serving cache: device-resident params + jitted logits fn. The query
    #: server calls logits() per request; re-shipping the [V, H] table every
    #: time would put a multi-MB host→device copy on the hot path.
    _serve_cache: Optional[tuple] = dataclasses.field(
        default=None, init=False, repr=False, compare=False
    )

    def _serving_fn(self):
        if self._serve_cache is None:
            import jax
            import jax.numpy as jnp

            from pio_tpu.ops.embedding import embedding_bag

            params = tuple(
                jnp.asarray(p)
                for p in (self.w_in, self.b_in, self.w_out, self.b_out)
            )

            @jax.jit
            def fwd(params, ids, weights):
                w_in, b_in, w_out, b_out = params
                h = embedding_bag(w_in, ids, weights)
                h = jnp.maximum(h + b_in, 0.0)
                return (
                    jnp.dot(h, w_out, preferred_element_type=jnp.float32)
                    + b_out
                )

            self._serve_cache = (fwd, params)
        return self._serve_cache

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_serve_cache"] = None  # jitted fn/device buffers don't pickle
        return state

    def logits(self, ids: np.ndarray, weights: np.ndarray) -> np.ndarray:
        """[B, L] bags → [B, C] logits (device path via embedding_bag)."""
        import jax.numpy as jnp

        fwd, params = self._serving_fn()
        return np.asarray(
            fwd(params, jnp.asarray(ids), jnp.asarray(weights))
        )

    def predict(self, ids: np.ndarray, weights: np.ndarray) -> np.ndarray:
        return np.argmax(self.logits(ids, weights), axis=1).astype(np.int32)

    def predict_proba(self, ids: np.ndarray, weights: np.ndarray):
        z = self.logits(ids, weights)
        z = z - z.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)


def train_mlp(
    ctx,
    ids: np.ndarray,
    weights: np.ndarray,
    y: np.ndarray,
    n_features: int,
    n_classes: int,
    config: MLPConfig = MLPConfig(),
) -> MLPModel:
    """Full-batch Adam on the sparse-input MLP, data-parallel over the mesh.

    Args:
        ctx: ComputeContext (mesh + batch axis); mesh=None → single device.
        ids/weights: [N, L] packed bags (pio_tpu.ops.pack_bags layout).
        y: [N] int class codes.
        n_features: embedding-table rows V (vectorizer.n_features).
        n_classes: C.
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pio_tpu.ops.embedding import embedding_bag

    ids = np.asarray(ids, np.int32)
    weights = np.asarray(weights, np.float32)
    y = np.asarray(y, np.int32)
    n = len(y)

    mesh = ctx.mesh if ctx is not None else None
    axis = ctx.batch_axis if ctx is not None else "data"
    n_dev = ctx.num_devices if ctx is not None else 1

    # pad batch to a device multiple; padded rows carry mask 0
    n_pad = (-n) % max(n_dev, 1)
    if n_pad:
        ids = np.concatenate([ids, np.zeros((n_pad, ids.shape[1]), np.int32)])
        weights = np.concatenate(
            [weights, np.zeros((n_pad, weights.shape[1]), np.float32)]
        )
        y = np.concatenate([y, np.zeros(n_pad, np.int32)])
    mask = np.concatenate(
        [np.ones(n, np.float32), np.zeros(n_pad, np.float32)]
    )

    H, C, V = config.hidden, n_classes, n_features
    k1, k2 = jax.random.split(jax.random.PRNGKey(config.seed))
    params = {
        "w_in": jax.random.normal(k1, (V, H), jnp.float32)
        * (1.0 / np.sqrt(max(V, 1))),
        "b_in": jnp.zeros((H,), jnp.float32),
        "w_out": jax.random.normal(k2, (H, C), jnp.float32)
        * (1.0 / np.sqrt(H)),
        "b_out": jnp.zeros((C,), jnp.float32),
    }
    tx = optax.adam(config.learning_rate)

    def loss_fn(params, ids_s, w_s, ys, ms):
        h = embedding_bag(params["w_in"], ids_s, w_s)
        h = jnp.maximum(h + params["b_in"], 0.0)
        logits = (
            jnp.dot(h, params["w_out"], preferred_element_type=jnp.float32)
            + params["b_out"]
        )
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, ys)
        # the masked mean over the sharded batch is the psum point
        data_loss = jnp.sum(ce * ms) / jnp.sum(ms)
        return data_loss + config.reg * jnp.sum(params["w_out"] ** 2)

    def fit(params, ids_s, w_s, ys, ms):
        opt_state = tx.init(params)

        def step(carry, _):
            params, opt_state = carry
            grads = jax.grad(loss_fn)(params, ids_s, w_s, ys, ms)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), None

        (params, _), _ = jax.lax.scan(
            step, (params, opt_state), None, length=config.iterations
        )
        return params

    if mesh is not None:
        shard = NamedSharding(mesh, P(axis))
        repl = NamedSharding(mesh, P())
        fitted = jax.jit(
            fit,
            in_shardings=(repl, shard, shard, shard, shard),
            out_shardings=repl,
        )(
            jax.device_put(params, repl),
            jax.device_put(jnp.asarray(ids), shard),
            jax.device_put(jnp.asarray(weights), shard),
            jax.device_put(jnp.asarray(y), shard),
            jax.device_put(jnp.asarray(mask), shard),
        )
    else:
        fitted = jax.jit(fit)(
            params,
            jnp.asarray(ids),
            jnp.asarray(weights),
            jnp.asarray(y),
            jnp.asarray(mask),
        )

    return MLPModel(
        w_in=np.asarray(fitted["w_in"]),
        b_in=np.asarray(fitted["b_in"]),
        w_out=np.asarray(fitted["w_out"]),
        b_out=np.asarray(fitted["b_out"]),
        n_classes=n_classes,
    )
