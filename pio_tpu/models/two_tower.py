"""Two-tower retrieval model — dp × tp × ep sharded, in-batch softmax.

BASELINE.json config #5 names "Two-tower / Wide&Deep recommender template"
as a required measurement config; the reference itself has no neural
recommender (its similar-product/ecommerce templates are ALS-factor cosine —
SURVEY.md §2.5), so this model is capability-forward rather than parity.

Architecture: user tower and item tower, each ``embed → relu MLP → L2-norm
vector``; score = dot product; trained with in-batch sampled-softmax
contrastive loss (each row's positive item, everyone else's items as
negatives).

Sharding (the point of this model — it exercises every mesh axis class):

- **dp**: the pair batch shards over ``data``; in-batch negatives require an
  ``all_gather`` of item vectors over ``data`` (its transpose in the
  backward pass is the matching ``psum_scatter``).
- **ep** (vocab-parallel embeddings): each embedding table shards by rows
  over ``model``; a lookup masks ids outside the local shard, gathers
  locally, and ``psum``s partial rows over ``model`` — the expert-parallel
  addressing pattern, no replicated table anywhere.
- **tp** (Megatron-style MLP): first dense column-sharded over ``model``
  (activations ``[B, H/m]``), second dense row-sharded with a closing
  ``psum`` — one reduction per tower, matmuls stay MXU-sized.

The whole step is differentiated *through* ``shard_map`` so JAX transposes
the collectives (all_gather ↔ psum_scatter, psum ↔ broadcast) instead of us
hand-deriving gradient comms.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import numpy as np

from pio_tpu.parallel.mesh import mesh_axis_size
from pio_tpu.parallel.vocab import vocab_parallel_lookup
from pio_tpu.utils.numutil import round_up as _round_up


@dataclasses.dataclass(frozen=True)
class TwoTowerConfig:
    embed_dim: int = 64
    hidden: int = 128
    out_dim: int = 64
    temperature: float = 20.0  # logit scale on the unit sphere
    learning_rate: float = 1e-3
    steps: int = 200
    batch_size: int = 256
    seed: int = 0
    #: device→host dtype for the materialized vector tables. The tables
    #: are the run's dominant transfer on a slow host link (training is
    #: one compiled scan; the OUTPUT readback is what the host waits
    #: on). "bfloat16" halves those bytes; the returned arrays are
    #: still float32 (values rounded to bf16 precision — ~3 decimal
    #: digits, standard practice for retrieval embeddings).
    table_wire: str = "float32"
    #: epoch feed: "off" stages the full id arrays on device (the
    #: historical path), "on" streams per-step batch spans through
    #: parallel/stream.py (double-buffered h2d overlapping compute),
    #: "auto" streams only when staging (params + epoch arrays) would
    #: exceed PIO_TPU_DEVICE_BUDGET_BYTES. Streamed and staged runs
    #: with the same seed/config produce identical params (the span
    #: schedule replays the staged batch order exactly).
    stream: str = "auto"


@dataclasses.dataclass
class TwoTowerModel:
    """Trained towers, materialized as host arrays.

    ``item_vectors`` is the full item-tower output table — serving top-N is
    one ``[B, D] @ [D, V_i]`` MXU matmul exactly like the ALS template.
    """

    user_vectors: np.ndarray  # [n_users, D] unit rows
    item_vectors: np.ndarray  # [n_items, D] unit rows
    config: TwoTowerConfig

    def scores(self, user_rows: np.ndarray) -> np.ndarray:
        return np.asarray(user_rows @ self.item_vectors.T)


def _init_tower(key, vocab: int, cfg: TwoTowerConfig):
    import jax

    ke, k1, k2 = jax.random.split(key, 3)
    s = cfg.embed_dim ** -0.5
    return {
        "emb": jax.random.normal(ke, (vocab, cfg.embed_dim)) * s,
        "w1": jax.random.normal(k1, (cfg.embed_dim, cfg.hidden))
        * (cfg.embed_dim ** -0.5),
        "b1": np.zeros((cfg.hidden,), np.float32),
        "w2": jax.random.normal(k2, (cfg.hidden, cfg.out_dim))
        * (cfg.hidden ** -0.5),
        "b2": np.zeros((cfg.out_dim,), np.float32),
    }


def _tower_specs():
    """PartitionSpecs for one tower's params, from the partition-rule
    registry (``rules_for("two_tower")``) — ep embedding, tp MLP splits."""
    from pio_tpu.parallel.partition import match_partition_rules, rules_for

    skeleton = {k: np.empty(0) for k in ("emb", "w1", "b1", "w2", "b2")}
    return match_partition_rules(
        rules_for("two_tower"), skeleton, on_unmatched="error"
    )


def _tower_forward(params, ids, axis: Optional[str]):
    """Sharded tower: vocab-parallel embed → tp MLP → unit vectors.

    Runs inside shard_map; ``params`` are the *local* blocks.
    """
    import jax
    import jax.numpy as jnp

    x = vocab_parallel_lookup(params["emb"], ids, axis)

    h = jnp.maximum(
        jnp.dot(x, params["w1"], preferred_element_type=jnp.float32)
        + params["b1"],
        0.0,
    )  # [B, H/m] column-parallel
    out = jnp.dot(h, params["w2"], preferred_element_type=jnp.float32)
    if axis is not None:
        out = jax.lax.psum(out, axis)  # close the row-parallel matmul (tp)
    out = out + params["b2"]
    return out / jnp.maximum(
        jnp.linalg.norm(out, axis=-1, keepdims=True), 1e-6
    )


def _contrastive_loss(user_p, item_p, uids, iids, cfg, d_axis, m_axis):
    """In-batch softmax CE, all_gather'd negatives over the data axis."""
    import jax
    import jax.numpy as jnp

    from pio_tpu.parallel.compat import axis_size

    u = _tower_forward(user_p, uids, m_axis)  # [B_loc, D]
    v = _tower_forward(item_p, iids, m_axis)  # [B_loc, D]
    b_loc = u.shape[0]
    if d_axis is None:
        v_all = v
        labels = jnp.arange(b_loc)
    else:
        v_all = jax.lax.all_gather(v, d_axis, tiled=True)  # [B_glob, D]
        labels = jax.lax.axis_index(d_axis) * b_loc + jnp.arange(b_loc)
    logits = cfg.temperature * jnp.dot(
        u, v_all.T, preferred_element_type=jnp.float32
    )
    logz = jax.nn.logsumexp(logits, axis=-1)
    ce = logz - jnp.take_along_axis(
        logits, labels[:, None], axis=-1
    )[:, 0]
    loss = ce.sum()
    if d_axis is not None:
        loss = jax.lax.psum(loss, d_axis)
        total = b_loc * axis_size(d_axis)
    else:
        total = b_loc
    return loss / total


@dataclasses.dataclass(frozen=True)
class _TTTrainer:
    """Cached jitted pieces of one (mesh, static-config) two-tower setup."""

    init_params: "callable"  # (seed) → sharded param trees (never host)
    place_data: "callable"  # (uids, iids) → staged device id arrays
    put_span: "callable"  # (uids_np, iids_np) → streamed span arrays
    chunk: "callable"  # (state, uids_d, iids_d, n static) → (state, losses)
    stream_chunk: "callable"  # (state, u_span, i_span, n static) → (state, losses)
    tx_init: "callable"
    vectors: "callable"  # (tower_params, vocab static) → [vocab, D]


@functools.lru_cache(maxsize=32)
def _build_tt_trainer(mesh, cfg: TwoTowerConfig, n_batches: int,
                      batch: int, vu: int, vi: int) -> _TTTrainer:
    """One compiled trainer per (mesh, shape-static config) — the
    als._build_trainer discipline, so bench repeats / eval sweeps /
    retrains don't pay XLA again."""
    import jax
    import jax.numpy as jnp
    import optax
    from pio_tpu.parallel.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    d_axis = "data" if mesh is not None else None
    m_axis = "model" if mesh is not None else None
    tx = optax.adam(cfg.learning_rate)
    specs = {"user": _tower_specs(), "item": _tower_specs()}

    def global_loss(params, ub, ib):
        if mesh is None:
            return _contrastive_loss(
                params["user"], params["item"], ub, ib, cfg, None, None
            )

        def inner(user_p, item_p, ub, ib):
            return _contrastive_loss(
                user_p, item_p, ub, ib, cfg, d_axis, m_axis
            )

        return shard_map(
            inner,
            mesh=mesh,
            in_specs=(specs["user"], specs["item"], P("data"), P("data")),
            out_specs=P(),
            check_vma=False,
        )(params["user"], params["item"], ub, ib)

    def _init_all(seed):
        ku, ki = jax.random.split(jax.random.PRNGKey(seed))
        return {
            "user": _init_tower(ku, vu, cfg),
            "item": _init_tower(ki, vi, cfg),
        }

    if mesh is None:
        init_params = jax.jit(_init_all)
    else:
        # each device materializes only its table shard — a 10⁷–10⁸ row
        # vocab never exists unsharded on any chip (or on host)
        param_shardings = jax.tree.map(
            lambda spec: NamedSharding(mesh, spec),
            specs,
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
        )
        init_params = jax.jit(_init_all, out_shardings=param_shardings)

    def place_data(uids, iids):
        if mesh is None:
            return jnp.asarray(uids), jnp.asarray(iids)
        data_sh = NamedSharding(mesh, P(None))
        return (
            jax.device_put(jnp.asarray(uids), data_sh),
            jax.device_put(jnp.asarray(iids), data_sh),
        )

    def put_span(u_np, i_np):
        # span ids replicate like the staged epoch arrays (the batch
        # rows split over "data" inside shard_map) so streamed steps
        # see bit-identical inputs to staged ones
        if mesh is None:
            return jnp.asarray(u_np), jnp.asarray(i_np)
        data_sh = NamedSharding(mesh, P(None))
        return (
            jax.device_put(u_np, data_sh),
            jax.device_put(i_np, data_sh),
        )

    def _scan_steps(state, n, slice_fn):
        step0, params, opt_state = state

        def step(carry, i):
            params, opt_state = carry
            ub, ib = slice_fn(i, step0)
            loss, grads = jax.value_and_grad(global_loss)(params, ub, ib)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            step, (params, opt_state), jnp.arange(n)
        )
        # per-step losses ride along for the telemetry plane; callers
        # that don't want them drop the array undereferenced (no sync)
        return (step0 + n, params, opt_state), losses

    @functools.partial(jax.jit, static_argnums=3)
    def chunk(state, uids_d, iids_d, n):
        def slice_fn(i, step0):
            start = ((step0 + i) % n_batches) * batch
            return (jax.lax.dynamic_slice_in_dim(uids_d, start, batch),
                    jax.lax.dynamic_slice_in_dim(iids_d, start, batch))

        return _scan_steps(state, n, slice_fn)

    @functools.partial(jax.jit, static_argnums=3)
    def stream_chunk(state, u_span, i_span, n):
        # the span holds this chunk's batches contiguously: step i of
        # the chunk is span row block i (the host scheduler aligned the
        # span to the staged batch order)
        def slice_fn(i, step0):
            return (jax.lax.dynamic_slice_in_dim(u_span, i * batch, batch),
                    jax.lax.dynamic_slice_in_dim(i_span, i * batch, batch))

        return _scan_steps(state, n, slice_fn)

    @functools.partial(jax.jit, static_argnums=1)
    def vectors(tower_params, vocab):
        all_ids = jnp.arange(vocab)
        if mesh is None:
            return _tower_forward(tower_params, all_ids, None)

        def inner(tp, ids):
            return _tower_forward(tp, ids, m_axis)

        return shard_map(
            inner,
            mesh=mesh,
            in_specs=(_tower_specs(), P("data")),
            out_specs=P("data"),
            check_vma=False,
        )(tower_params, all_ids)

    return _TTTrainer(
        init_params=init_params, place_data=place_data, put_span=put_span,
        chunk=chunk, stream_chunk=stream_chunk, tx_init=jax.jit(tx.init),
        vectors=vectors,
    )


def train_two_tower(
    mesh,
    user_ids: np.ndarray,
    item_ids: np.ndarray,
    n_users: int,
    n_items: int,
    config: TwoTowerConfig = TwoTowerConfig(),
    checkpoint=None,
    checkpoint_every: int = 0,
    stats=None,
) -> TwoTowerModel:
    """Train on positive (user, item) pairs; returns unit vector tables.

    Args:
        mesh: a build_mesh() mesh (data/model axes used; seq/pipe ignored).
            None → single-device path (no collectives).
        user_ids/item_ids: [n_pairs] int32 positive interaction pairs.
        checkpoint/checkpoint_every: optional
            pio_tpu.workflow.checkpoint.CheckpointManager + snapshot
            interval in steps; resumes from the newest snapshot on restart.
        stats: optional dict receiving the phase split — place_s (h2d),
            steps_s (compiled scan), tables_d2h_s (output readback) —
            measured by blocking between phases (profiling runs only).
            Streamed runs additionally report the executor phases
            (h2d_s/device_s/h2d_bytes/encode_s) and n_stream.

    Raises:
        DeviceBudgetExceeded: the params can't fit — single-chip when
            ``mesh`` is None, or even sharded across the mesh. An epoch
            that merely doesn't fit NEXT TO the params falls back to the
            streamed feed instead (``stream="auto"``).
    """
    import jax
    import jax.numpy as jnp

    cfg = config
    if cfg.table_wire not in ("float32", "bfloat16"):
        raise ValueError(
            f"table_wire must be float32/bfloat16, got {cfg.table_wire!r}"
        )
    if cfg.stream not in ("auto", "on", "off"):
        raise ValueError(
            f"stream must be auto/on/off, got {cfg.stream!r}"
        )
    n_data = mesh_axis_size(mesh, "data")
    n_model = mesh_axis_size(mesh, "model")

    # vocab rounded up so tables shard evenly; batch to a data multiple
    vu = _round_up(max(n_users, 1), n_model)
    vi = _round_up(max(n_items, 1), n_model)
    batch = _round_up(min(cfg.batch_size, len(user_ids)), n_data)

    rng = np.random.default_rng(cfg.seed)
    perm = rng.permutation(len(user_ids))
    uids = np.asarray(user_ids, np.int32)[perm]
    iids = np.asarray(item_ids, np.int32)[perm]
    # wraparound so every scan step slices a full batch
    n_pairs = len(uids)
    reps = _round_up(max(n_pairs, batch), batch)
    uids = np.resize(uids, reps)
    iids = np.resize(iids, reps)
    n_batches = reps // batch

    # placement accounting BEFORE anything lands on device: params must
    # fit (sharded when a mesh is given — DeviceBudgetExceeded is the
    # honest single-chip answer for a giant table), and staging the
    # epoch id arrays next to them must fit or the feed streams instead
    from pio_tpu.parallel.partition import (
        assert_device_budget,
        device_budget_bytes,
        per_device_nbytes,
    )

    def _tower_skeleton(vocab):
        shapes = {
            "emb": (vocab, cfg.embed_dim),
            "w1": (cfg.embed_dim, cfg.hidden),
            "b1": (cfg.hidden,),
            "w2": (cfg.hidden, cfg.out_dim),
            "b2": (cfg.out_dim,),
        }
        z = np.zeros((), np.float32)
        return {k: np.broadcast_to(z, s) for k, s in shapes.items()}

    skeleton = {"user": _tower_skeleton(vu), "item": _tower_skeleton(vi)}
    params_nbytes = sum(
        a.nbytes for tower in skeleton.values() for a in tower.values()
    )
    staged_nbytes = 2 * reps * 4  # uids + iids, replicated per device
    if mesh is None:
        assert_device_budget(
            params_nbytes, 1, "two_tower params (single-chip placement)"
        )
        params_pd = params_nbytes
    else:
        specs_pd = {"user": _tower_specs(), "item": _tower_specs()}
        params_pd = per_device_nbytes(mesh, skeleton, specs_pd)
        assert_device_budget(params_pd, 1, "two_tower sharded params")
    budget = device_budget_bytes()
    streamed = cfg.stream == "on" or (
        cfg.stream == "auto"
        and budget > 0
        and params_pd + staged_nbytes > budget
    )
    n_stream = 0
    if streamed:
        from pio_tpu.parallel.stream import n_stream_chunks

        n_stream = max(
            2,
            n_stream_chunks(staged_nbytes, "PIO_TPU_TRAIN_STREAM_MB",
                            cap=256),
        )
        if budget > params_pd:
            # every span must fit in the budget headroom beside params
            n_stream = max(
                n_stream, -(-staged_nbytes // (budget - params_pd))
            )
        n_stream = min(n_batches, n_stream)

    # jitted trainer cached per (mesh, static config) — repeated calls
    # (bench repeats, eval sweeps, serving retrains) recompile only on
    # shape changes (the als._build_trainer discipline). seed/steps/
    # batch_size/stream are zeroed in the key: they don't shape the
    # program (both feed paths compile lazily off one trainer).
    tt = _build_tt_trainer(
        mesh,
        dataclasses.replace(cfg, steps=0, seed=0, batch_size=0,
                            table_wire="float32", stream="auto"),
        n_batches, batch, vu, vi,
    )

    from pio_tpu.obs import devicewatch, monotonic_s, trainwatch

    trainwatch.begin_algo(
        "two_tower", total_steps=cfg.steps, n_batches=n_batches,
        streamed=streamed, n_stream=n_stream,
        per_device_bytes=params_pd,
    )
    # lagged loss drain: the scan chunks hand their per-step losses back
    # as device arrays; each is fetched one chunk BEHIND the dispatch
    # frontier (that chunk's compute is already proven done by the feed
    # throttle / the state dependency), so telemetry never stalls the
    # pipe. With no active recorder the arrays drop undereferenced —
    # library callers (tests, bench) pay nothing.
    _pending: list = []
    _last_drain = [monotonic_s()]

    def _drain(keep: int = 0):
        while len(_pending) > keep:
            n_s, dev = _pending.pop(0)
            vals = np.asarray(jax.device_get(dev), np.float32)
            now = monotonic_s()
            trainwatch.record_steps(
                int(n_s), losses=[float(v) for v in vals],
                examples=int(n_s) * batch, dur_s=now - _last_drain[0],
            )
            _last_drain[0] = now

    def _note_chunk(n_s, losses_dev, keep: int):
        if trainwatch.active_recorder() is None:
            return
        _pending.append((n_s, losses_dev))
        _drain(keep)

    t0 = monotonic_s()
    params = tt.init_params(cfg.seed)
    uids_d = iids_d = None
    if not streamed:
        uids_d, iids_d = tt.place_data(uids, iids)
    if stats is not None:
        jax.block_until_ready((params, uids_d, iids_d))
        stats["place_s"] = monotonic_s() - t0
        stats["n_stream"] = n_stream
        t0 = monotonic_s()

    if streamed:
        from pio_tpu.parallel.stream import (
            epoch_spans,
            span_bounds,
            stream_feed,
        )

        # span boundaries in batch units: n_stream near-even contiguous
        # ranges of the epoch's batch sequence
        bounds = span_bounds(n_batches, n_stream)

        def chunk_fn(state, n):
            _drain()
            step0 = int(jax.device_get(state[0]))
            work = epoch_spans(step0, n, n_batches, bounds)

            def encode(span):
                b0, b1 = span
                return (
                    np.ascontiguousarray(uids[b0 * batch:b1 * batch]),
                    np.ascontiguousarray(iids[b0 * batch:b1 * batch]),
                )

            def dispatch(st, dev, i):
                b0, b1 = work[i]
                st, losses = tt.stream_chunk(st, dev[0], dev[1], b1 - b0)
                _note_chunk(b1 - b0, losses, keep=2)
                return st

            return stream_feed(
                work,
                encode=encode,
                put=lambda host, _i: tt.put_span(*host),
                init_carry=lambda: state,
                dispatch=dispatch,
                lookahead=2,
                stats=stats,
            )

    else:
        def chunk_fn(state, n):
            _drain()
            # compile attribution: n is static in the jitted chunk, so
            # each distinct chunk length is its own trainer program
            with devicewatch.compile_span(
                "train_step", key=("two_tower", "chunk", batch, int(n))
            ):
                state, losses = tt.chunk(state, uids_d, iids_d, n)
            _note_chunk(n, losses, keep=1)
            return state

    from pio_tpu.workflow.checkpoint import (
        run_chunked_steps,
        state_fingerprint,
    )

    # steps + table_wire + stream excluded: none shapes the trained
    # state (streamed and staged runs are parity-identical), so resuming
    # an interrupted run with a different total, readback wire, or feed
    # mode must still match the recorded identity
    fingerprint = state_fingerprint(
        "two_tower",
        dataclasses.replace(cfg, steps=0, table_wire="float32",
                            stream="auto"),
        n_users, n_items,
        reps, int(uids.sum()), int(iids.sum()),
    )
    state = (jnp.int32(0), params, tt.tx_init(params))
    state = run_chunked_steps(
        state, cfg.steps, chunk_fn,
        checkpoint=checkpoint, checkpoint_every=checkpoint_every,
        fingerprint=fingerprint,
    )
    _drain()  # flush the telemetry tail (no-op without a recorder)
    fitted = state[1]
    if stats is not None:
        jax.block_until_ready(fitted)
        stats["steps_s"] = monotonic_s() - t0
        t0 = monotonic_s()

    # materialize full vector tables. Round-5 finding: this OUTPUT
    # readback — not any per-step input feed (training is one compiled
    # scan over device-resident ids) — was ~78% of e2e on the tunneled
    # link. Both tables therefore dispatch first and come back in ONE
    # device_get (one round trip), optionally over a bf16 wire.
    vu_pad = _round_up(vu, max(n_data, 1))
    vi_pad = _round_up(vi, max(n_data, 1))
    uv_dev = tt.vectors(fitted["user"], vu_pad)
    iv_dev = tt.vectors(fitted["item"], vi_pad)
    if cfg.table_wire == "bfloat16":
        uv_dev = uv_dev.astype(jnp.bfloat16)
        iv_dev = iv_dev.astype(jnp.bfloat16)
    uv, iv = jax.device_get((uv_dev, iv_dev))
    user_vecs = np.asarray(uv, np.float32)[:n_users]
    item_vecs = np.asarray(iv, np.float32)[:n_items]
    if stats is not None:
        stats["tables_d2h_s"] = monotonic_s() - t0
        stats["table_wire"] = cfg.table_wire
    return TwoTowerModel(
        user_vectors=user_vecs, item_vectors=item_vecs, config=cfg
    )
