"""Two-tower retrieval model — dp × tp × ep sharded, in-batch softmax.

BASELINE.json config #5 names "Two-tower / Wide&Deep recommender template"
as a required measurement config; the reference itself has no neural
recommender (its similar-product/ecommerce templates are ALS-factor cosine —
SURVEY.md §2.5), so this model is capability-forward rather than parity.

Architecture: user tower and item tower, each ``embed → relu MLP → L2-norm
vector``; score = dot product; trained with in-batch sampled-softmax
contrastive loss (each row's positive item, everyone else's items as
negatives).

Sharding (the point of this model — it exercises every mesh axis class):

- **dp**: the pair batch shards over ``data``; in-batch negatives require an
  ``all_gather`` of item vectors over ``data`` (its transpose in the
  backward pass is the matching ``psum_scatter``).
- **ep** (vocab-parallel embeddings): each embedding table shards by rows
  over ``model``; a lookup masks ids outside the local shard, gathers
  locally, and ``psum``s partial rows over ``model`` — the expert-parallel
  addressing pattern, no replicated table anywhere.
- **tp** (Megatron-style MLP): first dense column-sharded over ``model``
  (activations ``[B, H/m]``), second dense row-sharded with a closing
  ``psum`` — one reduction per tower, matmuls stay MXU-sized.

The whole step is differentiated *through* ``shard_map`` so JAX transposes
the collectives (all_gather ↔ psum_scatter, psum ↔ broadcast) instead of us
hand-deriving gradient comms.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import numpy as np

from pio_tpu.parallel.mesh import mesh_axis_size
from pio_tpu.parallel.vocab import vocab_parallel_lookup
from pio_tpu.utils.numutil import round_up as _round_up


@dataclasses.dataclass(frozen=True)
class TwoTowerConfig:
    embed_dim: int = 64
    hidden: int = 128
    out_dim: int = 64
    temperature: float = 20.0  # logit scale on the unit sphere
    learning_rate: float = 1e-3
    steps: int = 200
    batch_size: int = 256
    seed: int = 0
    #: device→host dtype for the materialized vector tables. The tables
    #: are the run's dominant transfer on a slow host link (training is
    #: one compiled scan; the OUTPUT readback is what the host waits
    #: on). "bfloat16" halves those bytes; the returned arrays are
    #: still float32 (values rounded to bf16 precision — ~3 decimal
    #: digits, standard practice for retrieval embeddings).
    table_wire: str = "float32"


@dataclasses.dataclass
class TwoTowerModel:
    """Trained towers, materialized as host arrays.

    ``item_vectors`` is the full item-tower output table — serving top-N is
    one ``[B, D] @ [D, V_i]`` MXU matmul exactly like the ALS template.
    """

    user_vectors: np.ndarray  # [n_users, D] unit rows
    item_vectors: np.ndarray  # [n_items, D] unit rows
    config: TwoTowerConfig

    def scores(self, user_rows: np.ndarray) -> np.ndarray:
        return np.asarray(user_rows @ self.item_vectors.T)


def _init_tower(key, vocab: int, cfg: TwoTowerConfig):
    import jax

    ke, k1, k2 = jax.random.split(key, 3)
    s = cfg.embed_dim ** -0.5
    return {
        "emb": jax.random.normal(ke, (vocab, cfg.embed_dim)) * s,
        "w1": jax.random.normal(k1, (cfg.embed_dim, cfg.hidden))
        * (cfg.embed_dim ** -0.5),
        "b1": np.zeros((cfg.hidden,), np.float32),
        "w2": jax.random.normal(k2, (cfg.hidden, cfg.out_dim))
        * (cfg.hidden ** -0.5),
        "b2": np.zeros((cfg.out_dim,), np.float32),
    }


def _tower_specs():
    """PartitionSpecs for one tower's params, from the partition-rule
    registry (``rules_for("two_tower")``) — ep embedding, tp MLP splits."""
    from pio_tpu.parallel.partition import match_partition_rules, rules_for

    skeleton = {k: np.empty(0) for k in ("emb", "w1", "b1", "w2", "b2")}
    return match_partition_rules(
        rules_for("two_tower"), skeleton, on_unmatched="error"
    )


def _tower_forward(params, ids, axis: Optional[str]):
    """Sharded tower: vocab-parallel embed → tp MLP → unit vectors.

    Runs inside shard_map; ``params`` are the *local* blocks.
    """
    import jax
    import jax.numpy as jnp

    x = vocab_parallel_lookup(params["emb"], ids, axis)

    h = jnp.maximum(
        jnp.dot(x, params["w1"], preferred_element_type=jnp.float32)
        + params["b1"],
        0.0,
    )  # [B, H/m] column-parallel
    out = jnp.dot(h, params["w2"], preferred_element_type=jnp.float32)
    if axis is not None:
        out = jax.lax.psum(out, axis)  # close the row-parallel matmul (tp)
    out = out + params["b2"]
    return out / jnp.maximum(
        jnp.linalg.norm(out, axis=-1, keepdims=True), 1e-6
    )


def _contrastive_loss(user_p, item_p, uids, iids, cfg, d_axis, m_axis):
    """In-batch softmax CE, all_gather'd negatives over the data axis."""
    import jax
    import jax.numpy as jnp

    from pio_tpu.parallel.compat import axis_size

    u = _tower_forward(user_p, uids, m_axis)  # [B_loc, D]
    v = _tower_forward(item_p, iids, m_axis)  # [B_loc, D]
    b_loc = u.shape[0]
    if d_axis is None:
        v_all = v
        labels = jnp.arange(b_loc)
    else:
        v_all = jax.lax.all_gather(v, d_axis, tiled=True)  # [B_glob, D]
        labels = jax.lax.axis_index(d_axis) * b_loc + jnp.arange(b_loc)
    logits = cfg.temperature * jnp.dot(
        u, v_all.T, preferred_element_type=jnp.float32
    )
    logz = jax.nn.logsumexp(logits, axis=-1)
    ce = logz - jnp.take_along_axis(
        logits, labels[:, None], axis=-1
    )[:, 0]
    loss = ce.sum()
    if d_axis is not None:
        loss = jax.lax.psum(loss, d_axis)
        total = b_loc * axis_size(d_axis)
    else:
        total = b_loc
    return loss / total


@dataclasses.dataclass(frozen=True)
class _TTTrainer:
    """Cached jitted pieces of one (mesh, static-config) two-tower setup."""

    place: "callable"  # (params, uids, iids) → sharded device trees
    chunk: "callable"  # (state, uids_d, iids_d, n static) → state
    tx_init: "callable"
    vectors: "callable"  # (tower_params, vocab static) → [vocab, D]


@functools.lru_cache(maxsize=32)
def _build_tt_trainer(mesh, cfg: TwoTowerConfig, n_batches: int,
                      batch: int) -> _TTTrainer:
    """One compiled trainer per (mesh, shape-static config) — the
    als._build_trainer discipline, so bench repeats / eval sweeps /
    retrains don't pay XLA again."""
    import jax
    import jax.numpy as jnp
    import optax
    from pio_tpu.parallel.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    d_axis = "data" if mesh is not None else None
    m_axis = "model" if mesh is not None else None
    tx = optax.adam(cfg.learning_rate)
    specs = {"user": _tower_specs(), "item": _tower_specs()}

    def global_loss(params, ub, ib):
        if mesh is None:
            return _contrastive_loss(
                params["user"], params["item"], ub, ib, cfg, None, None
            )

        def inner(user_p, item_p, ub, ib):
            return _contrastive_loss(
                user_p, item_p, ub, ib, cfg, d_axis, m_axis
            )

        return shard_map(
            inner,
            mesh=mesh,
            in_specs=(specs["user"], specs["item"], P("data"), P("data")),
            out_specs=P(),
            check_vma=False,
        )(params["user"], params["item"], ub, ib)

    def place(params, uids, iids):
        if mesh is None:
            return params, jnp.asarray(uids), jnp.asarray(iids)
        param_shardings = jax.tree.map(
            lambda spec: NamedSharding(mesh, spec),
            specs,
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
        )
        params = jax.tree.map(jax.device_put, params, param_shardings)
        data_sh = NamedSharding(mesh, P(None))
        return (
            params,
            jax.device_put(jnp.asarray(uids), data_sh),
            jax.device_put(jnp.asarray(iids), data_sh),
        )

    @functools.partial(jax.jit, static_argnums=3)
    def chunk(state, uids_d, iids_d, n):
        step0, params, opt_state = state

        def step(carry, i):
            params, opt_state = carry
            start = ((step0 + i) % n_batches) * batch
            ub = jax.lax.dynamic_slice_in_dim(uids_d, start, batch)
            ib = jax.lax.dynamic_slice_in_dim(iids_d, start, batch)
            loss, grads = jax.value_and_grad(global_loss)(params, ub, ib)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, opt_state), _ = jax.lax.scan(
            step, (params, opt_state), jnp.arange(n)
        )
        return step0 + n, params, opt_state

    @functools.partial(jax.jit, static_argnums=1)
    def vectors(tower_params, vocab):
        all_ids = jnp.arange(vocab)
        if mesh is None:
            return _tower_forward(tower_params, all_ids, None)

        def inner(tp, ids):
            return _tower_forward(tp, ids, m_axis)

        return shard_map(
            inner,
            mesh=mesh,
            in_specs=(_tower_specs(), P("data")),
            out_specs=P("data"),
            check_vma=False,
        )(tower_params, all_ids)

    return _TTTrainer(
        place=place, chunk=chunk, tx_init=jax.jit(tx.init),
        vectors=vectors,
    )


def train_two_tower(
    mesh,
    user_ids: np.ndarray,
    item_ids: np.ndarray,
    n_users: int,
    n_items: int,
    config: TwoTowerConfig = TwoTowerConfig(),
    checkpoint=None,
    checkpoint_every: int = 0,
    stats=None,
) -> TwoTowerModel:
    """Train on positive (user, item) pairs; returns unit vector tables.

    Args:
        mesh: a build_mesh() mesh (data/model axes used; seq/pipe ignored).
            None → single-device path (no collectives).
        user_ids/item_ids: [n_pairs] int32 positive interaction pairs.
        checkpoint/checkpoint_every: optional
            pio_tpu.workflow.checkpoint.CheckpointManager + snapshot
            interval in steps; resumes from the newest snapshot on restart.
        stats: optional dict receiving the phase split — place_s (h2d),
            steps_s (compiled scan), tables_d2h_s (output readback) —
            measured by blocking between phases (profiling runs only).
    """
    import jax
    import jax.numpy as jnp

    cfg = config
    if cfg.table_wire not in ("float32", "bfloat16"):
        raise ValueError(
            f"table_wire must be float32/bfloat16, got {cfg.table_wire!r}"
        )
    n_data = mesh_axis_size(mesh, "data")
    n_model = mesh_axis_size(mesh, "model")

    # vocab rounded up so tables shard evenly; batch to a data multiple
    vu = _round_up(max(n_users, 1), n_model)
    vi = _round_up(max(n_items, 1), n_model)
    batch = _round_up(min(cfg.batch_size, len(user_ids)), n_data)

    rng = np.random.default_rng(cfg.seed)
    perm = rng.permutation(len(user_ids))
    uids = np.asarray(user_ids, np.int32)[perm]
    iids = np.asarray(item_ids, np.int32)[perm]
    # wraparound so every scan step slices a full batch
    n_pairs = len(uids)
    reps = _round_up(max(n_pairs, batch), batch)
    uids = np.resize(uids, reps)
    iids = np.resize(iids, reps)
    n_batches = reps // batch

    # jitted trainer cached per (mesh, static config) — repeated calls
    # (bench repeats, eval sweeps, serving retrains) recompile only on
    # shape changes (the als._build_trainer discipline). seed/steps/
    # batch_size are zeroed in the key: they don't shape the program.
    tt = _build_tt_trainer(
        mesh,
        dataclasses.replace(cfg, steps=0, seed=0, batch_size=0,
                            table_wire="float32"),
        n_batches, batch,
    )

    ku, ki = jax.random.split(jax.random.PRNGKey(cfg.seed))
    params = {
        "user": _init_tower(ku, vu, cfg),
        "item": _init_tower(ki, vi, cfg),
    }
    params = jax.tree.map(lambda a: jnp.asarray(a, jnp.float32), params)
    from pio_tpu.obs import monotonic_s

    t0 = monotonic_s()
    params, uids_d, iids_d = tt.place(params, uids, iids)
    if stats is not None:
        jax.block_until_ready((params, uids_d, iids_d))
        stats["place_s"] = monotonic_s() - t0
        t0 = monotonic_s()

    def chunk_fn(state, n):
        return tt.chunk(state, uids_d, iids_d, n)

    from pio_tpu.workflow.checkpoint import (
        run_chunked_steps,
        state_fingerprint,
    )

    # steps + table_wire excluded: neither shapes the trained state, so
    # resuming an interrupted run with a different total or readback
    # wire must still match the recorded identity
    fingerprint = state_fingerprint(
        "two_tower",
        dataclasses.replace(cfg, steps=0, table_wire="float32"),
        n_users, n_items,
        reps, int(uids.sum()), int(iids.sum()),
    )
    state = (jnp.int32(0), params, tt.tx_init(params))
    state = run_chunked_steps(
        state, cfg.steps, chunk_fn,
        checkpoint=checkpoint, checkpoint_every=checkpoint_every,
        fingerprint=fingerprint,
    )
    fitted = state[1]
    if stats is not None:
        jax.block_until_ready(fitted)
        stats["steps_s"] = monotonic_s() - t0
        t0 = monotonic_s()

    # materialize full vector tables. Round-5 finding: this OUTPUT
    # readback — not any per-step input feed (training is one compiled
    # scan over device-resident ids) — was ~78% of e2e on the tunneled
    # link. Both tables therefore dispatch first and come back in ONE
    # device_get (one round trip), optionally over a bf16 wire.
    vu_pad = _round_up(vu, max(n_data, 1))
    vi_pad = _round_up(vi, max(n_data, 1))
    uv_dev = tt.vectors(fitted["user"], vu_pad)
    iv_dev = tt.vectors(fitted["item"], vi_pad)
    if cfg.table_wire == "bfloat16":
        uv_dev = uv_dev.astype(jnp.bfloat16)
        iv_dev = iv_dev.astype(jnp.bfloat16)
    uv, iv = jax.device_get((uv_dev, iv_dev))
    user_vecs = np.asarray(uv, np.float32)[:n_users]
    item_vecs = np.asarray(iv, np.float32)[:n_items]
    if stats is not None:
        stats["tables_d2h_s"] = monotonic_s() - t0
        stats["table_wire"] = cfg.table_wire
    return TwoTowerModel(
        user_vectors=user_vecs, item_vectors=item_vecs, config=cfg
    )
