"""Sequence recommender — causal transformer over user event histories.

The reference has no sequential model (nearest concepts: MarkovChain in e2,
ALS over an interaction matrix — SURVEY.md §2.5); this model family makes
the framework's long-context support real: next-item prediction over a
user's **entire event history**, SASRec-style.

One jitted train step composes every parallelism axis in the mesh
(pio_tpu/parallel/mesh.py):

- **dp**    — batch rows shard over ``data``; the loss mean psums there.
- **sp**    — the sequence shards over ``seq``; attention is exact ring
  attention (pio_tpu/parallel/ring.py), K/V blocks rotating by ppermute.
- **tp**    — attention heads and FFN hidden shard over ``model``
  (Megatron split: column-parallel in, row-parallel out + psum).
- **ep**    — the item-embedding table shards by vocab rows over ``model``;
  logits use *vocab-parallel* cross-entropy (local partial logits, pmax /
  psum assembled log-softmax) so the ``[B, T, V]`` tensor never exists
  unsharded.
- **pp**    — transformer blocks stack over ``pipe`` and microbatches flow
  through :func:`pio_tpu.parallel.pipeline.pipeline_apply`.

Everything is differentiated through ``shard_map``; JAX transposes the
collectives (psum↔broadcast, ppermute↔reverse ppermute, gather↔scatter).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import numpy as np

from pio_tpu.parallel.mesh import mesh_axis_size
from pio_tpu.parallel.vocab import (
    vocab_parallel_lookup,
    vocab_parallel_target_gather,
)
from pio_tpu.utils.numutil import round_up as _round_up


@dataclasses.dataclass(frozen=True)
class SeqRecConfig:
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    ffn: int = 128
    max_len: int = 64
    dropout: float = 0.0  # reserved; deterministic v1
    learning_rate: float = 1e-3
    steps: int = 200
    #: sequence-parallel attention mode: "ring" (ppermute K/V rotation,
    #: O(T/n) memory — longest contexts) or "ulysses" (two all-to-alls,
    #: full-T for H/n heads — fewer collective hops; needs the local head
    #: count divisible by the seq-axis size). See pio_tpu/parallel/.
    attention: str = "ring"
    seed: int = 0
    #: rows per optimizer step. 0 = full-batch (every step consumes the
    #: whole dataset — the historical path); > 0 = minibatch SGD over
    #: wrapped contiguous row blocks, which is what lets the epoch
    #: STREAM through the mesh instead of staging on device.
    batch_size: int = 0
    #: epoch feed for the minibatch path: "off" stages the full epoch
    #: on device, "on" streams row spans through parallel/stream.py,
    #: "auto" streams only when staging would exceed
    #: PIO_TPU_DEVICE_BUDGET_BYTES. Streamed and staged runs with the
    #: same seed/config produce identical params.
    stream: str = "auto"


@dataclasses.dataclass
class SeqRecModel:
    """Trained transformer; host copies of params for persistence/serving."""

    params: dict  # layer-stacked pytree (host numpy)
    n_items: int
    config: SeqRecConfig
    _serve_cache: Optional[tuple] = dataclasses.field(
        default=None, init=False, repr=False, compare=False
    )

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_serve_cache"] = None
        return state

    def next_item_scores(self, histories: np.ndarray) -> np.ndarray:
        """[B, T] padded histories (0 = pad) → [B, V] next-item scores.

        Single-device serving path; jitted + device-cached like
        MLPModel (pio_tpu/models/mlp.py).
        """
        import jax
        import jax.numpy as jnp

        if self._serve_cache is None:
            params = jax.tree.map(jnp.asarray, self.params)

            @jax.jit
            def fwd(params, seqs):
                h = _trunk(params, seqs, self.config, None, None, None)
                # score from the last real position of each row
                lengths = (seqs > 0).sum(axis=1)
                last = jnp.take_along_axis(
                    h,
                    jnp.maximum(lengths - 1, 0)[:, None, None],
                    axis=1,
                )[:, 0]
                return jnp.dot(
                    last,
                    params["emb"].T,
                    preferred_element_type=jnp.float32,
                )

            self._serve_cache = (fwd, params)
        fwd, params = self._serve_cache
        return np.asarray(fwd(params, jnp.asarray(histories, jnp.int32)))


def init_params(vocab: int, cfg: SeqRecConfig):
    """Layer-stacked parameter pytree (leading dim = n_layers)."""
    import jax

    k = jax.random.PRNGKey(cfg.seed)
    keys = jax.random.split(k, 8)
    D, F, L = cfg.d_model, cfg.ffn, cfg.n_layers
    s = D ** -0.5

    def nrm(key, shape, scale):
        return jax.random.normal(key, shape) * scale

    return {
        "emb": nrm(keys[0], (vocab, D), s),
        "pos": nrm(keys[1], (cfg.max_len, D), s),
        "blocks": {
            "ln1_g": np.ones((L, D), np.float32),
            "ln1_b": np.zeros((L, D), np.float32),
            "wq": nrm(keys[2], (L, D, D), s),
            "wk": nrm(keys[6], (L, D, D), s),
            "wv": nrm(keys[7], (L, D, D), s),
            "wo": nrm(keys[3], (L, D, D), s),
            "ln2_g": np.ones((L, D), np.float32),
            "ln2_b": np.zeros((L, D), np.float32),
            "w1": nrm(keys[4], (L, D, F), s),
            "b1": np.zeros((L, F), np.float32),
            "w2": nrm(keys[5], (L, F, D), F ** -0.5),
            "b2": np.zeros((L, D), np.float32),
        },
        "lnf_g": np.ones((D,), np.float32),
        "lnf_b": np.zeros((D,), np.float32),
    }


def param_specs(cfg: SeqRecConfig):
    """PartitionSpecs: ep for emb, tp for heads/ffn, pp over the stack —
    derived from the partition-rule registry (``rules_for("seqrec")``)."""
    from pio_tpu.parallel.partition import match_partition_rules, rules_for

    block_keys = (
        "ln1_g", "ln1_b", "wq", "wk", "wv", "wo",
        "ln2_g", "ln2_b", "w1", "b1", "w2", "b2",
    )
    skeleton = {
        "emb": np.empty(0),
        "pos": np.empty(0),
        "blocks": {k: np.empty(0) for k in block_keys},
        "lnf_g": np.empty(0),
        "lnf_b": np.empty(0),
    }
    return match_partition_rules(
        rules_for("seqrec"), skeleton, on_unmatched="error"
    )


def _ln(x, g, b):
    import jax

    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * g + b


def _block(blk, h, cfg, m_axis, s_axis):
    """One pre-LN transformer block on the local [mb, T_loc, D] slice.

    ``blk`` leaves have NO layer dim (already sliced). Heads/FFN hidden are
    local tp shards; attention rides the ring over ``s_axis``.
    """
    import jax
    import jax.numpy as jnp

    from pio_tpu.parallel.compat import axis_size
    from pio_tpu.parallel.ring import ring_attention
    from pio_tpu.parallel.ulysses import ulysses_attention

    mb, t_loc, D = h.shape
    n_model = 1 if m_axis is None else axis_size(m_axis)
    heads_loc = cfg.n_heads // n_model
    hd = cfg.d_model // cfg.n_heads
    if cfg.attention == "ring":
        attn_fn = ring_attention
    elif cfg.attention == "ulysses":
        attn_fn = ulysses_attention
    else:
        raise ValueError(
            f"unknown attention mode {cfg.attention!r}; use ring/ulysses"
        )

    x = _ln(h, blk["ln1_g"], blk["ln1_b"])
    # separate projections: a fused [D, 3D] column shard would split at
    # arbitrary offsets and scramble the q/k/v boundaries across devices
    q = jnp.dot(x, blk["wq"], preferred_element_type=jnp.float32)
    k = jnp.dot(x, blk["wk"], preferred_element_type=jnp.float32)
    v = jnp.dot(x, blk["wv"], preferred_element_type=jnp.float32)

    def split_heads(a):
        return a.reshape(mb, t_loc, heads_loc, hd)

    attn = attn_fn(
        split_heads(q), split_heads(k), split_heads(v),
        axis=s_axis, causal=True,
    ).reshape(mb, t_loc, heads_loc * hd)
    out = jnp.dot(attn, blk["wo"], preferred_element_type=jnp.float32)
    if m_axis is not None:
        out = jax.lax.psum(out, m_axis)  # close row-parallel wo (tp)
    h = h + out

    x = _ln(h, blk["ln2_g"], blk["ln2_b"])
    f = jnp.maximum(
        jnp.dot(x, blk["w1"], preferred_element_type=jnp.float32)
        + blk["b1"],
        0.0,
    )
    f = jnp.dot(f, blk["w2"], preferred_element_type=jnp.float32)
    if m_axis is not None:
        f = jax.lax.psum(f, m_axis)
    return h + f + blk["b2"]


def _embed(params, seqs, cfg, m_axis, s_axis):
    """Vocab-parallel embedding + global-position encoding → [mb, T_loc, D]."""
    import jax
    import jax.numpy as jnp

    x = vocab_parallel_lookup(params["emb"], seqs, m_axis)
    t_loc = seqs.shape[1]
    t_off = 0 if s_axis is None else jax.lax.axis_index(s_axis) * t_loc
    pos = jax.lax.dynamic_slice_in_dim(params["pos"], t_off, t_loc)
    return x + pos[None]


def _trunk(params, seqs, cfg, m_axis, s_axis, p_axis):
    """Embed + all transformer blocks + final LN → [mb, T_loc, D].

    With a pipe axis the blocks run through pipeline_apply (the whole local
    batch as ONE microbatch per tick slot — callers microbatch upstream);
    otherwise a scan over the layer stack.
    """
    import jax
    import jax.numpy as jnp

    h = _embed(params, seqs, cfg, m_axis, s_axis)
    blocks = params["blocks"]

    def apply_stack(h, stack):
        def body(h, blk):
            return _block(blk, h, cfg, m_axis, s_axis), None

        h, _ = jax.lax.scan(body, h, stack)
        return h

    if p_axis is None:
        h = apply_stack(h, blocks)
    else:
        from pio_tpu.parallel.compat import axis_size
        from pio_tpu.parallel.pipeline import pipeline_apply

        # Microbatch so the pipe stays busy: with one microbatch every
        # stage computes discarded garbage for (n_pipe-1)/n_pipe of the
        # ticks. n_pipe microbatches ≈ 50% steady-state utilization.
        n_pipe = axis_size(p_axis)
        mb = h.shape[0]
        m = n_pipe if mb % n_pipe == 0 else 1
        hm = h.reshape(m, mb // m, *h.shape[1:])
        h = pipeline_apply(
            blocks, hm, lambda stack, x: apply_stack(x, stack),
            axis=p_axis,
        ).reshape(h.shape)
    return _ln(h, params["lnf_g"], params["lnf_b"])


def _vocab_parallel_ce(h, emb, targets, mask, m_axis):
    """CE over the vocab-sharded logits; [mb, T_loc] masked mean parts.

    Returns (sum_ce, sum_mask) — caller psums over data/seq axes.
    """
    import jax
    import jax.numpy as jnp

    logits = jnp.einsum(
        "btd,vd->btv", h, emb, preferred_element_type=jnp.float32
    )  # local vocab shard
    if m_axis is None:
        z = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(
            logits, targets[..., None], axis=-1
        )[..., 0]
    else:
        rows = emb.shape[0]
        offset = jax.lax.axis_index(m_axis) * rows
        # The stability shift carries no gradient (it cancels in
        # logsumexp), and pmax has no differentiation rule — so detach the
        # local max and reduce it with the (linear, differentiable)
        # all_gather instead.
        gmax = jax.lax.all_gather(
            jax.lax.stop_gradient(logits.max(axis=-1)), m_axis
        ).max(axis=0)
        z = gmax + jnp.log(
            jax.lax.psum(
                jnp.exp(logits - gmax[..., None]).sum(axis=-1), m_axis
            )
        )
        tgt = vocab_parallel_target_gather(logits, targets, m_axis)
    ce = (z - tgt) * mask
    return ce.sum(), mask.sum()


def train_seqrec(
    mesh,
    sequences: np.ndarray,
    n_items: int,
    config: SeqRecConfig = SeqRecConfig(),
    checkpoint=None,
    checkpoint_every: int = 0,
    stats=None,
) -> SeqRecModel:
    """Next-item training over padded histories.

    Args:
        mesh: build_mesh() mesh — data/seq/model/pipe all honored; None →
            single-device.
        sequences: [n, T] int32, item ids ≥ 1, 0 = pad (right-padded).
        n_items: vocabulary size (ids are 1..n_items; row 0 = pad).
        checkpoint/checkpoint_every: optional
            pio_tpu.workflow.checkpoint.CheckpointManager + snapshot
            interval in steps; resumes from the newest snapshot on restart.
        stats: optional dict — streamed runs report the executor phases
            (h2d_s/device_s/h2d_bytes/encode_s) plus n_stream; all runs
            report place_s/steps_s (profiling only: phases serialize).

    Raises:
        DeviceBudgetExceeded: the params can't fit (single-chip or even
            sharded), or the staged epoch can't fit next to them and
            ``batch_size`` is 0 so the feed cannot stream (full-batch
            steps need the whole dataset resident).
    """
    import jax
    import jax.numpy as jnp
    import optax
    from pio_tpu.parallel.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = config
    n_data = mesh_axis_size(mesh, "data")
    n_seq = mesh_axis_size(mesh, "seq")
    n_model = mesh_axis_size(mesh, "model")
    n_pipe = mesh_axis_size(mesh, "pipe")
    m_axis = "model" if mesh is not None else None
    s_axis = "seq" if mesh is not None else None
    p_axis = "pipe" if (mesh is not None and n_pipe > 1) else None

    if cfg.stream not in ("auto", "on", "off"):
        raise ValueError(
            f"stream must be auto/on/off, got {cfg.stream!r}"
        )
    if cfg.stream == "on" and cfg.batch_size <= 0:
        raise ValueError(
            "stream='on' needs batch_size > 0 (full-batch steps consume "
            "the whole dataset every step — nothing to stream)"
        )
    if cfg.n_heads % n_model:
        raise ValueError("n_heads must divide by the model axis")
    if cfg.n_layers % max(n_pipe, 1):
        raise ValueError("n_layers must divide by the pipe axis")
    if cfg.attention not in ("ring", "ulysses"):
        raise ValueError(
            f"unknown attention mode {cfg.attention!r}; use ring/ulysses"
        )
    if cfg.attention == "ulysses" and (cfg.n_heads // max(n_model, 1)) % max(
        n_seq, 1
    ):
        raise ValueError(
            "ulysses attention needs the per-device head count "
            f"(n_heads {cfg.n_heads} / model axis {n_model} = "
            f"{cfg.n_heads // max(n_model, 1)}) divisible by the seq axis "
            f"({n_seq}); use ring attention or adjust n_heads"
        )

    seqs = np.asarray(sequences, np.int32)
    n, t = seqs.shape
    t_pad = _round_up(min(t, cfg.max_len), n_seq)
    if t_pad > cfg.max_len:
        raise ValueError(
            f"max_len {cfg.max_len} not a multiple of seq axis {n_seq}"
        )
    buf = np.zeros((_round_up(n, n_data), t_pad), np.int32)
    if t <= t_pad:
        buf[:n, :t] = seqs
    else:
        # keep each row's NEWEST t_pad events: serving scores the tail of
        # the history (next_item_scores on codes[-max_len:]), so training
        # on the head would skew heavy users onto stale behavior
        for r in range(n):
            codes = seqs[r][seqs[r] > 0][-t_pad:]
            buf[r, : len(codes)] = codes
    seqs = buf

    if cfg.batch_size > 0:
        # minibatch SGD: contiguous row blocks with wraparound so every
        # scan step slices a full batch (the two_tower discipline)
        B = _round_up(min(cfg.batch_size, max(n, 1)), n_data)
        reps = _round_up(max(n, B), B)
        seqs = np.resize(seqs[:max(n, 1)], (reps, t_pad))
        n_batches = reps // B
    else:
        B, n_batches = seqs.shape[0], 1

    # next-item targets: target[t] = seq[t+1]; last position unsupervised
    targets = np.zeros_like(seqs)
    targets[:, :-1] = seqs[:, 1:]
    mask = (targets > 0) & (seqs > 0)

    vocab = _round_up(n_items + 1, n_model)  # +1 for the pad row
    tx = optax.adam(cfg.learning_rate)
    specs = param_specs(cfg)

    # placement accounting BEFORE anything lands on device (the
    # two_tower discipline): sharded params must fit the per-chip
    # budget, and the staged epoch must fit NEXT TO them or the feed
    # streams row spans instead
    from pio_tpu.parallel.partition import (
        DeviceBudgetExceeded,
        assert_device_budget,
        device_budget_bytes,
        per_device_nbytes,
    )

    def _skeleton():
        D, F, L, T = cfg.d_model, cfg.ffn, cfg.n_layers, cfg.max_len
        z = np.zeros((), np.float32)

        def bt(*shape):
            return np.broadcast_to(z, shape)

        return {
            "emb": bt(vocab, D),
            "pos": bt(T, D),
            "blocks": {
                "ln1_g": bt(L, D), "ln1_b": bt(L, D),
                "wq": bt(L, D, D), "wk": bt(L, D, D), "wv": bt(L, D, D),
                "wo": bt(L, D, D), "ln2_g": bt(L, D), "ln2_b": bt(L, D),
                "w1": bt(L, D, F), "b1": bt(L, F),
                "w2": bt(L, F, D), "b2": bt(L, D),
            },
            "lnf_g": bt(D), "lnf_b": bt(D),
        }

    skeleton = _skeleton()
    params_nbytes = sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(skeleton)
    )
    if mesh is None:
        assert_device_budget(
            params_nbytes, 1, "seqrec params (single-chip placement)"
        )
        params_pd = params_nbytes
    else:
        params_pd = per_device_nbytes(mesh, skeleton, specs)
        assert_device_budget(params_pd, 1, "seqrec sharded params")
    # seqs + targets (int32) + mask (float32), sharded over data × seq
    staged_pd = -(-12 * seqs.shape[0] * t_pad // (n_data * n_seq))
    budget = device_budget_bytes()
    over = budget > 0 and params_pd + staged_pd > budget
    streamed = cfg.batch_size > 0 and (
        cfg.stream == "on" or (cfg.stream == "auto" and over)
    )
    if over and cfg.batch_size <= 0 and cfg.stream != "off":
        raise DeviceBudgetExceeded(
            f"seqrec staged epoch ({staged_pd} B/device) does not fit "
            f"beside the params ({params_pd} B/device) under "
            f"PIO_TPU_DEVICE_BUDGET_BYTES={budget}; set batch_size > 0 "
            f"so the feed can stream row spans"
        )
    n_stream = 0
    if streamed:
        from pio_tpu.parallel.stream import n_stream_chunks

        n_stream = max(
            2,
            n_stream_chunks(12 * seqs.shape[0] * t_pad,
                            "PIO_TPU_TRAIN_STREAM_MB", cap=256),
        )
        if budget > params_pd:
            n_stream = max(n_stream, -(-staged_pd // (budget - params_pd)))
        n_stream = min(n_batches, n_stream)
    if stats is not None:
        stats["n_stream"] = n_stream

    def global_loss(params, seqs, targets, mask):
        if mesh is None:
            h = _trunk(params, seqs, cfg, None, None, None)
            ce, denom = _vocab_parallel_ce(
                h, params["emb"], targets, mask, None
            )
            return ce / jnp.maximum(denom, 1.0)

        def inner(params, seqs, targets, mask):
            h = _trunk(params, seqs, cfg, m_axis, s_axis, p_axis)
            ce, denom = _vocab_parallel_ce(
                h, params["emb"], targets, mask, m_axis
            )
            ce = jax.lax.psum(ce, ("data", "seq"))
            denom = jax.lax.psum(denom, ("data", "seq"))
            return ce / jnp.maximum(denom, 1.0)

        dspec = P("data", "seq")
        return shard_map(
            inner,
            mesh=mesh,
            in_specs=(specs, dspec, dspec, dspec),
            out_specs=P(),
            check_vma=False,
        )(params, seqs, targets, mask)

    mask = mask.astype(np.float32)

    def _init_all():
        p = init_params(vocab, cfg)
        return jax.tree.map(lambda a: jnp.asarray(a, jnp.float32), p)

    from pio_tpu.obs import monotonic_s

    t0 = monotonic_s()
    dsh = None
    if mesh is not None:
        psh = jax.tree.map(
            lambda spec: NamedSharding(mesh, spec),
            specs,
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
        )
        # each device materializes only its shard — the vocab-sharded
        # table never exists unsharded on any chip
        params = jax.jit(_init_all, out_shardings=psh)()
        dsh = NamedSharding(mesh, P("data", "seq"))
    else:
        params = jax.jit(_init_all)()

    def _put_epoch(s_np, t_np, m_np):
        if mesh is None:
            return jnp.asarray(s_np), jnp.asarray(t_np), jnp.asarray(m_np)
        return tuple(
            jax.device_put(jnp.asarray(a), dsh) for a in (s_np, t_np, m_np)
        )

    seqs_d = targets_d = mask_d = None
    if not streamed:
        seqs_d, targets_d, mask_d = _put_epoch(seqs, targets, mask)
    if stats is not None:
        jax.block_until_ready((params, seqs_d, targets_d, mask_d))
        stats["place_s"] = monotonic_s() - t0

    def _scan_steps(state, n, batch_fn):
        step0, params, opt_state = state

        def step(carry, i):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(global_loss)(
                params, *batch_fn(i, step0)
            )
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            step, (params, opt_state), jnp.arange(n)
        )
        # per-step losses ride along for the telemetry plane; callers
        # that don't want them drop the array undereferenced (no sync)
        return (step0 + n, params, opt_state), losses

    @functools.partial(jax.jit, static_argnums=1)
    def chunk_full(state, n):
        return _scan_steps(
            state, n, lambda i, step0: (seqs_d, targets_d, mask_d)
        )

    @functools.partial(jax.jit, static_argnums=1)
    def chunk_staged(state, n):
        def batch_fn(i, step0):
            start = ((step0 + i) % n_batches) * B
            return tuple(
                jax.lax.dynamic_slice_in_dim(a, start, B)
                for a in (seqs_d, targets_d, mask_d)
            )

        return _scan_steps(state, n, batch_fn)

    @functools.partial(jax.jit, static_argnums=4)
    def chunk_span(state, s_span, t_span, m_span, n):
        def batch_fn(i, step0):
            return tuple(
                jax.lax.dynamic_slice_in_dim(a, i * B, B)
                for a in (s_span, t_span, m_span)
            )

        return _scan_steps(state, n, batch_fn)

    from pio_tpu.obs import devicewatch, trainwatch

    trainwatch.begin_algo(
        "seqrec", total_steps=cfg.steps, n_batches=n_batches,
        streamed=streamed, n_stream=n_stream,
        per_device_bytes=params_pd,
    )
    # lagged loss drain (the two_tower discipline): per-step losses come
    # back as device arrays and are fetched one chunk behind the
    # dispatch frontier; no recorder → dropped undereferenced.
    _pending: list = []
    _last_drain = [monotonic_s()]

    def _drain(keep: int = 0):
        while len(_pending) > keep:
            n_s, dev = _pending.pop(0)
            vals = np.asarray(jax.device_get(dev), np.float32)
            now = monotonic_s()
            trainwatch.record_steps(
                int(n_s), losses=[float(v) for v in vals],
                examples=int(n_s) * B, dur_s=now - _last_drain[0],
            )
            _last_drain[0] = now

    def _note_chunk(n_s, losses_dev, keep: int):
        if trainwatch.active_recorder() is None:
            return
        _pending.append((n_s, losses_dev))
        _drain(keep)

    if streamed:
        from pio_tpu.parallel.stream import (
            epoch_spans,
            span_bounds,
            stream_feed,
        )

        bounds = span_bounds(n_batches, n_stream)

        def chunk_fn(state, n):
            _drain()
            step0 = int(jax.device_get(state[0]))
            work = epoch_spans(step0, n, n_batches, bounds)

            def encode(span):
                b0, b1 = span
                return tuple(
                    np.ascontiguousarray(a[b0 * B:b1 * B])
                    for a in (seqs, targets, mask)
                )

            def dispatch(st, dev, i):
                b0, b1 = work[i]
                st, losses = chunk_span(st, *dev, b1 - b0)
                _note_chunk(b1 - b0, losses, keep=2)
                return st

            return stream_feed(
                work,
                encode=encode,
                put=lambda host, _i: _put_epoch(*host),
                init_carry=lambda: state,
                dispatch=dispatch,
                lookahead=2,
                stats=stats,
            )

    elif cfg.batch_size > 0:
        def chunk_fn(state, n):
            _drain()
            # compile attribution: n is static in the jitted chunk, so
            # each distinct chunk length is its own trainer program
            with devicewatch.compile_span(
                "train_step", key=("seqrec", "staged", B, int(n))
            ):
                state, losses = chunk_staged(state, n)
            _note_chunk(n, losses, keep=1)
            return state
    else:
        def chunk_fn(state, n):
            _drain()
            with devicewatch.compile_span(
                "train_step", key=("seqrec", "full", int(n))
            ):
                state, losses = chunk_full(state, n)
            _note_chunk(n, losses, keep=1)
            return state

    from pio_tpu.workflow.checkpoint import (
        run_chunked_steps,
        state_fingerprint,
    )

    # steps excluded: resume with a different total must still match.
    # stream normalized: streamed and staged feeds walk the SAME batch
    # schedule, so their snapshots are interchangeable
    fingerprint = state_fingerprint(
        "seqrec", dataclasses.replace(cfg, steps=0, stream="auto"),
        n_items, seqs.shape, int(seqs.sum()),
    )
    state = (jnp.int32(0), params, jax.jit(tx.init)(params))
    state = run_chunked_steps(
        state, cfg.steps, chunk_fn,
        checkpoint=checkpoint, checkpoint_every=checkpoint_every,
        fingerprint=fingerprint,
    )
    _drain()  # flush the telemetry tail (no-op without a recorder)
    fitted = state[1]

    # ONE fused pull (device_get returns host numpy): per-leaf
    # np.asarray paid a host link round trip per parameter tensor
    host = jax.device_get(fitted)
    host["emb"] = host["emb"][: n_items + 1]
    return SeqRecModel(params=host, n_items=n_items, config=cfg)
