"""TF-IDF featurization — TPU-native rebuild of the MLlib HashingTF/IDF path.

The reference's text-classification template (upstream
``template-scala-parallel-textclassification``; the in-repo analog is the
MLlib ``HashingTF``/``IDF``/``NaiveBayes`` pipeline — UNVERIFIED; SURVEY.md
§2.5) featurizes documents on Spark as sparse vectors. The TPU rebuild keeps
documents **sparse on purpose**: a document becomes a (token-id, tf-idf
weight) bag that feeds :func:`pio_tpu.ops.embedding_bag`, so the first
model layer is a streamed sparse×dense matmul instead of a materialized
``[B, V]`` one-hot matrix.

Vocabulary is learned (top-``max_features`` by document frequency) rather
than hashed — hashing collisions cost accuracy and buy nothing on TPU where
the table row count only affects HBM footprint. Id 0 is reserved as the
padding row.
"""

from __future__ import annotations

import dataclasses
import re
from collections import Counter
from typing import Dict, List, Sequence, Tuple

import numpy as np

_TOKEN_RE = re.compile(r"[A-Za-z0-9']+")


def tokenize(text: str) -> List[str]:
    """Lowercase word tokens (letters/digits/apostrophes)."""
    return _TOKEN_RE.findall(text.lower())


@dataclasses.dataclass
class TfIdfVectorizer:
    """vocab: token → id (1-based; 0 is the pad row), idf: [V+1] float32."""

    vocab: Dict[str, int]
    idf: np.ndarray

    @property
    def n_features(self) -> int:
        """Table row count including the pad row."""
        return len(self.idf)

    @classmethod
    def fit(
        cls, docs: Sequence[str], max_features: int = 65536
    ) -> "TfIdfVectorizer":
        """Learn vocab + smoothed idf: ``log((1+N)/(1+df)) + 1``."""
        df: Counter = Counter()
        for doc in docs:
            df.update(set(tokenize(doc)))
        # deterministic order: by (-df, token)
        top = sorted(df.items(), key=lambda kv: (-kv[1], kv[0]))
        top = top[:max_features]
        vocab = {tok: i + 1 for i, (tok, _) in enumerate(top)}
        n = len(docs)
        idf = np.zeros(len(vocab) + 1, np.float32)
        for tok, i in vocab.items():
            idf[i] = np.log((1.0 + n) / (1.0 + df[tok])) + 1.0
        return cls(vocab=vocab, idf=idf)

    def transform_doc(self, doc: str) -> Tuple[List[int], List[float]]:
        """One document → (token ids, L2-normalized tf-idf weights)."""
        tf: Counter = Counter(
            self.vocab[t] for t in tokenize(doc) if t in self.vocab
        )
        if not tf:
            return [], []
        ids = sorted(tf)
        w = np.array([tf[i] for i in ids], np.float32) * self.idf[ids]
        norm = float(np.linalg.norm(w))
        if norm > 0:
            w = w / norm
        return ids, w.tolist()

    def transform(
        self, docs: Sequence[str], max_len: int | None = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Documents → padded (ids [B, L], weights [B, L]) bag arrays."""
        from pio_tpu.ops.embedding import pack_bags

        bags = [self.transform_doc(d) for d in docs]
        return pack_bags(
            [b[0] for b in bags], [b[1] for b in bags], max_len=max_len
        )
