"""Softmax / logistic regression — TPU-native classification trainer.

Rebuild of the reference classification template's training substrate:
MLlib's ``LogisticRegressionWithLBFGS`` / ``NaiveBayes``
(``examples/scala-parallel-classification``, UNVERIFIED paths; SURVEY.md
§2.6) runs full-batch gradient aggregation via Spark ``treeAggregate`` over
executor partitions.

TPU-first formulation: examples are sharded over the mesh ``data`` axis
(NamedSharding); parameters stay replicated. The per-device partial gradient
reduction that ``treeAggregate`` did over netty becomes the ``psum`` XLA
inserts over ICI when a mean over the sharded batch dimension flows into
replicated outputs — no hand-written collectives. The whole optimization
loop is a single compiled program (``lax.scan`` over iterations), so HBM
never round-trips to host between steps.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import numpy as np

from pio_tpu.utils.numutil import n_stream_chunks


@functools.lru_cache(maxsize=32)
def _jitted_fit(mesh, axis: str, n_parts: int, iterations: int,
                learning_rate: float, reg: float):
    """Build (once per static config) the jitted full-batch trainer.

    Cached so repeat trains — production retrains, benchmark repeats —
    reuse the compiled program instead of paying a fresh trace+XLA
    compile per call (the scan over ``iterations`` is the expensive
    compile). Everything run-dependent (params, feature chunks, labels,
    mask, quantization scales) is an ARGUMENT, never a baked constant;
    jax's own dispatch cache handles shape/dtype/backend variation
    under the one wrapper.
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    tx = optax.adam(learning_rate)

    def loss_fn(params, Xs, ys, ms, scales):
        w = params["w"]
        if scales is not None:
            # X ≈ X_q·s  ⇒  X@W = X_q@(s⊙W): a [D,C] elementwise per
            # step instead of a dequantized [N,D] HBM copy
            w = w * scales[:, None]
        if Xs.dtype == jnp.int8:
            Xs = Xs.astype(jnp.bfloat16)
        logits = (
            jnp.dot(Xs, w.astype(Xs.dtype),
                    preferred_element_type=jnp.float32)
            + params["b"]
        )
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, ys)
        # mean over real rows only; over sharded inputs this contraction
        # is where XLA inserts the cross-device psum (≙ treeAggregate)
        data_loss = jnp.sum(ce * ms) / jnp.sum(ms)
        return data_loss + reg * jnp.sum(params["w"] ** 2)

    def fit(params, X_parts, ys, ms, scales):
        # chunked wire arrives as row spans: assembled once here
        # (device-side copy at HBM rate), OUTSIDE the scan
        Xs = X_parts[0] if len(X_parts) == 1 else jnp.concatenate(X_parts)
        opt_state = tx.init(params)

        def step(carry, _):
            params, opt_state = carry
            grads = jax.grad(loss_fn)(params, Xs, ys, ms, scales)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), None

        (params, _), _ = jax.lax.scan(
            step, (params, opt_state), None, length=iterations
        )
        return params

    if mesh is not None:
        shard = NamedSharding(mesh, P(axis))
        repl = NamedSharding(mesh, P())
        return jax.jit(
            fit,
            in_shardings=(repl, (shard,) * n_parts, shard, shard, repl),
            out_shardings=repl,
        )
    return jax.jit(fit)


@dataclasses.dataclass(frozen=True)
class LogRegConfig:
    iterations: int = 100
    learning_rate: float = 0.1
    reg: float = 0.0  # L2 on weights (not bias)
    seed: int = 0
    #: feature wire + matmul dtype. "float32" (default) keeps exact
    #: full-precision numerics, matching the reference's MLlib path.
    #: Opt into "bfloat16" to halve the host→device feature shipment —
    #: the dominant cost of a full-batch train on a slow link — and run
    #: the logits matmul at the MXU's native rate. Opt into "int8" to
    #: quarter it: features ship as symmetric per-column int8 codes and
    #: the [D] float32 scales fold into the WEIGHTS on device
    #: (X ≈ X_q·s, so X@W = X_q@(s⊙W) — one tiny [D,C] elementwise per
    #: step, no dequantized [N,D] copy), so the learned weights still
    #: apply to raw float features at serving time. Gradients, optimizer
    #: state, and the loss stay float32 in every mode.
    input_dtype: str = "float32"


@dataclasses.dataclass
class LogRegModel:
    """weights [D, C] float32, bias [C] float32, plus class count.

    ``feature_scales`` [D] float32 are the per-column symmetric
    quantization scales observed on the TRAINING features (None on
    models persisted before they were recorded): the serving-side int8
    wire folds them into device-resident weights so query features can
    ship as one byte per column (see ``pio_tpu/server/residency.py``).
    """

    weights: np.ndarray
    bias: np.ndarray
    n_classes: int
    feature_scales: Optional[np.ndarray] = None

    def logits(self, X: np.ndarray) -> np.ndarray:
        return X.astype(np.float32) @ self.weights + self.bias

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Argmax class codes for a [B, D] feature matrix."""
        return np.argmax(self.logits(X), axis=1).astype(np.int32)

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        z = self.logits(X)
        z = z - z.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)


def train_logreg(
    ctx,
    X: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    config: LogRegConfig = LogRegConfig(),
    stats: Optional[dict] = None,
) -> LogRegModel:
    """Full-batch softmax regression with Adam, data-parallel over the mesh.

    Args:
        ctx: ComputeContext (mesh + batch axis); mesh=None → single device.
        X: [N, D] features (host numpy).
        y: [N] int class codes.
        n_classes: C.
        stats: optional dict that receives a phase decomposition of the
            run — pack_s (host encode), h2d_s (wire drain), device_s,
            d2h_s — with the h2d/compute overlap serialized so the
            phases are measurable (stats runs are slightly slower than
            plain runs, exactly like ``train_als``'s profiled mode).
    """
    import jax
    import jax.numpy as jnp

    if config.input_dtype not in ("bfloat16", "float32", "int8"):
        raise ValueError(
            f"input_dtype must be bfloat16/float32/int8, "
            f"got {config.input_dtype!r}"
        )
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.int32)
    n, d = X.shape

    mesh = ctx.mesh if ctx is not None else None
    axis = ctx.batch_axis if ctx is not None else "data"
    n_dev = ctx.num_devices if ctx is not None else 1

    # pad batch to a multiple of the device count; padded rows carry 0 weight
    n_pad = (-n) % max(n_dev, 1)
    if n_pad:
        X = np.concatenate([X, np.zeros((n_pad, d), np.float32)])
        y = np.concatenate([y, np.zeros(n_pad, np.int32)])
    mask = np.concatenate(
        [np.ones(n, np.float32), np.zeros(n_pad, np.float32)]
    )

    w_key = jax.random.PRNGKey(config.seed)
    params = {
        # small seeded init: breaks symmetry and makes `seed` a live knob
        "w": 0.01 * jax.random.normal(w_key, (d, n_classes), jnp.float32),
        "b": jnp.zeros((n_classes,), jnp.float32),
    }

    # per-column symmetric quantization scales: the int8 TRAINING wire
    # folds them into the weights on device so the learned W applies to
    # RAW floats; they also persist on the model (every mode — the pass
    # is one reduction) so the SERVING int8 wire can quantize query
    # features with the same training-side scales
    s = np.abs(X).max(axis=0)
    feature_scales = np.where(s == 0.0, 1.0, s / 127.0).astype(np.float32)
    scales = feature_scales if config.input_dtype == "int8" else None

    def _prep(chunk: np.ndarray) -> np.ndarray:
        """Host-side wire encoding of a row span (the per-chunk work the
        streamed path overlaps with the previous chunk's transfer)."""
        if config.input_dtype == "bfloat16":
            # cast on the HOST (ml_dtypes ships with jax) so only
            # 2 B/feature cross the link; a device-side cast would ship
            # f32 first
            import ml_dtypes

            return chunk.astype(ml_dtypes.bfloat16)
        if config.input_dtype == "int8":
            return np.clip(
                np.rint(chunk / scales), -127, 127
            ).astype(np.int8)
        return chunk

    # chunked double-buffered shipment (single-device path): encode span
    # k+1 on host while span k is still crossing the link (device_put is
    # async). Multi-device runs keep one put per device shard — chunking
    # WITHIN shards is the mesh-wire streaming discipline (als.py).
    itemsize = {"bfloat16": 2, "int8": 1}.get(config.input_dtype, 4)
    wire_bytes = X.shape[0] * d * itemsize
    n_stream = 1
    if mesh is None or n_dev == 1:
        n_stream = n_stream_chunks(wire_bytes, "PIO_TPU_LOGREG_STREAM_MB")
    bounds = np.linspace(0, X.shape[0], n_stream + 1, dtype=int)
    spans = [(a, b) for a, b in zip(bounds[:-1], bounds[1:]) if b > a]

    fit = _jitted_fit(mesh, axis, len(spans), config.iterations,
                      config.learning_rate, config.reg)

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        shard = NamedSharding(mesh, P(axis))
        repl = NamedSharding(mesh, P())
        put_x = lambda a: jax.device_put(a, shard)
        put_r = lambda a: jax.device_put(a, repl)
    else:
        put_x = put_r = jax.device_put
    from pio_tpu.obs import monotonic_s

    scales_dev = put_r(jnp.asarray(scales)) if scales is not None else None
    ys_dev = put_x(y)
    ms_dev = put_x(mask)
    params_dev = put_r(params)
    if stats is not None:
        # serialize pack vs drain: encode every span first (pack_s),
        # then let the transfers drain (h2d_s) — overlap off, like
        # train_als's profiled mode
        t0 = monotonic_s()
        encoded = [_prep(X[a:b]) for a, b in spans]
        stats["pack_s"] = monotonic_s() - t0
        t0 = monotonic_s()
        X_parts = tuple(put_x(e) for e in encoded)
        jax.block_until_ready((X_parts, ys_dev, ms_dev, params_dev))
        stats["h2d_s"] = monotonic_s() - t0
        stats["wire_bytes"] = int(
            wire_bytes + y.nbytes + mask.nbytes
        )
        stats["n_stream"] = len(spans)
        t0 = monotonic_s()
    else:
        X_parts = tuple(put_x(_prep(X[a:b])) for a, b in spans)
    fitted = fit(params_dev, X_parts, ys_dev, ms_dev, scales_dev)
    if stats is not None:
        jax.block_until_ready(fitted)
        stats["device_s"] = monotonic_s() - t0
        t0 = monotonic_s()
    # one fused pull: separate np.asarray calls pay the tunnel RTT twice
    weights, bias = jax.device_get((fitted["w"], fitted["b"]))
    weights, bias = np.asarray(weights), np.asarray(bias)
    if stats is not None:
        stats["d2h_s"] = monotonic_s() - t0

    return LogRegModel(
        weights=weights, bias=bias, n_classes=n_classes,
        feature_scales=feature_scales,
    )
