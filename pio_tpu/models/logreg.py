"""Softmax / logistic regression — TPU-native classification trainer.

Rebuild of the reference classification template's training substrate:
MLlib's ``LogisticRegressionWithLBFGS`` / ``NaiveBayes``
(``examples/scala-parallel-classification``, UNVERIFIED paths; SURVEY.md
§2.6) runs full-batch gradient aggregation via Spark ``treeAggregate`` over
executor partitions.

TPU-first formulation: examples are sharded over the mesh ``data`` axis
(NamedSharding); parameters stay replicated. The per-device partial gradient
reduction that ``treeAggregate`` did over netty becomes the ``psum`` XLA
inserts over ICI when a mean over the sharded batch dimension flows into
replicated outputs — no hand-written collectives. The whole optimization
loop is a single compiled program (``lax.scan`` over iterations), so HBM
never round-trips to host between steps.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class LogRegConfig:
    iterations: int = 100
    learning_rate: float = 0.1
    reg: float = 0.0  # L2 on weights (not bias)
    seed: int = 0
    #: feature wire + matmul dtype. "float32" (default) keeps exact
    #: full-precision numerics, matching the reference's MLlib path.
    #: Opt into "bfloat16" to halve the host→device feature shipment —
    #: the dominant cost of a full-batch train on a slow link — and run
    #: the logits matmul at the MXU's native rate; gradients, optimizer
    #: state, and the loss stay float32 either way.
    input_dtype: str = "float32"


@dataclasses.dataclass
class LogRegModel:
    """weights [D, C] float32, bias [C] float32, plus class count."""

    weights: np.ndarray
    bias: np.ndarray
    n_classes: int

    def logits(self, X: np.ndarray) -> np.ndarray:
        return X.astype(np.float32) @ self.weights + self.bias

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Argmax class codes for a [B, D] feature matrix."""
        return np.argmax(self.logits(X), axis=1).astype(np.int32)

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        z = self.logits(X)
        z = z - z.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)


def train_logreg(
    ctx,
    X: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    config: LogRegConfig = LogRegConfig(),
) -> LogRegModel:
    """Full-batch softmax regression with Adam, data-parallel over the mesh.

    Args:
        ctx: ComputeContext (mesh + batch axis); mesh=None → single device.
        X: [N, D] features (host numpy).
        y: [N] int class codes.
        n_classes: C.
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if config.input_dtype not in ("bfloat16", "float32"):
        raise ValueError(
            f"input_dtype must be bfloat16/float32, "
            f"got {config.input_dtype!r}"
        )
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.int32)
    n, d = X.shape

    mesh = ctx.mesh if ctx is not None else None
    axis = ctx.batch_axis if ctx is not None else "data"
    n_dev = ctx.num_devices if ctx is not None else 1

    # pad batch to a multiple of the device count; padded rows carry 0 weight
    n_pad = (-n) % max(n_dev, 1)
    if n_pad:
        X = np.concatenate([X, np.zeros((n_pad, d), np.float32)])
        y = np.concatenate([y, np.zeros(n_pad, np.int32)])
    mask = np.concatenate(
        [np.ones(n, np.float32), np.zeros(n_pad, np.float32)]
    )

    tx = optax.adam(config.learning_rate)
    w_key = jax.random.PRNGKey(config.seed)
    params = {
        # small seeded init: breaks symmetry and makes `seed` a live knob
        "w": 0.01 * jax.random.normal(w_key, (d, n_classes), jnp.float32),
        "b": jnp.zeros((n_classes,), jnp.float32),
    }

    def loss_fn(params, Xs, ys, ms):
        logits = (
            jnp.dot(Xs, params["w"].astype(Xs.dtype),
                    preferred_element_type=jnp.float32)
            + params["b"]
        )
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, ys)
        # mean over real rows only; over sharded inputs this contraction is
        # where XLA inserts the cross-device psum (≙ treeAggregate)
        data_loss = jnp.sum(ce * ms) / jnp.sum(ms)
        return data_loss + config.reg * jnp.sum(params["w"] ** 2)

    def fit(params, Xs, ys, ms):
        opt_state = tx.init(params)

        def step(carry, _):
            params, opt_state = carry
            grads = jax.grad(loss_fn)(params, Xs, ys, ms)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), None

        (params, _), _ = jax.lax.scan(
            step, (params, opt_state), None, length=config.iterations
        )
        return params

    if config.input_dtype == "bfloat16":
        # cast on the HOST (ml_dtypes ships with jax) so only 2 B/feature
        # cross the link; a device-side cast would ship f32 first
        import ml_dtypes

        X = X.astype(ml_dtypes.bfloat16)

    if mesh is not None:
        shard = NamedSharding(mesh, P(axis))
        repl = NamedSharding(mesh, P())
        Xs = jax.device_put(jnp.asarray(X), shard)
        ys = jax.device_put(jnp.asarray(y), shard)
        ms = jax.device_put(jnp.asarray(mask), shard)
        fitted = jax.jit(
            fit,
            in_shardings=(repl, shard, shard, shard),
            out_shardings=repl,
        )(jax.device_put(params, repl), Xs, ys, ms)
    else:
        fitted = jax.jit(fit)(
            params, jnp.asarray(X), jnp.asarray(y), jnp.asarray(mask)
        )

    return LogRegModel(
        weights=np.asarray(fitted["w"]),
        bias=np.asarray(fitted["b"]),
        n_classes=n_classes,
    )
