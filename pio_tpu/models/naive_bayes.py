"""Categorical Naive Bayes — TPU-native rebuild of the reference's e2 helper.

Reference: ``e2/src/main/scala/o/a/p/e2/engine/CategoricalNaiveBayes.scala``
(UNVERIFIED path; see SURVEY.md §2.5) — trains on labeled points whose
features are *categorical strings per position*, producing per-label priors
and per-(label, position, value) conditional log-likelihoods with add-one
smoothing, then predicts the argmax-log-score label.

TPU-first formulation: instead of the reference's nested
``Map[String, Map[String, Double]]`` lookups per prediction, we encode each
feature position's vocabulary densely (BiMap-style) and materialize a
log-likelihood tensor per position ``L_f[label, value]``. Scoring a batch of
points is then a sum of gathers — and for fully-batched serving,
``predict_batch`` is a single jittable program (one-hot × log-likelihood
matmuls ride the MXU for wide vocabularies).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    """A training example: string label + categorical string features.

    ≙ reference ``LabeledPoint(label: String, features: Seq[String])``.
    """

    label: str
    features: Tuple[str, ...]


@dataclasses.dataclass
class NaiveBayesModel:
    """Dense categorical-NB model.

    Attributes:
        labels: label vocabulary, index = label code.
        feature_vocabs: per position, value vocabulary (index = value code).
        priors: [L] float32 log P(label).
        likelihoods: per position f, [L, V_f] float32 log P(value | label)
            with add-one smoothing.
    """

    labels: List[str]
    feature_vocabs: List[Dict[str, int]]
    priors: np.ndarray
    likelihoods: List[np.ndarray]

    def log_score(
        self,
        point: LabeledPoint,
        default_likelihood: Optional[float] = None,
    ) -> Optional[float]:
        """Log score of ``point`` under its own label.

        Returns None when the label is unknown, or when a feature value is
        out-of-vocabulary and no ``default_likelihood`` is given (parity with
        the reference's ``logScore(point, defaultLikelihood)`` Option result).
        """
        self._check_arity([point.features])
        if point.label not in self._label_index:
            return None
        li = self._label_index[point.label]
        total = float(self.priors[li])
        for f, value in enumerate(point.features):
            code = self.feature_vocabs[f].get(value)
            if code is None:
                if default_likelihood is None:
                    return None
                total += default_likelihood
            else:
                total += float(self.likelihoods[f][li, code])
        return total

    def _check_arity(self, features: Sequence[Sequence[str]]) -> None:
        want = len(self.feature_vocabs)
        for f in features:
            if len(f) != want:
                raise ValueError(
                    f"feature tuple has {len(f)} positions, model expects {want}"
                )

    def predict(self, features: Sequence[str]) -> str:
        """Label with the highest posterior log score."""
        self._check_arity([features])
        scores = self.priors.copy()
        for f, value in enumerate(features):
            code = self.feature_vocabs[f].get(value)
            if code is not None:
                scores = scores + self.likelihoods[f][:, code]
        return self.labels[int(np.argmax(scores))]

    def encode_batch(
        self, points: Sequence[Sequence[str]]
    ) -> List[np.ndarray]:
        """Encode feature strings to dense codes (-1 = out-of-vocab)."""
        self._check_arity(points)
        cols = []
        for f, vocab in enumerate(self.feature_vocabs):
            cols.append(
                np.fromiter(
                    (vocab.get(p[f], -1) for p in points),
                    np.int32,
                    len(points),
                )
            )
        return cols

    def predict_batch(self, points: Sequence[Sequence[str]]) -> List[str]:
        """Batched argmax prediction via vectorized jnp gather/sum ops."""
        if not points:
            return []
        import jax.numpy as jnp

        codes = self.encode_batch(points)
        scores = jnp.broadcast_to(
            jnp.asarray(self.priors), (len(points), len(self.labels))
        )
        for f, col in enumerate(codes):
            lik = jnp.asarray(self.likelihoods[f])  # [L, V_f]
            col = jnp.asarray(col)
            # OOV (-1) contributes 0; clamp index for the gather then mask.
            gathered = lik[:, jnp.clip(col, 0)].T  # [B, L]
            scores = scores + jnp.where(
                (col >= 0)[:, None], gathered, 0.0
            )
        best = np.asarray(jnp.argmax(scores, axis=1))
        return [self.labels[int(i)] for i in best]

    @property
    def _label_index(self) -> Dict[str, int]:
        if not hasattr(self, "_label_index_cache"):
            object.__setattr__(
                self,
                "_label_index_cache",
                {lb: i for i, lb in enumerate(self.labels)},
            )
        return self._label_index_cache  # type: ignore[attr-defined]


def train_naive_bayes(points: Sequence[LabeledPoint]) -> NaiveBayesModel:
    """Train categorical NB with add-one (Laplace) smoothing.

    ≙ reference ``CategoricalNaiveBayes.train``. Counting is vectorized:
    labels/values are dense-coded, then per-position count matrices come from
    ``np.add.at`` scatter-adds (the host-side analog of the segment-sum the
    TPU path uses for big corpora).
    """
    if not points:
        raise ValueError("train_naive_bayes needs at least one LabeledPoint")
    n_features = len(points[0].features)
    for p in points:
        if len(p.features) != n_features:
            raise ValueError(
                "all LabeledPoints must have the same number of features"
            )

    labels: List[str] = []
    label_index: Dict[str, int] = {}
    y = np.empty(len(points), np.int32)
    for i, p in enumerate(points):
        if p.label not in label_index:
            label_index[p.label] = len(labels)
            labels.append(p.label)
        y[i] = label_index[p.label]
    n_labels = len(labels)

    label_counts = np.bincount(y, minlength=n_labels).astype(np.float64)
    priors = np.log(label_counts / len(points)).astype(np.float32)

    feature_vocabs: List[Dict[str, int]] = []
    likelihoods: List[np.ndarray] = []
    for f in range(n_features):
        vocab: Dict[str, int] = {}
        codes = np.empty(len(points), np.int32)
        for i, p in enumerate(points):
            v = p.features[f]
            if v not in vocab:
                vocab[v] = len(vocab)
            codes[i] = vocab[v]
        counts = np.zeros((n_labels, len(vocab)), np.float64)
        np.add.at(counts, (y, codes), 1.0)
        # add-one smoothing over the observed vocabulary
        lik = np.log(
            (counts + 1.0)
            / (label_counts[:, None] + len(vocab))
        ).astype(np.float32)
        feature_vocabs.append(vocab)
        likelihoods.append(lik)

    return NaiveBayesModel(labels, feature_vocabs, priors, likelihoods)


# --------------------------------------------------------- multinomial NB
@dataclasses.dataclass
class MultinomialNBModel:
    """MLlib-``NaiveBayes``-parity model over numeric count features.

    Scoring a batch is ``log_prior + X @ log_theta.T`` — one MXU matmul.

    Attributes:
        log_prior: [C] float32.
        log_theta: [C, D] float32 — smoothed log feature weights.
        feature_scales: [D] float32 per-column int8 quantization scales
            observed on the training features (None for bag-trained or
            pre-existing models) — the serving int8 wire folds them into
            device-resident weights (``pio_tpu/server/residency.py``).
    """

    log_prior: np.ndarray
    log_theta: np.ndarray
    feature_scales: Optional[np.ndarray] = None

    @property
    def n_classes(self) -> int:
        return len(self.log_prior)

    def scores(self, X: np.ndarray) -> np.ndarray:
        return X.astype(np.float32) @ self.log_theta.T + self.log_prior

    def scores_bags(self, ids: np.ndarray, weights: np.ndarray) -> np.ndarray:
        """Log-posterior scores from packed sparse bags (no densify).

        ``[B, L]`` ids/weights → ``[B, C]``: log_prior + Σ_l w_l ·
        log_theta[:, id_l]. Pad slots (weight 0) contribute nothing.
        """
        gathered = self.log_theta[:, ids]  # [C, B, L]
        return (
            np.einsum("cbl,bl->bc", gathered, weights.astype(np.float32))
            + self.log_prior
        )

    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.argmax(self.scores(X), axis=1).astype(np.int32)


def train_multinomial_nb(
    X: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    lambda_: float = 1.0,
) -> MultinomialNBModel:
    """Multinomial NB with Laplace smoothing ``lambda_``.

    ≙ the reference classification template's ``NaiveBayes.train(data,
    lambda)`` call into MLlib (examples/scala-parallel-classification,
    UNVERIFIED; SURVEY.md §2.5). Feature aggregation per class is a
    segment-sum over the class codes — the TPU analog of MLlib's
    ``combineByKey`` over label keys.
    """
    import jax
    import jax.numpy as jnp

    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.int32)
    if (X < 0).any():
        raise ValueError("multinomial NB requires non-negative features")

    @jax.jit
    def fit(Xj, yj):
        counts = jax.ops.segment_sum(
            jnp.ones_like(yj, jnp.float32), yj, num_segments=n_classes
        )
        feat_sums = jax.ops.segment_sum(Xj, yj, num_segments=n_classes)
        log_prior = jnp.log(counts / counts.sum())
        smoothed = feat_sums + lambda_
        log_theta = jnp.log(
            smoothed / smoothed.sum(axis=1, keepdims=True)
        )
        return log_prior, log_theta

    log_prior, log_theta = fit(jnp.asarray(X), jnp.asarray(y))
    s = np.abs(X).max(axis=0)
    return MultinomialNBModel(
        log_prior=np.asarray(log_prior, np.float32),
        log_theta=np.asarray(log_theta, np.float32),
        feature_scales=np.where(
            s == 0.0, 1.0, s / 127.0
        ).astype(np.float32),
    )


def train_multinomial_nb_bags(
    ids: np.ndarray,
    weights: np.ndarray,
    y: np.ndarray,
    n_features: int,
    n_classes: int,
    lambda_: float = 1.0,
) -> MultinomialNBModel:
    """Multinomial NB from packed sparse bags — no ``[n, V]`` densification.

    Same estimator as :func:`train_multinomial_nb`, but the per-class feature
    sums ``[C, V]`` are a single segment-sum over the flattened
    ``class·V + token_id`` keys, so memory is O(nnz + C·V) instead of the
    O(n·V) dense matrix (which at V=65536 would be gigabytes for a modest
    corpus). Pad slots (id 0, weight 0) contribute nothing.

    Args:
        ids/weights: [n, L] bags in the pio_tpu.ops.pack_bags layout.
        y: [n] int class codes.
    """
    import jax
    import jax.numpy as jnp

    ids = np.asarray(ids, np.int32)
    weights = np.asarray(weights, np.float32)
    y = np.asarray(y, np.int32)
    if (weights < 0).any():
        raise ValueError("multinomial NB requires non-negative features")

    @jax.jit
    def fit(ids_j, w_j, y_j):
        counts = jax.ops.segment_sum(
            jnp.ones_like(y_j, jnp.float32), y_j, num_segments=n_classes
        )
        flat_keys = (
            y_j[:, None] * n_features + ids_j
        ).reshape(-1)
        feat_sums = jax.ops.segment_sum(
            w_j.reshape(-1), flat_keys, num_segments=n_classes * n_features
        ).reshape(n_classes, n_features)
        log_prior = jnp.log(counts / counts.sum())
        smoothed = feat_sums + lambda_
        log_theta = jnp.log(
            smoothed / smoothed.sum(axis=1, keepdims=True)
        )
        return log_prior, log_theta

    log_prior, log_theta = fit(
        jnp.asarray(ids), jnp.asarray(weights), jnp.asarray(y)
    )
    return MultinomialNBModel(
        log_prior=np.asarray(log_prior, np.float32),
        log_theta=np.asarray(log_theta, np.float32),
    )
