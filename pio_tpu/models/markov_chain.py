"""First-order Markov chain — TPU-native rebuild of the reference e2 helper.

Reference: ``e2/src/main/scala/o/a/p/e2/engine/MarkovChain.scala``
(UNVERIFIED path; see SURVEY.md §2.5) — builds a transition model from a
sparse matrix of transition *counts* and keeps, per state, the top-K
normalized transition probabilities.

TPU-first formulation: the count matrix is dense ``[S, S]`` (states after
BiMap dense-coding), built with one scatter-add from the observed
(from, to, count) triples; row normalization + ``lax.top_k`` produce the
per-state top-K table in a single jittable program rather than the
reference's per-row Scala sort.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class MarkovChainModel:
    """Per-state top-K transition table.

    Attributes:
        top_indices: [S, K] int32 — destination state codes, by descending
            probability (padded with -1 where a state has < K successors).
        top_probs: [S, K] float32 — matching transition probabilities.
        n_states: S.
    """

    top_indices: np.ndarray
    top_probs: np.ndarray
    n_states: int

    def transitions_of(self, state: int) -> List[Tuple[int, float]]:
        """(to_state, prob) list for one state, descending probability."""
        out = []
        for idx, prob in zip(self.top_indices[state], self.top_probs[state]):
            if idx < 0 or prob <= 0.0:
                break
            out.append((int(idx), float(prob)))
        return out


def train_markov_chain(
    transitions: Sequence[Tuple[int, int, float]],
    n_states: int,
    top_k: int = 10,
) -> MarkovChainModel:
    """Build the model from (from_state, to_state, count) triples.

    ≙ reference ``MarkovChain.train(matrix, topCount)``. The sparse triples
    become one dense scatter-add + row-normalize + top-k on device.
    """
    if n_states <= 0:
        raise ValueError("n_states must be positive")
    k = min(top_k, n_states)

    counts = np.zeros((n_states, n_states), np.float32)
    if transitions:
        tr = np.asarray(transitions, np.float64)
        frm = tr[:, 0].astype(np.int32)
        to = tr[:, 1].astype(np.int32)
        if (frm < 0).any() or (frm >= n_states).any() or (
            (to < 0).any() or (to >= n_states).any()
        ):
            raise ValueError("transition state out of range")
        np.add.at(counts, (frm, to), tr[:, 2].astype(np.float32))

    import jax
    import jax.numpy as jnp

    @jax.jit
    def normalize_topk(c):
        row_sum = jnp.sum(c, axis=1, keepdims=True)
        probs = jnp.where(row_sum > 0, c / jnp.where(row_sum > 0, row_sum, 1), 0.0)
        top_p, top_i = jax.lax.top_k(probs, k)
        # mark zero-probability tail entries as absent
        top_i = jnp.where(top_p > 0, top_i, -1)
        return top_i.astype(jnp.int32), top_p.astype(jnp.float32)

    top_i, top_p = normalize_topk(jnp.asarray(counts))
    return MarkovChainModel(
        np.asarray(top_i), np.asarray(top_p), n_states
    )
