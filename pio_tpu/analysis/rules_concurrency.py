"""Concurrency rules: blocking work under a held lock, unguarded
``Condition.wait``, ``notify`` without the CV's lock, and admission /
breaker handles that escape their ``finally``.

All of these are lexical checks — they look at what a function does
*while a ``with <lock>:`` block is open* (nested ``def``s reset the
context: defining a closure under a lock runs nothing).
"""

from __future__ import annotations

import ast
from typing import Callable, Iterable, List, Optional, Tuple

from pio_tpu.analysis.core import (
    Finding,
    LintContext,
    ModuleInfo,
    ProjectRule,
    Rule,
    register,
)
from pio_tpu.analysis.locks import (
    LockIndex,
    build_lock_index,
    is_known_condition,
    lock_name_of,
    unparse,
)

# ---------------------------------------------------------------------------
# shared lexical scanner

#: (held, while_depth): held is [(short_name, with_expr_text)], innermost last
ScanCtx = Tuple[List[Tuple[str, str]], int]


class LockScanner:
    """Walks a module, calling ``on_call(call, held, while_depth, cls)``
    for every Call expression with its lexical lock context."""

    def __init__(self, module: ModuleInfo,
                 on_call: Callable[[ast.Call, List[Tuple[str, str]],
                                    int, Optional[str]], None]):
        self.module = module
        self.idx: LockIndex = build_lock_index(module.tree)
        self.on_call = on_call
        self._cls: Optional[str] = None

    def run(self) -> None:
        self._scan_stmts(self.module.tree.body, [], 0)

    # -- statements --------------------------------------------------------
    def _scan_stmts(self, stmts, held, while_depth) -> None:
        for stmt in stmts:
            self._scan_stmt(stmt, held, while_depth)

    def _scan_stmt(self, stmt, held, while_depth) -> None:
        if isinstance(stmt, ast.ClassDef):
            prev, self._cls = self._cls, stmt.name
            self._scan_stmts(stmt.body, [], 0)
            self._cls = prev
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def's body does not run under the enclosing locks
            self._scan_stmts(stmt.body, [], 0)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed: List[Tuple[str, str]] = []
            for item in stmt.items:
                self._walk_expr(item.context_expr, held, while_depth)
                name = lock_name_of(item.context_expr, self.idx, self._cls)
                if name is not None:
                    entry = (name, unparse(item.context_expr))
                    pushed.append(entry)
                    held = held + [entry]   # `with a, b:` -> a held for b
            self._scan_stmts(stmt.body, held, while_depth)
            return
        if isinstance(stmt, ast.While):
            self._walk_expr(stmt.test, held, while_depth)
            self._scan_stmts(stmt.body, held, while_depth + 1)
            self._scan_stmts(stmt.orelse, held, while_depth)
            return
        # generic compound/simple statement: recurse into stmt lists,
        # walk expression fields for calls
        for _field, value in ast.iter_fields(stmt):
            if isinstance(value, list):
                if value and isinstance(value[0], ast.stmt):
                    self._scan_stmts(value, held, while_depth)
                else:
                    for v in value:
                        if isinstance(v, ast.expr):
                            self._walk_expr(v, held, while_depth)
                        elif isinstance(v, ast.excepthandler):
                            self._scan_stmts(v.body, held, while_depth)
            elif isinstance(value, ast.expr):
                self._walk_expr(value, held, while_depth)

    # -- expressions -------------------------------------------------------
    def _walk_expr(self, expr, held, while_depth) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self.on_call(node, held, while_depth, self._cls)


# ---------------------------------------------------------------------------
# rule: blocking call while a lock is held

#: (receiver-substring-or-None, method-name) pairs considered blocking.
#: receiver None means "any receiver" for that method name.
_BLOCKING_METHODS = (
    ("time", "sleep"),
    ("subprocess", "run"),
    ("subprocess", "call"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    (None, "urlopen"),
    (None, "serve_forever"),
    (None, "create_connection"),
    ("sock", "recv"),
    ("sock", "accept"),
    ("sock", "connect"),
    ("conn", "commit"),     # sqlite3 fsync-on-commit under a lock
    ("db", "commit"),
)
_BLOCKING_BARE = {"sleep", "urlopen"}


def _blocking_reason(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id in _BLOCKING_BARE:
            return f"{fn.id}()"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    recv = unparse(fn.value).lower()
    for needle, meth in _BLOCKING_METHODS:
        if fn.attr != meth:
            continue
        if needle is None or needle in recv:
            return f"{unparse(fn.value)}.{fn.attr}()"
    return None


@register
class LockBlockingCallRule(ProjectRule):
    id = "lock-blocking-call"
    family = "concurrency"
    description = (
        "Blocking call (sleep / subprocess / socket / urlopen / sqlite "
        "commit) inside a `with <lock>:` block — directly or through a "
        "resolvable callee whose effect summary blocks — stalls every "
        "other thread contending for that lock."
    )

    def check(self, module: ModuleInfo, ctx: LintContext) -> Iterable[Finding]:
        # lexical fallback: no project context, direct calls only
        return self._check_module(module, None)

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> Iterable[Finding]:
        from pio_tpu.analysis.effects import get_analysis
        analysis = get_analysis(modules, ctx)
        findings: List[Finding] = []
        for m in modules:
            findings.extend(self._check_module(m, analysis))
        return findings

    def _check_module(self, module: ModuleInfo, analysis) -> List[Finding]:
        findings: List[Finding] = []
        scanner = analysis.scanner_for(module) if analysis else None

        def on_call(call, held, while_depth, cls):
            if not held:
                return
            lock = held[-1][1]
            reason = _blocking_reason(call)
            if reason is not None:
                findings.append(Finding(
                    self.id, module.display, call.lineno, call.col_offset,
                    f"blocking {reason} while holding `{lock}`; move the "
                    f"blocking work outside the lock or suppress if the "
                    f"serialization is intentional",
                ))
                return
            # interprocedural: a resolvable callee whose effect summary
            # blocks is just as much of a stall, one-or-more frames down
            if scanner is None:
                return
            key = scanner.callee_key(call, cls)
            if key is None:
                return
            chained = analysis.blocking_chain(key, self.id)
            if chained is None:
                return
            site, chain = chained
            via = " -> ".join(q.rsplit(".", 1)[-1] for q in chain)
            findings.append(Finding(
                self.id, module.display, call.lineno, call.col_offset,
                f"call while holding `{lock}` reaches blocking "
                f"{site.render()} via {via}; move the blocking work "
                f"outside the lock or suppress if the serialization is "
                f"intentional",
            ))

        LockScanner(module, on_call).run()
        return findings


# ---------------------------------------------------------------------------
# rule: Condition.wait outside a while-predicate loop

@register
class CvWaitOutsideLoopRule(Rule):
    id = "cv-wait-outside-loop"
    family = "concurrency"
    description = (
        "Condition.wait() must sit inside a `while <predicate>:` loop — "
        "wakeups are advisory (spurious wakeups, stolen batons), so an "
        "`if`-guarded or bare wait() loses updates."
    )

    def check(self, module: ModuleInfo, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        idx = build_lock_index(module.tree)

        def on_call(call, held, while_depth, cls):
            fn = call.func
            if not (isinstance(fn, ast.Attribute) and fn.attr == "wait"):
                return
            # wait_for embeds its own predicate loop; Events have no
            # predicate obligation — only real Conditions are checked
            if not is_known_condition(fn.value, idx, cls):
                return
            if while_depth == 0:
                findings.append(Finding(
                    self.id, module.display, call.lineno, call.col_offset,
                    f"`{unparse(fn.value)}.wait()` is not inside a "
                    f"`while <predicate>:` loop; use "
                    f"`while not <ready>: cv.wait()` (or wait_for) so "
                    f"spurious/stolen wakeups re-check the predicate",
                ))

        LockScanner(module, on_call).run()
        return findings


# ---------------------------------------------------------------------------
# rule: notify()/notify_all() without holding the CV's lock

@register
class CvNotifyUnlockedRule(Rule):
    id = "cv-notify-unlocked"
    family = "concurrency"
    description = (
        "Condition.notify()/notify_all() must run with the CV's lock "
        "held (`with cv:`); unlocked notify raises RuntimeError at "
        "runtime and indicates a racy handoff."
    )

    def check(self, module: ModuleInfo, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        idx = build_lock_index(module.tree)

        def on_call(call, held, while_depth, cls):
            fn = call.func
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr in ("notify", "notify_all")):
                return
            if not is_known_condition(fn.value, idx, cls):
                return
            cv_text = unparse(fn.value)
            if any(text == cv_text for _name, text in held):
                return
            findings.append(Finding(
                self.id, module.display, call.lineno, call.col_offset,
                f"`{cv_text}.{fn.attr}()` without `with {cv_text}:` "
                f"held in the enclosing block",
            ))

        LockScanner(module, on_call).run()
        return findings


# ---------------------------------------------------------------------------
# rule: admission / breaker-call handles must be released in a finally

def _assigned_names(target: ast.expr) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        return [e.id for e in target.elts if isinstance(e, ast.Name)]
    return []


def _is_admission_acquire(value: ast.expr) -> Optional[str]:
    """``x.admit(...)`` or ``<breaker-ish>.acquire(...)`` → a short
    description, else None."""
    if not (isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)):
        return None
    attr = value.func.attr
    recv = unparse(value.func.value).lower()
    if attr == "admit":
        return f"{unparse(value.func)}()"
    if attr == "acquire" and "breaker" in recv:
        return f"{unparse(value.func)}()"
    return None


@register
class ReleaseInFinallyRule(Rule):
    id = "release-in-finally"
    family = "convention"
    skip_tests = True
    description = (
        "A handle from `<gate>.admit(...)` or `<breaker>.acquire()` "
        "must be released/cancelled in a `finally` in the same "
        "function, or returned to the caller (ownership transfer); "
        "otherwise an early exit leaks the inflight slot / probe grant."
    )

    def check(self, module: ModuleInfo, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            findings.extend(self._check_fn(fn, module))
        return findings

    @staticmethod
    def _walk_local(fn):
        """Yield nodes of ``fn`` without descending into nested defs
        (they are analysed as functions in their own right)."""
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _check_fn(self, fn, module: ModuleInfo) -> Iterable[Finding]:
        acquires: List[Tuple[str, ast.Assign, str]] = []  # (var, node, what)
        returned: set = set()
        finally_released: set = set()

        for node in self._walk_local(fn):
            if isinstance(node, ast.Assign):
                what = _is_admission_acquire(node.value)
                if what is not None:
                    for t in node.targets:
                        names = _assigned_names(t)
                        if names:
                            acquires.append((names[0], node, what))
            elif isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name):
                        returned.add(sub.id)
            elif isinstance(node, ast.Try) and node.finalbody:
                for sub in ast.walk(ast.Module(body=node.finalbody,
                                               type_ignores=[])):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr in ("release", "cancel")):
                        base = sub.func.value
                        if isinstance(base, ast.Name):
                            finally_released.add(base.id)

        for var, node, what in acquires:
            if var in returned or var in finally_released:
                continue
            yield Finding(
                self.id, module.display, node.lineno, node.col_offset,
                f"`{var} = {what}` is neither released/cancelled in a "
                f"`finally` nor returned; an exception or early return "
                f"leaks the admission slot / breaker probe",
            )
