"""Statically-built cross-module lock-acquisition graph with cycle
(potential-deadlock) reporting.

Nodes are *definitively defined* locks — ``self._x = threading.Lock()``
inside ``class C`` in module ``m`` becomes node ``m.C._x``; module-level
``_g = threading.Lock()`` becomes ``m._g``. (Name-heuristic "lockish"
expressions are excluded: a fuzzy node would alias unrelated locks
across files and fabricate cycles.)

Edges: ``A -> B`` when some function acquires B (``with b:``) while
lexically holding A, **or** calls — possibly across modules, resolved
through imports — a function whose transitive acquire-set contains B.
Call resolution covers ``self.m()``, same-module ``f()``, and
``mod.f()`` / ``from mod import f`` call sites; attribute calls on
arbitrary objects are out of scope (documented limitation).

A strongly-connected component with more than one lock means two code
paths take the same locks in opposite orders — the classic AB/BA
deadlock — and is reported once per component with example edge sites.
The runtime twin of this rule is :mod:`pio_tpu.analysis.runtime`.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from pio_tpu.analysis.core import (
    Finding,
    LintContext,
    ModuleInfo,
    ProjectRule,
    register,
)
from pio_tpu.analysis.locks import (
    CV_FACTORY_NAMES,
    LOCK_FACTORY_NAMES,
    _factory_name,
)


@dataclass
class _FnInfo:
    qual: str
    direct_locks: Set[str] = field(default_factory=set)
    #: (held lock ids at the call, callee key, line)
    calls: List[Tuple[Tuple[str, ...], str, int]] = field(default_factory=list)


@dataclass
class _ModuleLocks:
    class_attrs: Dict[str, Dict[str, str]] = field(default_factory=dict)
    module_names: Dict[str, str] = field(default_factory=dict)


_Edge = Tuple[str, str]                      # (from lock id, to lock id)
_Site = Tuple[str, int]                      # (display path, line)


class _ModuleScanner:
    """One pass over a module: lock defs, per-function acquire/call
    records, and direct nesting edges."""

    def __init__(self, module: ModuleInfo):
        self.module = module
        self.mod = module.module_name
        self.locks = _ModuleLocks()
        self.fns: Dict[str, _FnInfo] = {}
        self.edges: Dict[_Edge, _Site] = {}
        self.imports: Dict[str, str] = {}    # alias -> module name
        self.from_imports: Dict[str, str] = {}  # bare name -> "mod.name"
        self._collect_defs()

    # -- pass 1: lock definitions + imports --------------------------------
    def _collect_defs(self) -> None:
        factories = LOCK_FACTORY_NAMES | CV_FACTORY_NAMES
        for node in ast.walk(self.module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name if alias.asname else alias.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"
        for top in self.module.tree.body:
            if isinstance(top, ast.ClassDef):
                for sub in ast.walk(top):
                    self._def_from_assign(sub, top.name)
            else:
                for sub in ast.walk(top):
                    self._def_from_assign(sub, None)

    def _def_from_assign(self, node: ast.AST, cls: Optional[str]) -> None:
        if not isinstance(node, ast.Assign):
            return
        factory = _factory_name(node.value)
        if factory not in LOCK_FACTORY_NAMES | CV_FACTORY_NAMES:
            return
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self" and cls is not None):
                self.locks.class_attrs.setdefault(cls, {})[t.attr] = \
                    f"{self.mod}.{cls}.{t.attr}"
            elif isinstance(t, ast.Name) and cls is None:
                self.locks.module_names[t.id] = f"{self.mod}.{t.id}"

    # -- pass 2: function bodies -------------------------------------------
    def scan_functions(self) -> None:
        for top in self.module.tree.body:
            if isinstance(top, ast.ClassDef):
                for item in top.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._scan_fn(item, top.name)
            elif isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_fn(top, None)

    def _lock_id(self, expr: ast.expr, cls: Optional[str]) -> Optional[str]:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and cls is not None):
            return self.locks.class_attrs.get(cls, {}).get(expr.attr)
        if isinstance(expr, ast.Name):
            return self.locks.module_names.get(expr.id)
        return None

    def _callee_key(self, call: ast.Call, cls: Optional[str]) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in self.from_imports:
                return self.from_imports[fn.id]
            return f"{self.mod}.{fn.id}"
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Name):
                if base.id == "self" and cls is not None:
                    return f"{self.mod}.{cls}.{fn.attr}"
                target = self.imports.get(base.id)
                if target is not None:
                    return f"{target}.{fn.attr}"
        return None

    def _scan_fn(self, fn, cls: Optional[str]) -> None:
        qual = f"{self.mod}.{cls}.{fn.name}" if cls else f"{self.mod}.{fn.name}"
        info = self.fns.setdefault(qual, _FnInfo(qual))

        def scan_stmts(stmts, held: List[str]) -> None:
            for stmt in stmts:
                scan_stmt(stmt, held)

        def scan_stmt(stmt, held: List[str]) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return  # nested scopes don't run here
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = list(held)
                for item in stmt.items:
                    walk_expr(item.context_expr, inner)
                    lock = self._lock_id(item.context_expr, cls)
                    if lock is not None:
                        info.direct_locks.add(lock)
                        for h in inner:
                            if h != lock:
                                self.edges.setdefault(
                                    (h, lock),
                                    (self.module.display, stmt.lineno))
                        inner = inner + [lock]
                scan_stmts(stmt.body, inner)
                return
            for _f, value in ast.iter_fields(stmt):
                if isinstance(value, list):
                    if value and isinstance(value[0], ast.stmt):
                        scan_stmts(value, held)
                    else:
                        for v in value:
                            if isinstance(v, ast.expr):
                                walk_expr(v, held)
                            elif isinstance(v, ast.excepthandler):
                                scan_stmts(v.body, held)
                elif isinstance(value, ast.expr):
                    walk_expr(value, held)

        def walk_expr(expr, held: List[str]) -> None:
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    key = self._callee_key(node, cls)
                    if key is not None:
                        info.calls.append((tuple(held), key, node.lineno))

        scan_stmts(fn.body, [])


@register
class LockOrderCycleRule(ProjectRule):
    id = "lock-order-cycle"
    family = "concurrency"
    description = (
        "Two code paths acquire the same locks in opposite orders "
        "(cycle in the static cross-module lock-acquisition graph): a "
        "potential AB/BA deadlock."
    )

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> List[Finding]:
        scanners = [_ModuleScanner(m) for m in modules]
        for s in scanners:
            s.scan_functions()

        fns: Dict[str, _FnInfo] = {}
        edges: Dict[_Edge, _Site] = {}
        for s in scanners:
            fns.update(s.fns)
            for e, site in s.edges.items():
                edges.setdefault(e, site)

        # transitive acquire-set fixpoint over resolved calls
        trans: Dict[str, Set[str]] = {
            q: set(i.direct_locks) for q, i in fns.items()
        }
        changed = True
        while changed:
            changed = False
            for q, info in fns.items():
                for _held, callee, _line in info.calls:
                    sub = trans.get(callee)
                    if sub and not sub <= trans[q]:
                        trans[q] |= sub
                        changed = True

        # call-induced edges: held locks order before everything the
        # callee (transitively) acquires
        for s in scanners:
            for info in s.fns.values():
                for held, callee, line in info.calls:
                    for lock in trans.get(callee, ()):
                        for h in held:
                            if h != lock:
                                edges.setdefault(
                                    (h, lock),
                                    (fns_site(s, line)))

        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())

        findings: List[Finding] = []
        for comp in _sccs(graph):
            if len(comp) < 2:
                continue
            comp_sorted = sorted(comp)
            comp_edges = sorted(
                (e, site) for e, site in edges.items()
                if e[0] in comp and e[1] in comp
            )
            detail = "; ".join(
                f"{a} -> {b} at {path}:{line}"
                for (a, b), (path, line) in comp_edges[:4]
            )
            path, line = comp_edges[0][1]
            findings.append(Finding(
                self.id, path, line, 0,
                f"lock-order cycle between {{{', '.join(comp_sorted)}}} "
                f"(potential deadlock): {detail}",
            ))
        return findings


def fns_site(scanner: _ModuleScanner, line: int) -> _Site:
    return (scanner.module.display, line)


def _sccs(graph: Dict[str, Set[str]]) -> List[Set[str]]:
    """Iterative Tarjan strongly-connected components."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    for root in graph:
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                out.append(comp)
    return out
