"""Contract-drift rules: the cross-process JSON/header/knob surfaces
extracted by :mod:`pio_tpu.analysis.contracts` must agree end to end.

These close the silent-failure class the distributed planes opened: a
producer renames ``worstBurn`` and the router's shed logic quietly reads
``None`` forever; two modules read the same env knob with different
defaults and behave differently in the same process tree; a failpoint
nobody arms bit-rots until the day it matters.

Rules (family ``contracts``):

* ``endpoint-drift`` — a consumer reads a payload key no producer of
  that endpoint writes (with producer file + nearest-key suggestion).
* ``header-drift`` — an ``X-Pio-*`` header is consumed but never
  produced anywhere, or produced but never consumed (tests count as
  consumers — an assertion is a contract).
* ``knob-default-drift`` — a literal ``PIO_TPU_*`` read bypasses the
  canonical registry (:mod:`pio_tpu.utils.knobs`), disagrees with its
  declared default/kind, or reads a name the registry never declared.
* ``knob-doc-drift`` — the registry and the docs/operations.md
  "Configuration knobs" table must match both ways, defaults included.
* ``failpoint-coverage`` — every registered failpoint must be armed by
  at least one test or a scripts/smoke.sh chaos spec (suppressible
  with justification where coverage is genuinely impossible).
"""

from __future__ import annotations

import ast
import difflib
import os
import re
from typing import Dict, Iterable, List, Set, Tuple

from pio_tpu.analysis.contracts import (
    DYNAMIC_DEFAULT,
    NO_DEFAULT,
    get_contracts,
)
from pio_tpu.analysis.core import (
    Finding,
    LintContext,
    ModuleInfo,
    ProjectRule,
    register,
)

#: modules allowed to touch env primitives directly: the registry and
#: the parse helpers it delegates to
_KNOB_EXEMPT_MODULES = {"pio_tpu.utils.knobs", "pio_tpu.utils.envutil"}


@register
class EndpointDriftRule(ProjectRule):
    id = "endpoint-drift"
    family = "contracts"
    description = (
        "A consumer reads a JSON payload key that no producer of that "
        "endpoint writes. Producers are payload builders carrying a "
        "`# pio: endpoint=/x.json` marker (plus route-registration "
        "handlers); consumer chains are tracked through fetch literals, "
        "`# pio: consumes=` markers, and scrape-loop attribute stores."
    )

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> Iterable[Finding]:
        c = get_contracts(modules, ctx)
        seen: Set[Tuple[str, str, int]] = set()
        for read in c.reads:
            producers = c.producers.get(read.endpoint)
            if not producers:
                # endpoint not built by anything in the linted set
                # (partial lint / member endpoint of another process
                # class) — nothing to check against
                continue
            keys = c.keys.get(read.endpoint, set())
            missing = next(
                (seg for seg in read.key.split(".") if seg not in keys),
                None,
            )
            if missing is None or "*" in keys:
                # "*": a producer builds a dynamic map (breaker names,
                # burn windows) — unknown segments get the benefit of
                # the doubt for this endpoint
                continue
            mark = (read.path, read.key, read.line)
            if mark in seen:
                continue
            seen.add(mark)
            prod = producers[0]
            hint = difflib.get_close_matches(missing, sorted(keys), n=1)
            suggestion = f"; closest produced key: {hint[0]!r}" \
                if hint else ""
            yield Finding(
                self.id, read.path, read.line, 0,
                f"reads {read.key!r} from {read.endpoint} but no "
                f"producer writes {missing!r} (producer: {prod.qual} "
                f"at {prod.path}:{prod.line}){suggestion}",
            )


@register
class HeaderDriftRule(ProjectRule):
    id = "header-drift"
    family = "contracts"
    description = (
        "X-Pio-* request/response headers must be both produced and "
        "consumed somewhere in the linted set — a header only written "
        "is dead weight on every response, a header only read is a "
        "contract nobody fulfils. Tests count as consumers."
    )

    #: forwarding allow-list: `forward_headers` copies the whole
    #: ``X-Pio-*`` prefix, so producing for downstream hops is not
    #: itself consumption
    _sentinel = "pio_tpu.obs.tracing"

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> Iterable[Finding]:
        names = {m.module_name for m in modules}
        # partial runs over a slice of the real tree would see phantom
        # one-sided headers; fixture sets (no pio_tpu.* modules) still
        # exercise the rule
        if any(n.startswith("pio_tpu.") for n in names) \
                and self._sentinel not in names:
            return
        c = get_contracts(modules, ctx)
        produced = {h.header for h in c.headers if h.role == "write"}
        consumed = {h.header for h in c.headers if h.role == "read"}
        for h in c.headers:
            if h.role == "read" and h.header not in produced:
                yield Finding(
                    self.id, h.path, h.line, 0,
                    f"header {h.canonical!r} is consumed here but never "
                    f"produced anywhere in the linted set",
                )
            elif h.role == "write" and h.header not in consumed:
                yield Finding(
                    self.id, h.path, h.line, 0,
                    f"header {h.canonical!r} is produced here but never "
                    f"consumed anywhere in the linted set (tests count)",
                )


def _fmt_default(value: object) -> str:
    if value is NO_DEFAULT:
        return "<none>"
    if value is DYNAMIC_DEFAULT:
        return "<dynamic>"
    return repr(value)


@register
class KnobDefaultDriftRule(ProjectRule):
    id = "knob-default-drift"
    family = "contracts"
    description = (
        "Every literal PIO_TPU_* env read must go through the canonical "
        "knob registry (pio_tpu.utils.knobs) — direct os.environ / "
        "env_int reads bypass the single declared default, and a "
        "bypassing site whose inline default disagrees with the "
        "registry is exactly the multi-module drift this rule exists "
        "to kill."
    )

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> Iterable[Finding]:
        registry = ctx.knob_registry
        c = get_contracts(modules, ctx)
        for site in c.knob_reads:
            if site.is_test or site.module_name in _KNOB_EXEMPT_MODULES:
                continue
            knob = registry.get(site.name)
            if site.via == "registry":
                if knob is None:
                    yield Finding(
                        self.id, site.path, site.line, 0,
                        f"knob_{site.kind}({site.name!r}) reads a knob "
                        f"the registry never declared — add it to "
                        f"pio_tpu/utils/knobs.py",
                    )
                elif site.kind not in ("raw", knob.kind):
                    yield Finding(
                        self.id, site.path, site.line, 0,
                        f"{site.name} is declared {knob.kind} but read "
                        f"as {site.kind} here",
                    )
                continue
            if knob is None:
                yield Finding(
                    self.id, site.path, site.line, 0,
                    f"undeclared knob {site.name} read via "
                    f"{site.via} — declare it in pio_tpu/utils/knobs.py "
                    f"and read it through knob_int/knob_float/knob_str",
                )
                continue
            detail = ""
            if site.default not in (NO_DEFAULT, DYNAMIC_DEFAULT) \
                    and site.default != knob.default:
                detail = (
                    f" and its inline default "
                    f"{_fmt_default(site.default)} disagrees with the "
                    f"declared default {knob.default!r}"
                )
            elif site.kind not in ("raw", "str", knob.kind):
                detail = (
                    f" and parses it as {site.kind} against the "
                    f"declared kind {knob.kind}"
                )
            yield Finding(
                self.id, site.path, site.line, 0,
                f"{site.name} read via {site.via} bypasses the knob "
                f"registry (use knobs.knob_{knob.kind}"
                f"({site.name!r})){detail}",
            )


#: docs table row: ``| `PIO_TPU_X` | kind | `default` | doc |``
_DOC_ROW_RE = re.compile(
    r"^\|\s*`(?P<name>PIO_TPU_[A-Z0-9_]+)`\s*\|\s*(?P<kind>[a-z]+)\s*\|"
    r"\s*`(?P<default>[^`]*)`\s*\|"
)


@register
class KnobDocDriftRule(ProjectRule):
    id = "knob-doc-drift"
    family = "contracts"
    description = (
        "The generated 'Configuration knobs' table in "
        "docs/operations.md must match the registry both ways: every "
        "declared knob documented, every documented row declared, "
        "kind and default cells agreeing. Regenerate with "
        "`python -m pio_tpu.utils.knobs`."
    )

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> Iterable[Finding]:
        doc = os.path.join(ctx.repo_root, "docs", "operations.md")
        try:
            with open(doc, "r", encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            return                 # no doc, no contract to keep
        display = os.path.join("docs", "operations.md")
        rows: Dict[str, Tuple[int, str, str]] = {}
        for i, line in enumerate(lines, start=1):
            m = _DOC_ROW_RE.match(line.strip())
            if m:
                rows[m.group("name")] = (i, m.group("kind"),
                                         m.group("default"))
        registry = ctx.knob_registry
        for name in sorted(set(registry) - set(rows)):
            yield Finding(
                self.id, display, 0, 0,
                f"knob {name} is declared in the registry but missing "
                f"from the docs/operations.md knob table — regenerate "
                f"it with `python -m pio_tpu.utils.knobs`",
            )
        for name in sorted(set(rows) - set(registry)):
            line, _kind, _default = rows[name]
            yield Finding(
                self.id, display, line, 0,
                f"documented knob {name} does not exist in the "
                f"registry — stale row, or the declaration was removed",
            )
        for name in sorted(set(rows) & set(registry)):
            line, kind, default = rows[name]
            knob = registry[name]
            if kind != knob.kind:
                yield Finding(
                    self.id, display, line, 0,
                    f"{name} documented as {kind} but declared "
                    f"{knob.kind}",
                )
            elif default != knob.default_repr():
                yield Finding(
                    self.id, display, line, 0,
                    f"{name} documented default `{default}` disagrees "
                    f"with the declared default "
                    f"`{knob.default_repr()}`",
                )


@register
class FailpointCoverageRule(ProjectRule):
    id = "failpoint-coverage"
    family = "contracts"
    description = (
        "Every registered failpoint must be armed by at least one test "
        "or a scripts/smoke.sh chaos spec — an unarmed failpoint is "
        "untested error handling wearing a tested-looking name. "
        "Suppress with justification where arming is impossible."
    )

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> Iterable[Finding]:
        test_modules = [m for m in modules if m.is_test]
        if not test_modules:
            # linting a production slice: the arming corpus isn't in
            # view, so absence proves nothing
            return
        from pio_tpu.analysis.rules_convention import failpoint_inventory

        corpus: List[str] = []
        for m in test_modules:
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str):
                    corpus.append(node.value)
        smoke = os.path.join(ctx.repo_root, "scripts", "smoke.sh")
        try:
            with open(smoke, "r", encoding="utf-8") as fh:
                corpus.append(fh.read())
        except OSError:
            pass
        blob = "\n".join(corpus)
        seen_points: Set[str] = set()
        for entry in failpoint_inventory(modules):
            point = entry["point"]
            if point in seen_points:
                continue
            seen_points.add(point)
            # dynamic sites report a static prefix; any armed name
            # under the prefix covers the site
            needle = point.split("{")[0] if entry["dynamic"] else point
            if needle and needle in blob:
                continue
            yield Finding(
                self.id, entry["file"], entry["line"], 0,
                f"failpoint {point!r} is never armed by tests/ or a "
                f"scripts/smoke.sh chaos spec",
            )
