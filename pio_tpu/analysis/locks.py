"""Shared lock/CV identification for the concurrency rules.

Locks are recognised two ways, in preference order:

1. **Definitions** — an assignment whose RHS is ``threading.Lock()``,
   ``threading.RLock()``, ``threading.Condition()`` (bare names imported
   from threading count too) or one of the project's debug factories
   ``make_lock()`` / ``make_rlock()`` / ``make_condition()``. Targets
   ``self.<attr>`` (inside a class) and module-level names are indexed.
2. **Naming convention fallback** — an attribute/name that *looks* like
   a lock (``…lock``, ``_cv``, ``…cond``) so `with`-statements over
   locks defined in a different file still participate.

``threading.Event`` is deliberately NOT a lock: ``event.wait()`` has no
predicate-loop obligation and holding no mutex is its whole point.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Optional, Set

#: RHS callables that create a mutex-like object
LOCK_FACTORY_NAMES = {"Lock", "RLock", "make_lock", "make_rlock"}
CV_FACTORY_NAMES = {"Condition", "make_condition"}

_LOCKISH_SUFFIXES = ("lock", "_cv", "cond", "mutex")


def _factory_name(node: ast.AST) -> Optional[str]:
    """``threading.Lock()`` / ``Lock()`` / ``make_lock(...)`` → the
    callable's terminal name, else None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


@dataclass
class LockIndex:
    """Lock/CV definitions for one module."""

    #: "ClassName" -> set of self-attribute names that hold locks
    class_attrs: Dict[str, Set[str]] = field(default_factory=dict)
    #: module-level names that hold locks
    module_names: Set[str] = field(default_factory=set)
    #: subset of the above that are Conditions ("Class.attr" / "name")
    conditions: Set[str] = field(default_factory=set)

    def is_condition(self, cls: Optional[str], name: str) -> bool:
        key = f"{cls}.{name}" if cls else name
        return key in self.conditions


def build_lock_index(tree: ast.Module) -> LockIndex:
    idx = LockIndex()

    def record(cls: Optional[str], name: str, factory: str) -> None:
        if cls:
            idx.class_attrs.setdefault(cls, set()).add(name)
            key = f"{cls}.{name}"
        else:
            idx.module_names.add(name)
            key = name
        if factory in CV_FACTORY_NAMES:
            idx.conditions.add(key)

    def scan(body, cls: Optional[str]) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    _scan_assign(sub, node.name)
            else:
                for sub in ast.walk(node):
                    _scan_assign(sub, cls)

    def _scan_assign(node: ast.AST, cls: Optional[str]) -> None:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            return
        factory = _factory_name(value)
        if factory not in LOCK_FACTORY_NAMES | CV_FACTORY_NAMES:
            return
        for t in targets:
            if isinstance(t, ast.Name):
                # module-level name, or a function-local lock: either
                # way `with <name>:` in this module should resolve
                record(None, t.id, factory)
            elif (isinstance(t, ast.Attribute)
                  and isinstance(t.value, ast.Name)
                  and t.value.id == "self" and cls is not None):
                record(cls, t.attr, factory)

    scan(tree.body, None)
    return idx


def looks_lockish(name: str) -> bool:
    low = name.lower()
    return low.endswith(_LOCKISH_SUFFIXES) or low in ("cv", "cond")


def lock_name_of(node: ast.expr, idx: LockIndex,
                 cls: Optional[str]) -> Optional[str]:
    """If ``node`` (a with-item / method receiver) denotes a known or
    lockish-looking lock, return its short name, else None."""
    if isinstance(node, ast.Attribute):
        base_self = isinstance(node.value, ast.Name) and node.value.id == "self"
        if base_self and cls and node.attr in idx.class_attrs.get(cls, ()):
            return node.attr
        if looks_lockish(node.attr):
            return node.attr
        return None
    if isinstance(node, ast.Name):
        if node.id in idx.module_names or looks_lockish(node.id):
            return node.id
    return None


def is_known_condition(node: ast.expr, idx: LockIndex,
                       cls: Optional[str]) -> bool:
    """True when ``node`` denotes a Condition: a tracked Condition
    definition, or an attribute/name following the ``_cv``/``…cond``
    convention."""
    if isinstance(node, ast.Attribute):
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and cls is not None and idx.is_condition(cls, node.attr)):
            return True
        low = node.attr.lower()
        return low in ("cv", "_cv") or low.endswith(("cond", "_cv"))
    if isinstance(node, ast.Name):
        if node.id in idx.conditions:
            return True
        low = node.id.lower()
        return low in ("cv", "cond") or low.endswith(("cond", "_cv"))
    return False


def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return "<expr>"
