"""Checker framework behind ``pio lint``: file collection, the rule
registry, per-line suppressions, and the ``run_lint`` entry point.

Rules come in two shapes. A *module rule* inspects one parsed file at a
time (``check(module, ctx)``); a *project rule* sees every parsed file
at once (``check_project(modules, ctx)``) — that is how cross-module
properties (lock-order cycles, failpoint uniqueness) are checked.

Suppressions are comments, checked per finding line::

    time.time()  # pio: disable=wallclock-duration
    # pio: disable=lock-blocking-call   <- alone on a line: covers the
    conn.commit()                          line immediately below
    # pio: disable-file=metric-name     <- anywhere: whole file

Suppression comments are read from the token stream (not regexed out of
raw source), so a string literal that merely *contains* the marker text
never suppresses anything.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

_SUPPRESS_RE = re.compile(
    r"#\s*pio:\s*disable(?P<whole_file>-file)?=(?P<rules>[A-Za-z0-9_,\- ]+)"
)

#: analysis markers, same comment grammar as suppressions:
#:   # pio: hotpath                  <- function is a hot-path root
#:   # pio: hotpath=zerocopy         <- additionally no JSON / bytes copies
#:   # pio: frame=lane-slot          <- struct call site belongs to a frame
#:   # pio: endpoint=/fleet.json     <- function builds this endpoint's payload
#:   # pio: consumes=/fleet.json     <- function parses this endpoint's payload
#: A marker alone on its line covers the line below it (so a def whose
#: signature spans lines can carry the marker above itself).
_MARKER_RE = re.compile(
    r"#\s*pio:\s*(?P<kind>hotpath|frame|endpoint|consumes)"
    r"(?:=(?P<value>[A-Za-z0-9_./\-]+))?"
)

#: directories never descended into when a lint path is a directory
_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules"}


@dataclass(frozen=True)
class Finding:
    """One lint diagnostic, pointing at ``path:line``."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


@dataclass
class ModuleInfo:
    """One parsed source file plus everything rules need to know about it."""

    path: str                      # absolute path on disk
    display: str                   # path as reported in findings
    source: str
    tree: ast.Module
    is_test: bool                  # under tests/ or named test_*/conftest
    module_name: str               # dotted name ("pio_tpu.qos.gate" / "a")
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)
    file_suppressions: Set[str] = field(default_factory=set)
    #: line -> "" (plain hotpath) | "zerocopy"  (`# pio: hotpath[=...]`)
    hotpath_markers: Dict[int, str] = field(default_factory=dict)
    #: line -> frame family name  (`# pio: frame=<family>`)
    frame_markers: Dict[int, str] = field(default_factory=dict)
    #: line -> endpoint path  (`# pio: endpoint=/fleet.json`)
    endpoint_markers: Dict[int, str] = field(default_factory=dict)
    #: line -> endpoint path  (`# pio: consumes=/fleet.json`)
    consumes_markers: Dict[int, str] = field(default_factory=dict)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions:
            return True
        rules = self.suppressions.get(line)
        return bool(rules) and rule in rules

    def suppressed_at_any(self, rule: str, lines: Iterable[int]) -> bool:
        """True when any of ``lines`` carries a disable for ``rule`` —
        how project rules honor a disable placed on a root function's
        def/marker line rather than on the finding's own line."""
        return any(self.suppressed(rule, ln) for ln in lines)


class LintContext:
    """Shared, lazily-populated state handed to every rule."""

    def __init__(self, repo_root: Optional[str] = None,
                 catalog: Optional[Set[str]] = None,
                 knob_registry: Optional[Dict[str, object]] = None):
        self.repo_root = repo_root or _default_repo_root()
        self._catalog = catalog
        self._catalog_loaded = catalog is not None
        self._catalog_kinds: Optional[Dict[str, str]] = None
        # an injected catalog (tests) has no type info: skip kind checks
        self._catalog_kinds_loaded = catalog is not None
        self._knob_registry = knob_registry

    @property
    def knob_registry(self) -> Dict[str, object]:
        """Canonical knob declarations (name -> :class:`~pio_tpu.utils.
        knobs.Knob`). The in-tree registry by default; tests inject a
        synthetic one to lint fixtures against it."""
        if self._knob_registry is None:
            from pio_tpu.utils.knobs import KNOBS
            self._knob_registry = dict(KNOBS)
        return self._knob_registry

    @property
    def metric_catalog(self) -> Optional[Set[str]]:
        """Metric names documented in ``docs/observability.md`` (the
        backticked ``pio_tpu_*`` tokens), or ``None`` when the doc is
        not present (catalog agreement is then skipped)."""
        if not self._catalog_loaded:
            self._catalog = _load_catalog(self.repo_root)
            self._catalog_loaded = True
        return self._catalog

    @property
    def metric_catalog_kinds(self) -> Optional[Dict[str, str]]:
        """Documented metric type per catalog name, parsed from the
        ``| `name` | type | ...`` table rows of docs/observability.md —
        lets the metric-name rule flag a registration whose kind
        disagrees with its documented row (e.g. a counter documented as
        a gauge), not just an undocumented name. ``None`` when the doc
        is absent."""
        if not self._catalog_kinds_loaded:
            self._catalog_kinds = _load_catalog_kinds(self.repo_root)
            self._catalog_kinds_loaded = True
        return self._catalog_kinds


class Rule:
    """Base class for module rules. Subclasses set the class attrs and
    implement :meth:`check`."""

    id: str = ""
    family: str = ""               # "concurrency" | "convention"
    description: str = ""
    #: convention rules about production registrations/call sites skip
    #: test files (tests register scratch metrics, seed failpoints, and
    #: poke os.environ on purpose); concurrency rules apply everywhere
    skip_tests: bool = False

    def check(self, module: ModuleInfo, ctx: LintContext) -> Iterable[Finding]:
        raise NotImplementedError


class ProjectRule(Rule):
    """Rule that needs the whole file set at once."""

    def check(self, module: ModuleInfo, ctx: LintContext) -> Iterable[Finding]:
        return ()

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> Iterable[Finding]:
        raise NotImplementedError


_RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and index the rule by its id."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in _RULES:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    _RULES[inst.id] = inst
    return cls


def all_rules() -> Dict[str, Rule]:
    _load_rule_modules()
    return dict(_RULES)


def _load_rule_modules() -> None:
    # deferred so core can be imported by the rule modules themselves
    from pio_tpu.analysis import effects  # noqa: F401
    from pio_tpu.analysis import lockgraph  # noqa: F401
    from pio_tpu.analysis import rules_concurrency  # noqa: F401
    from pio_tpu.analysis import rules_contracts  # noqa: F401
    from pio_tpu.analysis import rules_convention  # noqa: F401


def _default_repo_root() -> str:
    # pio_tpu/analysis/core.py -> repo root two levels above the package
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _load_catalog(repo_root: str) -> Optional[Set[str]]:
    doc = os.path.join(repo_root, "docs", "observability.md")
    try:
        with open(doc, "r", encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        return None
    return set(re.findall(r"`(pio_tpu_[a-z0-9_]+)`", text))


#: catalog table row: ``| `pio_tpu_x` | counter | ... |`` — first two
#: cells are the name and the documented type
_CATALOG_ROW_RE = re.compile(
    r"^\|\s*`(pio_tpu_[a-z0-9_]+)`\s*\|\s*([a-z]+)\s*\|", re.MULTILINE
)


def _load_catalog_kinds(repo_root: str) -> Optional[Dict[str, str]]:
    doc = os.path.join(repo_root, "docs", "observability.md")
    try:
        with open(doc, "r", encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        return None
    return dict(_CATALOG_ROW_RE.findall(text))


def _is_test_path(path: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    base = os.path.basename(path)
    return (
        "tests" in parts
        or base.startswith("test_")
        or base == "conftest.py"
    )


def _module_name(path: str) -> str:
    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    stem = os.path.splitext(parts[-1])[0]
    if "pio_tpu" in parts:
        i = parts.index("pio_tpu")
        dotted = parts[i:-1] + ([] if stem == "__init__" else [stem])
        return ".".join(dotted)
    return stem


def _collect_suppressions(source: str):
    per_line: Dict[int, Set[str]] = {}
    whole_file: Set[str] = set()
    hotpath: Dict[int, str] = {}
    frames: Dict[int, str] = {}
    endpoints: Dict[int, str] = {}
    consumes: Dict[int, str] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            alone = tok.line[:tok.start[1]].strip() == ""
            line = tok.start[0]
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                rules = {
                    r.strip() for r in m.group("rules").split(",") if r.strip()
                }
                if m.group("whole_file"):
                    whole_file |= rules
                    continue
                per_line.setdefault(line, set()).update(rules)
                # a comment alone on its line covers the line below it
                if alone:
                    per_line.setdefault(line + 1, set()).update(rules)
                continue
            m = _MARKER_RE.search(tok.string)
            if not m:
                continue
            kind, value = m.group("kind"), m.group("value") or ""
            if kind == "hotpath":
                hotpath[line] = value
                if alone:
                    hotpath.setdefault(line + 1, value)
            elif kind == "frame" and value:
                frames[line] = value
                if alone:
                    frames.setdefault(line + 1, value)
            elif kind == "endpoint" and value:
                endpoints[line] = value
                if alone:
                    endpoints.setdefault(line + 1, value)
            elif kind == "consumes" and value:
                consumes[line] = value
                if alone:
                    consumes.setdefault(line + 1, value)
    except tokenize.TokenError:
        pass
    return per_line, whole_file, hotpath, frames, endpoints, consumes


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand the lint targets into a sorted, de-duplicated .py list."""
    out: List[str] = []
    seen: Set[str] = set()
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in _SKIP_DIRS and not d.startswith(".")
                )
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        full = os.path.join(dirpath, name)
                        if full not in seen:
                            seen.add(full)
                            out.append(full)
        elif p.endswith(".py"):
            full = p
            if full not in seen:
                seen.add(full)
                out.append(full)
    return out


def parse_module(path: str, display: Optional[str] = None
                 ) -> "ModuleInfo | Finding":
    """Parse one file; returns a ``parse-error`` Finding on bad syntax."""
    display = display or _display_path(path)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return Finding("parse-error", display, exc.lineno or 0,
                       exc.offset or 0, f"syntax error: {exc.msg}")
    except OSError as exc:
        return Finding("parse-error", display, 0, 0, f"unreadable: {exc}")
    (per_line, whole_file, hotpath, frames,
     endpoints, consumes) = _collect_suppressions(source)
    return ModuleInfo(
        path=os.path.abspath(path),
        display=display,
        source=source,
        tree=tree,
        is_test=_is_test_path(path),
        module_name=_module_name(path),
        suppressions=per_line,
        file_suppressions=whole_file,
        hotpath_markers=hotpath,
        frame_markers=frames,
        endpoint_markers=endpoints,
        consumes_markers=consumes,
    )


def _display_path(path: str) -> str:
    ap = os.path.abspath(path)
    cwd = os.getcwd()
    if ap.startswith(cwd + os.sep):
        return os.path.relpath(ap, cwd)
    return path


def run_lint(paths: Sequence[str],
             rule_ids: Optional[Sequence[str]] = None,
             catalog: Optional[Set[str]] = None,
             repo_root: Optional[str] = None,
             only: Optional[Sequence[str]] = None,
             knob_registry: Optional[Dict[str, object]] = None
             ) -> List[Finding]:
    """Lint ``paths`` and return the surviving (unsuppressed) findings,
    sorted by file/line. ``rule_ids`` restricts to a subset of rules;
    ``catalog`` overrides the docs/observability.md metric catalog
    (tests use this to lint fixtures against a synthetic catalog).
    ``only`` (absolute or display paths) keeps findings from just those
    files while every file in ``paths`` still feeds project context —
    the ``pio lint --changed`` fast path: call graphs and frame
    families are built whole-tree, findings are reported per-diff."""
    rules = all_rules()
    if rule_ids is not None:
        unknown = set(rule_ids) - set(rules)
        if unknown:
            raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
        rules = {rid: rules[rid] for rid in rule_ids}
    ctx = LintContext(repo_root=repo_root, catalog=catalog,
                      knob_registry=knob_registry)

    modules: List[ModuleInfo] = []
    findings: List[Finding] = []
    for path in collect_files(paths):
        parsed = parse_module(path)
        if isinstance(parsed, Finding):
            findings.append(parsed)
        else:
            modules.append(parsed)

    mod_by_path = {m.display: m for m in modules}
    for rule in rules.values():
        if isinstance(rule, ProjectRule):
            findings.extend(rule.check_project(modules, ctx))
        else:
            for m in modules:
                if rule.skip_tests and m.is_test:
                    continue
                findings.extend(rule.check(m, ctx))

    focus: Optional[Set[str]] = None
    if only is not None:
        focus = set()
        for p in only:
            focus.add(p)
            focus.add(os.path.abspath(p))

    kept = []
    for f in findings:
        m = mod_by_path.get(f.path)
        if m is not None and m.suppressed(f.rule, f.line):
            continue
        if focus is not None:
            fp = m.path if m is not None else os.path.abspath(f.path)
            if f.path not in focus and fp not in focus:
                continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept


def render_text(findings: Sequence[Finding]) -> str:
    if not findings:
        return "pio lint: clean"
    lines = [f.render() for f in findings]
    lines.append(f"pio lint: {len(findings)} finding(s)")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps(
        {"findings": [f.to_dict() for f in findings],
         "count": len(findings)},
        indent=2, sort_keys=True,
    )
