"""Interprocedural effect analysis + shm frame-layout verifier.

Two halves, both project rules over the whole parsed file set:

**Effect summaries.** Every function gets a summary of what it *does*
to the machine — ``blocks`` (sleep / subprocess / socket / file IO /
fsync / sqlite commit / event & CV waits), ``json_codec`` (json
encode/decode), ``copies_bytes`` (``bytes()``, ``.decode``/``.encode``,
``.tobytes``, ``b"".join``, slicing a bytes-ish buffer), ``allocates``
(comprehensions, container constructors) and ``wallclock`` (time.time /
datetime.now reads). Summaries propagate to a fixpoint over the same
call edges :mod:`pio_tpu.analysis.lockgraph` resolves — ``self.m()``,
same-module ``f()``, ``mod.f()`` and ``from mod import f`` — plus two
extensions: ``from mod import Cls`` method calls (``Cls.m()``) and
re-export chains through package ``__init__``\\s (so
``pio_tpu.faults.failpoint`` resolves to the def in
``faults/registry.py``). Attribute calls on arbitrary objects stay out
of scope, exactly like the lock graph (documented limitation).

Hot-path roots are declared in source with a marker comment::

    def query(self, req):  # pio: hotpath
    def submit(self, body):  # pio: hotpath=zerocopy

``hotpath-blocking`` reports every *reachable* ``blocks`` effect from
any root, with the full call chain; ``hotpath-zero-copy`` additionally
reports reachable ``json_codec``/``copies_bytes`` effects from
``zerocopy`` roots — the contract the epoll/int8 front must hold
(ROADMAP item 1). A ``# pio: disable=<rule>`` comment suppresses at
three grains: on the root's def/marker line (the whole root), on a call
site along the chain (cuts that edge for everything behind it), or on
the effect line itself (that one site, for every root).

**Frame layouts.** ``shm-frame-layout`` cross-checks the writer and
reader sides of every ``struct`` wire format. Call sites and
``struct.Struct`` declarations opt in with ``# pio: frame=<family>``;
within a family the union of writer fields (offset → type code) must
equal the union of reader fields — field count, per-offset type,
pad-stripped extent, declared struct size, and endianness prefix all
have to agree, and a module that declares any family must assign every
``struct`` use to one (so a new ``pack_into`` cannot dodge the check).
Magic/size constants participate: a reader whose absolute offset lands
inside the module's ``MAGIC`` bytes, or a header family that overflows
``HEADER_BYTES``, is a finding.
"""

from __future__ import annotations

import ast
import os
import re
import struct as _structmod
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from pio_tpu.analysis.core import (
    Finding,
    LintContext,
    ModuleInfo,
    ProjectRule,
    register,
)

# ---------------------------------------------------------------------------
# effect lexicon

#: (receiver-substring-or-None, method) -> blocking; mirrors (and
#: extends) the lexical lock-rule lexicon in rules_concurrency
_BLOCKING_ATTRS = (
    ("time", "sleep"),
    ("subprocess", "run"),
    ("subprocess", "call"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    (None, "urlopen"),
    (None, "serve_forever"),
    (None, "create_connection"),
    ("sock", "recv"),
    ("sock", "accept"),
    ("sock", "connect"),
    ("sock", "sendall"),
    ("conn", "commit"),
    ("db", "commit"),
    ("os", "fsync"),
)
_BLOCKING_BARE = {"sleep", "urlopen"}

#: bytes-ish receiver names whose slice reads count as a copy
_BYTEISH_RE = re.compile(
    r"(payload|body|buf|data|frame|raw|blob|_m)\b", re.IGNORECASE
)

_ALLOC_CALLS = {"list", "dict", "set", "bytearray"}

EFFECT_KINDS = (
    "blocks", "json_codec", "copies_bytes", "allocates", "wallclock",
)


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return "<expr>"


@dataclass(frozen=True)
class EffectSite:
    """One direct effect occurrence inside a function body."""

    kind: str       # one of EFFECT_KINDS
    what: str       # human label, e.g. "time.sleep()"
    path: str       # module display path
    line: int

    def render(self) -> str:
        return f"{self.what} at {self.path}:{self.line}"


@dataclass
class FnEffects:
    """Per-function scan result: direct effects + resolved call edges."""

    qual: str
    module: ModuleInfo
    line: int                      # def line
    marker: Optional[str] = None   # None | "" (hotpath) | "zerocopy"
    direct: List[EffectSite] = field(default_factory=list)
    calls: List[Tuple[str, int]] = field(default_factory=list)


def _resolve_import_from(module: ModuleInfo, node: ast.ImportFrom
                         ) -> Optional[str]:
    """Absolute dotted module a ``from X import …`` refers to, handling
    relative levels against this module's own dotted name."""
    if node.level == 0:
        return node.module
    parts = module.module_name.split(".")
    is_pkg = os.path.basename(module.path) == "__init__.py"
    drop = node.level - (1 if is_pkg else 0)
    if drop > len(parts):
        return None
    if drop > 0:
        parts = parts[:-drop]
    base = ".".join(parts)
    if node.module:
        return f"{base}.{node.module}" if base else node.module
    return base or None


class _EffectScanner:
    """One pass over a module: imports, per-function direct effects and
    call records, and hot-path markers bound to their defs."""

    def __init__(self, module: ModuleInfo):
        self.module = module
        self.mod = module.module_name
        self.fns: Dict[str, FnEffects] = {}
        self.imports: Dict[str, str] = {}        # alias -> module
        self.from_imports: Dict[str, str] = {}   # name -> "mod.name"
        self._collect_imports()

    def _collect_imports(self) -> None:
        for node in ast.walk(self.module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name if alias.asname else alias.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                target = _resolve_import_from(self.module, node)
                if target is None:
                    continue
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = \
                        f"{target}.{alias.name}"

    def scan(self) -> None:
        for top in self.module.tree.body:
            if isinstance(top, ast.ClassDef):
                for item in top.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._scan_fn(item, top.name)
            elif isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_fn(top, None)

    # -- call resolution ----------------------------------------------------
    def callee_key(self, call: ast.Call, cls: Optional[str]) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in self.from_imports:
                return self.from_imports[fn.id]
            return f"{self.mod}.{fn.id}"
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Name):
                if base.id == "self" and cls is not None:
                    return f"{self.mod}.{cls}.{fn.attr}"
                target = self.imports.get(base.id)
                if target is not None:
                    return f"{target}.{fn.attr}"
                target = self.from_imports.get(base.id)
                if target is not None:          # from mod import Cls; Cls.m()
                    return f"{target}.{fn.attr}"
        return None

    # -- direct effects -----------------------------------------------------
    def _effects_of_call(self, call: ast.Call) -> Iterable[Tuple[str, str]]:
        fn = call.func
        if isinstance(fn, ast.Name):
            name = fn.id
            if name in _BLOCKING_BARE:
                yield "blocks", f"{name}()"
            elif name == "open":
                yield "blocks", "open() file IO"
            elif name == "bytes" and call.args:
                yield "copies_bytes", "bytes() copy"
            elif name in _ALLOC_CALLS and (call.args or call.keywords):
                yield "allocates", f"{name}() construction"
            resolved = self.from_imports.get(name, "")
            if resolved in ("json.loads", "json.dumps",
                            "json.load", "json.dump"):
                yield "json_codec", f"{resolved}()"
            return
        if not isinstance(fn, ast.Attribute):
            return
        recv = _unparse(fn.value)
        recv_l = recv.lower()
        attr = fn.attr
        for needle, meth in _BLOCKING_ATTRS:
            if attr == meth and (needle is None or needle in recv_l):
                yield "blocks", f"{recv}.{attr}()"
                break
        else:
            if attr in ("wait", "wait_for"):
                yield "blocks", f"{recv}.{attr}() lock/event wait"
            elif attr == "join" and "thread" in recv_l:
                yield "blocks", f"{recv}.join()"
        if recv_l == "json" and attr in ("loads", "dumps", "load", "dump"):
            yield "json_codec", f"json.{attr}()"
        if attr in ("decode", "encode"):
            yield "copies_bytes", f"{recv}.{attr}()"
        elif attr == "tobytes":
            yield "copies_bytes", f"{recv}.tobytes()"
        elif (attr == "join" and isinstance(fn.value, ast.Constant)
                and isinstance(fn.value.value, bytes)):
            yield "copies_bytes", "bytes .join()"
        if attr in ("time", "time_ns") and recv_l == "time":
            yield "wallclock", f"time.{attr}()"
        elif attr in ("now", "utcnow") and "datetime" in recv_l:
            yield "wallclock", f"{recv}.{attr}()"

    def _scan_fn(self, fn, cls: Optional[str]) -> None:
        qual = f"{self.mod}.{cls}.{fn.name}" if cls else f"{self.mod}.{fn.name}"
        marker = self.module.hotpath_markers.get(fn.lineno)
        if marker is None and fn.decorator_list:
            # marker above a decorated def covers the first decorator line
            marker = self.module.hotpath_markers.get(
                fn.decorator_list[0].lineno
            )
        info = self.fns.setdefault(
            qual, FnEffects(qual, self.module, fn.lineno, marker)
        )
        display = self.module.display
        seen: Set[Tuple[str, str, int]] = set()

        def note(kind: str, what: str, line: int) -> None:
            key = (kind, what, line)
            if key not in seen:
                seen.add(key)
                info.direct.append(EffectSite(kind, what, display, line))

        for node in _walk_local(fn):
            if isinstance(node, ast.Call):
                for kind, what in self._effects_of_call(node):
                    note(kind, what, node.lineno)
                key = self.callee_key(node, cls)
                if key is not None:
                    info.calls.append((key, node.lineno))
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                note("allocates", "comprehension", node.lineno)
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.slice, ast.Slice)
                    and isinstance(node.ctx, ast.Load)
                    and _BYTEISH_RE.search(_unparse(node.value))):
                note("copies_bytes",
                     f"slice of {_unparse(node.value)}", node.lineno)


def _walk_local(fn) -> Iterable[ast.AST]:
    """Walk ``fn``'s body without descending into nested defs/classes
    (a closure defined here runs elsewhere, if at all)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# the project-wide analysis

class EffectAnalysis:
    """Call graph + effect summaries over one parsed module set."""

    def __init__(self, modules: Sequence[ModuleInfo]):
        scanners = [_EffectScanner(m) for m in modules]
        for s in scanners:
            s.scan()
        self.fns: Dict[str, FnEffects] = {}
        self._scanner_by_module: Dict[str, _EffectScanner] = {}
        #: "mod.name" re-export/alias targets from every from-import
        alias: Dict[str, str] = {}
        for s in scanners:
            self.fns.update(s.fns)
            self._scanner_by_module[s.module.path] = s
            for name, target in s.from_imports.items():
                alias.setdefault(f"{s.mod}.{name}", target)
        self._alias = alias

        # resolved edges (only those landing on a known function)
        self.edges: Dict[str, List[Tuple[str, int]]] = {}
        for info in self.fns.values():
            out = []
            for key, line in info.calls:
                target = self.resolve(key)
                if target is not None and target != info.qual:
                    out.append((target, line))
            self.edges[info.qual] = out

        # transitive effect-kind fixpoint (cycle-safe, like lockgraph)
        self.trans: Dict[str, Set[str]] = {
            q: {site.kind for site in i.direct}
            for q, i in self.fns.items()
        }
        changed = True
        while changed:
            changed = False
            for q in self.fns:
                mine = self.trans[q]
                for callee, _line in self.edges[q]:
                    sub = self.trans.get(callee)
                    if sub and not sub <= mine:
                        mine |= sub
                        changed = True

    # -- lookups ------------------------------------------------------------
    def resolve(self, key: str) -> Optional[str]:
        """Follow re-export aliases until ``key`` names a known function
        (or give up). ``pkg.name`` re-exported from ``pkg.sub`` resolves
        through the package ``__init__``'s from-imports; ``mod.Cls.m``
        follows an aliased ``mod.Cls`` prefix."""
        seen = set()
        while key not in self.fns and key not in seen:
            seen.add(key)
            nxt = self._alias.get(key)
            if nxt is None and "." in key:
                head, _, tail = key.rpartition(".")
                base = self._alias.get(head)
                if base is not None:
                    nxt = f"{base}.{tail}"
            if nxt is None:
                return None
            key = nxt
        return key if key in self.fns else None

    def scanner_for(self, module: ModuleInfo) -> Optional[_EffectScanner]:
        return self._scanner_by_module.get(module.path)

    def roots(self) -> List[FnEffects]:
        return sorted(
            (i for i in self.fns.values() if i.marker is not None),
            key=lambda i: i.qual,
        )

    # -- reachability -------------------------------------------------------
    def reachable_sites(self, start: str, kinds: Sequence[str],
                        rule_id: Optional[str] = None
                        ) -> List[Tuple[EffectSite, List[str]]]:
        """Every direct effect site of ``kinds`` reachable from
        ``start`` (inclusive), with the shortest call chain (function
        quals, ``start`` first). ``rule_id`` applies suppressions: a
        disabled call line cuts the edge, a disabled effect line drops
        the site."""
        out: List[Tuple[EffectSite, List[str]]] = []
        seen: Set[str] = {start}
        queue: List[Tuple[str, List[str]]] = [(start, [start])]
        wanted = set(kinds)
        while queue:
            qual, chain = queue.pop(0)
            info = self.fns.get(qual)
            if info is None:
                continue
            for site in info.direct:
                if site.kind not in wanted:
                    continue
                if rule_id is not None and info.module.suppressed(
                        rule_id, site.line):
                    continue
                out.append((site, chain))
            for callee, line in self.edges.get(qual, ()):
                if callee in seen:
                    continue
                if rule_id is not None and info.module.suppressed(
                        rule_id, line):
                    continue  # suppressed call: the chain is cut here
                seen.add(callee)
                queue.append((callee, chain + [callee]))
        return out

    def blocking_chain(self, key: str, rule_id: str
                       ) -> Optional[Tuple[EffectSite, List[str]]]:
        """Shortest unsuppressed chain from call target ``key`` (a raw
        callee key — resolved here) to a ``blocks`` effect, or None."""
        target = self.resolve(key)
        if target is None or "blocks" not in self.trans.get(target, ()):
            return None
        sites = self.reachable_sites(target, ("blocks",), rule_id)
        return sites[0] if sites else None


def get_analysis(modules: Sequence[ModuleInfo],
                 ctx: LintContext) -> EffectAnalysis:
    """Build (or reuse) the effect analysis for this lint run — the
    hot-path rules and the interprocedural lock rule share one fixpoint
    per ``LintContext``."""
    key = tuple(m.path for m in modules)
    cached = getattr(ctx, "_effects_analysis", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    analysis = EffectAnalysis(modules)
    ctx._effects_analysis = (key, analysis)
    return analysis


def _chain_text(chain: List[str]) -> str:
    return " -> ".join(q.rsplit(".", 1)[-1] for q in chain)


def _root_suppressed(root: FnEffects, rule_id: str) -> bool:
    lines = [root.line]
    for ln, _v in root.module.hotpath_markers.items():
        if abs(ln - root.line) <= 1:
            lines.append(ln)
    return root.module.suppressed_at_any(rule_id, lines)


# ---------------------------------------------------------------------------
# rules: hot-path contracts

@register
class HotpathBlockingRule(ProjectRule):
    id = "hotpath-blocking"
    family = "hotpath"
    description = (
        "A function marked `# pio: hotpath` (query/dispatch/drain "
        "roots) reaches a blocking call — sleep, subprocess, socket, "
        "file IO, fsync, sqlite commit or event/CV wait — through the "
        "interprocedural call graph; the full chain is reported."
    )

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> List[Finding]:
        analysis = get_analysis(modules, ctx)
        findings: List[Finding] = []
        for root in analysis.roots():
            if _root_suppressed(root, self.id):
                continue
            for site, chain in analysis.reachable_sites(
                    root.qual, ("blocks",), self.id):
                findings.append(Finding(
                    self.id, root.module.display, root.line, 0,
                    f"hot path `{root.qual}` reaches blocking "
                    f"{site.render()} via {_chain_text(chain)}; move the "
                    f"blocking work off the hot path or suppress at the "
                    f"site with a justification",
                ))
        return findings


@register
class HotpathZeroCopyRule(ProjectRule):
    id = "hotpath-zero-copy"
    family = "hotpath"
    description = (
        "A function marked `# pio: hotpath=zerocopy` (the int8 packed-"
        "frame path) reaches a JSON encode/decode or a bytes copy "
        "(bytes()/.decode/.encode/.tobytes/slice) — the zero-copy "
        "contract the epoll front depends on."
    )

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> List[Finding]:
        analysis = get_analysis(modules, ctx)
        findings: List[Finding] = []
        for root in analysis.roots():
            if root.marker != "zerocopy":
                continue
            if _root_suppressed(root, self.id):
                continue
            for site, chain in analysis.reachable_sites(
                    root.qual, ("json_codec", "copies_bytes"), self.id):
                findings.append(Finding(
                    self.id, root.module.display, root.line, 0,
                    f"zero-copy path `{root.qual}` reaches {site.kind} "
                    f"{site.render()} via {_chain_text(chain)}; keep the "
                    f"packed frame untouched or suppress at the site "
                    f"with a justification",
                ))
        return findings


# ---------------------------------------------------------------------------
# frame-layout verifier

@dataclass
class FrameRecord:
    family: str
    role: str                  # "writer" | "reader"
    fmt: str
    delta: Optional[int]       # constant byte offset (None = none given)
    absolute: bool             # delta was a bare constant offset arg
    path: str
    line: int

    def site(self) -> str:
        return f"{self.path}:{self.line}"


def _parse_fmt(fmt: str):
    """(endian, fields [(offset, code, size)], total size, non-pad
    extent) or None when the format does not parse."""
    endian = fmt[0] if fmt[:1] in "<>=!@" else "@"
    body = fmt[1:] if fmt[:1] in "<>=!@" else fmt
    try:
        total = _structmod.calcsize(fmt)
    except _structmod.error:
        return None
    fields: List[Tuple[int, str, int]] = []
    consumed = ""
    extent = 0
    for count_s, code in re.findall(r"\s*(\d*)([a-zA-Z?])", body):
        pre = _structmod.calcsize((fmt[:1] if endian != "@" else "")
                                  + consumed) if consumed else 0
        consumed += count_s + code
        if code == "x":
            continue
        count = int(count_s) if count_s else 1
        if code == "s":
            fields.append((pre, f"{count}s", count))
            extent = max(extent, pre + count)
            continue
        size = _structmod.calcsize(
            (fmt[:1] if endian != "@" else "") + code
        )
        for i in range(count):
            fields.append((pre + i * size, code, size))
        extent = max(extent, pre + count * size)
    return endian, fields, total, extent


_PACK_METHS = {"pack", "pack_into"}
_UNPACK_METHS = {"unpack", "unpack_from", "iter_unpack"}


def _const_offset(node: Optional[ast.expr]) -> Tuple[Optional[int], bool]:
    """(constant byte delta, was-absolute) for an offset argument:
    a bare constant is absolute; ``base + C`` contributes delta C from
    a symbolic base; anything else is symbolic delta 0."""
    if node is None:
        return None, False
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value, True
    if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add)):
        for a, b in ((node.left, node.right), (node.right, node.left)):
            if isinstance(b, ast.Constant) and isinstance(b.value, int) \
                    and not isinstance(a, ast.Constant):
                return b.value, False
    return 0, False


class _FrameScanner:
    """Collect frame records + magic/size constants from one module."""

    def __init__(self, module: ModuleInfo):
        self.module = module
        self.records: List[FrameRecord] = []
        self.unassigned: List[Tuple[str, int]] = []   # (what, line)
        self.magics: Dict[str, bytes] = {}
        self.consts: Dict[str, int] = {}
        self._struct_vars: Dict[str, Tuple[str, Optional[str]]] = {}
        if module.frame_markers:
            self._scan()

    def _family_at(self, line: int) -> Optional[str]:
        return self.module.frame_markers.get(line)

    def _scan(self) -> None:
        tree = self.module.tree
        for top in tree.body:
            if not isinstance(top, ast.Assign):
                continue
            for t in top.targets:
                if not isinstance(t, ast.Name):
                    continue
                v = top.value
                if isinstance(v, ast.Constant) and isinstance(v.value, bytes) \
                        and "MAGIC" in t.id:
                    self.magics[t.id] = v.value
                elif isinstance(v, ast.Constant) and isinstance(v.value, int):
                    self.consts[t.id] = v.value
                elif (isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Attribute)
                        and v.func.attr == "Struct"
                        and v.args
                        and isinstance(v.args[0], ast.Constant)
                        and isinstance(v.args[0].value, str)):
                    fam = self._family_at(top.lineno)
                    self._struct_vars[t.id] = (v.args[0].value, fam)
                    if fam is None:
                        self.unassigned.append(
                            (f"struct.Struct assigned to {t.id}", top.lineno)
                        )
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._scan_call(node)

    def _record(self, family: Optional[str], role: str, fmt: str,
                off_node: Optional[ast.expr], line: int, what: str) -> None:
        if family is None:
            family = self._family_at(line)
        if family is None:
            self.unassigned.append((what, line))
            return
        delta, absolute = _const_offset(off_node)
        self.records.append(FrameRecord(
            family, role, fmt, delta, absolute,
            self.module.display, line,
        ))

    def _scan_call(self, call: ast.Call) -> None:
        fn = call.func
        if not isinstance(fn, ast.Attribute):
            return
        attr = fn.attr
        if attr not in _PACK_METHS | _UNPACK_METHS:
            return
        role = "writer" if attr in _PACK_METHS else "reader"
        base = fn.value
        if isinstance(base, ast.Name) and base.id == "struct":
            # struct.pack(fmt, ...) / struct.pack_into(fmt, buf, off, ...)
            if not (call.args and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)):
                return
            fmt = call.args[0].value
            off = None
            if attr in ("pack_into", "unpack_from"):
                off = call.args[2] if len(call.args) > 2 else None
                if off is None:
                    off = next((kw.value for kw in call.keywords
                                if kw.arg == "offset"), None)
            self._record(None, role, fmt, off, call.lineno,
                         f"struct.{attr}({fmt!r}, …)")
            return
        if isinstance(base, ast.Name) and base.id in self._struct_vars:
            fmt, fam = self._struct_vars[base.id]
            off = None
            if attr in ("pack_into", "unpack_from"):
                off = call.args[1] if len(call.args) > 1 else None
                if off is None:
                    off = next((kw.value for kw in call.keywords
                                if kw.arg == "offset"), None)
            self._record(fam, role, fmt, off, call.lineno,
                         f"{base.id}.{attr}(…)")


@dataclass
class _FamilyState:
    records: List[FrameRecord] = field(default_factory=list)
    magics: Dict[str, bytes] = field(default_factory=dict)
    consts: Dict[str, int] = field(default_factory=dict)


def _family_layouts(fam: str, state: _FamilyState):
    """Per-role normalized field maps + metadata; yields findings for
    parse failures and intra-role conflicts, returns the summary."""
    findings: List[str] = []   # (message) — caller attaches path/line
    roles: Dict[str, Dict[int, Tuple[str, int, FrameRecord]]] = {}
    endians: Dict[str, Set[Tuple[str, str]]] = {}
    declared: Dict[str, Dict[int, FrameRecord]] = {}
    min_raw_reader: Optional[int] = None
    for rec in state.records:
        parsed = _parse_fmt(rec.fmt)
        if parsed is None:
            findings.append(
                f"frame family `{fam}`: unparsable struct format "
                f"{rec.fmt!r} at {rec.site()}"
            )
            continue
        endian, fields, total, extent = parsed
        endians.setdefault(rec.role, set()).add((endian, rec.site()))
        base = rec.delta or 0
        if rec.role == "reader" and rec.absolute and rec.delta is not None:
            if min_raw_reader is None or rec.delta < min_raw_reader:
                min_raw_reader = rec.delta
        # a record that lays out the whole frame (multi-field or padded,
        # anchored at the frame base) declares the frame's true size
        if (rec.delta in (None, 0) or rec.absolute) \
                and (len(fields) > 1 or total > extent):
            declared.setdefault(rec.role, {})[total] = rec
        entries = roles.setdefault(rec.role, {})
        for off, code, size in fields:
            key = base + off
            prev = entries.get(key)
            if prev is not None and prev[0] != code:
                findings.append(
                    f"frame family `{fam}`: conflicting {rec.role} field "
                    f"at byte {key}: `{prev[0]}` ({prev[2].site()}) vs "
                    f"`{code}` ({rec.site()})"
                )
            entries[key] = (code, size, rec)
    # normalize each role to its own base (a header writer that packs
    # sequentially after the magic and a reader that unpack_from's at
    # the absolute offset describe the same fields)
    norm: Dict[str, Dict[int, Tuple[str, int, FrameRecord]]] = {}
    for role, entries in roles.items():
        if not entries:
            continue
        lo = min(entries)
        norm[role] = {off - lo: v for off, v in entries.items()}
    return findings, norm, endians, declared, min_raw_reader


def _check_family(fam: str, state: _FamilyState) -> List[str]:
    msgs, norm, endians, declared, min_reader = _family_layouts(fam, state)
    # endianness: every record in the family must agree
    prefixes = {e for sides in endians.values() for (e, _s) in sides}
    if len(prefixes) > 1:
        detail = "; ".join(
            f"{role}: " + ", ".join(
                sorted(f"{e!r} at {s}" for e, s in sides)
            )
            for role, sides in sorted(endians.items())
        )
        msgs.append(
            f"frame family `{fam}`: endianness prefixes disagree "
            f"({detail})"
        )
    writer = norm.get("writer")
    reader = norm.get("reader")
    if writer and reader:
        if len(writer) != len(reader):
            msgs.append(
                f"frame family `{fam}`: field count disagrees — "
                f"writers cover {len(writer)} field(s), readers "
                f"{len(reader)}"
            )
        for off in sorted(set(writer) | set(reader)):
            w, r = writer.get(off), reader.get(off)
            if w is None or r is None:
                side, rec = ("writer", r) if w is None else ("reader", w)
                msgs.append(
                    f"frame family `{fam}`: byte {off} has no {side} "
                    f"(field `{(w or r)[0]}` from {(w or r)[2].site()})"
                )
            elif w[0] != r[0]:
                msgs.append(
                    f"frame family `{fam}`: field type at byte {off} "
                    f"disagrees — writer `{w[0]}` ({w[2].site()}) vs "
                    f"reader `{r[0]}` ({r[2].site()})"
                )
        w_ext = max(o + v[1] for o, v in writer.items())
        r_ext = max(o + v[1] for o, v in reader.items())
        if w_ext != r_ext:
            msgs.append(
                f"frame family `{fam}`: field extent disagrees — "
                f"writers end at byte {w_ext}, readers at {r_ext}"
            )
        dw, dr = declared.get("writer"), declared.get("reader")
        if dw and dr and set(dw) != set(dr):
            w_sz, r_sz = sorted(dw), sorted(dr)
            msgs.append(
                f"frame family `{fam}`: computed byte size disagrees — "
                f"writer frame {w_sz} byte(s) "
                f"({dw[w_sz[0]].site()}) vs reader frame {r_sz} byte(s) "
                f"({dr[r_sz[0]].site()})"
            )
    # magic/header constants: a reader anchored at an absolute offset
    # must clear the magic, and a header frame must fit HEADER_BYTES
    if min_reader is not None and state.magics:
        magic_len = max(len(v) for v in state.magics.values())
        if 0 < min_reader < magic_len:
            msgs.append(
                f"frame family `{fam}`: reader offset {min_reader} "
                f"lands inside the {magic_len}-byte magic"
            )
        hdr = state.consts.get("HEADER_BYTES")
        if hdr is not None and reader:
            r_ext = max(o + v[1] for o, v in reader.items())
            if min_reader >= magic_len and min_reader + r_ext > hdr:
                msgs.append(
                    f"frame family `{fam}`: header fields end at byte "
                    f"{min_reader + r_ext}, past HEADER_BYTES={hdr}"
                )
    return msgs


@register
class ShmFrameLayoutRule(ProjectRule):
    id = "shm-frame-layout"
    family = "layout"
    description = (
        "Writer/reader struct layouts of a shared-memory or on-disk "
        "frame family (`# pio: frame=<name>` markers) disagree in "
        "field count, per-offset type, computed byte size or "
        "endianness — or a struct call in a frame module is not "
        "assigned to any family."
    )

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        families: Dict[str, _FamilyState] = {}
        anchor: Dict[str, Tuple[str, int]] = {}
        for m in modules:
            sc = _FrameScanner(m)
            for what, line in sc.unassigned:
                findings.append(Finding(
                    self.id, m.display, line, 0,
                    f"{what} in a frame-declaring module is not "
                    f"assigned to a family; add `# pio: frame=<name>`",
                ))
            for rec in sc.records:
                st = families.setdefault(rec.family, _FamilyState())
                st.records.append(rec)
                st.magics.update(sc.magics)
                st.consts.update(sc.consts)
                anchor.setdefault(rec.family, (rec.path, rec.line))
        for fam in sorted(families):
            path, line = anchor[fam]
            for msg in _check_family(fam, families[fam]):
                findings.append(Finding(self.id, path, line, 0, msg))
        return findings


# ---------------------------------------------------------------------------
# debug surfaces (pio lint --dump-callgraph / --dump-effects)

def callgraph_inventory(modules: Sequence[ModuleInfo]) -> dict:
    """Resolved call edges, caller qual -> sorted callee quals."""
    analysis = EffectAnalysis(modules)
    return {
        qual: sorted({callee for callee, _line in edges})
        for qual, edges in sorted(analysis.edges.items())
        if edges
    }


def effects_inventory(modules: Sequence[ModuleInfo]) -> dict:
    """Hot-path roots + per-function effect summaries (functions with
    at least one direct effect; `reaches` is the transitive kind set)."""
    analysis = EffectAnalysis(modules)
    functions = {}
    for qual, info in sorted(analysis.fns.items()):
        if not info.direct and not analysis.trans.get(qual):
            continue
        functions[qual] = {
            "direct": sorted(
                f"{s.kind}: {s.what} @ {s.path}:{s.line}"
                for s in info.direct
            ),
            "reaches": sorted(analysis.trans.get(qual, ())),
        }
    return {
        "roots": [
            {
                "function": r.qual,
                "marker": "zerocopy" if r.marker == "zerocopy" else "hotpath",
                "path": r.module.display,
                "line": r.line,
            }
            for r in analysis.roots()
        ],
        "functions": functions,
        "stats": {
            "functions": len(analysis.fns),
            "edges": sum(len(e) for e in analysis.edges.values()),
        },
    }


def frame_inventory(modules: Sequence[ModuleInfo]) -> dict:
    """Per-family writer/reader census — the guard test's view that the
    real frame families each have at least one verified pair."""
    families: Dict[str, _FamilyState] = {}
    for m in modules:
        sc = _FrameScanner(m)
        for rec in sc.records:
            st = families.setdefault(rec.family, _FamilyState())
            st.records.append(rec)
            st.magics.update(sc.magics)
            st.consts.update(sc.consts)
    out = {}
    for fam, st in sorted(families.items()):
        _msgs, norm, _endians, _declared, _min = _family_layouts(fam, st)
        writers = [r for r in st.records if r.role == "writer"]
        readers = [r for r in st.records if r.role == "reader"]
        disagreements = _check_family(fam, st)
        fields = norm.get("reader") or norm.get("writer") or {}
        out[fam] = {
            "writers": len(writers),
            "readers": len(readers),
            "fields": len(fields),
            "extent": (
                max(o + v[1] for o, v in fields.items()) if fields else 0
            ),
            "verified": bool(writers) and bool(readers)
            and not disagreements,
            "findings": len(disagreements),
        }
    return out
