"""Cross-surface contract extraction: who writes which JSON keys, who
reads them, which ``X-Pio-*`` headers flow, and every ``PIO_TPU_*`` env
knob read with its parse type and default.

The distributed planes (router scraping member ``/fleet.json``, the
rollout judge reading candidate metrics, the CLI/dashboard parsing every
status endpoint) communicate through JSON payloads that no type checker
sees — a renamed producer key fails silently as ``None`` in another
process. This pass makes those surfaces checkable:

* **Producers** — payload-builder functions found via ``# pio:
  endpoint=/fleet.json`` markers and route-registration literals
  (``router.add("GET", "/fleet\\.json", self.fleet_json)``). Helper
  functions reached through the PR-12 effect call graph contribute
  their dict keys to the root's endpoint, so ``_member_entry`` keys
  attribute to ``/fleet.json``.
* **Consumers** — ``.get("k")``/``["k"]`` chains over values tainted by
  an endpoint: fetched with a literal path argument, seeded by a
  ``# pio: consumes=/fleet.json`` marker (for payloads that crossed a
  process boundary before arriving as a parameter), or read off an
  attribute a scrape loop stored a tainted payload into.
* **Headers** — ``X-Pio-*`` writes (subscript stores, dict literals,
  ``send_header``/``add_header``) vs reads (``.get``/``[...]``/
  ``.getheader``), resolving module header constants across imports.
* **Knobs** — every ``env_int``/``env_float``/``os.environ`` read of a
  literal ``PIO_TPU_*`` name (including names held in module constants)
  plus registry reads via :mod:`pio_tpu.utils.knobs`.

``rules_contracts`` turns disagreements into findings; ``pio lint
--dump-contracts`` emits the whole inventory as JSON.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pio_tpu.analysis.core import LintContext, ModuleInfo
from pio_tpu.analysis.effects import get_analysis

#: a JSON endpoint path at the end of a (possibly larger URL) literal
_ENDPOINT_RE = re.compile(r"(/[A-Za-z0-9_\-][A-Za-z0-9_\-/.]*\.json)$")
_KNOB_RE = re.compile(r"^PIO_TPU_[A-Z0-9_]+$")
_HEADER_PREFIX = "x-pio-"


@dataclass(frozen=True)
class ProducerRoot:
    """One payload-builder function attributed to an endpoint."""

    endpoint: str
    qual: str
    path: str                      # display path
    line: int


@dataclass(frozen=True)
class ConsumerRead:
    """One key chain a consumer reads off an endpoint payload."""

    endpoint: str
    key: str                       # dotted, e.g. "members.slo.worstBurn"
    path: str
    line: int
    is_test: bool


@dataclass(frozen=True)
class HeaderUse:
    """One ``X-Pio-*`` header touch point."""

    header: str                    # lower-cased for set algebra
    canonical: str                 # as written in source
    role: str                      # "write" | "read" | "declare"
    path: str
    line: int
    is_test: bool


#: sentinel: the read site passed no default expression at all
NO_DEFAULT = object()
#: sentinel: a default expression was present but not statically foldable
DYNAMIC_DEFAULT = object()


@dataclass(frozen=True)
class KnobRead:
    """One ``PIO_TPU_*`` env read site."""

    name: str
    via: str                       # "registry" | "envutil" | "environ"
    kind: str                      # "int" | "float" | "str" | "raw"
    default: object                # literal default / NO_DEFAULT / DYNAMIC...
    path: str
    line: int
    is_test: bool
    module_name: str


@dataclass
class Contracts:
    """The extracted cross-surface inventory for one module set."""

    producers: Dict[str, List[ProducerRoot]] = field(default_factory=dict)
    #: endpoint -> flat union of every key any reached builder writes
    keys: Dict[str, Set[str]] = field(default_factory=dict)
    reads: List[ConsumerRead] = field(default_factory=list)
    headers: List[HeaderUse] = field(default_factory=list)
    knob_reads: List[KnobRead] = field(default_factory=list)


def get_contracts(modules: Sequence[ModuleInfo],
                  ctx: LintContext) -> Contracts:
    """Build (or reuse) the contract extraction for this lint run —
    all contract rules and ``--dump-contracts`` share one pass per
    :class:`LintContext`, like :func:`effects.get_analysis`."""
    key = tuple(m.path for m in modules)
    cached = getattr(ctx, "_contracts", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    extracted = _extract(modules, ctx)
    ctx._contracts = (key, extracted)
    return extracted


# ---------------------------------------------------------------------------
# shared per-module scaffolding

class _ModScan:
    """Imports, module-level constants, and top-level function nodes of
    one module — the cheap per-file substrate every extractor shares."""

    def __init__(self, module: ModuleInfo):
        self.module = module
        self.mod = module.module_name
        self.imports: Dict[str, str] = {}        # alias -> module
        self.from_imports: Dict[str, str] = {}   # name -> "mod.name"
        self.str_consts: Dict[str, str] = {}     # NAME -> value
        self.num_consts: Dict[str, object] = {}  # NAME -> folded number
        #: (qual, class name or None, fn node)
        self.fns: List[Tuple[str, Optional[str], ast.AST]] = []
        self._collect()

    def _collect(self) -> None:
        from pio_tpu.analysis.effects import _resolve_import_from
        for node in self.module.tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name if alias.asname else alias.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                target = _resolve_import_from(self.module, node)
                if target is None:
                    continue
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = \
                        f"{target}.{alias.name}"
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    self.str_consts[name] = node.value.value
                else:
                    num = _fold_number(node.value)
                    if num is not None:
                        self.num_consts[name] = num
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.fns.append(
                            (f"{self.mod}.{node.name}.{item.name}",
                             node.name, item))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.fns.append((f"{self.mod}.{node.name}", None, node))


def _fold_number(node: ast.AST) -> Optional[object]:
    """Statically fold a numeric constant expression (``4 * 1024 *
    1024``, ``-1.5``) — how declared defaults held in module constants
    become comparable values."""
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _fold_number(node.operand)
        return None if inner is None else -inner
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Mult, ast.Add, ast.Sub, ast.FloorDiv, ast.Div)):
        left, right = _fold_number(node.left), _fold_number(node.right)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.FloorDiv):
                return left // right
            return left / right
        except (ZeroDivisionError, OverflowError):
            return None
    return None


def _resolve_str(node: ast.AST, scan: _ModScan,
                 global_consts: Dict[str, str]) -> Optional[str]:
    """A string-valued expression: literal, module constant, imported
    constant, or ``mod.CONST`` attribute."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        if node.id in scan.str_consts:
            return scan.str_consts[node.id]
        target = scan.from_imports.get(node.id)
        if target is not None:
            return global_consts.get(target)
        return None
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        target = scan.imports.get(node.value.id)
        if target is not None:
            return global_consts.get(f"{target}.{node.attr}")
        target = scan.from_imports.get(node.value.id)
        if target is not None:
            return global_consts.get(f"{target}.{node.attr}")
    return None


# ---------------------------------------------------------------------------
# producers

def _route_registrations(scan: _ModScan) -> List[ProducerRoot]:
    """``router.add("GET", "/fleet\\.json", self.fleet_json)`` calls —
    the handler method becomes a producer root for the unescaped path."""
    out: List[ProducerRoot] = []
    for qual, cls, fn in scan.fns:
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add"
                    and len(node.args) >= 3):
                continue
            pat = node.args[1]
            if not (isinstance(pat, ast.Constant)
                    and isinstance(pat.value, str)):
                continue
            path = pat.value.replace("\\", "")
            if not _ENDPOINT_RE.search(path):
                continue
            handler = node.args[2]
            if isinstance(handler, ast.Attribute) \
                    and isinstance(handler.value, ast.Name) \
                    and handler.value.id == "self" and cls is not None:
                hq = f"{scan.mod}.{cls}.{handler.attr}"
            elif isinstance(handler, ast.Name):
                hq = f"{scan.mod}.{handler.id}"
            else:
                continue
            out.append(ProducerRoot(path, hq, scan.module.display,
                                    node.lineno))
    return out


def _marker_roots(scan: _ModScan) -> List[ProducerRoot]:
    out: List[ProducerRoot] = []
    markers = scan.module.endpoint_markers
    if not markers:
        return out
    for qual, _cls, fn in scan.fns:
        ep = markers.get(fn.lineno)
        if ep:
            out.append(ProducerRoot(ep, qual, scan.module.display,
                                    fn.lineno))
    return out


def _produced_keys(fn: ast.AST) -> Set[str]:
    """Every JSON key this function can write: dict-literal keys,
    ``payload["k"] = ...`` stores, ``dict(k=...)`` keywords, and
    ``.setdefault("k", ...)`` seeds.

    A dynamic map — dict comprehension, f-string/computed key, plain
    ``dict(pairs)``, ``dataclasses.asdict(...)`` — contributes the
    wildcard ``"*"``: its keys are runtime values (breaker names, burn
    windows, partition ids) the AST cannot enumerate, so consumers of
    that endpoint get the benefit of the doubt for unknown segments."""
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
                else:
                    keys.add("*")
        elif isinstance(node, ast.DictComp):
            keys.add("*")
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Store):
            if isinstance(node.slice, ast.Constant):
                if isinstance(node.slice.value, str):
                    keys.add(node.slice.value)
            else:
                keys.add("*")
        elif isinstance(node, ast.Call):
            fname = node.func
            if isinstance(fname, ast.Name) and fname.id == "dict":
                keys.update(kw.arg for kw in node.keywords if kw.arg)
                if node.args:
                    keys.add("*")
            elif (isinstance(fname, ast.Name) and fname.id == "asdict") \
                    or (isinstance(fname, ast.Attribute)
                        and fname.attr == "asdict"):
                keys.add("*")
            elif isinstance(fname, ast.Attribute) \
                    and fname.attr == "setdefault" and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                    keys.add(a0.value)
    return keys


# ---------------------------------------------------------------------------
# consumers

#: taint = (endpoint, dotted prefix inside its payload; "" = the root).
#: Every binding carries a *set* of taints: a name rebound across two
#: scrape loops (``for p in fleet[...]`` then ``for p in storage[...]``)
#: is ambiguous, and reads through an ambiguous name are skipped rather
#: than misattributed.
_Taint = Tuple[str, str]
_Taints = Set[_Taint]


def _join(prefix: str, key: str) -> str:
    return f"{prefix}.{key}" if prefix else key


def _endpoint_in_call(node: ast.Call) -> Optional[str]:
    """An endpoint path literal anywhere in the call's arguments —
    ``_get_json(m, "/train.json")``, ``urlopen(url + "/slo.json")``,
    f-string URLs. Route registrations (``.add``) don't count: they
    declare a producer, they don't fetch."""
    if isinstance(node.func, ast.Attribute) and node.func.attr == "add":
        return None
    for arg in node.args + [kw.value for kw in node.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                m = _ENDPOINT_RE.search(sub.value)
                if m:
                    return m.group(1)
    return None


class _ConsumerScan:
    """Per-module taint pass binding payload values to endpoints and
    recording the key chains read off them."""

    def __init__(self, scan: _ModScan):
        self.scan = scan
        #: attribute name -> endpoints, from ``m.train = <tainted>``
        self.attr_bindings: Dict[str, Set[str]] = {}
        self.reads: List[ConsumerRead] = []

    def run(self) -> None:
        # two passes so attribute bindings written in one function
        # (scrape loop) are visible to taints in another (renderer)
        fn_taints: Dict[str, Dict[str, _Taints]] = {}
        for _pass in range(2):
            for qual, _cls, fn in self.scan.fns:
                fn_taints[qual] = self._taints_of(fn)
        for qual, _cls, fn in self.scan.fns:
            self._collect_reads(fn, fn_taints[qual])

    # -- taint seeding ------------------------------------------------------
    def _taints_of(self, fn: ast.AST) -> Dict[str, _Taints]:
        taints: Dict[str, _Taints] = {}
        marker = self.scan.module.consumes_markers.get(fn.lineno)
        if marker:
            for arg in list(fn.args.posonlyargs) + list(fn.args.args) \
                    + list(fn.args.kwonlyargs):
                if arg.arg not in ("self", "cls"):
                    taints[arg.arg] = {(marker, "")}
        # assignments/loops to a local fixpoint (chains assign forward,
        # so a few passes close out nested rebinding)
        for _round in range(3):
            before = sum(len(s) for s in taints.values())
            for node in ast.walk(fn):
                self._seed_stmt(node, taints)
            if sum(len(s) for s in taints.values()) == before:
                break
        return taints

    def _bind(self, taints: Dict[str, _Taints], name: str,
              t: _Taints, value: ast.AST) -> None:
        if t:
            taints.setdefault(name, set()).update(t)
        elif name in taints \
                and not isinstance(value, (ast.Constant, ast.Dict,
                                           ast.List, ast.Tuple, ast.Set)):
            # the name is rebound to something we can't trace (a helper
            # call, a different loop's iterable): the flat per-function
            # table can no longer say WHICH binding a later read sees, so
            # poison it to ambiguous rather than misattribute.  Literal
            # inits (``x = None`` before the fetch) don't poison.
            taints[name].add(("?", ""))

    def _seed_stmt(self, node: ast.AST,
                   taints: Dict[str, _Taints]) -> None:
        if isinstance(node, ast.Assign):
            t = self._taint_of(node.value, taints)
            for target in node.targets:
                if isinstance(target, ast.Name):
                    self._bind(taints, target.id, t, node.value)
                elif isinstance(target, ast.Tuple) and target.elts \
                        and isinstance(target.elts[-1], ast.Name):
                    # ``status, body = http(...)`` / ``st, hdrs, body =``
                    # — the JSON payload rides last in every fetch-helper
                    # idiom in this tree; the status/headers positions
                    # must NOT inherit payload taint (their reads are
                    # HTTP metadata, not payload keys)
                    self._bind(taints, target.elts[-1].id, t, node.value)
                elif isinstance(target, ast.Attribute) and t:
                    # m.train = train  -> every later `<x>.train` read in
                    # this module is a /train.json payload
                    self.attr_bindings.setdefault(
                        target.attr, set()).update(ep for ep, _pfx in t)
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            self._bind(taints, node.target.id,
                       self._taint_of(node.value, taints), node.value)
        elif isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            self._bind(taints, node.target.id,
                       self._taint_of(node.iter, taints), node.iter)
        elif isinstance(node, ast.comprehension) \
                and isinstance(node.target, ast.Name):
            self._bind(taints, node.target.id,
                       self._taint_of(node.iter, taints), node.iter)
        elif isinstance(node, ast.withitem) \
                and node.optional_vars is not None \
                and isinstance(node.optional_vars, ast.Name):
            self._bind(taints, node.optional_vars.id,
                       self._taint_of(node.context_expr, taints),
                       node.context_expr)

    # -- expression taint ---------------------------------------------------
    def _taint_of(self, node: ast.AST,
                  taints: Dict[str, _Taints]) -> _Taints:
        if isinstance(node, ast.Name):
            return taints.get(node.id, set())
        if isinstance(node, ast.Await):
            return self._taint_of(node.value, taints)
        if isinstance(node, ast.Attribute):
            return {(ep, "")
                    for ep in self.attr_bindings.get(node.attr, ())}
        if isinstance(node, ast.Subscript):
            base = self._taint_of(node.value, taints)
            if isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str):
                key = node.slice.value
                return {(ep, _join(pfx, key)) for ep, pfx in base}
            # list indexing / slicing keeps the payload position
            return base
        if isinstance(node, ast.BoolOp):
            return self._taint_of(node.values[0], taints)
        if isinstance(node, ast.IfExp):
            return (self._taint_of(node.body, taints)
                    | self._taint_of(node.orelse, taints))
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute):
                base = self._taint_of(fn.value, taints)
                if fn.attr == "get" and node.args:
                    a0 = node.args[0]
                    if isinstance(a0, ast.Constant) \
                            and isinstance(a0.value, str):
                        key = a0.value
                        return {(ep, _join(pfx, key))
                                for ep, pfx in base}
                    return set()
                if fn.attr in ("read", "json", "copy", "items", "values"):
                    # decode/iterate wrappers keep the payload taint
                    return base
            ep = _endpoint_in_call(node)
            if ep is not None:
                return {(ep, "")}
            # json.load(resp) / json.loads(body) propagate their
            # argument's root taint through the decode
            return {t for arg in node.args
                    for t in self._taint_of(arg, taints) if t[1] == ""}
        return set()

    # -- reads --------------------------------------------------------------
    def _collect_reads(self, fn: ast.AST,
                       taints: Dict[str, _Taints]) -> None:
        module = self.scan.module
        seen: Set[Tuple[str, str, int]] = set()
        for node in ast.walk(fn):
            key = ep = line = None
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" and node.args:
                a0 = node.args[0]
                base = self._taint_of(node.func.value, taints)
                if len(base) == 1 and isinstance(a0, ast.Constant) \
                        and isinstance(a0.value, str):
                    (bep, pfx), = base
                    ep, key, line = bep, _join(pfx, a0.value), node.lineno
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str):
                base = self._taint_of(node.value, taints)
                if len(base) == 1:
                    (bep, pfx), = base
                    ep, key, line = bep, \
                        _join(pfx, node.slice.value), node.lineno
            if ep is None or key is None:
                continue
            mark = (ep, key, line)
            if mark in seen:
                continue
            seen.add(mark)
            self.reads.append(ConsumerRead(ep, key, module.display,
                                           line, module.is_test))


# ---------------------------------------------------------------------------
# headers

def _resolve_header(node: ast.AST, scan: _ModScan,
                    global_consts: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "lower" and not node.args:
        return _resolve_header(node.func.value, scan, global_consts)
    val = _resolve_str(node, scan, global_consts)
    if val is not None and val.lower().startswith(_HEADER_PREFIX):
        return val
    return None


def _scan_headers(scan: _ModScan, global_consts: Dict[str, str],
                  out: List[HeaderUse]) -> None:
    module = scan.module

    def use(node: ast.AST, role: str, line: int) -> None:
        name = _resolve_header(node, scan, global_consts)
        if name is not None:
            out.append(HeaderUse(name.lower(), name, role,
                                 module.display, line, module.is_test))

    for name, value in scan.str_consts.items():
        if value.lower().startswith(_HEADER_PREFIX):
            out.append(HeaderUse(value.lower(), value, "declare",
                                 module.display, 0, module.is_test))
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Subscript) \
                and not isinstance(node.slice, ast.Slice):
            role = "write" if isinstance(node.ctx, ast.Store) else "read"
            use(node.slice, role, node.lineno)
        elif isinstance(node, ast.Dict):
            for k in node.keys:
                if k is not None:
                    use(k, "write", getattr(k, "lineno", node.lineno))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) and node.args:
            attr = node.func.attr
            if attr in ("send_header", "add_header", "putheader"):
                use(node.args[0], "write", node.lineno)
            elif attr in ("get", "getheader", "header", "pop",
                          "setdefault"):
                # `.header(NAME)` is the tree's Request accessor
                use(node.args[0], "read", node.lineno)


# ---------------------------------------------------------------------------
# knobs

_ENV_READ_FNS = {"env_int": "int", "env_float": "float"}
_REGISTRY_FNS = {"knob_int": "int", "knob_float": "float",
                 "knob_str": "str", "knob_raw": "raw"}


def _is_environ(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "environ") \
        or (isinstance(node, ast.Name) and node.id == "environ")


def _fn_leaf(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _scan_knobs(scan: _ModScan, global_consts: Dict[str, str],
                out: List[KnobRead]) -> None:
    module = scan.module

    def knob_name(node: ast.AST) -> Optional[str]:
        val = _resolve_str(node, scan, global_consts)
        if val is not None and _KNOB_RE.match(val):
            return val
        return None

    def default_of(call: ast.Call, idx: int) -> object:
        args = list(call.args)
        for kw in call.keywords:
            if kw.arg in ("default", "fallback"):
                args = args[:idx] + [kw.value]
                break
        if len(args) <= idx:
            return NO_DEFAULT
        node = args[idx]
        if isinstance(node, ast.Constant) and not isinstance(
                node.value, bool):
            return node.value
        num = _fold_number(node)
        if num is not None:
            return num
        if isinstance(node, ast.Name):
            if node.id in scan.num_consts:
                return scan.num_consts[node.id]
            if node.id in scan.str_consts:
                return scan.str_consts[node.id]
        return DYNAMIC_DEFAULT

    for top in ast.walk(module.tree):
        name = via = kind = None
        default: object = NO_DEFAULT
        line = 0
        if isinstance(top, ast.Call):
            leaf = _fn_leaf(top.func)
            if leaf in _ENV_READ_FNS and top.args:
                name = knob_name(top.args[0])
                via, kind = "envutil", _ENV_READ_FNS[leaf]
                default = default_of(top, 1)
            elif leaf in _REGISTRY_FNS and top.args:
                name = knob_name(top.args[0])
                via, kind = "registry", _REGISTRY_FNS[leaf]
                default = default_of(top, 1)
            elif leaf in ("get", "getenv") and top.args:
                recv_ok = (
                    leaf == "getenv"
                    or (isinstance(top.func, ast.Attribute)
                        and _is_environ(top.func.value))
                )
                if recv_ok:
                    name = knob_name(top.args[0])
                    via, kind = "environ", "str"
                    default = default_of(top, 1)
            line = top.lineno
        elif isinstance(top, ast.Subscript) \
                and isinstance(top.ctx, ast.Load) \
                and _is_environ(top.value):
            name = knob_name(top.slice)
            via, kind, line = "environ", "str", top.lineno
        if name is None or via is None:
            continue
        out.append(KnobRead(name, via, kind, default, module.display,
                            line, module.is_test, module.module_name))


# ---------------------------------------------------------------------------
# the extraction pass + inventory dump

def _extract(modules: Sequence[ModuleInfo], ctx: LintContext) -> Contracts:
    scans = [_ModScan(m) for m in modules]
    global_consts: Dict[str, str] = {}
    for s in scans:
        for name, value in s.str_consts.items():
            global_consts.setdefault(f"{s.mod}.{name}", value)
    # re-export propagation: a package facade that `from x import C`s a
    # constant republishes it under its own name (pio_tpu.qos exposes
    # deadline.py's DEADLINE_HEADER), and consumers import through the
    # facade — chase the chains to a fixpoint so they still resolve
    for _ in range(3):
        changed = False
        for s in scans:
            for name, target in s.from_imports.items():
                value = global_consts.get(target)
                key = f"{s.mod}.{name}"
                if value is not None and key not in global_consts:
                    global_consts[key] = value
                    changed = True
        if not changed:
            break

    c = Contracts()

    # producers: marker + route roots, then keys over the call graph
    fn_nodes: Dict[str, ast.AST] = {}
    for s in scans:
        for qual, _cls, fn in s.fns:
            fn_nodes[qual] = fn
    roots: List[ProducerRoot] = []
    for s in scans:
        roots.extend(_marker_roots(s))
        roots.extend(_route_registrations(s))
    analysis = get_analysis(modules, ctx)
    for root in roots:
        c.producers.setdefault(root.endpoint, []).append(root)
        keys = c.keys.setdefault(root.endpoint, set())
        stack, visited = [root.qual], {root.qual}
        while stack:
            qual = stack.pop()
            node = fn_nodes.get(qual)
            if node is not None:
                keys |= _produced_keys(node)
            for callee, _line in analysis.edges.get(qual, ()):
                if callee not in visited:
                    visited.add(callee)
                    stack.append(callee)

    for s in scans:
        consumer = _ConsumerScan(s)
        consumer.run()
        c.reads.extend(consumer.reads)
        _scan_headers(s, global_consts, c.headers)
        _scan_knobs(s, global_consts, c.knob_reads)
    return c


def _default_json(value: object) -> object:
    if value is NO_DEFAULT:
        return None
    if value is DYNAMIC_DEFAULT:
        return "<dynamic>"
    return value


def contracts_inventory(modules: Sequence[ModuleInfo],
                        ctx: LintContext) -> dict:
    """The ``pio lint --dump-contracts`` payload: endpoints with their
    producer roots / produced keys / consumer reads, header flows, and
    the knob inventory joined against the canonical registry."""
    c = get_contracts(modules, ctx)
    endpoints = {}
    for ep in sorted(set(c.producers) | {r.endpoint for r in c.reads}):
        endpoints[ep] = {
            "producers": [
                {"function": p.qual, "file": p.path, "line": p.line}
                for p in sorted(c.producers.get(ep, ()),
                                key=lambda p: (p.path, p.line))
            ],
            "keys": sorted(c.keys.get(ep, ())),
            "consumers": [
                {"key": r.key, "file": r.path, "line": r.line}
                for r in sorted((r for r in c.reads if r.endpoint == ep),
                                key=lambda r: (r.path, r.line, r.key))
            ],
        }
    headers: Dict[str, dict] = {}
    for h in c.headers:
        entry = headers.setdefault(
            h.header, {"canonical": h.canonical, "produced": [],
                       "consumed": [], "declared": []})
        bucket = {"write": "produced", "read": "consumed",
                  "declare": "declared"}[h.role]
        entry[bucket].append({"file": h.path, "line": h.line})
    knobs: Dict[str, dict] = {}
    registry = ctx.knob_registry
    for site in c.knob_reads:
        entry = knobs.setdefault(site.name, {"sites": []})
        entry["sites"].append({
            "file": site.path, "line": site.line, "via": site.via,
            "kind": site.kind, "default": _default_json(site.default),
        })
    for name, knob in registry.items():
        entry = knobs.setdefault(name, {"sites": []})
        entry.update({
            "kind": knob.kind, "default": knob.default,
            "positive": knob.positive, "doc": knob.doc,
        })
    for entry in knobs.values():
        entry["sites"].sort(key=lambda s: (s["file"], s["line"]))
    return {
        "endpoints": endpoints,
        "headers": {k: headers[k] for k in sorted(headers)},
        "knobs": {k: knobs[k] for k in sorted(knobs)},
    }
