"""Convention rules: metric naming/catalog agreement, failpoint
uniqueness + namespaces, hardened env parsing, the one-clock rule, and
the span-name convention.

These encode project conventions that no general-purpose linter knows:

* every registered metric is ``pio_tpu_*``, counters end ``_total``,
  and the name appears in the catalog in ``docs/observability.md``;
* every ``failpoint("…")`` call-site name is unique and lives in a
  documented namespace (the same inventory backs
  ``pio lint --dump-failpoints``);
* numeric env knobs go through ``pio_tpu.utils.envutil`` (warn +
  default on garbage) instead of ``float(os.environ.get(...))``;
* durations are measured with ``pio_tpu.obs.monotonic_s`` — raw
  ``time.time()`` / ``time.monotonic()`` calls are flagged (suppress
  the rare true wall-clock use, e.g. an HTTP Date header);
* trace span/stage names are dot-scoped ``stage`` / ``stage.substage``
  atoms of ``[a-z0-9_]`` — the /debug/hotpath.json budget math keys on
  exactly this shape (top-level stages tile; dotted substages nest).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Tuple

from pio_tpu.analysis.core import (
    Finding,
    LintContext,
    ModuleInfo,
    ProjectRule,
    Rule,
    register,
)
from pio_tpu.analysis.locks import unparse

# ---------------------------------------------------------------------------
# rule: metric naming + catalog agreement

_METRIC_METHODS = ("counter", "gauge", "histogram")
_METRIC_NAME_RE = re.compile(r"^pio_tpu_[a-z0-9_]+$")


@register
class MetricNameRule(Rule):
    id = "metric-name"
    family = "convention"
    skip_tests = True
    description = (
        "Registered metric names must match pio_tpu_[a-z0-9_]+, "
        "counters must end _total (gauges/histograms must not), and "
        "the name must appear in the docs/observability.md catalog."
    )

    def check(self, module: ModuleInfo, ctx: LintContext) -> Iterable[Finding]:
        catalog = ctx.metric_catalog
        kinds = ctx.metric_catalog_kinds
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_METHODS
                    and len(node.args) >= 2):
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue  # dynamic names are out of scope
            name = first.value
            kind = node.func.attr
            msg = self._bad(name, kind, catalog, kinds)
            if msg:
                yield Finding(self.id, module.display, node.lineno,
                              node.col_offset, msg)

    @staticmethod
    def _bad(name: str, kind: str, catalog,
             kinds: Optional[Dict[str, str]] = None) -> Optional[str]:
        if not _METRIC_NAME_RE.match(name):
            return (f"metric `{name}` must match pio_tpu_[a-z0-9_]+ "
                    f"(project namespace prefix)")
        if kind == "counter" and not name.endswith("_total"):
            return f"counter `{name}` must end with `_total`"
        if kind != "counter" and name.endswith("_total"):
            return (f"{kind} `{name}` must not end with `_total` "
                    f"(reserved for counters)")
        if catalog is not None and name not in catalog:
            return (f"metric `{name}` is not in the docs/observability.md "
                    f"catalog; add a row (or fix the name)")
        # kind agreement with the catalog's Type column: a name whose row
        # documents a different type is a doc/code drift bug (names only
        # mentioned in prose, with no table row, are skipped)
        if kinds is not None:
            doc_kind = kinds.get(name)
            if doc_kind is not None and doc_kind != kind:
                return (f"{kind} `{name}` is documented as `{doc_kind}` in "
                        f"the docs/observability.md catalog; fix the row "
                        f"or the registration")
        return None


# ---------------------------------------------------------------------------
# rule: failpoint names — unique, namespaced; powers --dump-failpoints

#: documented failpoint namespaces (see docs/engine-development.md);
#: a call-site name must start with one of these prefixes
FAILPOINT_NAMESPACES = (
    "eventlog.",
    "storage.",
    "groupcommit.",
    "scorer.",
    # device-resident serving sub-namespaces (subsumed by "scorer." but
    # listed so --dump-failpoints readers see them as first-class)
    "scorer.h2d.",
    "scorer.donate.",
    "worker.",
    "batchlane.",
    # partitioned event log + its replication protocol (ISSUE 9)
    "partlog.",
    "repl.",
    # mesh-sharded placement + shard-manifest reassembly (ISSUE 10)
    "shard.",
    # streamed training feed executor (parallel/stream.py, ISSUE 14)
    "stream.",
    # training telemetry plane (obs/trainwatch.py, ISSUE 16)
    "trainwatch.",
    # device telemetry plane (obs/devicewatch.py, ISSUE 17)
    "devicewatch.",
    # serving fabric front tier (pio_tpu/router/, ISSUE 18)
    "router.",
    # progressive-delivery rollout controller (router/rollout.py,
    # ISSUE 19)
    "rollout.",
)


def _failpoint_name(call: ast.Call) -> Optional[Tuple[str, bool]]:
    """``failpoint(...)`` first arg → (name_or_static_prefix, dynamic)."""
    fn = call.func
    fname = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    if fname != "failpoint" or not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, False
    if isinstance(arg, ast.JoinedStr):
        prefix = ""
        for part in arg.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                prefix += part.value
            else:
                break
        return prefix, True
    return None


def failpoint_inventory(modules: List[ModuleInfo]) -> List[dict]:
    """Machine-readable inventory of every failpoint call site in
    non-test modules: ``{point, dynamic, file, line}`` sorted by name.
    Dynamic (f-string) sites report their static prefix."""
    out: List[dict] = []
    for m in modules:
        if m.is_test:
            continue
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            named = _failpoint_name(node)
            if named is None:
                continue
            point, dynamic = named
            out.append({
                "point": point,
                "dynamic": dynamic,
                "file": m.display,
                "line": node.lineno,
            })
    out.sort(key=lambda d: (d["point"], d["file"], d["line"]))
    return out


@register
class FailpointNameRule(ProjectRule):
    id = "failpoint-name"
    family = "convention"
    skip_tests = True
    description = (
        "failpoint() call-site names must be globally unique and start "
        "with a documented namespace (eventlog./storage./groupcommit./"
        "scorer./worker.); chaos specs target points by name, so a "
        "duplicate makes two distinct sites indistinguishable."
    )

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> Iterable[Finding]:
        inventory = failpoint_inventory(modules)
        by_name: Dict[str, List[dict]] = {}
        for entry in inventory:
            ns_ok = any(entry["point"].startswith(ns)
                        for ns in FAILPOINT_NAMESPACES)
            if not ns_ok:
                yield Finding(
                    self.id, entry["file"], entry["line"], 0,
                    f"failpoint `{entry['point']}` is outside the "
                    f"documented namespaces "
                    f"({', '.join(FAILPOINT_NAMESPACES)})",
                )
            if not entry["dynamic"]:
                by_name.setdefault(entry["point"], []).append(entry)
        for name, sites in sorted(by_name.items()):
            if len(sites) < 2:
                continue
            first = sites[0]
            for s in sites[1:]:
                yield Finding(
                    self.id, s["file"], s["line"], 0,
                    f"failpoint `{name}` duplicates "
                    f"{first['file']}:{first['line']}; chaos specs can't "
                    f"target one site — rename (e.g. `{name}.<variant>`)",
                )


# ---------------------------------------------------------------------------
# rule: hardened env parsing

@register
class EnvHardeningRule(Rule):
    id = "env-hardening"
    family = "convention"
    skip_tests = True
    description = (
        "int()/float() directly over os.environ reads crashes the "
        "process on a garbled knob; use pio_tpu.utils.envutil.env_int/"
        "env_float (warn + default on garbage)."
    )

    def check(self, module: ModuleInfo, ctx: LintContext) -> Iterable[Finding]:
        if module.module_name == "pio_tpu.utils.envutil":
            return  # the helpers themselves
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in ("int", "float")
                    and node.args):
                continue
            inner = node.args[0]
            if self._is_environ_read(inner):
                yield Finding(
                    self.id, module.display, node.lineno, node.col_offset,
                    f"`{node.func.id}({unparse(inner)})` raises on a "
                    f"garbled env value; use pio_tpu.utils.envutil."
                    f"env_{node.func.id}(name, default) instead",
                )

    @staticmethod
    def _is_environ_read(node: ast.expr) -> bool:
        # os.environ.get(...) / os.environ[...] / environ.get(...)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr != "get":
                return False
            node = node.func.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        else:
            return False
        text = unparse(node)
        return text in ("os.environ", "environ")


# ---------------------------------------------------------------------------
# rule: one duration clock

@register
class WallclockDurationRule(Rule):
    id = "wallclock-duration"
    family = "convention"
    description = (
        "Durations are measured with pio_tpu.obs.monotonic_s — the one "
        "project clock (time.perf_counter). time.time() jumps with NTP "
        "and time.monotonic() forks the clock domain; suppress only "
        "true wall-clock uses (Date headers, log timestamps)."
    )

    def check(self, module: ModuleInfo, ctx: LintContext) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("time", "monotonic")
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "time"):
                continue
            yield Finding(
                self.id, module.display, node.lineno, node.col_offset,
                f"`time.{node.func.attr}()`: use pio_tpu.obs.monotonic_s "
                f"for durations (suppress if this is a true wall-clock "
                f"read)",
            )


# ---------------------------------------------------------------------------
# rule: span-name convention

_SPAN_NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)*$")
#: span-recording entry points whose first positional arg is a name
_SPAN_METHODS = ("span", "add_span", "add_active_span")


@register
class SpanNameRule(Rule):
    id = "span-name"
    family = "convention"
    skip_tests = True
    description = (
        "Trace span/stage names must be dot-scoped [a-z0-9_] atoms "
        "(`stage` or `stage.substage`) — /debug/hotpath.json budget "
        "math treats undotted names as tiling top-level stages and "
        "dotted ones as nested substages, so a stray name silently "
        "corrupts the attribution sums. Checked at .span()/.add_span()/"
        "add_active_span() literal call sites and *_STAGES/*_SUBSTAGES "
        "tuple declarations."
    )

    def check(self, module: ModuleInfo, ctx: LintContext) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                fname = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                if fname not in _SPAN_METHODS or not node.args:
                    continue
                arg = node.args[0]
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and not _SPAN_NAME_RE.match(arg.value)):
                    yield Finding(
                        self.id, module.display, node.lineno,
                        node.col_offset,
                        f"span name `{arg.value}` breaks the "
                        f"`stage.substage` convention "
                        f"([a-z0-9_] atoms joined by dots)",
                    )
            elif isinstance(node, ast.Assign):
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                if not any(n.endswith(("_STAGES", "_SUBSTAGES"))
                           for n in names):
                    continue
                if not isinstance(node.value, ast.Tuple):
                    continue
                for elt in node.value.elts:
                    if (isinstance(elt, ast.Constant)
                            and isinstance(elt.value, str)
                            and not _SPAN_NAME_RE.match(elt.value)):
                        yield Finding(
                            self.id, module.display, elt.lineno,
                            elt.col_offset,
                            f"declared stage `{elt.value}` breaks the "
                            f"`stage.substage` convention "
                            f"([a-z0-9_] atoms joined by dots)",
                        )


# ---------------------------------------------------------------------------
# rule: metric catalog drift (fleet/replication families)

#: high-churn metric namespaces whose docs/observability.md rows must
#: have a live registration (or collector emission) in the source set —
#: a row surviving a family rename/removal would document a phantom
_CATALOG_DRIFT_PREFIXES = ("pio_tpu_fleet_", "pio_tpu_repl_",
                           "pio_tpu_train_", "pio_tpu_device_",
                           "pio_tpu_xla_", "pio_tpu_router_",
                           "pio_tpu_rollout_")

_CATALOG_ROW_RE = re.compile(r"^\|\s*`(pio_tpu_[a-z0-9_]+)`\s*\|")


@register
class MetricCatalogDriftRule(ProjectRule):
    id = "metric-catalog-drift"
    family = "convention"
    description = (
        "Every documented pio_tpu_fleet_*/pio_tpu_repl_*/pio_tpu_train_* "
        "catalog row in "
        "docs/observability.md must correspond to a live registration "
        "or collector emission in the linted sources (the inverse of "
        "metric-name: code->doc there, doc->code here)."
    )

    def check_project(self, modules: List[ModuleInfo],
                      ctx: LintContext) -> Iterable[Finding]:
        # only meaningful against the real tree: fixture subsets (the
        # lint rule tests) and partial runs would see phantom drift
        if not any(m.module_name == "pio_tpu.obs.fleet" for m in modules):
            return
        import os as _os

        doc = _os.path.join(ctx.repo_root, "docs", "observability.md")
        try:
            with open(doc, "r", encoding="utf-8") as fh:
                doc_lines = fh.readlines()
        except OSError:
            return
        emitted = self._emitted_names(modules)
        for lineno, line in enumerate(doc_lines, 1):
            mm = _CATALOG_ROW_RE.match(line.strip())
            if not mm:
                continue
            name = mm.group(1)
            if not name.startswith(_CATALOG_DRIFT_PREFIXES):
                continue
            if name not in emitted:
                yield Finding(
                    self.id, _os.path.join("docs", "observability.md"),
                    lineno, 0,
                    f"catalog row `{name}` has no registration or "
                    f"emission in the linted sources — remove the row "
                    f"or restore the family",
                )

    @staticmethod
    def _emitted_names(modules: List[ModuleInfo]) -> set:
        """Metric names the code can actually expose: first args of
        counter/gauge/histogram registrations plus any pio_tpu_* token
        inside a string literal (collector-emitted families render
        their exposition lines from literals)."""
        out: set = set()
        for m in modules:
            for node in ast.walk(m.tree):
                if not (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)):
                    continue
                out.update(re.findall(
                    r"(pio_tpu_[a-z0-9_]+)", node.value
                ))
        return out
