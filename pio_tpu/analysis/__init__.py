"""Project-native static analysis (``pio lint``) + runtime sync debugging.

The serving stack rests on ~20 lock/condition-variable-bearing modules
and on conventions — metric naming, failpoint namespaces, hardened env
parsing, monotonic-clock timing, admit/breaker release-in-finally —
that no general-purpose linter knows about. This package encodes them:

* :mod:`pio_tpu.analysis.core` — AST visitor framework: rule registry,
  per-line ``# pio: disable=<rule>`` suppressions, ``run_lint``.
* :mod:`pio_tpu.analysis.rules_concurrency` — blocking call under a
  held lock, ``Condition.wait`` outside a ``while`` predicate loop,
  ``notify`` without the CV's lock, admission/breaker handles that
  escape their ``finally``.
* :mod:`pio_tpu.analysis.lockgraph` — statically-built cross-module
  lock-acquisition graph with cycle (potential-deadlock) reporting.
* :mod:`pio_tpu.analysis.rules_convention` — metric catalog/naming,
  failpoint uniqueness + namespaces, env hardening, wall-clock misuse.
* :mod:`pio_tpu.analysis.runtime` — debug-armed
  (``PIO_TPU_DEBUG_SYNC=1``) instrumented Lock/RLock/Condition that
  record per-thread acquisition edges and raise/log on lock-order
  inversion at run time.

CLI: ``pio lint [paths] [--json] [--dump-failpoints] [--list-rules]``.
"""

from pio_tpu.analysis.core import (  # noqa: F401
    Finding,
    all_rules,
    run_lint,
)
from pio_tpu.analysis.runtime import (  # noqa: F401
    LockOrderInversion,
    make_condition,
    make_lock,
    make_rlock,
    sync_debugger,
)

__all__ = [
    "Finding",
    "all_rules",
    "run_lint",
    "LockOrderInversion",
    "make_lock",
    "make_rlock",
    "make_condition",
    "sync_debugger",
]
