"""Runtime lock-order detection: debug-armed instrumented sync
primitives, the dynamic twin of the static ``lock-order-cycle`` rule.

The serving stack creates its locks through the factories here::

    self._qlock = make_lock("groupcommit.qlock")
    self._cond = make_condition("qos.limiter")

Disarmed (the default) the factories return plain ``threading``
primitives — zero overhead, zero behaviour change. With
``PIO_TPU_DEBUG_SYNC=1`` (or ``raise``) set **at creation time** they
return instrumented wrappers that

* keep a per-thread stack of currently-held locks,
* record every (held -> newly-acquired) edge into a process-global
  order graph, and
* on an acquisition that would close a cycle in that graph (i.e. some
  other code path takes these locks in the opposite order), log the
  inversion with both hold sites and raise :class:`LockOrderInversion`.

``PIO_TPU_DEBUG_SYNC=log`` records + logs but does not raise (for
soaking a live system). The detector is deliberately name-annotated:
inversions print ``groupcommit.qlock -> qos.limiter`` rather than
``<locked _thread.lock object>``.

Re-entrant acquisition of the *same* instance (RLock, Condition re-use)
records nothing; ``Condition.wait`` releases through the wrapper, so
the held-stack stays truthful across waits.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from typing import Dict, List, Optional, Set, Tuple

from pio_tpu.utils import knobs

ENV_VAR = "PIO_TPU_DEBUG_SYNC"

log = logging.getLogger("pio_tpu.analysis.sync")


class LockOrderInversion(RuntimeError):
    """Acquiring this lock here contradicts an order observed earlier."""


class SyncDebugger:
    """Process-global acquisition-order graph + per-thread held stacks."""

    def __init__(self) -> None:
        self._graph_lock = threading.Lock()
        #: edge a -> {b: (thread name, b acquired while a held)}
        self._edges: Dict[int, Dict[int, str]] = {}
        self._names: Dict[int, str] = {}
        self._tls = threading.local()
        self._inversions: List[str] = []

    # -- per-thread held stack ---------------------------------------------
    def _held(self) -> List[int]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
        return held

    # -- events from the wrappers ------------------------------------------
    def register(self, lock: "_DebugBase") -> None:
        """Track the lock's name and prune its graph node when the lock
        is garbage-collected — ``id()`` values get reused, and a fresh
        lock aliasing a dead one's id would inherit its stale edges
        (phantom inversions)."""
        lid = id(lock)
        with self._graph_lock:
            self._names[lid] = lock.name
        weakref.finalize(lock, self._forget, lid)

    def _forget(self, lid: int) -> None:
        with self._graph_lock:
            self._edges.pop(lid, None)
            for nbrs in self._edges.values():
                nbrs.pop(lid, None)
            self._names.pop(lid, None)

    def on_acquired(self, lock: "_DebugBase") -> Optional[str]:
        """Record the acquisition; returns the inversion description if
        this acquisition contradicts a previously-observed order (the
        wrapper decides whether to raise)."""
        held = self._held()
        lid = id(lock)
        self._names[lid] = lock.name
        if lid in held:          # re-entrant: no new ordering information
            held.append(lid)
            return None
        inversion = None
        with self._graph_lock:
            for h in held:
                if h == lid:
                    continue
                # would edge (h -> lid) close a cycle? i.e. lid already
                # orders before h somewhere else
                if self._reaches(lid, h):
                    inversion = (
                        f"lock-order inversion: acquiring "
                        f"`{self._names[lid]}` while holding "
                        f"`{self._names[h]}`, but the opposite order "
                        f"`{self._names[lid]}` -> `{self._names[h]}` was "
                        f"observed earlier ({self._edges[lid].get(h, '?')})"
                    )
                self._edges.setdefault(h, {}).setdefault(
                    lid, threading.current_thread().name)
            if inversion:
                self._inversions.append(inversion)
        held.append(lid)
        if inversion:
            log.warning("%s", inversion)
        return inversion

    def on_released(self, lock: "_DebugBase") -> None:
        held = self._held()
        lid = id(lock)
        # release in LIFO discipline is the norm; tolerate out-of-order
        # release by removing the most recent matching entry
        for i in range(len(held) - 1, -1, -1):
            if held[i] == lid:
                del held[i]
                break

    def _reaches(self, src: int, dst: int) -> bool:
        """DFS: is there a path src -> ... -> dst in the order graph?"""
        seen: Set[int] = set()
        stack = [src]
        while stack:
            node = stack.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            stack.extend(self._edges.get(node, ()))
        return False

    # -- inspection / test hooks -------------------------------------------
    def inversions(self) -> List[str]:
        with self._graph_lock:
            return list(self._inversions)

    def edges(self) -> List[Tuple[str, str]]:
        with self._graph_lock:
            return sorted(
                (self._names.get(a, "?"), self._names.get(b, "?"))
                for a, nbrs in self._edges.items() for b in nbrs
            )

    def reset(self) -> None:
        with self._graph_lock:
            self._edges.clear()
            self._names.clear()
            self._inversions.clear()


_DEBUGGER = SyncDebugger()


def sync_debugger() -> SyncDebugger:
    """The process-global detector (test/inspection surface)."""
    return _DEBUGGER


def _mode() -> str:
    return knobs.knob_str(ENV_VAR).strip().lower()


def _armed() -> bool:
    return _mode() not in ("", "0", "off")


#: lock waits shorter than this never become spans — an uncontended
#: acquire costs ~1 µs and would bury real stages in lock.* noise.
LOCK_SPAN_MIN_S = 100e-6


def _report_lock_wait(name: str, wait_s: float) -> None:
    """Attach a ``lock.<name>`` span to the active request trace (armed
    runs only — disarmed factories hand out plain primitives, so this
    costs nothing in production). Lazy import: analysis must stay
    importable without the obs stack."""
    try:
        from pio_tpu.obs.tracing import add_active_span
    except Exception:
        return
    add_active_span(f"lock.{name}", wait_s)


class _DebugBase:
    """Common acquire/release bookkeeping over an inner primitive."""

    def __init__(self, name: str, inner) -> None:
        self.name = name
        self._inner = inner
        _DEBUGGER.register(self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t_req = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            wait_s = time.perf_counter() - t_req
            if wait_s >= LOCK_SPAN_MIN_S:
                _report_lock_wait(self.name, wait_s)
            inversion = _DEBUGGER.on_acquired(self)
            if inversion is not None and _mode() != "log":
                # back out so the raising thread doesn't strand the lock
                _DEBUGGER.on_released(self)
                self._inner.release()
                raise LockOrderInversion(inversion)
        return ok

    def release(self) -> None:
        self._inner.release()
        _DEBUGGER.on_released(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class DebugLock(_DebugBase):
    def __init__(self, name: str):
        super().__init__(name, threading.Lock())

    def locked(self) -> bool:
        return self._inner.locked()


class DebugRLock(_DebugBase):
    def __init__(self, name: str):
        super().__init__(name, threading.RLock())

    # threading.Condition probes these when handed an RLock-like object
    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def make_lock(name: str) -> "threading.Lock | DebugLock":
    """A mutex named for diagnostics; plain ``threading.Lock`` unless
    ``PIO_TPU_DEBUG_SYNC`` is armed at creation time."""
    return DebugLock(name) if _armed() else threading.Lock()


def make_rlock(name: str) -> "threading.RLock | DebugRLock":
    return DebugRLock(name) if _armed() else threading.RLock()


def make_condition(name: str,
                   lock: Optional[object] = None) -> threading.Condition:
    """A condition variable whose underlying mutex participates in
    lock-order detection (``Condition`` routes every acquire/release —
    including the release inside ``wait()`` — through the lock object
    it is given)."""
    if lock is not None:
        return threading.Condition(lock)  # caller supplied (maybe debug)
    if _armed():
        return threading.Condition(DebugLock(name))
    return threading.Condition()
