"""Mesh / sharding / collective helpers — the Spark-substrate replacement.

Where the reference scales via Spark RDD partitioning + shuffle +
treeAggregate (SURVEY.md §2.6), this package provides the TPU-native
vocabulary: device meshes (dp/tp/sp/ep/pp axes), named shardings, ring
attention for sequence parallelism, pipeline scheduling, and multi-host
process-group bring-up over ICI/DCN.

Ring-attention/pipeline symbols are lazily re-exported: those modules import
jax at module level, and eagerly loading them here would make every consumer
of :mod:`pio_tpu.parallel` (controller, storage, the event server) pay the
multi-second jax import at startup.
"""

from pio_tpu.parallel.context import ComputeContext, default_mesh
from pio_tpu.parallel.distributed import maybe_initialize
from pio_tpu.parallel.mesh import AXIS_ORDER, MeshSpec, build_mesh, mesh_axis_size
from pio_tpu.parallel.partition import (
    DeviceBudgetExceeded,
    make_shard_and_gather_fns,
    match_partition_rules,
    register_partition_rules,
    rules_for,
    shard_params,
)

_LAZY = {
    "pipeline_apply": "pio_tpu.parallel.pipeline",
    "stage_slice": "pio_tpu.parallel.pipeline",
    "ring_attention": "pio_tpu.parallel.ring",
    "ring_attention_sharded": "pio_tpu.parallel.ring",
    "ulysses_attention": "pio_tpu.parallel.ulysses",
    "ulysses_attention_sharded": "pio_tpu.parallel.ulysses",
}

__all__ = [
    "AXIS_ORDER",
    "ComputeContext",
    "DeviceBudgetExceeded",
    "MeshSpec",
    "build_mesh",
    "default_mesh",
    "make_shard_and_gather_fns",
    "match_partition_rules",
    "maybe_initialize",
    "mesh_axis_size",
    "register_partition_rules",
    "rules_for",
    "shard_params",
    *sorted(_LAZY),
]


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module = importlib.import_module(_LAZY[name])
        # Rebind every lazy symbol of this module into the package namespace:
        # the import above also set the *submodule itself* as a package
        # attribute (e.g. ``ring_attention`` the module shadowing
        # ``ring_attention`` the function), and plain attribute hits bypass
        # this hook.
        for sym, mod_name in _LAZY.items():
            if mod_name == _LAZY[name]:
                globals()[sym] = getattr(module, sym)
        return globals()[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
