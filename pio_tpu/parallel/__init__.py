"""Mesh / sharding / collective helpers — the Spark-substrate replacement.

Where the reference scales via Spark RDD partitioning + shuffle +
treeAggregate (SURVEY.md §2.6), this package provides the TPU-native
vocabulary: device meshes, named shardings, and pjit-visible collectives.
"""

from pio_tpu.parallel.context import ComputeContext, default_mesh

__all__ = ["ComputeContext", "default_mesh"]
