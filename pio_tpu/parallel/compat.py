"""Version-bridging imports for jax APIs that moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the jax
top level, renaming its replication-check kwarg ``check_rep`` →
``check_vma`` on the way. Call sites import :func:`shard_map` from here
and use the modern spelling; on an older jax the kwarg is translated.

This module imports jax at module level — import it lazily (inside the
compiled-path functions), like the call sites already import jax itself,
so storage/server consumers of :mod:`pio_tpu.parallel` don't pay the
jax import at startup.
"""

from __future__ import annotations

import functools

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    @functools.wraps(_legacy_shard_map)
    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _legacy_shard_map(f, *args, **kwargs)


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:

    def axis_size(axis_name):
        """Classic spelling: ``psum(1, axis)`` constant-folds to the
        static group size under pmap/shard_map."""
        return jax.lax.psum(1, axis_name)


try:
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
except ImportError:  # pre-jax.sharding releases
    try:
        from jax.experimental.sharding import (  # noqa: F401
            NamedSharding,
        )
    except ImportError:
        from jax.experimental.pjit import (  # noqa: F401
            NamedSharding,
        )
    from jax.experimental import PartitionSpec  # noqa: F401
    from jax.experimental.maps import Mesh  # noqa: F401


if hasattr(jax.lax, "pcast"):
    pcast = jax.lax.pcast
else:

    def pcast(x, axis_name, to):
        """Pre-varying-type jax (the ``check_rep`` era) tracks
        replication dynamically — there is no type to cast."""
        return x
