"""ComputeContext — the TPU-native replacement for Spark's SparkContext.

Where the reference threads a ``SparkContext`` through every P-component
(``PDataSource.readTraining(sc)`` etc., ``core/.../controller/*.scala``,
UNVERIFIED paths; see SURVEY.md), this framework threads a
:class:`ComputeContext`: a ``jax.sharding.Mesh`` over the available devices
plus RNG and placement helpers. Components use it to shard host data onto the
mesh and to run pjit-compiled programs; XLA collectives over ICI/DCN do what
Spark shuffles and treeAggregate did.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import numpy as np


def default_mesh(axis_names: Tuple[str, ...] = ("data",), devices=None):
    """Build a mesh over all devices (1-D ``data`` axis by default).

    Multi-axis: pass e.g. ``("data", "model")`` and a device array shaped
    accordingly, or let this helper fold all devices into the first axis.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devs = np.asarray(devices)
    if len(axis_names) == 1:
        devs = devs.reshape(-1)
    elif devs.ndim != len(axis_names):
        # fold everything into the leading axis, trailing axes size 1
        devs = devs.reshape((-1,) + (1,) * (len(axis_names) - 1))
    return Mesh(devs, axis_names)


@dataclasses.dataclass
class ComputeContext:
    """Carries the device mesh + RNG through DASE components.

    Attributes:
        mesh: the device mesh; None means "single default device".
        seed: base RNG seed for this run.
        batch_axis: mesh axis name training data shards over.
        model_axis: mesh axis name model tensors may shard over (tensor
            parallelism); usually size 1 in v1 configs but reserved so
            two-tower/MLP engines can scale (SURVEY.md §2.6).
    """

    mesh: Optional[object] = None
    seed: int = 0
    batch_axis: str = "data"
    model_axis: str = "model"
    #: checkpointing (WorkflowParams.checkpoint_every > 0): run_train sets
    #: ``checkpoint_base`` (a directory) + ``checkpoint_every``;
    #: Engine.train derives a per-algorithm CheckpointManager into
    #: ``checkpoint`` so concurrent algorithms never share snapshot state
    checkpoint: Optional[object] = None
    checkpoint_base: Optional[str] = None
    checkpoint_every: int = 0

    @staticmethod
    def create(seed: int = 0, axis_names: Tuple[str, ...] = ("data",)):
        return ComputeContext(mesh=default_mesh(axis_names), seed=seed)

    @staticmethod
    def local(seed: int = 0):
        """No mesh — single-device jit path (reference L* components)."""
        return ComputeContext(mesh=None, seed=seed)

    # -- helpers ------------------------------------------------------------
    def rng(self):
        import jax

        return jax.random.PRNGKey(self.seed)

    @property
    def num_devices(self) -> int:
        if self.mesh is None:
            return 1
        return int(np.prod(list(self.mesh.shape.values())))

    def batch_sharding(self):
        """NamedSharding that shards dim 0 over the batch axis."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, PartitionSpec(self.batch_axis))

    def replicated_sharding(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, PartitionSpec())

    def shard_batch(self, arrays: Dict[str, np.ndarray], pad_value=0):
        """Pad dim 0 to a mesh multiple and place sharded. Adds ``mask``.

        The host→device materialization step: the analog of the reference
        handing an RDD to executors, minus the shuffle.
        """
        import jax
        import jax.numpy as jnp

        n = len(next(iter(arrays.values())))
        for k, v in arrays.items():
            if len(v) != n:
                raise ValueError(
                    f"all arrays must share dim-0 length; {k!r} has "
                    f"{len(v)} != {n}"
                )
        if self.mesh is None:
            out = {k: jnp.asarray(v) for k, v in arrays.items()}
            out["mask"] = jnp.ones((n,), dtype=jnp.float32)
            return out
        shards = self.mesh.shape[self.batch_axis]
        padded = -(-n // shards) * shards
        sharding = self.batch_sharding()
        out = {}
        for k, v in arrays.items():
            v = np.asarray(v)
            if len(v) != n:
                raise ValueError("all arrays must share dim-0 length")
            pv = np.full((padded,) + v.shape[1:], pad_value, dtype=v.dtype)
            pv[:n] = v
            out[k] = jax.device_put(pv, sharding)
        mask = np.zeros((padded,), dtype=np.float32)
        mask[:n] = 1.0
        out["mask"] = jax.device_put(mask, sharding)
        return out

    def shard_params(self, params, rules=None, template=None,
                     on_unmatched="replicate"):
        """Place a parameter pytree on the mesh under partition rules.

        ``rules`` is an ordered ``(path_regex, PartitionSpec)`` list;
        pass ``template`` instead to use the registered rule set
        (``"als"`` / ``"two_tower"`` / ``"seqrec"``). Returns
        ``(sharded_params, specs)``; with no mesh the params come back
        as single-device jnp arrays.
        """
        from pio_tpu.parallel import partition as _partition

        if rules is None:
            rules = _partition.rules_for(template) if template else []
        return _partition.shard_params(
            self.mesh, params, rules, on_unmatched=on_unmatched
        )

    def replicate(self, array):
        """Fully replicate an array over the mesh (broadcast analog)."""
        import jax

        if self.mesh is None:
            import jax.numpy as jnp

            return jnp.asarray(array)
        return jax.device_put(np.asarray(array), self.replicated_sharding())
