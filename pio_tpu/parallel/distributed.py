"""Multi-host process-group bring-up — the NCCL/MPI-shaped hole, TPU-way.

The reference moves inter-node bytes through Spark shuffle/broadcast over
netty plus storage-client RPC (SURVEY.md §2.6); its "process group" is the
Spark driver↔executor registration protocol. The TPU rebuild has no
driver/worker split: every host runs the same program, and
``jax.distributed.initialize`` forms the group (GCS/coordinator handshake),
after which XLA collectives ride ICI within a slice and DCN across slices.

This module is the thin, env-driven wrapper the CLI and workflow call so a
multi-host ``pio train`` is: run the same command on every host.

Env contract (all optional — absent means single-host):

- ``PIO_TPU_COORDINATOR``    — ``host:port`` of process 0.
- ``PIO_TPU_NUM_PROCESSES``  — world size.
- ``PIO_TPU_PROCESS_ID``     — this host's rank.

On TPU pods with a metadata server, plain ``jax.distributed.initialize()``
autodetects all three; the env vars are for CPU fleets and tests.
"""

from __future__ import annotations

import os
from typing import Optional

from pio_tpu.utils import knobs

_initialized = False


def maybe_initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host process group if one is configured.

    Returns True when running multi-host (group joined), False for the
    single-host path. Idempotent. Must run before any other JAX call:
    touching the backend (even ``jax.process_count()``) before
    ``jax.distributed.initialize`` makes the XLA client single-host
    permanently, so this function decides purely from its args/env and only
    then imports jax.
    """
    global _initialized

    if _initialized:
        return True

    coordinator = coordinator or knobs.knob_raw("PIO_TPU_COORDINATOR")
    if coordinator is None:
        # Single host. (On TPU pods with a metadata server, set
        # PIO_TPU_COORDINATOR or call jax.distributed.initialize() yourself
        # before any JAX use.)
        return False
    num_str = knobs.knob_raw("PIO_TPU_NUM_PROCESSES")
    num_processes = num_processes or (int(num_str) if num_str else None)
    pid_str = knobs.knob_raw("PIO_TPU_PROCESS_ID")
    process_id = process_id if process_id is not None else (
        int(pid_str) if pid_str else None
    )

    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # group already formed (operator called initialize directly, or a
        # library did) — idempotency beats strictness here
        if "already" not in str(e).lower():
            raise
    _initialized = True
    return True


def process_index() -> int:
    import jax

    return jax.process_index()


def is_coordinator() -> bool:
    return process_index() == 0


def host_local_to_global(mesh, pspec, host_arrays):
    """Assemble per-host shards into one global sharded array (pytree).

    Each host passes the rows *it* loaded (e.g. its shard of the event
    store); the result is a global ``jax.Array`` laid out per ``pspec`` —
    the multi-host analog of ``ComputeContext.shard_batch``. The reference's
    counterpart is executors scanning their own storage partitions into RDD
    blocks (HBase/JDBC region-aligned scans).
    """
    import jax

    def one(x):
        return jax.make_array_from_process_local_data(
            jax.sharding.NamedSharding(mesh, pspec), x
        )

    return jax.tree.map(one, host_arrays)
