"""Vocab-parallel (ep) addressing over a row-sharded table.

The expert/embedding-parallel pattern shared by the two-tower and
sequence-recommender models: a ``[V, D]`` table shards by rows over the
``model`` mesh axis; lookups mask ids outside the local shard, gather
locally, and ``psum`` the partial rows — no replicated table anywhere.
Call these from inside ``shard_map`` with the *local* table block.
"""

from __future__ import annotations

from typing import Optional


def vocab_parallel_lookup(table, ids, axis: Optional[str]):
    """Row lookup on a vocab-sharded table: ``table[ids]`` assembled by psum.

    Args:
        table: local ``[V_local, D]`` shard (or the full table if axis is
            None).
        ids: integer array of any shape; out-of-range ids yield zero rows.
        axis: mesh axis the vocab rows shard over; None → plain gather.

    Returns ``ids.shape + (D,)`` embedding rows.
    """
    import jax
    import jax.numpy as jnp

    if axis is None:
        return table[ids]
    rows = table.shape[0]
    offset = jax.lax.axis_index(axis) * rows
    local = ids - offset
    hit = (local >= 0) & (local < rows)
    gathered = table[jnp.clip(local, 0, rows - 1)]
    return jax.lax.psum(
        jnp.where(hit[..., None], gathered, 0.0), axis
    )


def vocab_parallel_target_gather(logits_local, targets, axis: Optional[str]):
    """Pick each target's logit from vocab-sharded ``[..., V_local]`` logits.

    The target-column gather of a vocab-parallel cross-entropy: exactly one
    shard holds each target id; the rest contribute zero to the psum.
    """
    import jax
    import jax.numpy as jnp

    if axis is None:
        return jnp.take_along_axis(
            logits_local, targets[..., None], axis=-1
        )[..., 0]
    rows = logits_local.shape[-1]
    offset = jax.lax.axis_index(axis) * rows
    local = targets - offset
    hit = (local >= 0) & (local < rows)
    picked = jnp.take_along_axis(
        logits_local, jnp.clip(local, 0, rows - 1)[..., None], axis=-1
    )[..., 0]
    return jax.lax.psum(jnp.where(hit, picked, 0.0), axis)
