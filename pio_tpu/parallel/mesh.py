"""Multi-axis device meshes — dp / tp(ep) / sp / pp layout for the framework.

The reference's only parallelism axis is Spark RDD partitioning (data
parallelism; SURVEY.md §2.6 — its executors know no tensor/pipeline/sequence
split). The TPU rebuild makes the full mesh vocabulary first-class so model
families beyond MLlib-parity (two-tower retrieval, sequence recommenders)
shard naturally:

- ``data``   — batch dimension (≙ Spark partitions / treeAggregate).
- ``model``  — tensor-parallel weight shards AND expert/vocab-sharded
  embedding tables (EP rides the same axis: experts/vocab rows are laid out
  along ``model`` and addressed with all_to_all / psum).
- ``seq``    — sequence/context parallelism (ring attention,
  pio_tpu/parallel/ring.py).
- ``pipe``   — pipeline stages (pio_tpu/parallel/pipeline.py).

Axis *order* puts ``data`` outermost and ``model`` innermost so that the
highest-traffic collectives (tensor-parallel psum/all_gather, per-layer) ride
contiguous ICI neighbours while low-frequency gradient reductions span the
outer (possibly DCN) dimension — the standard layout recipe.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

#: canonical axis order, outermost → innermost
AXIS_ORDER = ("data", "pipe", "seq", "model")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: axis name → size (-1 = absorb remainder).

    Exactly one axis may be -1; it takes every device the named axes leave
    over. Axes not mentioned get size 1 (so shardings over them are no-ops
    and the same program runs on any mesh).
    """

    data: int = -1
    pipe: int = 1
    seq: int = 1
    model: int = 1

    def sizes(self, n_devices: int) -> Dict[str, int]:
        fixed = {
            name: getattr(self, name)
            for name in AXIS_ORDER
            if getattr(self, name) != -1
        }
        free = [n for n in AXIS_ORDER if getattr(self, n) == -1]
        if len(free) > 1:
            raise ValueError(f"at most one -1 axis, got {free}")
        prod = math.prod(fixed.values())
        if free:
            if n_devices % prod:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            fixed[free[0]] = n_devices // prod
        elif prod != n_devices:
            raise ValueError(
                f"mesh spec {fixed} needs {prod} devices, have {n_devices}"
            )
        return {name: fixed[name] for name in AXIS_ORDER}


def build_mesh(spec: MeshSpec = MeshSpec(), devices=None):
    """Materialize a ``jax.sharding.Mesh`` for the spec.

    Single-host: devices are reshaped in row-major order, which for a TPU
    slice keeps the innermost (``model``) axis on adjacent ICI neighbours.
    Multi-host (``jax.process_count() > 1``): the outermost non-trivial axis
    is laid out across hosts via ``mesh_utils.create_hybrid_device_mesh`` so
    its collectives ride DCN and everything inner stays on ICI.
    """
    import jax
    from jax.sharding import Mesh

    use_default_devices = devices is None
    if use_default_devices:
        devices = jax.devices()
    sizes = spec.sizes(len(devices))
    shape = tuple(sizes[n] for n in AXIS_ORDER)

    if use_default_devices and jax.process_count() > 1:
        from jax.experimental import mesh_utils

        per_host = len(devices) // jax.process_count()
        # split the outermost axes onto DCN until a host's devices are used up
        dcn_shape, ici_shape, budget = [], [], jax.process_count()
        for s in shape:
            g = math.gcd(s, budget)
            dcn_shape.append(g)
            ici_shape.append(s // g)
            budget //= g
        if budget != 1:
            raise ValueError(
                f"mesh {sizes} cannot be split over "
                f"{jax.process_count()} hosts × {per_host} devices"
            )
        arr = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_shape), tuple(dcn_shape), devices=devices
        )
        return Mesh(arr, AXIS_ORDER)

    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


def mesh_axis_size(mesh, axis: str) -> int:
    """Size of a named axis (1 when the mesh lacks it or is None)."""
    if mesh is None:
        return 1
    return int(mesh.shape.get(axis, 1))
