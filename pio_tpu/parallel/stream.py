"""Streamed host→device feed executor — ONE streaming discipline in-tree.

Generalizes the double-buffered shipment loop that ALS grew privately
(``models/als.py _run_streamed``): an epoch is sliced into chunks, each
chunk is encoded on host (quantize/pack/slice), its ``device_put``s are
queued on the transfer stream, and the per-chunk compute program is
dispatched so it waits only on its *own* inputs — chunk k's compute runs
while chunk k+1 is still crossing the link. The same loop now feeds the
two-tower and seqrec trainers (per-step minibatch spans instead of a
staged epoch) and the ALS normal-equation accumulators.

Two scheduling modes:

- **queue-ahead** (``lookahead=0``, the ALS discipline): every chunk's
  ``device_put`` is issued up front — they drain in order on the
  transfer stream — then the chunk programs are chained. Right when all
  chunks together fit on device (ALS retains the wire chunks for its
  finalize program anyway).
- **double-buffered** (``lookahead=k``): at most ``k`` chunks are
  encoded/shipped ahead of the chunk whose compute the host last
  synced, bounding device residency to ~``k+1`` chunks — the training
  feed, where the whole epoch deliberately does NOT fit under
  ``PIO_TPU_DEVICE_BUDGET_BYTES``. The host blocks on chunk
  ``i-lookahead``'s carry before shipping further, which keeps the pipe
  full (the next ``k`` chunks are already queued) without ever staging
  the epoch.

With a ``stats`` dict the phases are *serialized* (encode all → ship
all + block → dispatch all + block) so each is measurable — overlap
off, exactly ALS's profiling contract: ``h2d_s`` (transfer),
``device_s`` (compute), the encode time under ``encode_stat_key``
(ALS maps it onto its ``pack_s``), plus ``h2d_bytes``. Overlap itself
is proven by comparing a profiled run's ``h2d_s + device_s`` against an
overlapped run's wall time — :func:`record_overlap_ratio` computes the
ratio and publishes the gauge. With an active trainwatch recorder
(a real ``pio train``), overlapped runs self-measure: chunk 0 runs
phase-serialized as a probe (extra blocks only — the math stays
bit-exact) and the remaining chunks' wall time yields the ratio, so
``pio_tpu_train_stream_overlap_ratio`` reports from real runs, not
just bench.

Failpoints: ``stream.encode`` / ``stream.put`` / ``stream.dispatch``
fire per chunk per phase (fault-injection surface for the feed loop).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from pio_tpu.obs import REGISTRY
from pio_tpu.utils import knobs

#: host→device bytes shipped by the streamed training feed (all
#: stream_feed callers: two-tower/seqrec batch spans, ALS wire chunks)
_H2D_BYTES = REGISTRY.counter(
    "pio_tpu_train_h2d_bytes_total",
    "Host-to-device bytes shipped by the streamed training feed",
)

#: transfer time hidden behind compute, from the last profiled pair
_OVERLAP = REGISTRY.gauge(
    "pio_tpu_train_stream_overlap_ratio",
    "Fraction of streamed-feed transfer time hidden behind compute "
    "(profiled h2d_s + device_s vs overlapped wall time)",
)


def n_stream_chunks(n_bytes: int, env_var: str, default: str = "8",
                    cap: int = 8) -> int:
    """Chunk count for a streamed host→device shipment: ``ceil(bytes /
    chunk_mb)`` capped at ``cap``; 1 (streaming off) when the env knob
    is ≤ 0. THE sizing rule for every streamed wire (ALS edges, logreg
    features, training batch spans) so the threshold semantics can't
    drift — ``utils.numutil.n_stream_chunks`` delegates here.

    Registered knobs take their default from the canonical registry
    (``pio_tpu.utils.knobs``); ``default`` applies only to scratch env
    names tests invent."""
    mb = knobs.knob_float(env_var, fallback=float(default))
    if mb <= 0:
        return 1
    return int(min(cap, -(-n_bytes // max(1, int(mb * 2 ** 20)))))


def span_bounds(n_batches: int, n_stream: int) -> list:
    """``n_stream`` near-even contiguous span boundaries over an epoch
    of ``n_batches`` batches (``n_stream`` ≤ ``n_batches`` — strictly
    increasing by construction)."""
    n_stream = max(1, min(n_batches, n_stream))
    return [n_batches * c // n_stream for c in range(n_stream + 1)]


def epoch_spans(step0: int, n_steps: int, n_batches: int,
                bounds: Sequence[int]) -> list:
    """Batch spans covering steps ``[step0, step0 + n_steps)`` of a
    wrapped epoch schedule (step ``s`` consumes batch ``s % n_batches``)
    as ``(b0, b1)`` ranges — each a contiguous run of batches inside one
    span of ``bounds``, clipped to the step range per epoch pass. The
    streamed feed replays EXACTLY the staged batch order, which is what
    makes streamed-vs-staged training parity bit-exact."""
    import bisect

    work = []
    s, end = step0, step0 + n_steps
    while s < end:
        base = (s // n_batches) * n_batches
        b0 = s - base
        c = bisect.bisect_right(bounds, b0) - 1
        b1 = min(bounds[c + 1], end - base)
        work.append((b0, b1))
        s = base + b1
    return work


def _tree_nbytes(tree: Any) -> int:
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def stream_feed(
    chunks: Sequence[Any],
    *,
    encode: Callable[[Any], Any],
    dispatch: Callable[[Any, Any, int], Any],
    init_carry: Callable[[], Any],
    put: Optional[Callable[[Any, int], Any]] = None,
    put_extra: Optional[Callable[[], Any]] = None,
    finalize: Optional[Callable[[Any, tuple], Any]] = None,
    lookahead: int = 0,
    stats: Optional[dict] = None,
    encode_stat_key: str = "encode_s",
) -> Any:
    """Run the streamed feed over ``chunks``; returns the final carry
    (or ``finalize``'s result).

    Args:
        chunks: opaque per-chunk descriptors (span bounds, slices, …).
        encode: ``chunk → host pytree`` — host-side slice/quantize/pack.
        dispatch: ``(carry, device_chunk, idx) → carry`` — the chunk's
            compute program; must not block (async dispatch is the
            overlap).
        init_carry: builds the initial carry at dispatch-phase start
            (inside ``device_s`` when profiling — ALS's ``init(seed)``).
        put: ``(host_pytree, idx) → device pytree``; default is a
            tree-mapped ``jax.device_put``. Callers supply sharded puts
            (``NamedSharding`` over batch axes) here — the "per-shard"
            in per-shard streaming.
        put_extra: optional once-per-run extra shipment (ALS's
            counts_u/counts_i), issued after every chunk put so it rides
            the same transfer-stream tail; timed inside ``h2d_s``.
        finalize: ``(carry, device_chunks) → result``. When present the
            device chunks are RETAINED and handed over (ALS re-decodes
            the wire for the item side); when absent each chunk is
            dropped right after its dispatch so streamed epochs never
            accumulate on device.
        lookahead: 0 → queue every put up front; k>0 → double-buffer,
            at most k chunks in flight ahead of synced compute.
        stats: phase-serialized profiling (see module docstring) —
            overlap is OFF while measuring.
        encode_stat_key: stats key the encode time accumulates under.
    """
    import jax

    from pio_tpu.faults import failpoint
    from pio_tpu.obs import devicewatch, monotonic_s, trainwatch

    if put is None:
        def put(host, _idx):
            return jax.tree_util.tree_map(jax.device_put, host)

    def _encode(i):
        failpoint("stream.encode")
        return encode(chunks[i])

    shipped = [0]  # bytes shipped this call (overlap-probe bookkeeping)
    chunk_bytes: dict = {}  # in-flight chunk footprint (device ledger)

    def _put(host, i):
        failpoint("stream.put")
        nbytes = _tree_nbytes(host)
        _H2D_BYTES.inc(nbytes)
        shipped[0] += nbytes
        trainwatch.record_h2d(nbytes)
        chunk_bytes[i] = nbytes
        devicewatch.stream_carry(nbytes)
        if stats is not None:
            stats["h2d_bytes"] = stats.get("h2d_bytes", 0) + nbytes
        return put(host, i)

    def _dispatch(carry, dev, i):
        failpoint("stream.dispatch")
        # compile attribution: a chunk whose leaf shapes are new to the
        # feed's program cache (typically the first chunk and a ragged
        # tail) pays the trace+compile inside this call
        with devicewatch.compile_span(
            "stream_dispatch", key=devicewatch.shape_key(dev)
        ):
            out = dispatch(carry, dev, i)
        if not retain:
            # chunk consumed, device buffers released with the refs
            devicewatch.stream_carry(-chunk_bytes.pop(i, 0))
        return out

    n = len(chunks)
    retain = finalize is not None

    if stats is not None:
        # serialized phases: host encode cost must not pollute the
        # transfer measurement, so every chunk encodes first
        t0 = monotonic_s()
        encoded = [_encode(i) for i in range(n)]
        stats[encode_stat_key] = stats.get(encode_stat_key, 0.0) + (
            monotonic_s() - t0
        )
        t0 = monotonic_s()
        devs = [_put(encoded[i], i) for i in range(n)]
        extra = put_extra() if put_extra is not None else None
        jax.block_until_ready((devs, extra))
        stats["h2d_s"] = stats.get("h2d_s", 0.0) + (monotonic_s() - t0)
        t0 = monotonic_s()
        carry = init_carry()
        for i in range(n):
            carry = _dispatch(carry, devs[i], i)
            if not retain:
                devs[i] = None
        result = finalize(carry, tuple(devs)) if retain else carry
        jax.block_until_ready(result)
        stats["device_s"] = stats.get("device_s", 0.0) + (
            monotonic_s() - t0
        )
        if chunk_bytes:  # retained chunks freed with finalize's result
            devicewatch.stream_carry(-sum(chunk_bytes.values()))
            chunk_bytes.clear()
        return result

    # overlapped: puts drain on the transfer stream while earlier
    # chunks' (async-dispatched) programs compute
    window = n if lookahead <= 0 else lookahead
    devs: dict = {}
    put_idx = 0
    extra_done = put_extra is None
    synced: list = []  # per-chunk carry leaf, for lookahead throttling
    carry = init_carry()
    probe = None
    start = 0
    rec = trainwatch.active_recorder()
    if rec is not None and lookahead > 0 and n >= 3:
        # overlap probe for REAL runs (the ISSUE-14 proof lived only in
        # bench's profiled/overlapped pair): chunk 0 runs phase-
        # serialized — extra blocks only, bit-exact math — to sample its
        # transfer and compute costs; the remaining chunks run
        # overlapped under a wall clock, and the serialized pair scales
        # by shipped bytes to estimate how much transfer hid.
        host0 = _encode(0)
        bytes0 = _tree_nbytes(host0)
        t0 = monotonic_s()
        devs[0] = _put(host0, 0)
        jax.block_until_ready(devs[0])
        h2d_s0 = monotonic_s() - t0
        t0 = monotonic_s()
        carry = _dispatch(carry, devs[0], 0)
        jax.block_until_ready(jax.tree_util.tree_leaves(carry)[:1])
        device_s0 = monotonic_s() - t0
        if not retain:
            del devs[0]
        put_idx = 1
        start = 1
        synced.append(None)  # chunk 0 already synced
        probe = (bytes0, h2d_s0, device_s0, monotonic_s())
    for i in range(start, n):
        while put_idx < min(n, i + window):
            devs[put_idx] = _put(_encode(put_idx), put_idx)
            put_idx += 1
        if put_idx == n and not extra_done:
            put_extra()
            extra_done = True
        carry = _dispatch(carry, devs[i], i)
        if not retain:
            del devs[i]
        if lookahead > 0:
            # bound device residency: before shipping chunk i+window,
            # chunk i-lookahead's compute must be done (its carry is
            # ready). The next `lookahead` chunks are already queued,
            # so the device never starves while the host waits here.
            synced.append(jax.tree_util.tree_leaves(carry)[:1])
            j = i - lookahead
            if j >= 0 and synced[j] is not None:
                jax.block_until_ready(synced[j])
                synced[j] = None
    if not extra_done:
        put_extra()
    if probe is not None:
        jax.block_until_ready(jax.tree_util.tree_leaves(carry)[:1])
        bytes0, h2d_s0, device_s0, t_rest = probe
        wall_rest = monotonic_s() - t_rest
        bytes_rest = shipped[0] - bytes0
        if bytes0 > 0 and bytes_rest > 0:
            scale = bytes_rest / bytes0
            ratio = record_overlap_ratio(
                h2d_s0 * scale, device_s0 * scale, wall_rest
            )
            rec.set_overlap(ratio)
    result = finalize(carry, tuple(devs[i] for i in range(n))) if retain \
        else carry
    if chunk_bytes:  # retained chunks freed with finalize's result
        devicewatch.stream_carry(-sum(chunk_bytes.values()))
        chunk_bytes.clear()
    return result


def record_overlap_ratio(h2d_s: float, device_s: float,
                         wall_s: float) -> float:
    """Overlap achieved by a (profiled, overlapped) run pair: the
    fraction of the smaller phase hidden inside the larger one —
    ``(h2d_s + device_s - wall_s) / min(h2d_s, device_s)`` clamped to
    [0, 1]. Publishes ``pio_tpu_train_stream_overlap_ratio``."""
    lo = min(h2d_s, device_s)
    ratio = 0.0 if lo <= 0 else max(
        0.0, min(1.0, (h2d_s + device_s - wall_s) / lo)
    )
    _OVERLAP.set(ratio)
    return ratio
