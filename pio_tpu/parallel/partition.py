"""Partition-rule registry: parameter-path regexes → ``PartitionSpec``s.

Templates used to hand-write one sharding dict per model
(``two_tower._tower_specs``, ``seqrec.param_specs``); every new tensor
meant another edit in bespoke code, and optimizer state had to be
threaded separately. This module replaces that with the rule pattern
from the exemplars (SNIPPETS.md [3]): an ordered list of
``(path_regex, PartitionSpec)`` pairs matched first-hit against the
``/``-joined tree path of every leaf. Optimizer-state inheritance is
free — ``re.search`` finds ``blocks/wq`` inside ``0/mu/blocks/wq``, and
the scalar guard keeps step counters replicated.

Rules are registered per template (``als`` / ``two_tower`` / ``seqrec``)
so training, persistence and serving all shard from one source of truth:
:meth:`ComputeContext.shard_params` applies them at train/deploy time,
the shard store records them in the shard manifest, and the query server
re-applies them when placing a model onto a serving mesh.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from pio_tpu.utils import knobs

#: Per-device parameter budget (bytes); 0 = unlimited. The OOM guard the
#: multichip proof leans on: set it below total model size and only a
#: sharded placement fits.
DEVICE_BUDGET_ENV = "PIO_TPU_DEVICE_BUDGET_BYTES"


class DeviceBudgetExceeded(RuntimeError):
    """A placement would exceed ``PIO_TPU_DEVICE_BUDGET_BYTES`` per chip."""


def tree_path_name(path: Sequence[Any]) -> str:
    """``/``-joined human name for a jax ``tree_flatten_with_path`` key path.

    ``DictKey('emb')`` → ``emb``, ``SequenceKey(0)`` → ``0``,
    ``GetAttrKey('mu')`` → ``mu``; unknown key types fall back to ``str``.
    """
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:  # pragma: no cover - future key types
            parts.append(str(k).strip("[].'\""))
    return "/".join(parts)


def _is_scalar_leaf(leaf: Any) -> bool:
    return np.ndim(leaf) == 0


def match_partition_rules(
    rules: Iterable[Tuple[str, Any]],
    pytree: Any,
    *,
    on_unmatched: str = "replicate",
):
    """Spec tree for ``pytree``: first rule whose regex ``search``es the
    leaf's ``/``-joined path wins; scalars are always replicated.

    ``on_unmatched``: ``"replicate"`` (default — unmatched leaves get
    ``PartitionSpec()``) or ``"error"`` (raise ``ValueError`` naming the
    leaf, for templates that want every tensor accounted for).
    """
    import jax

    from pio_tpu.parallel.compat import PartitionSpec as P

    rules = list(rules)
    leaves, treedef = jax.tree_util.tree_flatten_with_path(pytree)
    specs = []
    for path, leaf in leaves:
        name = tree_path_name(path)
        if _is_scalar_leaf(leaf):
            specs.append(P())
            continue
        for pat, spec in rules:
            if re.search(pat, name):
                specs.append(spec if isinstance(spec, P) else P(*spec))
                break
        else:
            if on_unmatched == "error":
                raise ValueError(
                    f"no partition rule matches leaf {name!r} "
                    f"(shape {np.shape(leaf)})"
                )
            specs.append(P())
    return jax.tree_util.tree_unflatten(treedef, specs)


def is_partition_spec(x: Any) -> bool:
    from pio_tpu.parallel.compat import PartitionSpec as P

    return isinstance(x, P)


def spec_for_mesh(mesh, spec):
    """Project a spec onto ``mesh``: axis names the mesh doesn't carry
    become ``None`` (replicated on that dim).

    Lets one rule set serve both the full training mesh
    (``data×pipe×seq×model``) and a 1-D serving mesh (``("data",)``)
    without per-consumer rule forks.
    """
    from pio_tpu.parallel.compat import PartitionSpec as P

    axes = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, str):
            return entry if entry in axes else None
        # tuple of axis names on one dim
        kept = tuple(a for a in entry if a in axes)
        return kept if kept else None

    return P(*[keep(e) for e in spec])


def make_shard_and_gather_fns(mesh, specs):
    """Per-leaf ``(shard_fns, gather_fns)`` trees for a spec tree.

    ``shard_fns[leaf](x)`` places ``x`` on ``mesh`` under the leaf's
    spec (projected onto the mesh's axes); ``gather_fns[leaf](x)`` pulls
    it back to one host numpy array regardless of how it was sharded.
    """
    import jax

    from pio_tpu.parallel.compat import NamedSharding

    def mk_shard(spec):
        sharding = NamedSharding(mesh, spec_for_mesh(mesh, spec))

        def shard_fn(x):
            return jax.device_put(x, sharding)

        return shard_fn

    def mk_gather(spec):
        def gather_fn(x):
            return np.asarray(jax.device_get(x))

        return gather_fn

    shard_fns = jax.tree_util.tree_map(
        mk_shard, specs, is_leaf=is_partition_spec
    )
    gather_fns = jax.tree_util.tree_map(
        mk_gather, specs, is_leaf=is_partition_spec
    )
    return shard_fns, gather_fns


# -- per-template rule registry ---------------------------------------------

_TEMPLATE_RULES: Dict[str, Callable[[], List[Tuple[str, Any]]]] = {}


def register_partition_rules(
    template: str, rules: Callable[[], List[Tuple[str, Any]]]
) -> None:
    """Register (or override) the rule list for a template name.

    ``rules`` is a zero-arg callable so ``PartitionSpec`` construction —
    a jax import — stays lazy until a mesh consumer needs it.
    """
    _TEMPLATE_RULES[template] = rules


def rules_for(template: str) -> List[Tuple[str, Any]]:
    """The registered rule list for ``template`` (raises KeyError)."""
    try:
        factory = _TEMPLATE_RULES[template]
    except KeyError:
        raise KeyError(
            f"no partition rules registered for template {template!r}; "
            f"known: {sorted(_TEMPLATE_RULES)}"
        ) from None
    return list(factory())


def _als_rules():
    from pio_tpu.parallel.compat import PartitionSpec as P

    # factor matrices row-sharded over the entity (data) axis; indexes and
    # everything else replicated
    return [
        (r"(user_factors|item_factors)$", P("data", None)),
    ]


def _two_tower_rules():
    from pio_tpu.parallel.compat import PartitionSpec as P

    # vocab-parallel embedding (ep), Megatron column/row MLP splits (tp);
    # the trained serving vectors row-shard over entities like ALS factors
    return [
        (r"(user_vectors|item_vectors)$", P("data", None)),
        (r"emb$", P("model", None)),
        (r"w1$", P(None, "model")),
        (r"b1$", P("model")),
        (r"w2$", P("model", None)),
        (r"b2$", P()),
    ]


def _seqrec_rules():
    from pio_tpu.parallel.compat import PartitionSpec as P

    # layer-stacked blocks ride pipe on the leading (layer) dim; heads and
    # ffn hidden are tp column/row splits; embedding is vocab-sharded
    return [
        (r"blocks/(wq|wk|wv|w1)$", P("pipe", None, "model")),
        (r"blocks/(wo|w2)$", P("pipe", "model", None)),
        (r"blocks/b1$", P("pipe", "model")),
        (r"blocks/", P("pipe", None)),
        (r"emb$", P("model", None)),
        (r"(pos|lnf_g|lnf_b)$", P()),
    ]


register_partition_rules("als", _als_rules)
register_partition_rules("two_tower", _two_tower_rules)
register_partition_rules("seqrec", _seqrec_rules)


# -- placement budget --------------------------------------------------------


def device_budget_bytes() -> int:
    """Per-device parameter budget from the env; 0 = unlimited."""
    return knobs.knob_int(DEVICE_BUDGET_ENV)


def tree_nbytes(tree: Any) -> int:
    """Total bytes across array leaves (host or device)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None and hasattr(leaf, "size"):
            nbytes = leaf.size * np.dtype(
                getattr(leaf, "dtype", np.float32)
            ).itemsize
        total += int(nbytes or 0)
    return total


def assert_device_budget(
    nbytes: int, n_devices: int, what: str = "placement"
) -> None:
    """Raise :class:`DeviceBudgetExceeded` when ``nbytes`` spread over
    ``n_devices`` chips exceeds the per-device budget (no-op when the
    budget env is unset)."""
    budget = device_budget_bytes()
    if budget <= 0:
        return
    per_device = -(-nbytes // max(1, n_devices))
    if per_device > budget:
        raise DeviceBudgetExceeded(
            f"{what}: {per_device} B/device over {n_devices} device(s) "
            f"exceeds {DEVICE_BUDGET_ENV}={budget}"
        )


def per_device_nbytes(mesh, params: Any, specs: Any) -> int:
    """Bytes each device holds after placing ``params`` under ``specs``:
    sharded dims divide a leaf's footprint by the product of its mesh
    axis sizes; replicated leaves cost their full size per chip."""
    import jax

    leaves = jax.tree_util.tree_leaves(params)
    spec_leaves = jax.tree_util.tree_leaves(specs, is_leaf=is_partition_spec)
    total = 0
    for leaf, spec in zip(leaves, spec_leaves):
        nbytes = tree_nbytes(leaf)
        factor = 1
        for entry in spec_for_mesh(mesh, spec):
            if entry is None:
                continue
            for axis in (entry,) if isinstance(entry, str) else entry:
                factor *= int(mesh.shape[axis])
        total += -(-nbytes // max(1, factor))
    return total


def shard_params(
    mesh,
    params: Any,
    rules: Iterable[Tuple[str, Any]],
    *,
    on_unmatched: str = "replicate",
    enforce_budget: bool = True,
) -> Tuple[Any, Any]:
    """Match ``rules`` over ``params`` and place every leaf on ``mesh``.

    Returns ``(sharded_params, specs)``. With ``mesh=None`` the params
    pass through as single-device jnp arrays (specs still computed, all
    projected onto nothing — callers can ignore them).
    """
    import jax

    specs = match_partition_rules(rules, params, on_unmatched=on_unmatched)
    if mesh is None:
        import jax.numpy as jnp

        return jax.tree_util.tree_map(jnp.asarray, params), specs
    per_dev = per_device_nbytes(mesh, params, specs)
    if enforce_budget:
        assert_device_budget(per_dev, 1, "shard_params")
    shard_fns, _ = make_shard_and_gather_fns(mesh, specs)
    sharded = jax.tree_util.tree_map(lambda f, x: f(x), shard_fns, params)
    # device ledger (ISSUE 17): latest sharded training placement's
    # per-chip footprint, replaced on each call (the params it books
    # are superseded wholesale by the next placement)
    from pio_tpu.obs import devicewatch

    devicewatch.ledger_place(
        "shard", "shard_params", per_dev,
        name="shard_params per-device",
    )
    return sharded, specs
