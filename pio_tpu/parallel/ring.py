"""Ring attention — sequence-parallel exact attention over the ``seq`` axis.

Long-context support is first-class in this framework even though the
reference has no sequence models at all (SURVEY.md §5 "long-context:
ABSENT" — its nearest concept is Spark partitioning of the event RDD along
time). The sequence-recommendation template (pio_tpu/templates/sequence.py)
consumes **entire user event histories**, so attention over sequences longer
than one chip's HBM must shard the sequence dimension.

Design (blockwise / ring formulation):

- The sequence is sharded over mesh axis ``seq``: each device holds
  ``[B, T/n, heads, d]`` blocks of Q, K, V.
- K/V blocks rotate around the ring with ``ppermute`` while each device's Q
  stays put; a ``lax.scan`` of ``n`` steps overlaps the neighbour exchange
  with the local block matmuls (both ride the MXU).
- Softmax is computed **online** (running row-max ``m``, normalizer ``l``,
  accumulator ``o``) so the full ``[T, T]`` score matrix never exists —
  exact attention, O(T/n) memory per device.
- Causality uses *global* positions: device ``i`` owns q-positions
  ``i·T/n + [0, T/n)``; after ``s`` rotations it is looking at the K/V block
  that started on device ``(i - s) mod n``. Blocks entirely in the future
  still flow through the ring (uniform program on every device — XLA cannot
  skip them) but contribute zero weight.

Inside ``jit`` with a sharded mesh this function must be wrapped in
``shard_map`` over the ``seq`` axis (see :func:`ring_attention_sharded`);
on a single device (``axis=None``) it degrades to plain blockwise attention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from pio_tpu.parallel.compat import axis_size

_NEG_BIG = -1e30


def _block_attn_update(o, m, l, q, k, v, q_pos, k_pos, causal, scale):
    """One online-softmax accumulation of a (q-block, kv-block) pair.

    Shapes: q [B, Tq, H, D], k/v [B, Tk, H, D]; o/m/l accumulators.
    """
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
        scores = jnp.where(mask[None, None], scores, _NEG_BIG)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    p = jnp.exp(scores - m_new[..., None])
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + p.sum(axis=-1)
    o_new = o * correction[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(p.dtype),
        preferred_element_type=jnp.float32,
    )
    return o_new, m_new, l_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: Optional[str],
    causal: bool = True,
) -> jax.Array:
    """Exact attention over a sequence sharded on mesh axis ``axis``.

    Call from inside ``shard_map``; each device passes its local
    ``[B, T_local, H, D]`` blocks. With ``axis=None`` computes plain
    single-device attention (same code path, ring of size 1).
    Returns the local ``[B, T_local, H, D]`` output block.
    """
    b, t_loc, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    n = 1 if axis is None else axis_size(axis)
    idx = 0 if axis is None else jax.lax.axis_index(axis)

    q32 = q.astype(jnp.float32)
    o = jnp.zeros((b, h, t_loc, d), jnp.float32)
    m = jnp.full((b, h, t_loc), _NEG_BIG, jnp.float32)
    l = jnp.zeros((b, h, t_loc), jnp.float32)
    q_pos = idx * t_loc + jnp.arange(t_loc)

    def update(o, m, l, k_blk, v_blk, s):
        src = (idx - s) % n  # which device this K/V block started on
        k_pos = src * t_loc + jnp.arange(t_loc)
        return _block_attn_update(
            o, m, l, q32, k_blk.astype(jnp.float32),
            v_blk.astype(jnp.float32), q_pos, k_pos, causal, scale,
        )

    def step(carry, s):
        o, m, l, k_blk, v_blk = carry
        o, m, l = update(o, m, l, k_blk, v_blk, s)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        return (o, m, l, k_blk, v_blk), None

    if n > 1:
        # n-1 rotating steps, then the last block's update with no final
        # ppermute (the rotated result would be discarded — wasted ICI).
        (o, m, l, k, v), _ = jax.lax.scan(
            step, (o, m, l, k, v), jnp.arange(n - 1)
        )
    o, m, l = update(o, m, l, k, v, n - 1)
    out = o / jnp.maximum(l, 1e-20)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention_sharded(mesh, q, k, v, *, causal: bool = True):
    """``shard_map``-wrapped ring attention: global [B, T, H, D] in/out.

    Batch rides the ``data`` axis, sequence the ``seq`` axis; heads and
    head-dim stay unsharded (shard heads over ``model`` upstream if needed).
    """
    from pio_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P("data", "seq", None, None)
    fn = functools.partial(ring_attention, axis="seq", causal=causal)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
