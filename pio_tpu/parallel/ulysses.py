"""Ulysses-style all-to-all sequence parallelism — the second SP mode.

Complement to ring attention (pio_tpu/parallel/ring.py). Where the ring
rotates K/V blocks with ``ppermute`` (n steps, O(T/n) memory, bandwidth
spread over the whole computation), the all-to-all formulation re-shards
ONCE per attention call: heads scatter across the ``seq`` axis while the
sequence gathers, every device computes exact attention over the FULL
sequence for its head subset, and a second all-to-all restores the
sequence sharding. Two collectives per call; the local compute
materializes the ``[B, H/n, T, T]`` score matrix, so per-device memory is
quadratic in the FULL sequence length (for 1/n of the heads).

Trade-off guide (why both exist):

- **ring**: the O(T²) score matrix would not fit — memory-bound long
  contexts; online softmax keeps O(T/n · T_blk) and overlaps the
  ppermute hops with block matmuls.
- **ulysses (all-to-all)**: T moderate enough that full-T scores fit for
  H/n heads; two ICI collectives beat n ppermute hops — latency-bound
  regimes. Requires ``n_heads % n == 0``.

The reference has no sequence models at all (SURVEY.md §5 "long-context:
ABSENT"); this subsystem is a deliberate capability extension, first-class
per the rebuild's goals.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from pio_tpu.parallel.compat import axis_size

_NEG_BIG = -1e30


def _dense_causal_attention(q, k, v, causal: bool, scale: float):
    """Plain exact attention on full-sequence [B, T, h, D] blocks."""
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_BIG)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(p.dtype),
        preferred_element_type=jnp.float32,
    )
    return out


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: Optional[str],
    causal: bool = True,
) -> jax.Array:
    """Exact attention over a sequence sharded on mesh axis ``axis``.

    Call from inside ``shard_map``; each device passes its local
    ``[B, T_local, H, D]`` blocks, with ``H`` divisible by the axis size.
    all-to-all #1: [B, T/n, H, D] → [B, T, H/n, D] (scatter heads, gather
    sequence); local dense attention; all-to-all #2 restores the layout.
    With ``axis=None`` computes plain single-device attention.
    """
    b, t_loc, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    if axis is None:
        return _dense_causal_attention(
            q.astype(jnp.float32), k, v, causal, scale
        ).astype(q.dtype)

    n = axis_size(axis)
    if h % n != 0:
        raise ValueError(
            f"ulysses attention needs n_heads divisible by the '{axis}' "
            f"axis size ({h} heads over {n} devices)"
        )
    # scatter heads (axis 2), gather sequence (axis 1); inputs cross the
    # interconnect in their own (possibly bf16) dtype — upcasting happens
    # AFTER the collective so the wire carries half the bytes
    a2a = functools.partial(
        jax.lax.all_to_all, axis_name=axis, split_axis=2, concat_axis=1,
        tiled=True,
    )
    qg, kg, vg = a2a(q), a2a(k), a2a(v)
    out = _dense_causal_attention(
        qg.astype(jnp.float32), kg, vg, causal, scale
    ).astype(q.dtype)
    # inverse: scatter sequence back, gather heads
    out = jax.lax.all_to_all(
        out, axis_name=axis, split_axis=1, concat_axis=2, tiled=True
    )
    return out


def ulysses_attention_sharded(mesh, q, k, v, *, causal: bool = True):
    """``shard_map``-wrapped all-to-all attention: global [B, T, H, D]
    in/out, batch on ``data``, sequence on ``seq`` (same contract as
    :func:`pio_tpu.parallel.ring.ring_attention_sharded`)."""
    from pio_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P("data", "seq", None, None)
    fn = functools.partial(ulysses_attention, axis="seq", causal=causal)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
