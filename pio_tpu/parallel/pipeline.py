"""Pipeline parallelism over the ``pipe`` mesh axis (GPipe-style).

Absent in the reference (Spark knows only data partitioning — SURVEY.md
§2.6); first-class here so deep towers can span chips. The formulation is
the SPMD one: every device runs the same program over its *stage slice* of a
layer-stacked parameter pytree, microbatches enter at stage 0, activations
hop stage→stage with ``ppermute``, and results drain from the last stage.
The schedule is a single ``lax.scan`` of ``n_micro + n_stages - 1`` ticks —
steady-state keeps every stage busy; bubble fraction is the usual
``(n_stages-1)/(n_micro+n_stages-1)``. Reverse-mode AD differentiates
through ``ppermute``/``scan``, so the same helper serves training.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from pio_tpu.parallel.compat import axis_size


def pipeline_apply(params, x, stage_fn: Callable, *, axis: str = "pipe"):
    """Run ``x`` through ``n_stages`` chained applications of ``stage_fn``.

    Call from inside ``shard_map``. Args:
        params: this device's stage parameters (pytree; caller shards the
            layer-stacked tree over ``axis`` and squeezes the stage dim).
        x: ``[n_micro, micro_b, ...]`` microbatched input, replicated over
            ``axis`` (only stage 0 reads it).
        stage_fn: ``(params, [micro_b, ...]) -> [micro_b, ...]`` — one
            stage's compute; activation shape must be stage-invariant.

    Returns ``[n_micro, micro_b, ...]`` outputs of the final stage,
    identical on every device of the axis (psum-reconciled), so callers can
    use ``out_specs=P(...)`` with the pipe dim unsharded.
    """
    n = axis_size(axis)
    idx = jax.lax.axis_index(axis)
    n_micro = x.shape[0]
    ticks = n_micro + n - 1
    perm_fwd = [(i, i + 1) for i in range(n - 1)]

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (clamped; extra ticks feed garbage
        # that never reaches the output window)
        feed = jax.lax.dynamic_index_in_dim(
            x, jnp.minimum(t, n_micro - 1), keepdims=False
        )
        inp = jnp.where(idx == 0, feed, state)
        out = stage_fn(params, inp)
        # last stage's tick-t output is microbatch t-(n-1)
        slot = t - (n - 1)
        contrib = jnp.where(idx == n - 1, out, jnp.zeros_like(out))
        outputs = jax.lax.cond(
            slot >= 0,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, contrib.astype(o.dtype), jnp.maximum(slot, 0), 0
            ),
            lambda o: o,
            outputs,
        )
        if n > 1:
            state = jax.lax.ppermute(out, axis, perm_fwd)
        else:
            state = out
        return (state, outputs), None

    state0 = jnp.zeros_like(x[0])
    out0 = jnp.zeros((n_micro,) + x.shape[1:], x.dtype)
    (_, outputs), _ = jax.lax.scan(
        tick, (state0, out0), jnp.arange(ticks)
    )
    # outputs are nonzero only on the last stage; make them uniform
    return jax.lax.psum(outputs, axis)


def stage_slice(params_stacked, *, axis: str = "pipe"):
    """Inside shard_map: squeeze the per-device stage dim of a stacked tree.

    The caller shards a ``[n_stages, ...]``-stacked parameter pytree with
    ``P(axis)`` so each device's block has leading dim 1; this drops it.
    """
    return jax.tree.map(lambda a: a[0], params_stacked)
