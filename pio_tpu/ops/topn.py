"""Device-resident factor scoring for serving — SURVEY.md §7 hard part (d).

The reference's query server scores on the driver JVM per request
(``CreateServer`` → ``predictBase``, reference core/.../workflow/
CreateServer.scala — UNVERIFIED path; see SURVEY.md). The TPU-first serving
story instead uploads the factor/embedding matrices to the accelerator ONCE
at deploy (the ``Engine.prepareDeploy`` analog — see
``Algorithm.prepare_for_serving``) and jits score + top-k, so each request
is one device dispatch of a ``[B, K] @ [K, N]`` MXU matmul and only integer
codes + top-N results cross the host link.

**Adaptive routing.** What dominates per-request cost is the host↔device
round trip, not the math: on a TPU VM the link RTT is microseconds and the
device path wins at every batch size, while on a tunneled/remote device a
single transfer can cost ~100 ms. The scorer therefore probes BOTH costs
once at deploy — one tiny transfer round trip, one host-scored row — and
routes each call by batch size: ``B ≥ RTT / host_row_cost`` goes to the
accelerator (the RTT amortizes across the batch), smaller batches use the
host mirror of the factors (which exists anyway — it is the serialized
model state). ``PIO_TPU_SERVE_DEVICE=1|0`` forces device/host for all
calls.

Shape discipline: jit specializes per shape, so both the batch dimension
and the top-k width are bucketed to powers of two (a handful of
compilations total, each cached by jax). Padding rows use code 0 and are
sliced off on the way out; excluded item slots use the sentinel index
``n_cols``, which ``.at[].set(mode="drop")`` discards as out-of-bounds.
"""

from __future__ import annotations

import ctypes
import functools
import os
from pio_tpu.utils import knobs
from pio_tpu.obs import monotonic_s
from typing import Optional, Tuple

import numpy as np

#: largest per-dispatch batch bucket; bigger batches loop in chunks of this
_MAX_BATCH_BUCKET = 512

#: ctypes pointer types for the native host scorer (hoisted off the
#: per-request path)
_F32P = ctypes.POINTER(ctypes.c_float)
_I32P = ctypes.POINTER(ctypes.c_int32)
_I64P = ctypes.POINTER(ctypes.c_int64)


def _bucket(n: int, cap: int) -> int:
    """Smallest power of two ≥ n, capped."""
    b = 1
    while b < n and b < cap:
        b <<= 1
    return min(b, cap)


@functools.lru_cache(maxsize=None)
def _topn_fn(k: int, with_exclude: bool, n_valid: Optional[int] = None):
    """Jitted [B,K]@[K,N] + top-k (cached per static k / exclusion arity).

    ``n_valid``: static count of real columns when the col table is padded
    to a mesh multiple — pad columns are masked to -inf before top-k so a
    zero-vector pad row can never outrank a real negative score.
    """
    import jax
    import jax.numpy as jnp

    def _mask_pad(scores):
        if n_valid is None:
            return scores
        keep = jnp.arange(scores.shape[1]) < n_valid
        return jnp.where(keep[None, :], scores, -jnp.inf)

    if with_exclude:

        def fn(rows, cols, codes, excl):
            q = rows[codes]
            scores = jnp.einsum(
                "bk,nk->bn", q, cols, preferred_element_type=jnp.float32
            )
            scores = _mask_pad(scores)
            b = jnp.arange(codes.shape[0])[:, None]
            # sentinel index n_cols is out of bounds → dropped, not wrapped
            scores = scores.at[b, excl].set(-jnp.inf, mode="drop")
            return jax.lax.top_k(scores, k)

    else:

        def fn(rows, cols, codes):
            q = rows[codes]
            scores = jnp.einsum(
                "bk,nk->bn", q, cols, preferred_element_type=jnp.float32
            )
            return jax.lax.top_k(_mask_pad(scores), k)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _scores_fn():
    import jax
    import jax.numpy as jnp

    def fn(rows, cols, codes):
        return jnp.einsum(
            "bk,nk->bn", rows[codes], cols,
            preferred_element_type=jnp.float32,
        )

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _pairs_fn():
    import jax
    import jax.numpy as jnp

    def fn(rows, cols, rcodes, ccodes):
        return jnp.einsum(
            "bk,bk->b", rows[rcodes], cols[ccodes],
            preferred_element_type=jnp.float32,
        )

    return jax.jit(fn)


def _env_mode() -> str:
    env = knobs.knob_str("PIO_TPU_SERVE_DEVICE").lower()
    if env in ("1", "true", "yes", "device"):
        return "device"
    if env in ("0", "false", "no", "host"):
        return "host"
    return "auto"


@functools.lru_cache(maxsize=1)
def _probe_link_rtt_s() -> float:
    """One-time cost of a minimal host→device→host round trip (measures the
    link, not the math — 4 bytes each way). Microseconds on a local
    PCIe/ICI-attached device, ~100 ms over a tunneled remote device."""
    import jax

    x = np.ones(1, np.float32)
    jax.device_get(jax.device_put(x))  # warm the path
    best = float("inf")
    for _ in range(3):
        t0 = monotonic_s()
        jax.device_get(jax.device_put(x))
        best = min(best, monotonic_s() - t0)
    return best


class DeviceTopNScorer:
    """Row-factors × col-factors top-N scorer, resident on the accelerator.

    ``rows`` is the query-side table (user factors / user tower output),
    ``cols`` the scored-item table. All methods accept/return host numpy —
    only integer codes and the top-N results cross the link.

    ``prefer_device``: True/False pins every call to the device/host path;
    None consults ``PIO_TPU_SERVE_DEVICE`` and defaults to adaptive
    batch-size routing (see module docstring). ``link_rtt_s`` overrides the
    probed link round-trip (tests inject synthetic link speeds).

    ``mesh``: a multi-device mesh to shard the factor tables over. Both
    tables row-shard on the mesh's entity axis (``data``), padded up to a
    shard multiple — each chip holds 1/n of the model, and the jitted
    score + top-k runs GSPMD-sharded with stable input shardings (no
    steady-state retraces). The per-device footprint is enforced against
    ``PIO_TPU_DEVICE_BUDGET_BYTES`` when set.
    """

    def __init__(
        self,
        row_factors: np.ndarray,
        col_factors: np.ndarray,
        prefer_device: Optional[bool] = None,
        warmup: bool = False,
        link_rtt_s: Optional[float] = None,
        mesh=None,
    ):
        rows = np.ascontiguousarray(row_factors, dtype=np.float32)
        cols = np.ascontiguousarray(col_factors, dtype=np.float32)
        if rows.shape[1] != cols.shape[1]:
            raise ValueError(
                f"rank mismatch: rows {rows.shape} vs cols {cols.shape}"
            )
        self.n_rows, self.rank = rows.shape
        self.n_cols = cols.shape[0]
        self._rows_np = rows
        self._cols_np = cols
        self._rows_dev = self._cols_dev = None
        self._cols_t = None  # lazy transposed mirror (native host path)
        if mesh is not None and int(np.prod(mesh.devices.shape)) <= 1:
            mesh = None  # a 1-chip mesh is the plain device path
        self._mesh = mesh
        self._ncols_pad = self.n_cols

        if self.n_rows == 0 or self.n_cols == 0:
            # degenerate factor tables cannot be probed (the host-row
            # probe would index row 0) and have nothing to score on the
            # accelerator; every call takes the host path, whose public
            # methods handle the empty dimensions explicitly
            self.min_device_batch = float("inf")
            self.min_pair_batch = float("inf")
            return

        if prefer_device is True:
            mode = "device"
        elif prefer_device is False:
            mode = "host"
        else:
            mode = _env_mode()
        if mode == "host":
            self.min_device_batch = float("inf")
            self.min_pair_batch = float("inf")
        else:
            import jax

            from pio_tpu.parallel.partition import assert_device_budget

            # the single upload of the deploy lifetime
            if self._mesh is not None:
                n_dev = int(np.prod(self._mesh.devices.shape))
                assert_device_budget(
                    rows.nbytes + cols.nbytes, n_dev, "topn mesh placement"
                )
                self._rows_dev, self._cols_dev, self._ncols_pad = (
                    self._place_sharded(rows, cols)
                )
            else:
                assert_device_budget(
                    rows.nbytes + cols.nbytes, 1, "topn device placement"
                )
                self._rows_dev = jax.device_put(rows)
                self._cols_dev = jax.device_put(cols)
            if mode == "device":
                self.min_device_batch = 1
                self.min_pair_batch = 1
            else:  # adaptive: break-even batch sizes from measured costs.
                # A pair query is a rank-length dot (~n_cols× cheaper on
                # host than a full score row), so its break-even batch is
                # correspondingly larger — per-item queries essentially
                # always stay on the host mirror.
                rtt = link_rtt_s if link_rtt_s is not None \
                    else _probe_link_rtt_s()
                host_row = self._probe_host_row_s()
                host_pair = max(host_row / self.n_cols, 1e-9)
                self.min_device_batch = max(1, int(np.ceil(rtt / host_row)))
                self.min_pair_batch = max(1, int(np.ceil(rtt / host_pair)))
            if warmup and self.min_device_batch <= 1:
                # pre-compile the single-query buckets (the first live
                # request must not pay the ~seconds-scale XLA compile)
                self.top_n_batch(np.zeros(1, np.int32), 16)
                if self.min_pair_batch <= 1:
                    self.score_pairs(
                        np.zeros(1, np.int32), np.zeros(1, np.int32)
                    )
        if warmup and self.min_device_batch > 1:
            # small batches will route to the host mirror: pay the
            # native-library g++ build and the transposed-table copy at
            # DEPLOY time, not inside the first live request
            self.top_n_batch(np.zeros(1, np.int32), 1)

    def _place_sharded(self, rows, cols):
        """Row-shard both tables over the mesh entity axis (padded to a
        shard multiple; pad rows are zero and masked out of top-k)."""
        import jax

        from pio_tpu.parallel.compat import NamedSharding
        from pio_tpu.parallel.compat import PartitionSpec as P

        mesh = self._mesh
        axis = "data" if "data" in mesh.axis_names else mesh.axis_names[0]
        size = int(mesh.shape[axis])
        sharding = NamedSharding(mesh, P(axis, None))

        def pad_rows(a):
            n = -(-a.shape[0] // size) * size
            if n == a.shape[0]:
                return a
            out = np.zeros((n, a.shape[1]), a.dtype)
            out[: a.shape[0]] = a
            return out

        rows_dev = jax.device_put(pad_rows(rows), sharding)
        cols_p = pad_rows(cols)
        return rows_dev, jax.device_put(cols_p, sharding), cols_p.shape[0]

    @property
    def mesh_sharded(self) -> bool:
        """True when the factor tables are sharded over a serving mesh."""
        return self._mesh is not None and self.on_device

    def sharding_info(self) -> Optional[dict]:
        """Placement summary for /stats.json; None when unsharded."""
        if not self.mesh_sharded:
            return None
        mesh = self._mesh
        n_dev = int(np.prod(mesh.devices.shape))
        total = self._rows_np.nbytes + self._cols_np.nbytes
        return {
            "meshShape": {
                k: int(v) for k, v in mesh.shape.items() if int(v) > 1
            } or {"data": 1},
            "nDevices": n_dev,
            "rows": [int(self.n_rows), int(self.rank)],
            "cols": [int(self.n_cols), int(self.rank)],
            "colsPadded": int(self._ncols_pad),
            "bytesPerDevice": -(-total // n_dev),
            "totalBytes": int(total),
        }

    @property
    def on_device(self) -> bool:
        """True when at least some batch sizes route to the accelerator."""
        return self._rows_dev is not None

    def _probe_host_row_s(self) -> float:
        best = float("inf")
        for _ in range(3):
            t0 = monotonic_s()
            self._rows_np[0] @ self._cols_np.T
            best = min(best, monotonic_s() - t0)
        return max(best, 1e-7)

    def _route_to_device(self, batch: int) -> bool:
        return self.on_device and batch >= self.min_device_batch

    # ----------------------------------------------------------- device path
    def _top_n_device(self, codes, n, exclude):
        import jax

        B = codes.shape[0]
        k = _bucket(n, self.n_cols) if n < self.n_cols else self.n_cols
        padded_cols = self._ncols_pad != self.n_cols
        n_valid = self.n_cols if padded_cols else None
        idx_out = np.empty((B, k), np.int64)
        val_out = np.empty((B, k), np.float32)
        for lo in range(0, B, _MAX_BATCH_BUCKET):
            chunk = codes[lo:lo + _MAX_BATCH_BUCKET]
            bb = _bucket(chunk.shape[0], _MAX_BATCH_BUCKET)
            pad = bb - chunk.shape[0]
            cp = np.pad(chunk, (0, pad))
            if exclude is not None:
                # bucket the exclusion width too — every distinct raw E
                # would otherwise trigger a fresh XLA compile per request
                E = exclude.shape[1]
                ep = np.pad(
                    exclude[lo:lo + _MAX_BATCH_BUCKET],
                    ((0, pad), (0, _bucket(max(E, 1), 1 << 30) - E)),
                    constant_values=self._ncols_pad,  # OOB → dropped
                )
                vals, idx = _topn_fn(k, True, n_valid)(
                    self._rows_dev, self._cols_dev, cp, ep
                )
            else:
                vals, idx = _topn_fn(k, False, n_valid)(
                    self._rows_dev, self._cols_dev, cp
                )
            vals, idx = jax.device_get((vals, idx))
            m = chunk.shape[0]
            idx_out[lo:lo + m] = idx[:m]
            val_out[lo:lo + m] = vals[:m]
        if padded_cols:
            # a fully-masked row could surface a pad index at -inf; pin
            # such slots to col 0 so callers never see an OOB item code
            idx_out = np.where(np.isfinite(val_out), idx_out, 0)
        return idx_out[:, :n], val_out[:, :n]

    #: native host scorer is a SINGLE-CORE fused loop targeting the
    #: per-request serving path; larger batches keep the multithreaded
    #: BLAS GEMM + argpartition (batch_predict on many-core hosts)
    _NATIVE_HOST_MAX_BATCH = 8

    # ------------------------------------------------------------- host path
    def _top_n_host(self, codes, n, exclude):
        if exclude is None and codes.shape[0] <= self._NATIVE_HOST_MAX_BATCH:
            got = self._top_n_host_native(codes, n)
            if got is not None:
                return got
        B = codes.shape[0]
        # chunk rows so the [chunk, N] score + key planes stay ~100 MB
        # regardless of batch size (batch_predict can send thousands)
        chunk = max(1, (8 << 20) // max(1, self.n_cols))
        idx_out = np.empty((B, n), np.int64)
        val_out = np.empty((B, n), np.float32)
        for lo in range(0, B, chunk):
            hi = min(B, lo + chunk)
            ex = exclude[lo:hi] if exclude is not None else None
            idx_out[lo:hi], val_out[lo:hi] = self._top_n_host_chunk(
                codes[lo:hi], n, ex
            )
        return idx_out, val_out

    def _top_n_host_chunk(self, codes, n, exclude):
        scores = self._rows_np[codes] @ self._cols_np.T  # [B, N]
        if exclude is not None:
            b = np.arange(scores.shape[0])[:, None]
            keep = exclude < self.n_cols  # sentinel slots stay untouched
            scores[
                np.broadcast_to(b, exclude.shape)[keep],
                exclude[keep],
            ] = -np.inf
        # composite u64 keys encode (-score, index): selection and order
        # become DETERMINISTIC under score ties — the same (-score, idx)
        # contract the native serving path implements, so predict and
        # batch_predict agree on tied items (exactly, up to summation
        # rounding differences between the two dot-product loops). NaN
        # (diverged factors) maps to -inf in BOTH paths: ranks tied-last,
        # surfaces as -inf. `+ 0.0` canonicalizes -0.0 to +0.0 so the
        # bit transform ties them like the native float compare does.
        scores += np.float32(0.0)
        np.copyto(scores, -np.inf, where=np.isnan(scores))
        bits = scores.view(np.uint32)
        ordered = np.where(
            (bits >> np.uint32(31)).astype(bool),
            ~bits, bits | np.uint32(0x80000000),
        )
        keys = (
            ((np.uint32(0xFFFFFFFF) - ordered).astype(np.uint64)
             << np.uint64(32))
            | np.arange(self.n_cols, dtype=np.uint64)[None, :]
        )
        if n < self.n_cols:
            part = np.argpartition(keys, n - 1, axis=1)[:, :n]
        else:
            part = np.argsort(keys, axis=1)
        pk = np.take_along_axis(keys, part, axis=1)
        order = np.argsort(pk, axis=1)
        idx = np.take_along_axis(part, order, axis=1).astype(np.int64)
        return idx, np.take_along_axis(scores, idx, axis=1)

    def _top_n_host_native(self, codes, n):
        """Fused native blocked scan-and-select (no [B, N] score array):
        stride-1 FMA over a transposed [K, N] table in L1-sized blocks,
        heap selection while each block is cache-hot. None → caller uses
        the numpy path (library unavailable, or exclusions requested)."""
        try:
            from pio_tpu.native import topn_host_lib

            lib = topn_host_lib()
        except Exception:  # no toolchain → numpy fallback
            self._top_n_host_native = lambda codes, n: None
            return None
        if self._cols_t is None:
            # one-time transposed mirror (the kernel's layout); built
            # lazily so scorers that never take the host path skip it
            self._cols_t = np.ascontiguousarray(self._cols_np.T)
        B = codes.shape[0]
        out_idx = np.empty((B, n), np.int64)
        out_val = np.empty((B, n), np.float32)
        rc = lib.topn_host_f32(
            self._rows_np.ctypes.data_as(_F32P),
            self._cols_t.ctypes.data_as(_F32P),
            self.n_rows, self.n_cols, self.rank,
            np.ascontiguousarray(codes).ctypes.data_as(_I32P),
            B, n,
            out_idx.ctypes.data_as(_I64P),
            out_val.ctypes.data_as(_F32P),
        )
        if rc != 0:
            return None  # out-of-range code: numpy path raises the error
        return out_idx, out_val

    # -------------------------------------------------------------- public
    def top_n_batch(
        self,
        codes: np.ndarray,
        n: int,
        exclude: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Top-n col indices + scores for each row code.

        ``exclude``: optional ``[B, E]`` int array of col codes to mask out
        per row; pad unused slots with any value ≥ ``n_cols``.
        """
        codes = np.asarray(codes, np.int32)
        if codes.ndim != 1:
            raise ValueError("codes must be 1-D")
        n = max(1, min(n, self.n_cols))
        if exclude is not None:
            exclude = np.asarray(exclude, np.int32)
            if exclude.ndim != 2 or exclude.shape[0] != codes.shape[0]:
                raise ValueError("exclude must be [B, E]")
        if codes.shape[0] == 0 or self.n_cols == 0:
            b = codes.shape[0]
            n = 0 if self.n_cols == 0 else n
            return (np.empty((b, n), np.int64), np.empty((b, n), np.float32))
        if self._route_to_device(codes.shape[0]):
            return self._top_n_device(codes, n, exclude)
        return self._top_n_host(codes, n, exclude)

    def scores_batch(self, codes: np.ndarray) -> np.ndarray:
        """Full ``[B, n_cols]`` score matrix (host numpy out).

        Unlike top-N, the result is B × n_cols floats back over the link —
        on a slow link that payload, not the matmul, dominates, so the
        device route is taken only when the link probe found it effectively
        free (min_device_batch == 1, i.e. a local device or forced mode).
        """
        import jax

        codes = np.asarray(codes, np.int32)
        B = codes.shape[0]
        if B == 0 or self.min_device_batch > 1 or not self.on_device:
            return self._rows_np[codes] @ self._cols_np.T
        out = np.empty((B, self.n_cols), np.float32)
        for lo in range(0, B, _MAX_BATCH_BUCKET):
            chunk = codes[lo:lo + _MAX_BATCH_BUCKET]
            bb = _bucket(chunk.shape[0], _MAX_BATCH_BUCKET)
            cp = np.pad(chunk, (0, bb - chunk.shape[0]))
            s = jax.device_get(
                _scores_fn()(self._rows_dev, self._cols_dev, cp)
            )
            # sharded placement pads the col table; trim pad columns
            out[lo:lo + chunk.shape[0]] = s[: chunk.shape[0], : self.n_cols]
        return out

    def score_pairs(
        self, row_codes: np.ndarray, col_codes: np.ndarray
    ) -> np.ndarray:
        """Per-pair dot products ``rows[rc] · cols[cc]`` → ``[B]``."""
        rc = np.asarray(row_codes, np.int32)
        cc = np.asarray(col_codes, np.int32)
        B = rc.shape[0]
        if B == 0 or B < self.min_pair_batch or not self.on_device:
            return np.einsum(
                "bk,bk->b", self._rows_np[rc], self._cols_np[cc]
            )
        import jax

        chunk_cap = 1 << 20
        out = np.empty(B, np.float32)
        for lo in range(0, B, chunk_cap):
            rcc, ccc = rc[lo:lo + chunk_cap], cc[lo:lo + chunk_cap]
            bb = _bucket(rcc.shape[0], chunk_cap)
            pad = bb - rcc.shape[0]
            got = jax.device_get(_pairs_fn()(
                self._rows_dev, self._cols_dev,
                np.pad(rcc, (0, pad)), np.pad(ccc, (0, pad)),
            ))
            out[lo:lo + rcc.shape[0]] = got[: rcc.shape[0]]
        return out
