"""Pallas TPU kernel: weighted embedding-bag lookup (sparse × dense matmul).

The sparse hot loop of the text-classification and two-tower templates is

    out[b] = Σ_l weights[b, l] · table[ids[b, l]]        # [B, D]

i.e. a TF-IDF document (or a feature-bag) times an embedding table. On the
reference's substrate this is a Spark-side sparse-vector dot
(MLlib ``HashingTF``/``IDF`` pipelines — UNVERIFIED paths; SURVEY.md §2.6).
The XLA lowering materializes the gathered ``[B, L, D]`` tensor in HBM and
contracts it on the MXU. The Pallas kernel instead streams table rows
HBM→VMEM with an N-deep ring of async DMAs and accumulates in float32 on
the VPU — the ``[B, L, D]`` intermediate never exists.

Measured on v5e-1 (V=50k, D=256, f32; the bench records these each round
in ``secondary.textclassification``):

- At B=4096, L=64 (intermediate 268 MB, fits HBM): jitted XLA wins —
  23.3M tokens/s at max err 9e-8 vs f64 (the jitted default contracts
  f32 inputs via 3-pass bf16, so there is NO accuracy gap to close);
  the kernel does 13.9M tokens/s at err 2.6e-7.
- At B=16384, L=1436 the intermediate alone would be **24 GB — over
  v5e HBM, XLA cannot run at all**; the kernel streams it at 11.3M
  tokens/s through a 4 KB VMEM ring.

So the kernel is the MEMORY-robust path and ``embedding_bag`` dispatches
by intermediate size: shapes whose ``[B, L, D]`` gather fits comfortably
take XLA, larger ones take the kernel
(``PIO_TPU_EMBED_PALLAS_OVER_MB`` overrides the cutoff; CPU always XLA).

Layout notes (Mosaic constraints):

- ids/weights ride in **SMEM input blocks** of one bag-tile each — whole-
  array scalar prefetch overflows the 1 MB SMEM at large B·L.
- The table is viewed ``[V, 1, D]`` so a one-row slice has trailing dims
  equal to the array's — single-row HBM DMAs are otherwise rejected
  (8-sublane alignment rule).
- The DMA ring is statically unrolled (slot = token index mod depth): a
  ``lax.switch`` over slots measured ~2× slower (scalar-unit bound).

Gradients: ``embedding_bag`` carries a custom VJP — d(table) is a
segment-sum scatter-add in plain XLA (scatters don't ride the MXU; there is
nothing for Pallas to win), d(weights) re-uses the gathered rows.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pio_tpu.utils import knobs
from pio_tpu.utils.numutil import round_up as _round_up




# --------------------------------------------------------------------- kernel
BAGS_PER_TILE = 8  # sublane granule: output blocks are [8, D]
DMA_DEPTH = 4  # in-flight row fetches (ring of VMEM row buffers)


def _make_bag_kernel(L: int, D: int, depth: int):
    import jax.lax as lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T = BAGS_PER_TILE * L  # flat token stream per tile
    assert T % depth == 0

    def kernel(id_ref, w_ref, table_ref, out_ref, bufs, sems):
        """One grid step = 8 bags: stream their 8·L table rows, accumulate.

        id_ref/w_ref: per-tile [1, 1, T] SMEM blocks (row id, weight).
        table_ref: [V, 1, D] table in HBM; rows DMA'd one at a time.
        out_ref: [8, D] VMEM block for this bag tile.
        bufs: [depth, 1, D] VMEM DMA ring; sems: depth DMA semaphores.
        The ring spans bag boundaries — padding rows (weight 0) keep the
        stream dense, so DMA overlap never stalls between bags.
        """

        def start(slot, t):
            pltpu.make_async_copy(
                table_ref.at[pl.ds(id_ref[0, 0, t], 1)],
                bufs.at[pl.ds(slot, 1)],
                sems.at[slot],
            ).start()

        def wait(slot, t):
            pltpu.make_async_copy(
                table_ref.at[pl.ds(id_ref[0, 0, t], 1)],
                bufs.at[pl.ds(slot, 1)],
                sems.at[slot],
            ).wait()

        for s in range(depth):
            start(s, s)

        def body(chunk, acc):
            base = chunk * depth
            # static unroll: each position owns a fixed ring slot, so slot
            # choice costs no scalar branching
            for s in range(depth):
                t = base + s
                wait(s, t)
                row = bufs[s, 0, :]
                acc = acc + w_ref[0, 0, t] * row.astype(jnp.float32)

                # re-arm this slot for the token one ring-turn ahead; the
                # row read above has retired (in-order core), so the DMA
                # cannot clobber it
                @pl.when(t + depth < T)
                def _():
                    start(s, t + depth)

                bag_done = lax.rem(t + 1, L) == 0

                @pl.when(bag_done)
                def _():  # flush this bag's row of the output tile
                    out_ref[pl.ds(t // L, 1), :] = acc[None, :].astype(
                        out_ref.dtype
                    )

                acc = jnp.where(bag_done, jnp.zeros_like(acc), acc)
            return acc

        lax.fori_loop(0, T // depth, body, jnp.zeros((D,), jnp.float32))

    return kernel


def _embedding_bag_pallas(
    table: jax.Array,
    ids: jax.Array,
    weights: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, L = ids.shape
    V, D = table.shape

    # pad bags to the 8-bag tile; extra bags read row 0 with weight 0.
    # Pad L so the DMA ring divides the token stream.
    L_pad = _round_up(L, DMA_DEPTH)
    if L_pad != L:
        ids = jnp.pad(ids, ((0, 0), (0, L_pad - L)))
        weights = jnp.pad(weights, ((0, 0), (0, L_pad - L)))
        L = L_pad
    B_pad = _round_up(B, BAGS_PER_TILE)
    if B_pad != B:
        ids = jnp.pad(ids, ((0, B_pad - B), (0, 0)))
        weights = jnp.pad(weights, ((0, B_pad - B), (0, 0)))

    n_tiles = B_pad // BAGS_PER_TILE
    T = BAGS_PER_TILE * L
    tiled_ids = ids.reshape(n_tiles, 1, T)
    tiled_w = weights.reshape(n_tiles, 1, T).astype(jnp.float32)

    smem_blk = pl.BlockSpec(
        (1, 1, T), lambda b: (b, 0, 0), memory_space=pltpu.SMEM
    )
    out = pl.pallas_call(
        _make_bag_kernel(L, D, DMA_DEPTH),
        out_shape=jax.ShapeDtypeStruct((B_pad, D), jnp.float32),
        grid=(n_tiles,),
        in_specs=[
            smem_blk,  # row ids
            smem_blk,  # weights
            pl.BlockSpec(memory_space=pl.ANY),  # table in HBM
        ],
        out_specs=pl.BlockSpec(
            (BAGS_PER_TILE, D), lambda b: (b, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((DMA_DEPTH, 1, D), table.dtype),
            pltpu.SemaphoreType.DMA((DMA_DEPTH,)),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * B_pad * L * D,
            bytes_accessed=B_pad * L * D * table.dtype.itemsize
            + B_pad * D * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(tiled_ids, tiled_w, table.reshape(V, 1, D))
    return out[:B]


# ----------------------------------------------------------------- fallback
def _embedding_bag_xla(
    table: jax.Array, ids: jax.Array, weights: jax.Array
) -> jax.Array:
    """Gather + weighted sum; materializes [B, L, D] in HBM.

    Precision is PINNED to HIGHEST: the jitted default already contracts
    f32 inputs via 3-pass bf16 (f32-level accuracy, measured err 9e-8),
    but the eager default and ``jax_default_matmul_precision='bfloat16'``
    would silently drop to single-pass bf16 (~2 digits) — the public op
    must not lose accuracy based on how it's called."""
    rows = table[ids]  # [B, L, D]
    return jnp.einsum(
        "bld,bl->bd",
        rows.astype(jnp.float32),
        weights.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )


#: dispatch cutoff: intermediates up to this many MB take the (faster)
#: XLA path; beyond it the kernel's O(1) scratch wins (a [B, L, D] gather
#: several GB deep crowds HBM; past HBM size XLA cannot run at all)
_PALLAS_OVER_MB_DEFAULT = 2048.0


def _pallas_cutoff_bytes() -> float:

    return knobs.knob_float("PIO_TPU_EMBED_PALLAS_OVER_MB") * 2 ** 20


def _use_pallas(table) -> bool:
    # Mosaic single-row DMA slices must be lane-aligned: D % 128. Smaller
    # tables are cheap XLA gathers anyway (they fit VMEM).
    try:
        if table.shape[1] % 128 != 0:
            return False
        # a committed concrete array knows its platform — a CPU-resident
        # table under jax.default_device(cpu) must NOT take the Mosaic
        # path even when the process default backend is TPU (the bench's
        # own-CPU anchor runs exactly that way)
        devs = getattr(table, "devices", None)
        if callable(devs):
            ds = devs()
            if ds:
                return next(iter(ds)).platform == "tpu"
        return jax.default_backend() == "tpu"
    except Exception:  # tracers under jit: fall back to the backend
        try:
            return (
                jax.default_backend() == "tpu"
                and table.shape[1] % 128 == 0
            )
        except Exception:  # pragma: no cover
            return False


# ------------------------------------------------------------------- public
@jax.custom_vjp
def embedding_bag(table, ids, weights):
    """``out[b] = Σ_l weights[b,l] · table[ids[b,l]]`` → float32 [B, D].

    ``ids`` int32 [B, L] (pad with any valid row + weight 0), ``weights``
    [B, L]. Differentiable in ``table`` and ``weights``. Dispatch: XLA
    while the gathered ``[B, L, D]`` intermediate fits comfortably (it
    measured faster at equal accuracy — see module docstring), the
    Pallas streaming kernel beyond that (O(1) scratch; shapes XLA OOMs
    on)."""
    B, L = ids.shape
    D = table.shape[1]
    intermediate = B * L * D * max(4, table.dtype.itemsize)
    if _use_pallas(table) and intermediate > _pallas_cutoff_bytes():
        return _embedding_bag_pallas(table, ids, weights)
    return _embedding_bag_xla(table, ids, weights)


def _fwd(table, ids, weights):
    return embedding_bag(table, ids, weights), (table, ids, weights)


def _bwd(res, g):
    table, ids, weights = res
    V, D = table.shape
    B, L = ids.shape
    # d table: scatter-add of g[b] * w[b,l] into row ids[b,l] — a segment
    # sum over the flattened edge list (XLA; scatters don't ride the MXU).
    contrib = (g[:, None, :] * weights[:, :, None].astype(g.dtype)).reshape(
        B * L, D
    )
    d_table = jax.ops.segment_sum(
        contrib, ids.reshape(-1), num_segments=V
    ).astype(table.dtype)
    # d weights: dot of g[b] with the gathered row.
    rows = table[ids].astype(g.dtype)  # [B, L, D]
    d_w = jnp.einsum("bld,bd->bl", rows, g).astype(weights.dtype)
    return d_table, None, d_w


embedding_bag.defvjp(_fwd, _bwd)


# --------------------------------------------------- host-side bag packing
def pack_bags(
    indices_per_bag, weights_per_bag, max_len: int | None = None
):
    """Ragged per-bag (ids, weights) lists → padded int32/float32 arrays.

    Pads with id 0 / weight 0 (contributes exactly zero). ``max_len`` is
    rounded up to a multiple of 8 so the token stream tiles evenly.
    """
    B = len(indices_per_bag)
    L = max_len or max((len(x) for x in indices_per_bag), default=1)
    L = max(1, _round_up(L, 8))
    ids = np.zeros((B, L), np.int32)
    w = np.zeros((B, L), np.float32)
    for b, (ix, wx) in enumerate(zip(indices_per_bag, weights_per_bag)):
        n = min(len(ix), L)
        ids[b, :n] = np.asarray(ix[:n], np.int32)
        w[b, :n] = np.asarray(wx[:n], np.float32)
    return ids, w
