"""Pallas TPU kernels for the framework's sparse hot paths.

The reference's compute substrate (Spark/MLlib) has no custom kernels — its
hot loops are RDD shuffles and JVM math. Here the XLA-resistant ops get
hand-written Pallas TPU kernels with plain-XLA fallbacks for CPU:

- :func:`embedding_bag` — weighted embedding-bag lookup (TF-IDF × table,
  feature-bag × table) streaming rows HBM→VMEM via an async-DMA ring.
- :class:`DeviceTopNScorer` — device-resident factor scoring for serving
  (upload once at deploy, jitted matmul + top-k per request).
"""

from pio_tpu.ops.embedding import embedding_bag, pack_bags
from pio_tpu.ops.topn import DeviceTopNScorer

__all__ = ["embedding_bag", "pack_bags", "DeviceTopNScorer"]
