"""``QoSGate`` — the per-service composition of policy, limiters,
breakers, and the stale cache, with its metrics pre-registered so pool
workers bind them into the shared segment.

Both servers build one gate in ``__init__`` (BEFORE any pool binding —
slot assignment is by registration order) and consult it at the top of
every request handler. Shed decisions return an :class:`Admission` the
handler turns into a 429/503 + ``Retry-After`` or a stale-cache serve.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Optional

from pio_tpu.analysis.runtime import make_lock
from pio_tpu.qos.breaker import STATE_CODES, CircuitBreaker
from pio_tpu.qos.degrade import StaleCache
from pio_tpu.qos.limiter import ConcurrencyLimiter, KeyedBuckets, TokenBucket
from pio_tpu.qos.policy import QoSPolicy, priority_floor

#: every shed reason, pre-created so the counter cells exist at
#: pool-bind time (cells created later would stay local-only)
SHED_REASONS = (
    "rate_limit", "key_rate_limit", "queue_full", "queue_timeout",
    "deadline", "breaker",
)


class Admission:
    """Outcome of :meth:`QoSGate.admit`. When ``ok``, call
    :meth:`release` exactly once after the request finishes; when shed,
    ``reason`` names the cause and ``retry_after_s`` hints the client."""

    __slots__ = ("ok", "reason", "retry_after_s", "_gate", "_released",
                 "queue_wait_s")

    def __init__(self, ok: bool, reason: Optional[str] = None,
                 retry_after_s: float = 0.0, gate: "QoSGate" = None,
                 queue_wait_s: float = 0.0):
        self.ok = ok
        self.reason = reason
        self.retry_after_s = retry_after_s
        self._gate = gate
        self._released = False
        #: seconds spent blocked in the concurrency limiter's admission
        #: queue — the server turns this into an ``admit.queue`` span
        self.queue_wait_s = queue_wait_s

    def release(self) -> None:
        if self.ok and not self._released and self._gate is not None:
            self._released = True
            self._gate._release()

    def retry_after_header(self) -> Dict[str, str]:
        return retry_after_header(self.retry_after_s)


def retry_after_header(retry_after_s: float) -> Dict[str, str]:
    """``Retry-After`` is delta-seconds, integral, minimum 1 — a 0 would
    invite an instant retry storm from well-behaved clients."""
    return {"Retry-After": str(max(int(math.ceil(retry_after_s)), 1))}


class QoSGate:
    def __init__(self, policy: QoSPolicy, registry, scope: str,
                 clock=None):
        from pio_tpu.obs.metrics import monotonic_s

        self.policy = policy
        self.scope = scope
        self._clock = clock or monotonic_s

        # -- metrics (pre-created: pool binding is by registration order)
        self.shed_total = registry.counter(
            "pio_tpu_qos_shed_total",
            "Requests rejected by admission control, by reason",
            labelnames=("scope", "reason"),
        )
        for reason in SHED_REASONS:
            self.shed_total.labels(scope, reason)
        self.degraded_total = registry.counter(
            "pio_tpu_qos_degraded_total",
            "Requests answered from the stale cache instead of shed",
            labelnames=("scope",),
        )
        self.degraded_total.labels(scope)
        admitted = registry.counter(
            "pio_tpu_qos_admitted_total",
            "Requests admitted past the engine token bucket "
            "(each worker's stripe carries its own admissions; the "
            "pool-wide sum is the shared budget's consumption)",
            labelnames=("scope",),
        )
        self._admitted_cell = admitted.labels(scope)
        self.inflight_gauge = registry.gauge(
            "pio_tpu_qos_inflight",
            "Requests currently executing past admission (this worker)",
            labelnames=("scope",),
        )
        self.queue_gauge = registry.gauge(
            "pio_tpu_qos_queue_depth",
            "Requests waiting in the bounded admission queue (this worker)",
            labelnames=("scope",),
        )
        self.inflight_gauge.set(0, scope=scope)
        self.queue_gauge.set(0, scope=scope)
        self.breaker_state_gauge = registry.gauge(
            "pio_tpu_qos_breaker_state",
            "Circuit breaker state (0=closed, 1=open, 2=half_open)",
            labelnames=("scope", "dependency"),
        )

        # -- mechanisms (each enabled only when its knob is set)
        self.bucket: Optional[TokenBucket] = None
        if policy.rps:
            self.bucket = TokenBucket(
                policy.rps, policy.effective_burst(),
                cell=self._admitted_cell, clock=self._clock,
            )
        self.key_buckets: Optional[KeyedBuckets] = None
        if policy.key_rps:
            self.key_buckets = KeyedBuckets(
                policy.key_rps, policy.effective_key_burst(),
                clock=self._clock,
            )
        self.limiter: Optional[ConcurrencyLimiter] = None
        if policy.inflight:
            self.limiter = ConcurrencyLimiter(
                policy.inflight, policy.queue or 0, clock=self._clock,
            )
        self.stale: Optional[StaleCache] = None
        if policy.cache:
            self.stale = StaleCache(policy.cache)
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._breaker_lock = make_lock("qos.gate.breakers")

    # -- pool --------------------------------------------------------------
    def on_pool_bound(self) -> None:
        """Call right after ``registry.bind_pool_segment`` so the bucket
        doesn't treat pre-existing stripe totals as fresh admissions."""
        if self.bucket is not None:
            self.bucket.rebase()

    # -- breakers ----------------------------------------------------------
    def breaker(self, dependency: str) -> CircuitBreaker:
        """The named breaker (created on first use, watched by a state
        gauge)."""
        with self._breaker_lock:
            b = self._breakers.get(dependency)
            if b is None:
                gauge, scope = self.breaker_state_gauge, self.scope

                def on_change(state, _dep=dependency):
                    gauge.set(
                        STATE_CODES[state], scope=scope, dependency=_dep
                    )

                b = CircuitBreaker(
                    failure_rate=self.policy.fail_rate,
                    window=self.policy.fail_window,
                    cooldown_s=self.policy.cooldown_s,
                    probes=self.policy.probes,
                    clock=self._clock,
                    on_state_change=on_change,
                )
                gauge.set(0.0, scope=scope, dependency=dependency)
                self._breakers[dependency] = b
            return b

    # -- admission ---------------------------------------------------------
    def admit(self, priority: Optional[str] = None,
              key: Optional[str] = None,
              timeout_s: Optional[float] = None) -> Admission:
        """Run the cheap checks in shedding order: engine bucket, per-key
        bucket, then the concurrency gate (the only one that queues).
        ``timeout_s`` bounds the queue wait (a deadline's remaining
        budget); sheds are NOT counted here — the caller counts them via
        :meth:`count_shed` once it knows whether the stale cache saved
        the request."""
        floor = priority_floor(priority)
        if self.bucket is not None:
            ok, retry = self.bucket.try_acquire(floor=floor)
            if not ok:
                return Admission(False, "rate_limit", retry, self)
        if self.key_buckets is not None and key:
            ok, retry = self.key_buckets.try_acquire(key, floor=floor)
            if not ok:
                return Admission(False, "key_rate_limit", retry, self)
        queue_wait_s = 0.0
        if self.limiter is not None:
            self.queue_gauge.set(
                self.limiter.queued + 1, scope=self.scope
            )
            t_enter = self._clock()
            outcome = self.limiter.enter(timeout_s)
            queue_wait_s = self._clock() - t_enter
            self.queue_gauge.set(self.limiter.queued, scope=self.scope)
            if outcome != ConcurrencyLimiter.OK:
                reason = (
                    "queue_full"
                    if outcome == ConcurrencyLimiter.QUEUE_FULL
                    else "queue_timeout"
                )
                # a full queue drains at roughly max_inflight per
                # service time; 1s is an honest coarse hint
                return Admission(False, reason, 1.0, self,
                                 queue_wait_s=queue_wait_s)
            self.inflight_gauge.set(self.limiter.inflight, scope=self.scope)
        return Admission(True, gate=self, queue_wait_s=queue_wait_s)

    def _release(self) -> None:
        if self.limiter is not None:
            self.limiter.exit()
            self.inflight_gauge.set(self.limiter.inflight, scope=self.scope)
            self.queue_gauge.set(self.limiter.queued, scope=self.scope)

    # -- accounting --------------------------------------------------------
    def count_shed(self, reason: str) -> None:
        self.shed_total.inc(scope=self.scope, reason=reason)

    def count_degraded(self) -> None:
        self.degraded_total.inc(scope=self.scope)

    # -- /qos.json ---------------------------------------------------------
    # pio: endpoint=/qos.json
    def snapshot(self) -> dict:
        out = {
            "enabled": True,
            "scope": self.scope,
            "policy": self.policy.to_dict(),
            "shed": {
                reason: self.shed_total.value(self.scope, reason)
                for reason in SHED_REASONS
            },
            "degraded": self.degraded_total.value(self.scope),
            "admitted": self._admitted_cell._pool_value(),
            "breakers": {
                dep: b.snapshot() for dep, b in self._breakers.items()
            },
        }
        if self.bucket is not None:
            out["bucket"] = {
                "rate": self.bucket.rate,
                "burst": self.bucket.burst,
                "tokens": round(self.bucket.level(), 3),
            }
        if self.key_buckets is not None:
            out["keyBuckets"] = {
                "rate": self.key_buckets.rate,
                "burst": self.key_buckets.burst,
                "keys": len(self.key_buckets),
            }
        if self.limiter is not None:
            out["concurrency"] = {
                "maxInflight": self.limiter.max_inflight,
                "maxQueue": self.limiter.max_queue,
                "inflight": self.limiter.inflight,
                "queued": self.limiter.queued,
            }
        if self.stale is not None:
            out["staleCache"] = self.stale.stats()
        return out
