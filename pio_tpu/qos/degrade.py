"""Graceful degradation: a size-bounded LRU of recent query→response
pairs. When the breaker is open or admission shedding kicks in, a query
the server answered recently gets that stale answer back — explicitly
marked ``X-Pio-Degraded: stale-cache`` — instead of a hard 429/503. A
slightly old recommendation beats an error page; the marker keeps the
client honest about what it received.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import Any, Optional

#: response header marking a degraded (stale) answer
DEGRADED_HEADER = "X-Pio-Degraded"
DEGRADED_VALUE = "stale-cache"


def cache_key(query: Any) -> str:
    """Canonical key for a parsed query body (sorted-key JSON, so
    ``{"user": "u1", "num": 3}`` and ``{"num": 3, "user": "u1"}`` hit
    the same entry)."""
    try:
        return json.dumps(query, sort_keys=True, default=str)
    except (TypeError, ValueError):
        return repr(query)


class StaleCache:
    """Thread-safe LRU: ``capacity`` most-recently-touched entries."""

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be > 0")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._d: "OrderedDict[str, Any]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self.hits += 1
                return self._d[key]
            self.misses += 1
            return None

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    # pio: endpoint=/qos.json
    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._d),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
            }
