"""Circuit breaker (closed → open → half-open) with failure-RATE
tripping over a bounded outcome window.

Guards the two dependencies a serving request leans on — storage reads
and scorer calls. A dependency that is failing for everyone should fail
FAST for everyone: tripping converts a pile-up of slow errors into
immediate sheds (which the degradation layer may turn into stale
answers), and the half-open probe trickle discovers recovery without a
thundering herd.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Tuple

from pio_tpu.analysis.runtime import make_lock
from pio_tpu.obs.metrics import monotonic_s

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

#: numeric state for the ``pio_tpu_qos_breaker_state`` gauge
STATE_CODES = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}


class BreakerCall:
    """One guarded call, handed out by :meth:`CircuitBreaker.acquire`.

    Exactly one of :meth:`success` / :meth:`failure` after the call;
    :meth:`cancel` in a ``finally`` releases an ABANDONED grant (a path
    that never reached the dependency — parse errors, deadline sheds)
    without recording an outcome. All three are idempotent-once, so
    ``cancel`` after ``success``/``failure`` is a no-op and the finally
    can run it unconditionally — a half-open probe grant can therefore
    never leak, whatever exit the handler takes.

    The call is tagged with the breaker generation at grant time; an
    outcome recorded after the breaker changed state (a straggler
    admitted under the previous CLOSED epoch finishing in HALF_OPEN) is
    dropped instead of polluting the new state's probe accounting.
    """

    __slots__ = ("allowed", "retry_after_s", "_breaker", "_gen", "_probe",
                 "_done")

    def __init__(self, breaker: "CircuitBreaker", allowed: bool,
                 retry_after_s: float, gen: int, probe: bool):
        self.allowed = allowed
        self.retry_after_s = retry_after_s
        self._breaker = breaker
        self._gen = gen
        self._probe = probe
        self._done = not allowed  # a refused call has nothing to record

    def success(self) -> None:
        self._finish(failed=False)

    def failure(self) -> None:
        self._finish(failed=True)

    def cancel(self) -> None:
        """Release the grant without an outcome (call abandoned before
        it touched the dependency). No-op after success/failure."""
        self._finish(failed=False, abandoned=True)

    def _finish(self, failed: bool, abandoned: bool = False) -> None:
        if self._done:
            return
        self._done = True
        self._breaker._record(self._gen, self._probe, failed, abandoned)


class CircuitBreaker:
    """:meth:`acquire` before the call, then exactly one of
    ``success()`` / ``failure()`` on the returned :class:`BreakerCall`
    (with ``cancel()`` in a finally for abandoned paths). The legacy
    ``allow()`` / ``record_success()`` / ``record_failure()`` trio is
    kept for simple bracketed callers.

    - CLOSED: everything passes; the last ``window`` outcomes are kept,
      and once ≥ ``window`` samples show a failure fraction ≥
      ``failure_rate`` the breaker opens.
    - OPEN: every call is refused (with the cooldown remaining as a
      Retry-After hint) until ``cooldown_s`` elapses, then HALF_OPEN.
    - HALF_OPEN: up to ``probes`` calls pass; any probe failure reopens,
      ``probes`` probe successes close and clear the window. Outcomes
      from calls granted under an earlier state (generation mismatch)
      are ignored — stragglers can neither close nor reopen it.
    """

    def __init__(self, failure_rate: float = 0.5, window: int = 20,
                 cooldown_s: float = 5.0, probes: int = 3,
                 clock: Callable[[], float] = monotonic_s,
                 on_state_change: Optional[Callable[[str], None]] = None):
        if not 0.0 < failure_rate <= 1.0:
            raise ValueError("failure_rate must be in (0, 1]")
        self.failure_rate = failure_rate
        self.window = max(int(window), 1)
        self.cooldown_s = cooldown_s
        self.probes = max(int(probes), 1)
        self._clock = clock
        self._on_change = on_state_change
        self._lock = make_lock("qos.breaker")
        self._state = CLOSED
        self._outcomes = []  # bounded ring of bools (True = failure)
        self._opened_at = 0.0
        self._probe_inflight = 0
        self._probe_successes = 0
        #: bumped on every state change; outcomes carry the generation
        #: they were granted under and stale ones are dropped
        self._gen = 0

    # -- state -------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _transition_locked(self, state: str) -> None:
        if state == self._state:
            return
        self._state = state
        self._gen += 1
        if state == OPEN:
            self._opened_at = self._clock()
        if state in (OPEN, HALF_OPEN):
            self._probe_inflight = 0
            self._probe_successes = 0
        if state == CLOSED:
            self._outcomes.clear()
        if self._on_change is not None:
            try:
                self._on_change(state)
            except Exception:
                pass  # a metrics/log hook must never wedge the breaker

    def _maybe_half_open_locked(self) -> None:
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.cooldown_s:
            self._transition_locked(HALF_OPEN)

    # -- call protocol -----------------------------------------------------
    def acquire(self) -> BreakerCall:
        """Grant or refuse one call; the returned handle carries the
        Retry-After hint when refused and records the outcome (or
        releases an abandoned grant) when allowed."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == CLOSED:
                return BreakerCall(self, True, 0.0, self._gen, False)
            if self._state == OPEN:
                retry = max(
                    self.cooldown_s - (self._clock() - self._opened_at), 0.0
                )
                return BreakerCall(self, False, retry, self._gen, False)
            # HALF_OPEN: a bounded probe trickle
            if self._probe_inflight < self.probes:
                self._probe_inflight += 1
                return BreakerCall(self, True, 0.0, self._gen, True)
            return BreakerCall(self, False, 0.0, self._gen, False)

    def allow(self) -> Tuple[bool, float]:
        """Legacy ``(allowed, retry_after_s)`` — retry_after is the
        cooldown remaining when refused (0 when refused only by probe
        contention). Prefer :meth:`acquire`, whose handle cannot leak a
        probe grant and ignores cross-state stragglers."""
        call = self.acquire()
        return call.allowed, call.retry_after_s

    def record_success(self) -> None:
        with self._lock:
            self._record_locked(
                self._gen, self._state == HALF_OPEN, failed=False,
                abandoned=False,
            )

    def record_failure(self) -> None:
        with self._lock:
            self._record_locked(
                self._gen, self._state == HALF_OPEN, failed=True,
                abandoned=False,
            )

    def _record(self, gen: int, probe: bool, failed: bool,
                abandoned: bool) -> None:
        with self._lock:
            self._record_locked(gen, probe, failed, abandoned)

    def _record_locked(self, gen: int, probe: bool, failed: bool,
                       abandoned: bool) -> None:
        if gen != self._gen:
            # granted under a previous state: its probe/window counters
            # were reset at the transition, so there is nothing to
            # release and counting the outcome would let stragglers
            # close (or reopen) a breaker no real probe has touched
            return
        if self._state == HALF_OPEN:
            if not probe:
                return  # pre-half-open straggler (legacy untagged only)
            self._probe_inflight = max(self._probe_inflight - 1, 0)
            if abandoned:
                return  # grant released, no outcome to count
            if failed:
                # the dependency is still sick — restart the cooldown
                self._transition_locked(OPEN)
                return
            self._probe_successes += 1
            if self._probe_successes >= self.probes:
                self._transition_locked(CLOSED)
            return
        if abandoned or self._state == OPEN:
            return
        self._record_outcome_locked(failed)
        if failed:
            n = len(self._outcomes)
            if n >= self.window:
                fails = sum(1 for f in self._outcomes if f)
                if fails / n >= self.failure_rate:
                    self._transition_locked(OPEN)

    def _record_outcome_locked(self, failed: bool) -> None:
        self._outcomes.append(failed)
        if len(self._outcomes) > self.window:
            del self._outcomes[0]

    # pio: endpoint=/qos.json
    def snapshot(self) -> dict:
        with self._lock:
            self._maybe_half_open_locked()
            n = len(self._outcomes)
            return {
                "state": self._state,
                "windowSamples": n,
                "windowFailures": sum(1 for f in self._outcomes if f),
                "cooldownRemainingS": (
                    max(self.cooldown_s
                        - (self._clock() - self._opened_at), 0.0)
                    if self._state == OPEN else 0.0
                ),
            }
