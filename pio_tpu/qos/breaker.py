"""Circuit breaker (closed → open → half-open) with failure-RATE
tripping over a bounded outcome window.

Guards the two dependencies a serving request leans on — storage reads
and scorer calls. A dependency that is failing for everyone should fail
FAST for everyone: tripping converts a pile-up of slow errors into
immediate sheds (which the degradation layer may turn into stale
answers), and the half-open probe trickle discovers recovery without a
thundering herd.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Tuple

from pio_tpu.obs.metrics import monotonic_s

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

#: numeric state for the ``pio_tpu_qos_breaker_state`` gauge
STATE_CODES = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}


class CircuitBreaker:
    """``allow()`` before the call, then exactly one of
    ``record_success()`` / ``record_failure()`` after it.

    - CLOSED: everything passes; the last ``window`` outcomes are kept,
      and once ≥ ``window`` samples show a failure fraction ≥
      ``failure_rate`` the breaker opens.
    - OPEN: every call is refused (with the cooldown remaining as a
      Retry-After hint) until ``cooldown_s`` elapses, then HALF_OPEN.
    - HALF_OPEN: up to ``probes`` calls pass; any failure reopens,
      ``probes`` successes close and clear the window.
    """

    def __init__(self, failure_rate: float = 0.5, window: int = 20,
                 cooldown_s: float = 5.0, probes: int = 3,
                 clock: Callable[[], float] = monotonic_s,
                 on_state_change: Optional[Callable[[str], None]] = None):
        if not 0.0 < failure_rate <= 1.0:
            raise ValueError("failure_rate must be in (0, 1]")
        self.failure_rate = failure_rate
        self.window = max(int(window), 1)
        self.cooldown_s = cooldown_s
        self.probes = max(int(probes), 1)
        self._clock = clock
        self._on_change = on_state_change
        self._lock = threading.Lock()
        self._state = CLOSED
        self._outcomes = []  # bounded ring of bools (True = failure)
        self._opened_at = 0.0
        self._probe_inflight = 0
        self._probe_successes = 0

    # -- state -------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _transition_locked(self, state: str) -> None:
        if state == self._state:
            return
        self._state = state
        if state == OPEN:
            self._opened_at = self._clock()
        if state in (OPEN, HALF_OPEN):
            self._probe_inflight = 0
            self._probe_successes = 0
        if state == CLOSED:
            self._outcomes.clear()
        if self._on_change is not None:
            try:
                self._on_change(state)
            except Exception:
                pass  # a metrics/log hook must never wedge the breaker

    def _maybe_half_open_locked(self) -> None:
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.cooldown_s:
            self._transition_locked(HALF_OPEN)

    # -- call protocol -----------------------------------------------------
    def allow(self) -> Tuple[bool, float]:
        """``(allowed, retry_after_s)`` — retry_after is the cooldown
        remaining when refused (0 when refused only by probe contention)."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == CLOSED:
                return True, 0.0
            if self._state == OPEN:
                return False, max(
                    self.cooldown_s - (self._clock() - self._opened_at), 0.0
                )
            # HALF_OPEN: a bounded probe trickle
            if self._probe_inflight < self.probes:
                self._probe_inflight += 1
                return True, 0.0
            return False, 0.0

    def record_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_inflight = max(self._probe_inflight - 1, 0)
                self._probe_successes += 1
                if self._probe_successes >= self.probes:
                    self._transition_locked(CLOSED)
                return
            self._record_outcome_locked(False)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # the dependency is still sick — restart the cooldown
                self._transition_locked(OPEN)
                return
            if self._state == OPEN:
                return
            self._record_outcome_locked(True)
            n = len(self._outcomes)
            if n >= self.window:
                fails = sum(1 for f in self._outcomes if f)
                if fails / n >= self.failure_rate:
                    self._transition_locked(OPEN)

    def _record_outcome_locked(self, failed: bool) -> None:
        self._outcomes.append(failed)
        if len(self._outcomes) > self.window:
            del self._outcomes[0]

    def snapshot(self) -> dict:
        with self._lock:
            self._maybe_half_open_locked()
            n = len(self._outcomes)
            return {
                "state": self._state,
                "windowSamples": n,
                "windowFailures": sum(1 for f in self._outcomes if f),
                "cooldownRemainingS": (
                    max(self.cooldown_s
                        - (self._clock() - self._opened_at), 0.0)
                    if self._state == OPEN else 0.0
                ),
            }
