"""Admission control & QoS: pool-wide rate limiting, deadline-aware
shedding, circuit breaking, and graceful degradation.

PR 2's SLO engine can *observe* an overload; this package *acts* on one.
The pieces, in request order:

- :mod:`policy` — priority classes + the ``rps=500,queue=64,deadline=100ms``
  spec grammar (``pio deploy --qos`` / ``PIO_TPU_QOS`` / engine.json
  ``qos`` block);
- :mod:`limiter` — token buckets (per engine, per access key; pool-wide
  via the obs shared-memory segment) and a concurrency limiter with a
  bounded admission queue;
- :mod:`deadline` — ``X-Pio-Deadline-Ms`` propagation into the
  micro-batcher, shedding expired-in-queue work before execution;
- :mod:`breaker` — closed/open/half-open circuit breakers around storage
  and scorer calls;
- :mod:`degrade` — a bounded LRU serving explicitly-marked stale
  responses (``X-Pio-Degraded: stale-cache``) instead of hard 503s;
- :mod:`gate` — the per-service composition + metrics
  (``pio_tpu_qos_shed_total{reason}``, inflight/queue gauges, breaker
  state) surfaced on ``GET /qos.json``.
"""

from pio_tpu.qos.breaker import BreakerCall, CircuitBreaker
from pio_tpu.qos.deadline import (
    DEADLINE_HEADER,
    Deadline,
    DeadlineExceeded,
    parse_deadline_ms,
)
from pio_tpu.qos.degrade import (
    DEGRADED_HEADER,
    DEGRADED_VALUE,
    StaleCache,
    cache_key,
)
from pio_tpu.qos.gate import (
    Admission,
    QoSGate,
    SHED_REASONS,
    retry_after_header,
)
from pio_tpu.qos.limiter import ConcurrencyLimiter, KeyedBuckets, TokenBucket
from pio_tpu.qos.policy import (
    PRIORITY_CLASSES,
    PRIORITY_FLOORS,
    PRIORITY_HEADER,
    QoSError,
    QoSPolicy,
    parse_qos,
    policy_from_dict,
    priority_floor,
    resolve_policy,
)

__all__ = [
    "Admission",
    "BreakerCall",
    "CircuitBreaker",
    "ConcurrencyLimiter",
    "DEADLINE_HEADER",
    "DEGRADED_HEADER",
    "DEGRADED_VALUE",
    "Deadline",
    "DeadlineExceeded",
    "KeyedBuckets",
    "PRIORITY_CLASSES",
    "PRIORITY_FLOORS",
    "PRIORITY_HEADER",
    "QoSError",
    "QoSGate",
    "QoSPolicy",
    "SHED_REASONS",
    "StaleCache",
    "TokenBucket",
    "cache_key",
    "parse_deadline_ms",
    "parse_qos",
    "policy_from_dict",
    "priority_floor",
    "resolve_policy",
    "retry_after_header",
]
