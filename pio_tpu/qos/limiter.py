"""Token-bucket rate limiting + bounded-queue concurrency limiting.

Pool-wide enforcement rides the observability shared-memory segment
(:mod:`pio_tpu.obs.shm`): that segment is single-writer-per-stripe, so a
classic shared bucket (every worker CASing one tokens cell) is off the
table. Instead each worker keeps a LOCAL bucket refilled at the FULL
pool rate and mirrors its own admission count into its stripe through a
pool-bound counter cell. Before deciding, a worker deducts the
admissions the *other* workers made since it last looked (pool sum minus
what it already accounted for). Every worker therefore converges on the
same pool-wide bucket level and ``--workers N`` shares ONE budget — the
race window is a single in-flight admission per peer, not N× the rate.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Optional, Tuple

from pio_tpu.analysis.runtime import make_condition, make_lock
from pio_tpu.obs.metrics import monotonic_s


class TokenBucket:
    """Thread-safe token bucket.

    ``cell`` (optional) is a metrics counter cell mirroring this
    worker's admission count into the pool segment — when bound, the
    bucket deducts every peer worker's admissions too, making the budget
    pool-wide. ``floor`` on :meth:`try_acquire` reserves a fraction of
    the burst for higher-priority classes (see ``policy.PRIORITY_FLOORS``).
    """

    def __init__(self, rate: float, burst: float, cell=None,
                 clock: Callable[[], float] = monotonic_s):
        if rate <= 0:
            raise ValueError("rate must be > 0")
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self._cell = cell
        self._clock = clock
        self._lock = make_lock("qos.bucket")
        self._tokens = self.burst
        self._last = clock()
        #: pool-wide admitted total already deducted from ``_tokens``
        self._seen = self._pool_total()

    def _pool_total(self) -> float:
        return self._cell._pool_value() if self._cell is not None else 0.0

    def rebase(self) -> None:
        """Forget pool history — call right after the cell is bound to
        the shared segment, so admissions that predate this worker (or
        survive in an adopted respawn stripe) don't drain a fresh bucket."""
        with self._lock:
            self._seen = self._pool_total()

    def _refill_locked(self) -> None:
        now = self._clock()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate
        )
        self._last = now
        pool = self._pool_total()
        if pool > self._seen:  # peers admitted since we last looked
            self._tokens -= pool - self._seen
            self._seen = pool

    def try_acquire(self, cost: float = 1.0,
                    floor: float = 0.0) -> Tuple[bool, float]:
        """``(admitted, retry_after_s)``. Admission requires the bucket
        to keep ``floor * burst`` tokens AFTER paying ``cost``."""
        reserve = floor * self.burst
        with self._lock:
            self._refill_locked()
            if self._tokens - cost >= reserve:
                self._tokens -= cost
                if self._cell is not None:
                    self._cell._add(cost)
                    self._seen += cost  # ours: already deducted above
                return True, 0.0
            need = reserve + cost - self._tokens
            return False, need / self.rate

    def level(self) -> float:
        """Current token count (for ``/qos.json``)."""
        with self._lock:
            self._refill_locked()
            return max(self._tokens, 0.0)


class KeyedBuckets:
    """Lazily-created per-key token buckets (access-key rate limits on
    the event server). Local to the process; bounded: least-recently-hit
    keys are evicted past ``max_keys`` — an evicted hot key merely
    restarts with a full bucket."""

    def __init__(self, rate: float, burst: float, max_keys: int = 4096,
                 clock: Callable[[], float] = monotonic_s):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self.max_keys = max_keys
        self._clock = clock
        self._lock = make_lock("qos.keyed_buckets")
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()

    def _bucket(self, key: str) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(key)
            if b is None:
                b = TokenBucket(self.rate, self.burst, clock=self._clock)
                self._buckets[key] = b
                while len(self._buckets) > self.max_keys:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(key)
            return b

    def try_acquire(self, key: str, cost: float = 1.0,
                    floor: float = 0.0) -> Tuple[bool, float]:
        return self._bucket(key).try_acquire(cost, floor)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buckets)


class ConcurrencyLimiter:
    """``max_inflight`` concurrent executions with a bounded admission
    queue of ``max_queue`` waiters behind them; anyone beyond that is
    shed immediately (the whole point — waiting costs a server thread,
    and an unbounded queue is just a slower way to fall over)."""

    #: :meth:`enter` outcomes
    OK, QUEUE_FULL, TIMEOUT = "ok", "queue_full", "timeout"

    def __init__(self, max_inflight: int, max_queue: int = 0,
                 clock: Callable[[], float] = monotonic_s):
        if max_inflight <= 0:
            raise ValueError("max_inflight must be > 0")
        self.max_inflight = int(max_inflight)
        self.max_queue = max(int(max_queue), 0)
        self._clock = clock
        self._cond = make_condition("qos.limiter")
        self._inflight = 0
        self._waiting = 0

    def enter(self, timeout_s: Optional[float] = None) -> str:
        """Take a slot, queueing up to ``timeout_s`` (None ⇒ wait for a
        slot indefinitely). Returns OK / QUEUE_FULL / TIMEOUT."""
        deadline = (
            None if timeout_s is None else self._clock() + timeout_s
        )
        with self._cond:
            if self._inflight < self.max_inflight:
                self._inflight += 1
                return self.OK
            if self._waiting >= self.max_queue:
                return self.QUEUE_FULL
            self._waiting += 1
            try:
                while self._inflight >= self.max_inflight:
                    if deadline is None:
                        self._cond.wait(0.5)
                        continue
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return self.TIMEOUT
                    self._cond.wait(remaining)
                self._inflight += 1
                return self.OK
            finally:
                self._waiting -= 1
                # lost-wakeup guard: exit() notifies ONE waiter. If that
                # notify landed on us and we leave without taking the
                # freed slot (deadline passed → TIMEOUT), or slots remain
                # after we took ours, pass the baton so the capacity is
                # used now instead of idling until another waiter's
                # timeout or poll tick.
                if self._inflight < self.max_inflight and self._waiting > 0:
                    self._cond.notify()

    def exit(self) -> None:
        with self._cond:
            self._inflight -= 1
            self._cond.notify()

    @property
    def inflight(self) -> int:
        return self._inflight

    @property
    def queued(self) -> int:
        return self._waiting
