"""QoS policy: priority classes + the ``rps=500,queue=64,deadline=100ms``
spec grammar shared by ``pio deploy --qos``, the ``PIO_TPU_QOS``
environment variable, and the ``engine.json`` ``qos`` block.

Precedence (highest wins): explicit spec (CLI flag / constructor arg) >
``PIO_TPU_QOS`` > ``engine.json``. No source at all means QoS is OFF —
the servers behave exactly as before this subsystem existed.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional, Union

from pio_tpu.utils import knobs
from pio_tpu.obs import parse_duration_s


class QoSError(ValueError):
    pass


#: Priority classes, most- to least-important. Lower classes see a HIGHER
#: token-bucket floor: a ``shadow`` request is only admitted while the
#: bucket still holds >50% of its burst, ``batchpredict`` >25%, so under
#: pressure the background traffic is shed first and ``interactive``
#: queries keep the whole remaining budget.
PRIORITY_CLASSES = ("interactive", "batchpredict", "shadow")
PRIORITY_FLOORS: Dict[str, float] = {
    "interactive": 0.0,
    "batchpredict": 0.25,
    "shadow": 0.5,
}

#: Request header naming the priority class (unknown/absent ⇒ interactive).
PRIORITY_HEADER = "X-Pio-Priority"


def priority_floor(name: Optional[str]) -> float:
    """Bucket floor (fraction of burst that must remain) for a priority
    class name; unknown names are treated as ``interactive`` — a typo'd
    header must not silently deprioritize a user query."""
    return PRIORITY_FLOORS.get((name or "interactive").strip().lower(), 0.0)


@dataclasses.dataclass(frozen=True)
class QoSPolicy:
    """Parsed admission-control policy. Every knob is optional; an unset
    knob disables that mechanism (``rps=None`` ⇒ no rate limit, …)."""

    #: engine-wide admission rate (requests/second) + bucket depth
    rps: Optional[float] = None
    burst: Optional[float] = None
    #: per-access-key rate (event-server ingest) + bucket depth
    key_rps: Optional[float] = None
    key_burst: Optional[float] = None
    #: concurrency cap + bounded admission-queue depth behind it
    inflight: Optional[int] = None
    queue: Optional[int] = None
    #: default per-request deadline (ms) when the client sends none
    deadline_ms: Optional[float] = None
    #: stale-response LRU entries (0 ⇒ degradation disabled)
    cache: int = 0
    #: circuit breaker: trip when ≥ ``fail_rate`` of the last
    #: ``fail_window`` calls failed (given ≥ ``fail_window`` samples);
    #: stay open ``cooldown`` seconds; close after ``probes`` successes
    fail_rate: float = 0.5
    fail_window: int = 20
    cooldown_s: float = 5.0
    probes: int = 3

    def effective_burst(self) -> float:
        """Bucket depth: explicit ``burst=`` or one second of ``rps``."""
        if self.burst is not None:
            return self.burst
        return max(self.rps or 0.0, 1.0)

    def effective_key_burst(self) -> float:
        if self.key_burst is not None:
            return self.key_burst
        return max(self.key_rps or 0.0, 1.0)

    # pio: endpoint=/qos.json
    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["priorities"] = dict(PRIORITY_FLOORS)
        return d


_FLOAT_KEYS = {"rps", "burst", "key_rps", "key_burst", "fail_rate"}
_INT_KEYS = {"inflight", "queue", "cache", "fail_window", "probes"}
_DURATION_KEYS = {"deadline": "deadline_ms", "cooldown": "cooldown_s"}


def parse_qos(spec: str) -> QoSPolicy:
    """Parse ``rps=500,queue=64,deadline=100ms`` into a policy.

    Keys: ``rps burst key_rps key_burst inflight queue deadline cache
    fail_rate fail_window probes cooldown``. Durations take the SLO
    suffixes (``us ms s m h d``); everything else is a plain number.
    """
    kw: Dict[str, Any] = {}
    for item in str(spec).split(","):
        item = item.strip()
        if not item:
            continue
        key, sep, raw = item.partition("=")
        key, raw = key.strip().lower(), raw.strip()
        if not sep or not raw:
            raise QoSError(f"qos spec item {item!r} is not key=value")
        try:
            if key in _FLOAT_KEYS:
                kw[key] = float(raw)
                if kw[key] < 0:
                    raise ValueError("negative")
            elif key in _INT_KEYS:
                kw[key] = int(raw)
                if kw[key] < 0:
                    raise ValueError("negative")
            elif key in _DURATION_KEYS:
                v = parse_duration_s(raw)
                kw[_DURATION_KEYS[key]] = (
                    v * 1000.0 if key == "deadline" else v
                )
            else:
                raise QoSError(
                    f"unknown qos key {key!r} (expected one of: "
                    f"{', '.join(sorted(_FLOAT_KEYS | _INT_KEYS | set(_DURATION_KEYS)))})"
                )
        except QoSError:
            raise
        except (TypeError, ValueError) as e:
            raise QoSError(f"bad qos value {item!r}: {e}") from None
    if kw.get("fail_rate") is not None and kw["fail_rate"] > 1.0:
        raise QoSError("fail_rate is a fraction in [0, 1]")
    return QoSPolicy(**kw)


def policy_from_dict(d: Dict[str, Any]) -> QoSPolicy:
    """An ``engine.json`` ``qos`` block: either ``{"spec": "rps=..."}`` or
    the policy fields spelled out as JSON keys."""
    if "spec" in d:
        return parse_qos(d["spec"])
    allowed = {f.name for f in dataclasses.fields(QoSPolicy)}
    unknown = set(d) - allowed
    if unknown:
        raise QoSError(f"unknown qos keys in engine.json: {sorted(unknown)}")
    try:
        return QoSPolicy(**d)
    except TypeError as e:
        raise QoSError(f"bad engine.json qos block: {e}") from None


def resolve_policy(
    spec: Union[None, str, QoSPolicy],
    variant: Optional[Dict[str, Any]] = None,
) -> Optional[QoSPolicy]:
    """Resolve the effective policy: explicit spec > ``PIO_TPU_QOS`` >
    ``engine.json`` ``qos`` block > None (QoS off)."""
    if isinstance(spec, QoSPolicy):
        return spec
    if spec:
        return parse_qos(spec)
    env = knobs.knob_str("PIO_TPU_QOS")
    if env:
        return parse_qos(env)
    block = (variant or {}).get("qos")
    if isinstance(block, str):
        return parse_qos(block)
    if isinstance(block, dict):
        return policy_from_dict(block)
    return None
