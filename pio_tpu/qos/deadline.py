"""Request deadlines: the ``X-Pio-Deadline-Ms`` header gives the query a
time budget counted from server receipt. A request whose budget elapses
while it sits in the micro-batch queue is shed BEFORE model execution —
the client already gave up; running the scorer for it is pure waste —
and a forming batch never waits past its tightest member's deadline.
"""

from __future__ import annotations

from typing import Callable, Optional

from pio_tpu.obs.metrics import monotonic_s

#: request header carrying the budget, in milliseconds (lowercase — the
#: HTTP layer lowercases header names)
DEADLINE_HEADER = "X-Pio-Deadline-Ms"


class DeadlineExceeded(Exception):
    """A request's budget elapsed before (or while) it could execute."""


def parse_deadline_ms(raw: Optional[str]) -> Optional[float]:
    """Header value → budget in ms. ``None``/empty → None; malformed or
    non-positive raises ``ValueError`` (the server maps it to a 400 — a
    garbled deadline must not silently become "no deadline")."""
    if raw is None or not str(raw).strip():
        return None
    v = float(raw)  # ValueError on garbage propagates
    if v != v or v <= 0:
        raise ValueError(f"deadline must be a positive number of ms: {raw!r}")
    return v


class Deadline:
    """Absolute deadline on the monotonic clock."""

    __slots__ = ("at", "_clock")

    def __init__(self, budget_ms: float,
                 clock: Callable[[], float] = monotonic_s):
        self._clock = clock
        self.at = clock() + budget_ms / 1000.0

    @classmethod
    def from_header(cls, raw: Optional[str],
                    default_ms: Optional[float] = None,
                    clock: Callable[[], float] = monotonic_s
                    ) -> Optional["Deadline"]:
        budget = parse_deadline_ms(raw)
        if budget is None:
            budget = default_ms
        return None if budget is None else cls(budget, clock=clock)

    def remaining_s(self) -> float:
        return self.at - self._clock()

    def expired(self) -> bool:
        return self._clock() >= self.at
