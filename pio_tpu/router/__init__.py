"""Serving fabric front tier: entity-affine routing across N members.

The pieces, in request order:

- :mod:`ring` — partition-affine slots (mirroring partlog's
  ``crc32(entity_id) % N``) composed with rendezvous hashing, so a
  user's events and serving replica co-locate and membership churn
  remaps only the failed member's keyspace;
- :mod:`core` — :class:`~pio_tpu.router.core.ServingRouter`: health
  gating (scrape status + passive forced-down), SLO-aware spreading
  (worst-burn + device-headroom demotion, priority-floor shedding with
  the QoS vocabulary), keep-alive forwarding with a single ring-order
  retry (optionally hedged for interactive tails), and the
  ``pio_tpu_router_*`` metric families;
- :mod:`deploy` — manifest-verified instance distribution: members
  sha256-verify every shard from their own store before the router
  flips their generation into rotation;
- :mod:`rollout` — progressive delivery: shadow mirroring, canary
  keyspace diversion, SLO-burn judging, auto-promote/rollback with a
  durable decision trail on ``/rollout.json``.

The daemon wiring (HTTP front, embedded fleet scraper, ``/router.json``)
lives in :mod:`pio_tpu.server.routerd`; ``pio route`` / ``pio rollout``
are the CLI verbs.
"""

from pio_tpu.router.core import (
    BURN_LIMIT_ENV,
    DEFAULT_BURN_LIMIT,
    HEDGE_ENV,
    MemberState,
    ServingRouter,
    Shed,
)
from pio_tpu.router.deploy import (
    DeployVerifyError,
    load_manifest,
    manifest_digests,
    push_deploy,
    verify_instance,
)
from pio_tpu.router.ring import Ring, hrw_score, slot_of
from pio_tpu.router.rollout import (
    STAGES,
    RolloutConfig,
    RolloutController,
    RolloutMetrics,
    diff_answers,
)

__all__ = [
    "BURN_LIMIT_ENV",
    "DEFAULT_BURN_LIMIT",
    "DeployVerifyError",
    "HEDGE_ENV",
    "MemberState",
    "Ring",
    "RolloutConfig",
    "RolloutController",
    "RolloutMetrics",
    "STAGES",
    "ServingRouter",
    "Shed",
    "diff_answers",
    "hrw_score",
    "load_manifest",
    "manifest_digests",
    "push_deploy",
    "slot_of",
    "verify_instance",
]
