"""Progressive-delivery rollout controller (ISSUE 19).

Drives one candidate engine instance through **shadow -> canary ->
promoted** against the live serving ring, with automatic rollback at
every stage:

- **shadow** — the router keeps relaying every request to the incumbent
  exactly as before (the ``# pio: hotpath=zerocopy`` relay is untouched:
  the controller observes completed relays through an opaque hook and
  mirrors a budgeted sample to the candidate asynchronously, with
  ``X-Pio-Priority: shadow``).  Answers are diffed — result parity for
  byte-identical bodies, itemScores set + score-delta histogram for JSON
  recommendations — and latency reservoirs track both sides' p50/p95.
- **canary** — a configurable keyspace fraction is routed to the
  candidate *for real*.  The fraction is carved with the same rendezvous
  hash the ring uses (:func:`~pio_tpu.router.ring.hrw_score` over a
  rollout-stable seed), so the canary keyspace is stable and
  entity-affine: one entity is either fully on the candidate or fully
  off it, across the whole stage.
- **judge** — every tick, a dedicated :class:`~pio_tpu.obs.slo.SLOEngine`
  evaluates the candidate's own scrape (availability from
  ``pio_tpu_queries_total`` / ``pio_tpu_query_errors_total``) through
  one fast/slow multi-window burn pair, alongside the shadow mismatch
  rate, the shadow latency ratio, and candidate reachability.  Any
  firing signal rolls the rollout back — the candidate can never hold
  traffic for more than one judging window past a regression.
- **promote / rollback** — both ride the manifest-verified deploy path
  (:func:`~pio_tpu.router.deploy.push_deploy`): a member's generation
  flips only on a verified 200, and rollback re-pushes the incumbent
  manifest byte-identically (same sha256 set — the property the test
  suite pins).

Every transition lands in a durable decision trail (who, when, which
signal, which window) served on ``/rollout.json`` and federated into
``/fleet.json``.  Chaos hooks: ``rollout.mirror`` / ``rollout.judge`` /
``rollout.promote`` / ``rollout.rollback`` failpoints.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from pio_tpu.faults import failpoint
from pio_tpu.obs import monotonic_s, promparse
from pio_tpu.obs.metrics import MetricsRegistry
from pio_tpu.obs.slo import SLOEngine, SLObjective
from pio_tpu.qos.policy import PRIORITY_HEADER
from pio_tpu.router.deploy import push_deploy
from pio_tpu.router.ring import hrw_score

log = logging.getLogger("pio_tpu.router.rollout")

__all__ = [
    "RolloutConfig",
    "RolloutController",
    "RolloutMetrics",
    "STAGES",
    "diff_answers",
]

#: stage -> numeric code for the ``pio_tpu_rollout_stage`` gauge
STAGES: Dict[str, int] = {
    "pending": 0,
    "deploying": 1,
    "shadow": 2,
    "canary": 3,
    "promoting": 4,
    "promoted": 5,
    "rolling_back": 6,
    "rolled_back": 7,
    "failed": 8,
}
TERMINAL = ("promoted", "rolled_back", "failed")

#: score-delta buckets for the shadow parity histogram (absolute
#: difference between incumbent and candidate scores for the same item)
SCORE_DELTA_BUCKETS = (
    1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1, 0.5, 1.0, 10.0,
)

_HRW_SPAN = float(2 ** 64)


@dataclass
class RolloutConfig:
    """Knobs for one progressive rollout (the ``POST /rollout`` body)."""

    candidate_instance: str
    #: candidate serving members as (name, base_url) pairs — the
    #: ``parse_targets`` shape; these join the router as aux members
    #: (pooled upstreams, never in the incumbent ring)
    candidate_targets: Sequence[Tuple[str, str]] = ()
    #: discovered from the ring members' ``GET /deploy.json`` when None
    incumbent_instance: Optional[str] = None
    #: fraction of live incumbent traffic mirrored during shadow/canary
    shadow_rate: float = 0.25
    #: shadow samples required before the stage may advance
    shadow_min_samples: int = 50
    #: minimum wall time in shadow before advancing
    shadow_hold_s: float = 10.0
    #: mismatch fraction at/over which the rollout rolls back
    mismatch_limit: float = 0.02
    #: |score delta| below which differing JSON answers still match
    score_tolerance: float = 1e-3
    #: candidate shadow p95 may be at most this multiple of incumbent's
    latency_limit_x: float = 5.0
    #: keyspace fraction served by the candidate during canary
    canary_fraction: float = 0.1
    #: minimum wall time in canary before promoting
    canary_hold_s: float = 30.0
    #: candidate-served requests required before promoting
    canary_min_requests: int = 20
    judge_interval_s: float = 2.0
    #: fast/slow burn windows for the candidate availability judge
    judge_fast_s: float = 30.0
    judge_slow_s: float = 120.0
    #: burn rate both windows must exceed to trigger rollback
    burn_limit: float = 2.0
    availability_objective: float = 0.99
    #: consecutive candidate scrape failures before rollback
    down_after_failures: int = 3
    #: advance/promote automatically; False parks at each gate until
    #: :meth:`RolloutController.approve` is called
    auto: bool = True

    def validate(self) -> None:
        if not self.candidate_instance:
            raise ValueError("rollout needs a candidate engineInstanceId")
        if not self.candidate_targets:
            raise ValueError(
                "rollout needs at least one candidate target "
                "(name=host:port)"
            )
        if not 0.0 <= self.shadow_rate <= 1.0:
            raise ValueError("shadow_rate must be in [0, 1]")
        if not 0.0 <= self.canary_fraction <= 1.0:
            raise ValueError("canary_fraction must be in [0, 1]")
        if not 0.0 < self.availability_objective < 1.0:
            raise ValueError("availability_objective must be in (0, 1)")


class RolloutMetrics:
    """``pio_tpu_rollout_*`` families, registered once per registry and
    shared by consecutive rollouts (registration is idempotent)."""

    def __init__(self, registry: MetricsRegistry):
        self.stage = registry.gauge(
            "pio_tpu_rollout_stage",
            "Current rollout stage as a code (0 pending, 1 deploying, "
            "2 shadow, 3 canary, 4 promoting, 5 promoted, "
            "6 rolling_back, 7 rolled_back, 8 failed)",
        )
        self.generation = registry.gauge(
            "pio_tpu_rollout_generation",
            "Monotone count of rollouts started on this router",
        )
        self.transitions = registry.counter(
            "pio_tpu_rollout_transitions_total",
            "Rollout stage transitions, labeled by the stage entered",
            ("to",),
        )
        self.mirrored = registry.counter(
            "pio_tpu_rollout_mirrored_total",
            "Shadow mirror attempts by outcome "
            "(ok / error / dropped)",
            ("outcome",),
        )
        self.shadow_samples = registry.counter(
            "pio_tpu_rollout_shadow_samples_total",
            "Diffed shadow answers by verdict (match / mismatch)",
            ("verdict",),
        )
        self.canary_requests = registry.counter(
            "pio_tpu_rollout_canary_requests_total",
            "Live requests served by the candidate during canary",
        )
        self.judge = registry.counter(
            "pio_tpu_rollout_judge_total",
            "Judge ticks by verdict (ok / rollback)",
            ("verdict",),
        )
        self.score_delta = registry.histogram(
            "pio_tpu_rollout_score_delta",
            "Absolute score difference between incumbent and candidate "
            "for the same recommended item (shadow diffing)",
            buckets=SCORE_DELTA_BUCKETS,
        )


def _item_scores(body: bytes) -> Optional[Dict[str, float]]:
    """``{item: score}`` when the body is a JSON prediction carrying
    ``itemScores`` (the reference recommendation answer shape)."""
    import json

    try:
        got = json.loads(body.decode("utf-8"))
    except Exception:
        return None
    if not isinstance(got, dict):
        return None
    rows = got.get("itemScores")
    if not isinstance(rows, list):
        return None
    out: Dict[str, float] = {}
    for row in rows:
        if not isinstance(row, dict):
            return None
        item = row.get("item", row.get("iid"))
        score = row.get("score")
        if item is None or score is None:
            return None
        out[str(item)] = float(score)
    return out


def diff_answers(
    inc_status: int,
    inc_body: bytes,
    cand_status: int,
    cand_body: bytes,
    score_tolerance: float = 1e-3,
) -> Tuple[bool, List[float]]:
    """Shadow parity verdict: ``(match, score_deltas)``.

    Status codes must agree; byte-identical bodies match outright; JSON
    answers carrying ``itemScores`` match when they recommend the same
    item set with every score within ``score_tolerance`` (the deltas are
    returned for the histogram either way).  Anything else is a
    mismatch.
    """
    if inc_status != cand_status:
        return False, []
    if bytes(inc_body) == bytes(cand_body):
        return True, []
    a, b = _item_scores(inc_body), _item_scores(cand_body)
    if a is None or b is None:
        return False, []
    if set(a) != set(b):
        return False, []
    deltas = [abs(a[k] - b[k]) for k in a]
    return all(d <= score_tolerance for d in deltas), deltas


def _percentiles(samples: Sequence[float]) -> Optional[dict]:
    if not samples:
        return None
    s = sorted(samples)

    def pct(q: float) -> float:
        idx = min(len(s) - 1, max(0, int(q * len(s))))
        return s[idx]

    return {
        "samples": len(s),
        "p50Ms": round(pct(0.50) * 1e3, 3),
        "p95Ms": round(pct(0.95) * 1e3, 3),
        "p99Ms": round(pct(0.99) * 1e3, 3),
    }


class RolloutController:
    """One candidate's journey through the stage machine.

    ``core`` is the live :class:`~pio_tpu.router.core.ServingRouter`;
    the controller attaches itself through the router's opaque
    observe/divert hooks so the relay hot path keeps its zero-copy
    contract.  ``manifest_loader(instance_id) -> Optional[dict]`` and
    ``fetch(url, timeout) -> bytes`` are injectable for tests.
    """

    def __init__(
        self,
        core,
        config: RolloutConfig,
        metrics: RolloutMetrics,
        manifest_loader: Optional[Callable[[str], Optional[dict]]] = None,
        fetch: Optional[Callable[[str, float], bytes]] = None,
        admin_key: Optional[str] = None,
        generation: int = 1,
        started_by: str = "operator",
    ):
        config.validate()
        self.core = core
        self.cfg = config
        self.metrics = metrics
        self.admin_key = admin_key
        self.generation = generation
        self.started_by = started_by
        self._manifest_loader = manifest_loader
        if fetch is None:
            from pio_tpu.obs.fleet import _default_fetch

            fetch = _default_fetch
        self._fetch = fetch

        self.stage = "pending"
        self.trail: List[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stage_entered = monotonic_s()
        self._approved = threading.Event()
        if config.auto:
            self._approved.set()

        self.candidate_members = [name for name, _ in
                                  config.candidate_targets]
        self._candidate_set = frozenset(self.candidate_members)
        self.incumbent_instance = config.incumbent_instance
        #: sha256 set of the incumbent manifest at rollout start — the
        #: byte-identity witness rollback is checked against
        self.incumbent_shas: List[str] = []
        self._candidate_manifest: Optional[dict] = None
        self._incumbent_manifest: Optional[dict] = None
        #: ring members whose generation flipped to the candidate during
        #: promote (rollback must re-push the incumbent to exactly these)
        self._promoted_members: List[str] = []

        # shadow mirroring
        self._mirror_q: deque = deque(maxlen=256)
        self._mirror_wake = threading.Event()
        self._mirror_thread: Optional[threading.Thread] = None
        self._sample_acc = 0.0
        self._mirror_rr = 0
        self.shadow_matches = 0
        self.shadow_mismatches = 0
        self.shadow_dropped = 0
        self._lat_incumbent: deque = deque(maxlen=512)
        self._lat_candidate: deque = deque(maxlen=512)

        # canary accounting
        self.canary_requests = 0
        self.canary_errors = 0

        # judge
        self._canary_seed = f"rollout:{config.candidate_instance}"
        self._scrape_failures = 0
        self._cand_good = 0.0
        self._cand_total = 0.0
        self.judge_ticks = 0
        self.last_verdict: Optional[str] = None
        self.last_burn: Dict[str, float] = {}
        self.slo = SLOEngine(burn_windows=(
            (config.judge_fast_s, config.judge_slow_s,
             config.burn_limit, "rollback"),
        ))
        self.slo.add(
            SLObjective(
                name="candidate_availability",
                kind="availability",
                objective=config.availability_objective,
                window_s=config.judge_slow_s,
            ),
            self._candidate_good_total,
        )

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        """Deploy the candidate and run the stage machine in the
        background; transitions land on the decision trail."""
        if self._thread is not None:
            return
        self.metrics.generation.set(float(self.generation))
        self._thread = threading.Thread(
            target=self._run, name="rollout-controller", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._mirror_wake.set()
        self._approved.set()
        for t in (self._thread, self._mirror_thread):
            if t is not None:
                t.join(timeout=5.0)
        self._thread = self._mirror_thread = None

    def abort(self, by: str = "operator") -> None:
        """Operator bail-out: immediate rollback from any live stage."""
        with self._lock:
            if self.stage in TERMINAL:
                return
        self._rollback("operator_abort", f"aborted by {by}")

    def approve(self) -> None:
        """Release a non-auto rollout's current gate (shadow->canary or
        canary->promote)."""
        self._approved.set()

    def active(self) -> bool:
        return self.stage not in TERMINAL

    def _run(self) -> None:
        try:
            self._deploy_candidate()
        except Exception as e:
            log.exception("rollout: candidate deploy failed")
            self._rollback("candidate_deploy_failed",
                           f"{type(e).__name__}: {e}")
            return
        if self.stage in TERMINAL:
            return
        self._enter_shadow()
        while not self._stop.is_set() and self.stage in ("shadow", "canary"):
            if self._stop.wait(self.cfg.judge_interval_s):
                return
            try:
                self.judge_once()
            except Exception as e:
                log.exception("rollout: judge tick failed")
                self._rollback("judge_error", f"{type(e).__name__}: {e}")
                return

    # -- trail / transitions -------------------------------------------------
    # pio: endpoint=/rollout.json
    def _transition(self, to: str, signal: str, detail: str = "",
                    window: Optional[str] = None) -> None:
        with self._lock:
            frm = self.stage
            self.stage = to
            self._stage_entered = monotonic_s()
            entry = {
                # wall time: the decision trail is operator-facing
                # evidence, correlated with logs across hosts
                "at": time.time(),  # pio: disable=wallclock-duration
                "from": frm,
                "to": to,
                "signal": signal,
                "detail": detail,
                "window": window,
                "by": self.started_by,
            }
            self.trail.append(entry)
        self.metrics.stage.set(float(STAGES.get(to, -1)))
        self.metrics.transitions.inc(to=to)
        log.info("rollout %s: %s -> %s (%s%s)", self.cfg.candidate_instance,
                 frm, to, signal, f": {detail}" if detail else "")

    # -- deploy -------------------------------------------------------------
    def _load_manifest(self, instance_id: str) -> Optional[dict]:
        if self._manifest_loader is not None:
            return self._manifest_loader(instance_id)
        from pio_tpu.router.deploy import load_manifest
        from pio_tpu.storage import Storage

        return load_manifest(Storage.get_model_data_models(), instance_id)

    @staticmethod
    def _manifest_shas(manifest: Optional[dict]) -> List[str]:
        from pio_tpu.router.deploy import manifest_digests

        if manifest is None:
            return []
        return sorted(
            sha for sha, _size in manifest_digests(manifest).values()
        )

    def _discover_incumbent(self) -> None:
        """Pin the incumbent instance from the ring members' own
        ``GET /deploy.json`` generation reports."""
        if self.incumbent_instance is not None:
            return
        import json

        for ms in self.core.ring_members():
            try:
                raw = self._fetch(
                    ms.base_url + "/deploy.json", self.core.timeout_s
                )
                got = json.loads(raw.decode("utf-8"))
            except Exception:
                continue
            iid = got.get("engineInstanceId")
            if iid:
                self.incumbent_instance = str(iid)
                return
        raise RuntimeError(
            "cannot discover the incumbent instance: no ring member "
            "answered GET /deploy.json (pass incumbentInstance explicitly)"
        )

    def _deploy_candidate(self) -> None:
        self._transition("deploying", "start",
                         f"candidate {self.cfg.candidate_instance}")
        self._discover_incumbent()
        self._candidate_manifest = self._load_manifest(
            self.cfg.candidate_instance
        )
        self._incumbent_manifest = self._load_manifest(
            self.incumbent_instance
        )
        self.incumbent_shas = self._manifest_shas(self._incumbent_manifest)
        for name, url in self.cfg.candidate_targets:
            self.core.add_member(name, url, aux=True)
        failures = []
        for name, url in self.cfg.candidate_targets:
            outcome, detail = push_deploy(
                url, self.cfg.candidate_instance, self._candidate_manifest,
                timeout_s=max(self.core.timeout_s, 60.0),
                admin_key=self.admin_key,
            )
            if outcome != "verified":
                failures.append(f"{name}: {outcome} ({detail})")
        if failures:
            raise RuntimeError("; ".join(failures))

    def _enter_shadow(self) -> None:
        self._transition("shadow", "candidate_verified",
                         f"{len(self.candidate_members)} candidate "
                         f"member(s) verified on "
                         f"{self.cfg.candidate_instance}")
        self._mirror_thread = threading.Thread(
            target=self._mirror_loop, name="rollout-mirror", daemon=True
        )
        self._mirror_thread.start()
        self.core.set_observer(self.observe)
        if not self.cfg.auto:
            self._approved.clear()

    # -- shadow mirroring ----------------------------------------------------
    def observe(self, method, path, body, headers, entity_id, priority,
                member, status, out, elapsed_s) -> None:
        """Router hook: one completed relay. Candidate-served relays
        feed canary accounting; incumbent-served ones feed the latency
        reservoir and (sampled) the mirror queue. Never raises."""
        try:
            if self.stage not in ("shadow", "canary"):
                return
            if member in self._candidate_set:
                with self._lock:
                    self.canary_requests += 1
                    if status >= 500:
                        self.canary_errors += 1
                    self._lat_candidate.append(elapsed_s)
                self.metrics.canary_requests.inc()
                return
            if priority == "shadow":
                return  # never mirror a mirror
            with self._lock:
                self._lat_incumbent.append(elapsed_s)
                self._sample_acc += self.cfg.shadow_rate
                if self._sample_acc < 1.0:
                    return
                self._sample_acc -= 1.0
                dropped = len(self._mirror_q) == self._mirror_q.maxlen
                self._mirror_q.append(
                    (method, path, bytes(body) if body is not None else b"",
                     dict(headers), entity_id, status, bytes(out))
                )
            if dropped:
                self.shadow_dropped += 1
                self.metrics.mirrored.inc(outcome="dropped")
            self._mirror_wake.set()
        except Exception:
            log.debug("rollout observer swallowed an error", exc_info=True)

    def _mirror_loop(self) -> None:
        while not self._stop.is_set():
            self._mirror_wake.wait(timeout=0.5)
            self._mirror_wake.clear()
            while True:
                try:
                    item = self._mirror_q.popleft()
                except IndexError:
                    break
                if self.stage not in ("shadow", "canary"):
                    continue
                self._mirror_one(*item)

    def _mirror_one(self, method, path, body, headers, entity_id,
                    inc_status, inc_body) -> None:
        name = self._pick_candidate(entity_id)
        if name is None:
            return
        try:
            failpoint("rollout.mirror")
            hdrs = {
                k: v for k, v in headers.items()
                if k.lower() in ("content-type",)
            }
            hdrs[PRIORITY_HEADER] = "shadow"
            t0 = monotonic_s()
            status, _reply, out = self.core.upstream_request(
                name, method, path, body, hdrs
            )
            self._lat_candidate.append(monotonic_s() - t0)
        except Exception:
            self.metrics.mirrored.inc(outcome="error")
            return
        self.metrics.mirrored.inc(outcome="ok")
        match, deltas = diff_answers(
            inc_status, inc_body, status, out,
            score_tolerance=self.cfg.score_tolerance,
        )
        for d in deltas:
            self.metrics.score_delta.observe(d)
        with self._lock:
            if match:
                self.shadow_matches += 1
            else:
                self.shadow_mismatches += 1
        self.metrics.shadow_samples.inc(
            verdict="match" if match else "mismatch"
        )

    def _pick_candidate(self, entity_id: Optional[str]) -> Optional[str]:
        live = [m for m in self.candidate_members
                if self.core.has_member(m)]
        if not live:
            return None
        if entity_id:
            return max(live, key=lambda m: hrw_score(m, str(entity_id)))
        self._mirror_rr += 1
        return live[self._mirror_rr % len(live)]

    # -- canary diversion ----------------------------------------------------
    def in_canary_keyspace(self, entity_id: str) -> bool:
        """Stable entity-affine fraction carve: the same rendezvous hash
        the ring runs, seeded per-rollout so consecutive rollouts canary
        different slices of the keyspace."""
        frac = self.cfg.canary_fraction
        if frac <= 0.0:
            return False
        if frac >= 1.0:
            return True
        return hrw_score(self._canary_seed, str(entity_id)) / _HRW_SPAN < frac

    def divert(self, entity_id, priority) -> Optional[str]:
        """Router hook consulted at pick time: the candidate member that
        should front this request, or None to route normally. Only real
        (non-shadow) traffic in the canary keyspace diverts; the
        incumbent plan stays behind the candidate, so a dead candidate
        costs one transparent retry, not an error."""
        try:
            if self.stage != "canary" or priority == "shadow":
                return None
            if not entity_id or not self.in_canary_keyspace(str(entity_id)):
                return None
            return self._pick_candidate(str(entity_id))
        except Exception:
            return None

    # -- judge --------------------------------------------------------------
    def _candidate_good_total(self) -> Tuple[float, float]:
        return self._cand_good, self._cand_total

    def _scrape_candidate(self) -> bool:
        """Pull every candidate's /metrics and fold the serving
        counters into the cumulative availability source."""
        good = total = 0.0
        any_ok = False
        for name, url in self.cfg.candidate_targets:
            try:
                raw = self._fetch(url + "/metrics", self.core.timeout_s)
                pm = promparse.parse_prometheus_text(raw.decode("utf-8"))
            except Exception:
                continue
            any_ok = True
            t = sum(pm.family("pio_tpu_queries_total").values())
            e = sum(pm.family("pio_tpu_query_errors_total").values())
            total += t
            good += max(t - e, 0.0)
        if any_ok:
            # monotone across partial scrapes: a member missing one tick
            # must not make the cumulative source step backwards
            self._cand_good = max(self._cand_good, good)
            self._cand_total = max(self._cand_total, total)
        return any_ok

    def _held_s(self) -> float:
        return monotonic_s() - self._stage_entered

    def judge_once(self, now: Optional[float] = None) -> str:
        """One judge tick: scrape, evaluate every rollback signal, then
        advance/promote when the stage's gate clears.  Returns the
        verdict (``ok`` / ``rollback`` / the stage entered).  Tests
        drive this directly with an explicit clock."""
        failpoint("rollout.judge")
        if self.stage not in ("shadow", "canary"):
            return self.stage
        t = monotonic_s() if now is None else now
        self.judge_ticks += 1

        if self._scrape_candidate():
            self._scrape_failures = 0
        else:
            self._scrape_failures += 1
            if self._scrape_failures >= self.cfg.down_after_failures:
                self.metrics.judge.inc(verdict="rollback")
                self.last_verdict = "rollback"
                self._rollback(
                    "candidate_unreachable",
                    f"{self._scrape_failures} consecutive scrape "
                    f"failures across "
                    f"{len(self.candidate_members)} candidate member(s)",
                )
                return "rollback"

        report = self.slo.evaluate(now=t)
        slo_row = report["slos"][0]
        self.last_burn = dict(slo_row["burnRates"])
        fast_key = f"{int(self.cfg.judge_fast_s)}s"
        slow_key = f"{int(self.cfg.judge_slow_s)}s"
        window_name = f"{fast_key}/{slow_key}"
        firing = any(a["firing"] for a in slo_row["alerts"])
        if firing and slo_row["total"] > 0:
            self.metrics.judge.inc(verdict="rollback")
            self.last_verdict = "rollback"
            self._rollback(
                "slo_burn",
                f"candidate availability burn "
                f"{self.last_burn.get(fast_key)} (fast) / "
                f"{self.last_burn.get(slow_key)} (slow) over limit "
                f"{self.cfg.burn_limit}",
                window=window_name,
            )
            return "rollback"

        with self._lock:
            samples = self.shadow_matches + self.shadow_mismatches
            mismatch_rate = (
                self.shadow_mismatches / samples if samples else 0.0
            )
            lat_inc = list(self._lat_incumbent)
            lat_cand = list(self._lat_candidate)
        if (samples >= self.cfg.shadow_min_samples
                and mismatch_rate > self.cfg.mismatch_limit):
            self.metrics.judge.inc(verdict="rollback")
            self.last_verdict = "rollback"
            self._rollback(
                "shadow_mismatch",
                f"mismatch rate {mismatch_rate:.4f} over limit "
                f"{self.cfg.mismatch_limit} ({samples} samples)",
            )
            return "rollback"
        if len(lat_inc) >= 20 and len(lat_cand) >= 20:
            p95_inc = _percentiles(lat_inc)["p95Ms"]
            p95_cand = _percentiles(lat_cand)["p95Ms"]
            if (p95_inc > 0.0
                    and p95_cand > p95_inc * self.cfg.latency_limit_x):
                self.metrics.judge.inc(verdict="rollback")
                self.last_verdict = "rollback"
                self._rollback(
                    "shadow_latency",
                    f"candidate p95 {p95_cand}ms over "
                    f"{self.cfg.latency_limit_x}x incumbent "
                    f"p95 {p95_inc}ms",
                )
                return "rollback"

        self.metrics.judge.inc(verdict="ok")
        self.last_verdict = "ok"

        held = self._held_s() if now is None else (t - self._stage_entered)
        if self.stage == "shadow":
            if (held >= self.cfg.shadow_hold_s
                    and samples >= self.cfg.shadow_min_samples
                    and self._approved.is_set()):
                self._enter_canary(samples, mismatch_rate)
                return "canary"
        elif self.stage == "canary":
            with self._lock:
                canaried = self.canary_requests
            if (held >= self.cfg.canary_hold_s
                    and canaried >= self.cfg.canary_min_requests
                    and self._approved.is_set()):
                self._promote(canaried)
                return self.stage
        return "ok"

    def _enter_canary(self, samples: int, mismatch_rate: float) -> None:
        self._transition(
            "canary", "shadow_clean",
            f"{samples} shadow samples, mismatch rate "
            f"{mismatch_rate:.4f}, diverting "
            f"{self.cfg.canary_fraction:.0%} of keyspace",
        )
        self.core.set_divert(self.divert)
        if not self.cfg.auto:
            self._approved.clear()

    # -- promote / rollback --------------------------------------------------
    def _promote(self, canaried: int) -> None:
        failpoint("rollout.promote")
        self._transition(
            "promoting", "canary_clean",
            f"{canaried} candidate-served requests, "
            f"burn {self.last_burn or '{}'}",
        )
        failures = []
        for ms in self.core.ring_members():
            outcome, detail = push_deploy(
                ms.base_url, self.cfg.candidate_instance,
                self._candidate_manifest,
                timeout_s=max(self.core.timeout_s, 60.0),
                admin_key=self.admin_key,
            )
            self.core.note_deploy(
                ms.name, self.cfg.candidate_instance, outcome
            )
            if outcome == "verified":
                self._promoted_members.append(ms.name)
            else:
                failures.append(f"{ms.name}: {outcome} ({detail})")
        if failures:
            self._rollback(
                "promote_failed",
                "; ".join(failures) or "unverified member(s)",
            )
            return
        self._detach()
        self._transition(
            "promoted", "all_verified",
            f"{len(self._promoted_members)} ring member(s) flipped to "
            f"{self.cfg.candidate_instance}",
        )
        self._teardown_candidates()

    def _rollback(self, signal: str, detail: str,
                  window: Optional[str] = None) -> None:
        with self._lock:
            if self.stage in TERMINAL or self.stage == "rolling_back":
                return
        # detach FIRST: no new traffic may reach the candidate while the
        # incumbent manifest is going back out
        self._detach()
        self._transition("rolling_back", signal, detail, window=window)
        try:
            failpoint("rollout.rollback")
        except Exception:
            log.warning("rollout.rollback failpoint fired during rollback")
        restore: List[Tuple[str, str]] = []
        for name in self._promoted_members:
            ms = self.core.member(name)
            if ms is not None:
                restore.append((name, ms.base_url))
        restore.extend(
            (name, url) for name, url in self.cfg.candidate_targets
        )
        restored = 0
        problems = []
        if self.incumbent_instance:
            for name, url in restore:
                outcome, detail_r = push_deploy(
                    url, self.incumbent_instance, self._incumbent_manifest,
                    timeout_s=max(self.core.timeout_s, 60.0),
                    admin_key=self.admin_key,
                )
                if name in self._promoted_members:
                    self.core.note_deploy(
                        name, self.incumbent_instance, outcome
                    )
                if outcome == "verified":
                    restored += 1
                else:
                    problems.append(f"{name}: {outcome}")
        self._promoted_members = []
        self._teardown_candidates()
        self._transition(
            "rolled_back", "incumbent_restored",
            f"incumbent {self.incumbent_instance} re-pushed to "
            f"{restored}/{len(restore)} member(s)"
            + (f"; unrestored: {', '.join(problems)}" if problems else ""),
        )
        self._stop.set()
        self._mirror_wake.set()

    def _detach(self) -> None:
        self.core.set_divert(None)
        self.core.set_observer(None)

    def _teardown_candidates(self) -> None:
        for name in self.candidate_members:
            try:
                self.core.remove_member(name)
            except Exception:
                pass

    # -- /rollout.json -------------------------------------------------------
    # pio: endpoint=/rollout.json
    def payload(self) -> dict:
        """The ``GET /rollout.json`` body (schema in
        docs/observability.md); federated into ``/fleet.json``."""
        with self._lock:
            samples = self.shadow_matches + self.shadow_mismatches
            body = {
                "stage": self.stage,
                "stageCode": STAGES.get(self.stage, -1),
                "generation": self.generation,
                "candidateInstance": self.cfg.candidate_instance,
                "incumbentInstance": self.incumbent_instance,
                "candidateMembers": list(self.candidate_members),
                "startedBy": self.started_by,
                "auto": self.cfg.auto,
                "config": {
                    "shadowRate": self.cfg.shadow_rate,
                    "shadowMinSamples": self.cfg.shadow_min_samples,
                    "shadowHoldSeconds": self.cfg.shadow_hold_s,
                    "mismatchLimit": self.cfg.mismatch_limit,
                    "scoreTolerance": self.cfg.score_tolerance,
                    "latencyLimitX": self.cfg.latency_limit_x,
                    "canaryFraction": self.cfg.canary_fraction,
                    "canaryHoldSeconds": self.cfg.canary_hold_s,
                    "canaryMinRequests": self.cfg.canary_min_requests,
                    "judgeIntervalSeconds": self.cfg.judge_interval_s,
                    "judgeWindowsSeconds": [
                        self.cfg.judge_fast_s, self.cfg.judge_slow_s
                    ],
                    "burnLimit": self.cfg.burn_limit,
                    "availabilityObjective":
                        self.cfg.availability_objective,
                },
                "shadow": {
                    "samples": samples,
                    "matches": self.shadow_matches,
                    "mismatches": self.shadow_mismatches,
                    "mismatchRate": round(
                        self.shadow_mismatches / samples, 4
                    ) if samples else 0.0,
                    "dropped": self.shadow_dropped,
                    "latency": {
                        "incumbent": _percentiles(self._lat_incumbent),
                        "candidate": _percentiles(self._lat_candidate),
                    },
                },
                "canary": {
                    "fraction": self.cfg.canary_fraction,
                    "requests": self.canary_requests,
                    "errors": self.canary_errors,
                },
                "judge": {
                    "ticks": self.judge_ticks,
                    "lastVerdict": self.last_verdict,
                    "burnRates": dict(self.last_burn),
                    "scrapeFailures": self._scrape_failures,
                },
                "incumbentManifestSha256": list(self.incumbent_shas),
                "trail": [dict(e) for e in self.trail],
            }
        return body
