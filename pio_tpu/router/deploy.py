"""Manifest-verified instance distribution.

A deploy through the router is a two-sided handshake over the
sharded-persist manifest (:mod:`pio_tpu.workflow.shard_store`):

- **router side** (:func:`push_deploy`) reads the manifest for the
  target instance out of the models store and POSTs it to every
  member's ``/deploy.json`` admin route;
- **member side** (:func:`verify_instance`, called from the query
  server's handler) re-hashes every shard record in its *own* store
  against the pushed manifest — sha256 and size, before a single byte
  is interpreted — and only then hot-swaps to the new generation.

A member that cannot verify answers 409 and keeps serving its current
generation; the router records the outcome and only flips verified
members' generation into rotation.  The invariant the chaos suite
leans on: **no member ever takes traffic on an instance whose shard
checksums failed**.
"""

from __future__ import annotations

import hashlib
import json
import urllib.error
import urllib.request
from typing import Dict, Optional, Tuple

from pio_tpu.faults import failpoint
from pio_tpu.workflow.shard_store import SHARD_MANIFEST_SUFFIX

__all__ = [
    "DeployVerifyError",
    "load_manifest",
    "manifest_digests",
    "push_deploy",
    "verify_instance",
]


class DeployVerifyError(RuntimeError):
    """Shard verification failed — the member must NOT swap."""


def load_manifest(models_store, instance_id: str) -> Optional[dict]:
    """The instance's shard manifest, or None for unsharded blobs."""
    record = models_store.get(instance_id + SHARD_MANIFEST_SUFFIX)
    if record is None:
        return None
    try:
        return json.loads(record.models.decode("utf-8"))
    except Exception as e:
        raise DeployVerifyError(
            f"unreadable shard manifest for instance {instance_id!r}: {e}"
        ) from e


def manifest_digests(manifest: dict) -> Dict[str, Tuple[str, int]]:
    """shard record id -> (sha256, size) across every algo/array."""
    out: Dict[str, Tuple[str, int]] = {}
    for algo in manifest.get("algos", []):
        if not algo:
            continue
        for entry in algo.get("arrays", []):
            for shard in entry.get("shards", []):
                out[str(shard["id"])] = (
                    str(shard["sha256"]), int(shard["size"])
                )
    return out


def verify_instance(
    models_store,
    instance_id: str,
    expected: Optional[dict] = None,
) -> dict:
    """Member-side verification gate, run BEFORE any swap.

    ``expected`` is the manifest the router pushed; when given, the
    member's own manifest must agree digest-for-digest (a diverged
    store — torn replication, wrong backend — is a rejection, not a
    surprise at restore time).  Every shard is then re-hashed from the
    member's store.  Raises :class:`DeployVerifyError` on any mismatch;
    returns a verification summary for the 200 body.
    """
    failpoint("router.verify")
    manifest = load_manifest(models_store, instance_id)
    if manifest is None:
        if expected is not None and manifest_digests(expected):
            raise DeployVerifyError(
                f"router pushed a shard manifest for {instance_id!r} "
                f"but this member's store has none"
            )
        # unsharded instance: nothing to checksum here — the blob
        # loader's own digest check guards the load — but the record
        # must at least exist so the swap cannot land on a 404.
        record = models_store.get(instance_id)
        if record is None:
            raise DeployVerifyError(
                f"instance {instance_id!r} absent from this member's store"
            )
        return {
            "instanceId": instance_id,
            "sharded": False,
            "shards": 0,
            "bytes": len(record.models),
        }
    digests = manifest_digests(manifest)
    if expected is not None:
        want = manifest_digests(expected)
        if want != digests:
            raise DeployVerifyError(
                f"member manifest for {instance_id!r} disagrees with the "
                f"pushed one ({len(digests)} vs {len(want)} shards or "
                f"differing digests)"
            )
    total = 0
    for shard_id, (sha, size) in sorted(digests.items()):
        record = models_store.get(shard_id)
        if record is None:
            raise DeployVerifyError(
                f"missing shard record {shard_id!r} for "
                f"instance {instance_id!r}"
            )
        got = hashlib.sha256(record.models).hexdigest()
        if got != sha or len(record.models) != size:
            raise DeployVerifyError(
                f"shard {shard_id!r} failed checksum verification "
                f"(manifest {sha}/{size}B, got {got}/"
                f"{len(record.models)}B)"
            )
        total += size
    return {
        "instanceId": instance_id,
        "sharded": True,
        "shards": len(digests),
        "bytes": total,
    }


def push_deploy(
    base_url: str,
    instance_id: str,
    manifest: Optional[dict],
    timeout_s: float = 60.0,
    admin_key: Optional[str] = None,
) -> Tuple[str, dict]:
    """POST the manifest to one member's ``/deploy.json``.

    Returns ``(outcome, detail)`` where outcome is ``verified`` (member
    swapped), ``rejected`` (member refused — verification failed, 4xx)
    or ``error`` (transport/5xx; member state unknown, generation NOT
    flipped).
    """
    body = json.dumps(
        {"engineInstanceId": instance_id, "manifest": manifest}
    ).encode("utf-8")
    headers = {"Content-Type": "application/json; charset=utf-8"}
    if admin_key:
        headers["Authorization"] = f"Bearer {admin_key}"
    req = urllib.request.Request(
        base_url.rstrip("/") + "/deploy.json",
        data=body, headers=headers, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            detail = json.loads(resp.read().decode("utf-8"))
        return "verified", detail
    except urllib.error.HTTPError as e:
        try:
            detail = json.loads(e.read().decode("utf-8"))
        except Exception:
            detail = {"error": f"HTTP {e.code}"}
        return ("rejected" if 400 <= e.code < 500 else "error"), detail
    except Exception as e:
        return "error", {"error": f"{type(e).__name__}: {e}"}
