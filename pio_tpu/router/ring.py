"""Entity-affine replica ring: partition slots + rendezvous hashing.

Two placement functions, composed:

- **Partition affinity** mirrors the partlog event path: partlog appends
  an event for entity ``e`` to partition ``crc32(e) % N``
  (:func:`pio_tpu.storage.partlog.partitioned.partition_of`).  When the
  ring is configured with ``partitions == len(members)``, serving
  member ``sorted(members)[slot]`` owns the same keyspace as partlog
  partition ``slot`` — a user's events and their serving replica
  co-locate, so follower reads and model lookups for one entity hit one
  host.
- **Rendezvous (HRW) ranking** orders the *other* replicas for a key,
  and takes over entirely when the member set does not match the
  partition count (scale-out, degraded fleet, partitions unset).  HRW
  gives the churn property the router needs: removing a member remaps
  only the keys that member owned, adding one back steals only its own
  keyspace — no mass reshuffle on failover.

The composition keeps both properties: while every configured member is
routable the primary is the partition slot owner (co-location); when a
member dies only its slot's keys fall through to their HRW order over
the survivors, every other key keeps its primary.
"""

from __future__ import annotations

import hashlib
import zlib
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = ["Ring", "hrw_score", "slot_of"]


def slot_of(entity_id: str, partitions: int) -> int:
    """Partition slot for an entity id — byte-for-byte the partlog
    mapping (``crc32(utf8) % N``), so slot ``p`` here and partition
    ``p`` there name the same keyspace."""
    return zlib.crc32(entity_id.encode("utf-8")) % partitions


def hrw_score(member: str, key: str) -> int:
    """Stable rendezvous weight of ``member`` for ``key``.

    blake2b over ``member NUL key`` so the score survives process
    restarts and differing PYTHONHASHSEEDs (hash() would not); 8 bytes
    keeps collisions negligible while staying a cheap int compare.
    """
    h = hashlib.blake2b(digest_size=8)
    h.update(member.encode("utf-8"))
    h.update(b"\x00")
    h.update(key.encode("utf-8"))
    return int.from_bytes(h.digest(), "big")


class Ring:
    """Replica ranking over a configured member set.

    ``members`` is the *configured* fleet (sorted internally — slot
    assignment must agree across router restarts); per-call ``routable``
    narrows to the members currently able to take traffic.
    """

    def __init__(
        self,
        members: Iterable[str],
        partitions: Optional[int] = None,
    ):
        self._all: Sequence[str] = tuple(sorted(set(members)))
        if partitions is not None and partitions <= 0:
            raise ValueError(f"partitions must be positive, got {partitions}")
        self._partitions = partitions

    @property
    def members(self) -> Sequence[str]:
        return self._all

    @property
    def partitions(self) -> Optional[int]:
        return self._partitions

    def slot_owner(self, entity_id: str) -> Optional[str]:
        """The partition-affine owner, regardless of liveness — None
        when affinity is off (partitions unset or fleet size differs,
        where slots and partitions would name different keyspaces)."""
        if self._partitions is None or len(self._all) != self._partitions:
            return None
        return self._all[slot_of(entity_id, self._partitions)]

    def rank(
        self,
        key: str,
        routable: Optional[Iterable[str]] = None,
    ) -> List[str]:
        """Replica order for ``key``: try ``[0]`` first, retry down the
        list.  Restricted to ``routable`` members when given."""
        if routable is None:
            live = list(self._all)
        else:
            allowed = set(routable)
            live = [m for m in self._all if m in allowed]
        if not live:
            return []
        order = sorted(
            live, key=lambda m: (hrw_score(m, key), m), reverse=True
        )
        owner = self.slot_owner(key)
        if owner is not None and owner in order and order[0] != owner:
            order.remove(owner)
            order.insert(0, owner)
        return order

    def keyspace(
        self,
        keys: Iterable[str],
        routable: Optional[Iterable[str]] = None,
    ) -> Dict[str, str]:
        """key -> primary member, for a sample of keys (tests, and the
        ``/router.json`` remap preview)."""
        out: Dict[str, str] = {}
        for k in keys:
            order = self.rank(k, routable)
            if order:
                out[k] = order[0]
        return out
