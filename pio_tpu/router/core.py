"""Serving-router core: health-gated affine pick, forward with one
retry, SLO-aware spreading, QoS-vocabulary shedding.

The router is the front tier of the serving fabric: it owns no model
bytes, only a :class:`~pio_tpu.router.ring.Ring` over the configured
members plus a continuously refreshed health/load view (ingested from
the embedded fleet aggregator's ``fleet_payload()``).  Request flow:

1. **pick** — ``router.pick`` failpoint, then rank replicas for the
   entity id (affinity + rendezvous), restricted to routable members
   (not scrape-``down``, not passively forced down, see below).  Keyless
   requests (the packed int8 wire carries no entity id) spread by load
   score with a rotation tiebreak instead.
2. **spread** — replicas whose worst SLO burn is at or past the burn
   limit are demoted behind calm ones; when *every* replica burns,
   classes with a non-zero priority floor (``batchpredict``/``shadow``)
   are shed with the standard QoS vocabulary (503 + ``Retry-After``)
   while ``interactive`` rides the least-burning replica.
3. **forward** — ``router.forward`` failpoint per attempt, then relay
   over a keep-alive upstream connection.  A transport error marks the
   member passively down for ``forced_down_s`` (so the very next pick
   skips it — scrape confirmation follows within two intervals) and the
   request is retried ONCE on the next replica in ring order.  Upstream
   status codes, including 5xx, are relayed as-is: a delivered response
   is the member's answer, not the router's to rewrite.

Shedding raises :class:`Shed`; the daemon maps it onto 429/503 with
``Retry-After`` via the qos helpers so clients see one overload grammar
whether a member or the router said no.
"""

from __future__ import annotations

import http.client
import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple
from urllib.parse import urlsplit

from pio_tpu.faults import failpoint
from pio_tpu.obs import monotonic_s
from pio_tpu.obs.metrics import MetricsRegistry
from pio_tpu.qos.policy import priority_floor
from pio_tpu.router.ring import Ring
from pio_tpu.utils.envutil import env_float

log = logging.getLogger("pio_tpu.router")

__all__ = [
    "BURN_LIMIT_ENV",
    "DEFAULT_BURN_LIMIT",
    "DEFAULT_LAG_SOFT_BYTES",
    "LAG_SOFT_ENV",
    "MemberState",
    "ServingRouter",
    "Shed",
    "UpstreamReply",
]

#: worst-burn at/over which a replica is demoted (and non-interactive
#: classes shed when every replica is there). 2.0 = burning the error
#: budget at twice the sustainable rate, the classic page threshold.
BURN_LIMIT_ENV = "PIO_TPU_ROUTER_BURN_LIMIT"
DEFAULT_BURN_LIMIT = 2.0

#: replication lag that adds +1.0 to a member's load score — soft
#: pressure away from laggy followers, never a hard gate.
LAG_SOFT_ENV = "PIO_TPU_ROUTER_LAG_SOFT_BYTES"
DEFAULT_LAG_SOFT_BYTES = 64 * 1024 * 1024

#: headers relayed member-ward: the QoS/trace vocabulary must survive
#: the hop (priority floors honored end-to-end) but hop-by-hop framing
#: must not.
_FORWARD_HEADER_PREFIX = "x-pio-"
_FORWARD_HEADERS = ("content-type", "authorization")
_DROP_REPLY_HEADERS = frozenset(
    ("connection", "keep-alive", "transfer-encoding", "content-length")
)


class Shed(Exception):
    """The router itself refused the request (no routable member, or
    SLO pressure + priority floor). Carries the QoS vocabulary."""

    def __init__(self, status: int, reason: str, retry_after_s: float):
        super().__init__(f"shed: {reason}")
        self.status = status
        self.reason = reason
        self.retry_after_s = retry_after_s


#: (status, reply headers, body bytes, member name)
UpstreamReply = Tuple[int, Dict[str, str], bytes, str]


@dataclass
class MemberState:
    """Router-side view of one serving member."""

    name: str
    base_url: str
    host: str
    port: int
    status: str = "unknown"        # scrape view: up|stale|down|unknown
    burn: float = 0.0              # worst SLO burn across objectives
    lag_bytes: int = 0             # worst follower replication lag
    generation: Optional[str] = None   # last verified-deployed instance
    forced_down_until: float = 0.0     # passive-failure gate (monotonic)


class _UpstreamPool:
    """Keep-alive ``http.client`` connections to one member; handler
    threads check one out per request and return it after a clean,
    fully-read response (anything else closes it)."""

    def __init__(self, host: str, port: int, timeout_s: float):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self._idle: List[http.client.HTTPConnection] = []
        self._lock = threading.Lock()

    def _checkout(self) -> http.client.HTTPConnection:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout_s
        )

    def _checkin(self, c: http.client.HTTPConnection) -> None:
        with self._lock:
            if len(self._idle) < 8:
                self._idle.append(c)
                return
        c.close()

    def request(self, method, path, body, headers):  # pio: hotpath=zerocopy
        """One relayed exchange; the request body bytes are handed to
        the kernel as-is (no re-encode, no staging copy)."""
        c = self._checkout()
        try:
            c.request(method, path, body=body, headers=headers)
            r = c.getresponse()
            out = r.read()
            reply = {}
            for k, v in r.getheaders():
                if k.lower() not in _DROP_REPLY_HEADERS:
                    reply[k] = v
            status = r.status
            reuse = not r.will_close
        except Exception:
            try:
                c.close()
            except Exception:
                pass
            raise
        if reuse:
            self._checkin(c)
        else:
            c.close()
        return status, reply, out

    def close(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
        for c in idle:
            try:
                c.close()
            except Exception:
                pass


def forward_headers(headers: Dict[str, str]) -> Dict[str, str]:
    """The member-ward header set: ``X-Pio-*`` (priority, deadline,
    trace) plus content framing; hop-by-hop headers stay behind."""
    out = {}
    for k, v in headers.items():
        lk = k.lower()
        if lk.startswith(_FORWARD_HEADER_PREFIX) or lk in _FORWARD_HEADERS:
            out[k] = v
    return out


class ServingRouter:
    """Pick/forward engine shared by the daemon and tests.

    ``targets`` is the configured fleet as ``(name, base_url)`` pairs
    (the :func:`pio_tpu.obs.fleet.parse_targets` shape).
    """

    def __init__(
        self,
        targets: Sequence[Tuple[str, str]],
        registry: MetricsRegistry,
        partitions: Optional[int] = None,
        burn_limit: Optional[float] = None,
        lag_soft_bytes: Optional[float] = None,
        timeout_s: float = 5.0,
        forced_down_s: float = 10.0,
    ):
        if not targets:
            raise ValueError("router needs at least one member target")
        self.burn_limit = (
            burn_limit if burn_limit is not None
            else env_float(BURN_LIMIT_ENV, DEFAULT_BURN_LIMIT, positive=True)
        )
        self.lag_soft_bytes = (
            lag_soft_bytes if lag_soft_bytes is not None
            else env_float(
                LAG_SOFT_ENV, float(DEFAULT_LAG_SOFT_BYTES), positive=True
            )
        )
        self.timeout_s = timeout_s
        self.forced_down_s = forced_down_s
        self._members: Dict[str, MemberState] = {}
        self._pools: Dict[str, _UpstreamPool] = {}
        for name, base_url in targets:
            parts = urlsplit(base_url)
            host = parts.hostname or "127.0.0.1"
            port = parts.port or (443 if parts.scheme == "https" else 80)
            self._members[name] = MemberState(
                name=name, base_url=base_url, host=host, port=port
            )
            self._pools[name] = _UpstreamPool(host, port, timeout_s)
        self.ring = Ring(self._members.keys(), partitions)
        self._lock = threading.Lock()
        self._rr = 0
        self.obs = registry
        self._forwarded = registry.counter(
            "pio_tpu_router_forwarded_total",
            "Requests relayed to a member (retries counted there too)",
            ("member",),
        )
        self._retried = registry.counter(
            "pio_tpu_router_retried_total",
            "Relays that were the one-shot retry after a transport "
            "error, labeled by the member that absorbed the retry",
            ("member",),
        )
        self._shed = registry.counter(
            "pio_tpu_router_shed_total",
            "Requests the router itself refused, by reason",
            ("reason",),
        )
        self._forward_errors = registry.counter(
            "pio_tpu_router_forward_errors_total",
            "Transport failures talking to a member",
            ("member",),
        )
        self._deploys = registry.counter(
            "pio_tpu_router_deploys_total",
            "Deploy pushes by member and outcome "
            "(verified / rejected / error)",
            ("member", "outcome"),
        )
        self._pick_seconds = registry.histogram(
            "pio_tpu_router_pick_seconds",
            "Replica ranking latency (health gate + ring rank + spread)",
        )
        self._ring_size = registry.gauge(
            "pio_tpu_router_ring_size",
            "Members currently routable (scrape-live, not forced down)",
        )
        self._member_routable = registry.gauge(
            "pio_tpu_router_member_routable",
            "1 while the member is in the ring, else 0",
            ("member",),
        )
        for name in self._members:
            self._forwarded.labels(name)
            self._retried.labels(name)
            self._forward_errors.labels(name)
            self._member_routable.set(0.0, member=name)
        self._ring_size.set(0.0)

    # -- health/load ingestion --------------------------------------------
    def ingest_fleet(self, payload: dict) -> None:
        """Fold a ``fleet_payload()`` snapshot into the member table:
        scrape status, per-member worst burn, worst follower lag."""
        lag_by_follower: Dict[str, int] = {}
        for leader in (payload.get("partlog") or {}).get("leaders", []):
            for part in leader.get("partitionDetail", []):
                for f in part.get("followers", []):
                    name, lag = f.get("follower"), f.get("lagBytes")
                    if name is None or lag is None:
                        continue
                    lag_by_follower[name] = max(
                        lag_by_follower.get(name, 0), int(lag)
                    )
        with self._lock:
            for entry in payload.get("members", []):
                ms = self._members.get(entry.get("member"))
                if ms is None:
                    continue
                ms.status = entry.get("status") or "unknown"
                slo = entry.get("slo") or {}
                burn = slo.get("worstBurn")
                ms.burn = float(burn) if burn is not None else 0.0
                ms.lag_bytes = lag_by_follower.get(ms.name, 0)
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        now = monotonic_s()
        n = 0
        for ms in self._members.values():
            ok = self._routable(ms, now)
            n += 1 if ok else 0
            self._member_routable.set(1.0 if ok else 0.0, member=ms.name)
        self._ring_size.set(float(n))

    @staticmethod
    def _routable(ms: MemberState, now: float) -> bool:
        # "unknown" rides: before the first scrape pass the router must
        # not blackhole the fleet — a truly dead member fails its first
        # forward and is forced down right there.
        if ms.forced_down_until > now:
            return False
        return ms.status in ("up", "stale", "unknown")

    def note_failure(self, member: str) -> None:
        """Passive health: a transport error takes the member out of
        the ring immediately, without waiting for the scrape loop's
        stale->down progression."""
        ms = self._members.get(member)
        if ms is None:
            return
        self._forward_errors.inc(member=member)
        ms.forced_down_until = monotonic_s() + self.forced_down_s
        self._refresh_gauges()
        log.warning(
            "member %s forced down for %.1fs after transport error",
            member, self.forced_down_s,
        )

    def note_deploy(self, member: str, instance_id: str,
                    outcome: str) -> None:
        self._deploys.inc(member=member, outcome=outcome)
        if outcome == "verified":
            ms = self._members.get(member)
            if ms is not None:
                ms.generation = instance_id

    # -- pick --------------------------------------------------------------
    def _load_score(self, ms: MemberState) -> float:
        return ms.burn + ms.lag_bytes / self.lag_soft_bytes

    def _spread_order(self, routable: List[str]) -> List[str]:
        with self._lock:
            self._rr += 1
            rot = self._rr % len(routable)
        rotated = routable[rot:] + routable[:rot]
        # stable sort: equal load scores keep the rotation, so an idle
        # fleet round-robins instead of hammering the first member
        return sorted(
            rotated, key=lambda m: self._load_score(self._members[m])
        )

    def pick(self, entity_id: Optional[str],
             priority: str = "") -> List[MemberState]:
        """Ordered forward plan for one request; raises :class:`Shed`
        when the router must answer the overload itself."""
        t0 = monotonic_s()
        failpoint("router.pick")
        routable = [
            name for name, ms in self._members.items()
            if self._routable(ms, t0)
        ]
        if not routable:
            self._shed.inc(reason="no_members")
            raise Shed(503, "no_members", self.forced_down_s)
        if entity_id:
            order = self.ring.rank(entity_id, routable)
        else:
            order = self._spread_order(routable)
        calm = [
            m for m in order if self._members[m].burn < self.burn_limit
        ]
        if calm:
            if len(calm) != len(order):
                # demote burning replicas behind calm ones, both halves
                # keeping ring order (affinity still wins among calm)
                order = calm + [m for m in order if m not in calm]
        else:
            if priority_floor(priority) > 0.0:
                # every replica is burning: non-interactive classes are
                # the error budget's relief valve, exactly as on-member
                self._shed.inc(reason="slo_burn")
                raise Shed(503, "slo_burn", self.forced_down_s)
            order = sorted(order, key=lambda m: self._members[m].burn)
        self._pick_seconds.observe(monotonic_s() - t0)
        return [self._members[m] for m in order]

    # -- forward -----------------------------------------------------------
    def forward(self, method, path, body, headers,
                entity_id=None, priority=""):  # pio: hotpath=zerocopy
        """Relay one request, retrying once on the next replica after a
        transport error.  ``body`` goes through untouched — on the
        packed int8 wire that is the zero-copy contract end to end."""
        plan = self.pick(entity_id, priority)
        hdrs = forward_headers(headers)
        last_exc = None
        for attempt, ms in enumerate(plan[:2]):
            failpoint("router.forward")
            try:
                status, reply, out = self._pools[ms.name].request(
                    method, path, body, hdrs
                )
            except Exception as e:
                self.note_failure(ms.name)
                last_exc = e
                continue
            self._forwarded.inc(member=ms.name)
            if attempt:
                self._retried.inc(member=ms.name)
            return status, reply, out, ms.name
        self._shed.inc(reason="upstream_unreachable")
        raise Shed(503, "upstream_unreachable", self.forced_down_s) \
            from last_exc

    # -- introspection -----------------------------------------------------
    def snapshot(self) -> dict:
        """The ``/router.json`` member/ring view (schema documented in
        docs/observability.md)."""
        now = monotonic_s()
        members = []
        for ms in self._members.values():
            members.append({
                "member": ms.name,
                "url": ms.base_url,
                "status": ms.status,
                "routable": self._routable(ms, now),
                "worstBurn": round(ms.burn, 4),
                "lagBytes": ms.lag_bytes,
                "generation": ms.generation,
                "forwarded": int(self._forwarded.value(ms.name)),
                "retried": int(self._retried.value(ms.name)),
                "errors": int(self._forward_errors.value(ms.name)),
            })
        routable = [m["member"] for m in members if m["routable"]]
        return {
            "ring": {
                "members": list(self.ring.members),
                "partitions": self.ring.partitions,
                "routable": routable,
                "size": len(routable),
            },
            "policy": {
                "burnLimit": self.burn_limit,
                "lagSoftBytes": self.lag_soft_bytes,
                "forcedDownSeconds": self.forced_down_s,
            },
            "members": members,
        }

    def close(self) -> None:
        for pool in self._pools.values():
            pool.close()
