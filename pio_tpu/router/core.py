"""Serving-router core: health-gated affine pick, forward with one
retry, SLO-aware spreading, QoS-vocabulary shedding.

The router is the front tier of the serving fabric: it owns no model
bytes, only a :class:`~pio_tpu.router.ring.Ring` over the configured
members plus a continuously refreshed health/load view (ingested from
the embedded fleet aggregator's ``fleet_payload()``).  Request flow:

1. **pick** — ``router.pick`` failpoint, then rank replicas for the
   entity id (affinity + rendezvous), restricted to routable members
   (not scrape-``down``, not passively forced down, see below).  Keyless
   requests (the packed int8 wire carries no entity id) spread by load
   score with a rotation tiebreak instead.
2. **spread** — replicas whose worst SLO burn is at or past the burn
   limit are demoted behind calm ones; when *every* replica burns,
   classes with a non-zero priority floor (``batchpredict``/``shadow``)
   are shed with the standard QoS vocabulary (503 + ``Retry-After``)
   while ``interactive`` rides the least-burning replica.
3. **forward** — ``router.forward`` failpoint per attempt, then relay
   over a keep-alive upstream connection.  A transport error marks the
   member passively down for ``forced_down_s`` (so the very next pick
   skips it — scrape confirmation follows within two intervals) and the
   request is retried ONCE on the next replica in ring order.  Upstream
   status codes, including 5xx, are relayed as-is: a delivered response
   is the member's answer, not the router's to rewrite.

Shedding raises :class:`Shed`; the daemon maps it onto 429/503 with
``Retry-After`` via the qos helpers so clients see one overload grammar
whether a member or the router said no.
"""

from __future__ import annotations

import http.client
import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple
from urllib.parse import urlsplit

from pio_tpu.utils import knobs
from pio_tpu.faults import failpoint
from pio_tpu.obs import monotonic_s
from pio_tpu.obs.metrics import MetricsRegistry
from pio_tpu.qos.policy import priority_floor
from pio_tpu.router.ring import Ring

log = logging.getLogger("pio_tpu.router")

__all__ = [
    "BURN_LIMIT_ENV",
    "DEFAULT_BURN_LIMIT",
    "DEFAULT_LAG_SOFT_BYTES",
    "HEDGE_ENV",
    "LAG_SOFT_ENV",
    "MemberState",
    "ServingRouter",
    "Shed",
    "UpstreamReply",
]

#: worst-burn at/over which a replica is demoted (and non-interactive
#: classes shed when every replica is there). 2.0 = burning the error
#: budget at twice the sustainable rate, the classic page threshold.
BURN_LIMIT_ENV = "PIO_TPU_ROUTER_BURN_LIMIT"
DEFAULT_BURN_LIMIT = 2.0

#: replication lag that adds +1.0 to a member's load score — soft
#: pressure away from laggy followers, never a hard gate.
LAG_SOFT_ENV = "PIO_TPU_ROUTER_LAG_SOFT_BYTES"
DEFAULT_LAG_SOFT_BYTES = 64 * 1024 * 1024

#: per-request hedge budget in milliseconds: after this long without a
#: primary answer, the same query is fired at the next ring replica and
#: the first answer wins. 0 / unset = hedging off (the default — tail
#: hedging doubles worst-case member load, an operator opt-in).
HEDGE_ENV = "PIO_TPU_ROUTER_HEDGE_MS"

#: headers relayed member-ward: the QoS/trace vocabulary must survive
#: the hop (priority floors honored end-to-end) but hop-by-hop framing
#: must not.
_FORWARD_HEADER_PREFIX = "x-pio-"
_FORWARD_HEADERS = ("content-type", "authorization")
_DROP_REPLY_HEADERS = frozenset(
    ("connection", "keep-alive", "transfer-encoding", "content-length")
)


class Shed(Exception):
    """The router itself refused the request (no routable member, or
    SLO pressure + priority floor). Carries the QoS vocabulary."""

    def __init__(self, status: int, reason: str, retry_after_s: float):
        super().__init__(f"shed: {reason}")
        self.status = status
        self.reason = reason
        self.retry_after_s = retry_after_s


#: (status, reply headers, body bytes, member name)
UpstreamReply = Tuple[int, Dict[str, str], bytes, str]


@dataclass
class MemberState:
    """Router-side view of one serving member."""

    name: str
    base_url: str
    host: str
    port: int
    status: str = "unknown"        # scrape view: up|stale|down|unknown
    burn: float = 0.0              # worst SLO burn across objectives
    lag_bytes: int = 0             # worst follower replication lag
    generation: Optional[str] = None   # last verified-deployed instance
    forced_down_until: float = 0.0     # passive-failure gate (monotonic)
    #: device-budget headroom from the member's fleet row; None until
    #: scraped. <= 0 demotes the member before it burns SLO budget.
    headroom_bytes: Optional[float] = None
    #: aux members (rollout candidates) hold a pooled upstream but never
    #: join the incumbent ring or take undiverted traffic
    aux: bool = False


class _UpstreamPool:
    """Keep-alive ``http.client`` connections to one member; handler
    threads check one out per request and return it after a clean,
    fully-read response (anything else closes it)."""

    def __init__(self, host: str, port: int, timeout_s: float):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self._idle: List[http.client.HTTPConnection] = []
        self._lock = threading.Lock()

    def _checkout(self) -> http.client.HTTPConnection:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout_s
        )

    def _checkin(self, c: http.client.HTTPConnection) -> None:
        with self._lock:
            if len(self._idle) < 8:
                self._idle.append(c)
                return
        c.close()

    def request(self, method, path, body, headers):  # pio: hotpath=zerocopy
        """One relayed exchange; the request body bytes are handed to
        the kernel as-is (no re-encode, no staging copy)."""
        c = self._checkout()
        try:
            c.request(method, path, body=body, headers=headers)
            r = c.getresponse()
            out = r.read()
            reply = {}
            for k, v in r.getheaders():
                if k.lower() not in _DROP_REPLY_HEADERS:
                    reply[k] = v
            status = r.status
            reuse = not r.will_close
        except Exception:
            try:
                c.close()
            except Exception:
                pass
            raise
        if reuse:
            self._checkin(c)
        else:
            c.close()
        return status, reply, out

    def close(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
        for c in idle:
            try:
                c.close()
            except Exception:
                pass


def forward_headers(headers: Dict[str, str]) -> Dict[str, str]:
    """The member-ward header set: ``X-Pio-*`` (priority, deadline,
    trace) plus content framing; hop-by-hop headers stay behind."""
    out = {}
    for k, v in headers.items():
        lk = k.lower()
        if lk.startswith(_FORWARD_HEADER_PREFIX) or lk in _FORWARD_HEADERS:
            out[k] = v
    return out


class ServingRouter:
    """Pick/forward engine shared by the daemon and tests.

    ``targets`` is the configured fleet as ``(name, base_url)`` pairs
    (the :func:`pio_tpu.obs.fleet.parse_targets` shape).
    """

    def __init__(
        self,
        targets: Sequence[Tuple[str, str]],
        registry: MetricsRegistry,
        partitions: Optional[int] = None,
        burn_limit: Optional[float] = None,
        lag_soft_bytes: Optional[float] = None,
        timeout_s: float = 5.0,
        forced_down_s: float = 10.0,
        hedge_ms: Optional[float] = None,
    ):
        if not targets:
            raise ValueError("router needs at least one member target")
        self.burn_limit = (
            burn_limit if burn_limit is not None
            else knobs.knob_float(BURN_LIMIT_ENV)
        )
        self.lag_soft_bytes = (
            lag_soft_bytes if lag_soft_bytes is not None
            else knobs.knob_float(LAG_SOFT_ENV)
        )
        if hedge_ms is None:
            hedge_ms = knobs.knob_float(HEDGE_ENV)
        self.hedge_s = max(float(hedge_ms), 0.0) / 1e3
        self.timeout_s = timeout_s
        self.forced_down_s = forced_down_s
        #: opaque rollout hooks (see router/rollout.py). ``_observer``
        #: sees every completed relay off the return path; ``_divert``
        #: may put a canary member in front of the ring plan. Stored
        #: untyped and called through locals so the relay keeps its
        #: zero-copy/blocking contract regardless of what a controller
        #: plugs in.
        self._observer = None
        self._divert = None
        self._members: Dict[str, MemberState] = {}
        self._pools: Dict[str, _UpstreamPool] = {}
        for name, base_url in targets:
            parts = urlsplit(base_url)
            host = parts.hostname or "127.0.0.1"
            port = parts.port or (443 if parts.scheme == "https" else 80)
            self._members[name] = MemberState(
                name=name, base_url=base_url, host=host, port=port
            )
            self._pools[name] = _UpstreamPool(host, port, timeout_s)
        self.ring = Ring(self._members.keys(), partitions)
        self._lock = threading.Lock()
        self._rr = 0
        self.obs = registry
        self._forwarded = registry.counter(
            "pio_tpu_router_forwarded_total",
            "Requests relayed to a member (retries counted there too)",
            ("member",),
        )
        self._retried = registry.counter(
            "pio_tpu_router_retried_total",
            "Relays that were the one-shot retry after a transport "
            "error, labeled by the member that absorbed the retry",
            ("member",),
        )
        self._shed = registry.counter(
            "pio_tpu_router_shed_total",
            "Requests the router itself refused, by reason",
            ("reason",),
        )
        self._forward_errors = registry.counter(
            "pio_tpu_router_forward_errors_total",
            "Transport failures talking to a member",
            ("member",),
        )
        self._deploys = registry.counter(
            "pio_tpu_router_deploys_total",
            "Deploy pushes by member and outcome "
            "(verified / rejected / error)",
            ("member", "outcome"),
        )
        self._hedged = registry.counter(
            "pio_tpu_router_hedged_total",
            "Relays that fired a hedge at the next replica, by outcome "
            "(primary_won / hedge_won / error)",
            ("outcome",),
        )
        self._pick_seconds = registry.histogram(
            "pio_tpu_router_pick_seconds",
            "Replica ranking latency (health gate + ring rank + spread)",
        )
        self._ring_size = registry.gauge(
            "pio_tpu_router_ring_size",
            "Members currently routable (scrape-live, not forced down)",
        )
        self._member_routable = registry.gauge(
            "pio_tpu_router_member_routable",
            "1 while the member is in the ring, else 0",
            ("member",),
        )
        for name in self._members:
            self._forwarded.labels(name)
            self._retried.labels(name)
            self._forward_errors.labels(name)
            self._member_routable.set(0.0, member=name)
        self._ring_size.set(0.0)

    # -- membership / rollout hooks ----------------------------------------
    def set_observer(self, observer) -> None:
        """Install (or clear, with None) the completed-relay hook:
        ``observer(method, path, body, headers, entity_id, priority,
        member, status, body_out, elapsed_s)``. Must never raise."""
        self._observer = observer

    def set_divert(self, divert) -> None:
        """Install (or clear, with None) the canary divert hook:
        ``divert(entity_id, priority) -> member_name | None`` consulted
        at pick time; a returned routable member fronts the plan with
        the normal ring order behind it (retry covers it dying)."""
        self._divert = divert

    def add_member(self, name: str, base_url: str,
                   aux: bool = False) -> MemberState:
        """Register a member at runtime. ``aux`` members (rollout
        candidates) get a pooled upstream and metric cells but stay out
        of the ring and take no traffic unless diverted."""
        with self._lock:
            existing = self._members.get(name)
            if existing is not None:
                return existing
            parts = urlsplit(base_url)
            host = parts.hostname or "127.0.0.1"
            port = parts.port or (443 if parts.scheme == "https" else 80)
            ms = MemberState(
                name=name, base_url=base_url, host=host, port=port, aux=aux
            )
            self._members[name] = ms
            self._pools[name] = _UpstreamPool(host, port, self.timeout_s)
        self._forwarded.labels(name)
        self._retried.labels(name)
        self._forward_errors.labels(name)
        if not aux:
            self.ring = Ring(
                [n for n, m in self._members.items() if not m.aux],
                self.ring.partitions,
            )
        self._refresh_gauges()
        return ms

    def remove_member(self, name: str) -> None:
        """Drop a member and close its keep-alive upstream sockets
        immediately — a removed member must leave no open FDs behind."""
        with self._lock:
            ms = self._members.pop(name, None)
            pool = self._pools.pop(name, None)
        if pool is not None:
            pool.close()
        if ms is None:
            return
        self._member_routable.set(0.0, member=name)
        if not ms.aux:
            self.ring = Ring(
                [n for n, m in self._members.items() if not m.aux],
                self.ring.partitions,
            )
        self._refresh_gauges()

    def has_member(self, name: str) -> bool:
        return name in self._members

    def member(self, name: str) -> Optional[MemberState]:
        return self._members.get(name)

    def ring_members(self) -> List[MemberState]:
        """The non-aux members (the incumbent ring's population)."""
        return [ms for ms in self._members.values() if not ms.aux]

    def upstream_request(self, member: str, method, path, body, headers):
        """One exchange over ``member``'s keep-alive pool (the rollout
        mirror path; the relay itself goes through :meth:`forward`)."""
        pool = self._pools.get(member)
        if pool is None:
            raise KeyError(f"unknown member {member!r}")
        return pool.request(method, path, body, headers)

    # -- health/load ingestion --------------------------------------------
    # pio: consumes=/fleet.json
    def ingest_fleet(self, payload: dict) -> None:
        """Fold a ``fleet_payload()`` snapshot into the member table:
        scrape status, per-member worst burn, worst follower lag."""
        lag_by_follower: Dict[str, int] = {}
        for leader in (payload.get("partlog") or {}).get("leaders", []):
            for part in leader.get("partitionDetail", []):
                for f in part.get("followers", []):
                    name, lag = f.get("follower"), f.get("lagBytes")
                    if name is None or lag is None:
                        continue
                    lag_by_follower[name] = max(
                        lag_by_follower.get(name, 0), int(lag)
                    )
        with self._lock:
            for entry in payload.get("members", []):
                ms = self._members.get(entry.get("member"))
                if ms is None:
                    continue
                ms.status = entry.get("status") or "unknown"
                slo = entry.get("slo") or {}
                burn = slo.get("worstBurn")
                ms.burn = float(burn) if burn is not None else 0.0
                dev = entry.get("devices") or {}
                headroom = dev.get("headroomBytes")
                ms.headroom_bytes = (
                    float(headroom) if headroom is not None else None
                )
                ms.lag_bytes = lag_by_follower.get(ms.name, 0)
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        now = monotonic_s()
        n = 0
        for ms in list(self._members.values()):
            ok = self._routable(ms, now)
            n += 1 if (ok and not ms.aux) else 0
            self._member_routable.set(1.0 if ok else 0.0, member=ms.name)
        self._ring_size.set(float(n))

    @staticmethod
    def _routable(ms: MemberState, now: float) -> bool:
        # "unknown" rides: before the first scrape pass the router must
        # not blackhole the fleet — a truly dead member fails its first
        # forward and is forced down right there.
        if ms.forced_down_until > now:
            return False
        return ms.status in ("up", "stale", "unknown")

    def note_failure(self, member: str) -> None:
        """Passive health: a transport error takes the member out of
        the ring immediately, without waiting for the scrape loop's
        stale->down progression."""
        ms = self._members.get(member)
        if ms is None:
            return
        self._forward_errors.inc(member=member)
        ms.forced_down_until = monotonic_s() + self.forced_down_s
        # a dead member's keep-alive sockets go NOW, not when they idle
        # out: every parked connection is an FD pointing at a corpse
        pool = self._pools.get(member)
        if pool is not None:
            pool.close()
        self._refresh_gauges()
        log.warning(
            "member %s forced down for %.1fs after transport error",
            member, self.forced_down_s,
        )

    def note_deploy(self, member: str, instance_id: str,
                    outcome: str) -> None:
        self._deploys.inc(member=member, outcome=outcome)
        if outcome == "verified":
            ms = self._members.get(member)
            if ms is not None:
                ms.generation = instance_id

    # -- pick --------------------------------------------------------------
    def _load_score(self, ms: MemberState) -> float:
        score = ms.burn + ms.lag_bytes / self.lag_soft_bytes
        if ms.headroom_bytes is not None and ms.headroom_bytes <= 0.0:
            # exhausted HBM weighs like a full burn-limit of SLO burn:
            # the member demotes before it starts failing for real
            score += self.burn_limit
        return score

    def _pressured(self, ms: MemberState) -> bool:
        """Demotion gate: SLO burn at/over the limit, or device budget
        headroom exhausted (the member would start thrashing/rejecting
        before the burn shows up in its scrape)."""
        if ms.burn >= self.burn_limit:
            return True
        return ms.headroom_bytes is not None and ms.headroom_bytes <= 0.0

    def _spread_order(self, routable: List[str]) -> List[str]:
        with self._lock:
            self._rr += 1
            rot = self._rr % len(routable)
        rotated = routable[rot:] + routable[:rot]
        # stable sort: equal load scores keep the rotation, so an idle
        # fleet round-robins instead of hammering the first member
        return sorted(
            rotated, key=lambda m: self._load_score(self._members[m])
        )

    def pick(self, entity_id: Optional[str],
             priority: str = "") -> List[MemberState]:
        """Ordered forward plan for one request; raises :class:`Shed`
        when the router must answer the overload itself."""
        t0 = monotonic_s()
        failpoint("router.pick")
        routable = [
            name for name, ms in self._members.items()
            if not ms.aux and self._routable(ms, t0)
        ]
        if not routable:
            self._shed.inc(reason="no_members")
            raise Shed(503, "no_members", self.forced_down_s)
        if entity_id:
            order = self.ring.rank(entity_id, routable)
        else:
            order = self._spread_order(routable)
        calm = [
            m for m in order if not self._pressured(self._members[m])
        ]
        if calm:
            if len(calm) != len(order):
                # demote pressured replicas (burning, or out of device
                # headroom) behind calm ones, both halves keeping ring
                # order (affinity still wins among calm)
                order = calm + [m for m in order if m not in calm]
        else:
            if priority_floor(priority) > 0.0:
                # every replica is pressured: non-interactive classes
                # are the error budget's relief valve, exactly as
                # on-member
                self._shed.inc(reason="slo_burn")
                raise Shed(503, "slo_burn", self.forced_down_s)
            order = sorted(order, key=lambda m: self._load_score(
                self._members[m]))
        divert = self._divert
        if divert is not None:
            cand = divert(entity_id, priority)
            if cand is not None and cand not in order:
                cms = self._members.get(cand)
                if cms is not None and self._routable(cms, t0):
                    # canary front: the candidate takes the request,
                    # the incumbent plan stays behind it as the retry
                    order = [cand] + order
        self._pick_seconds.observe(monotonic_s() - t0)
        return [self._members[m] for m in order]

    # -- forward -----------------------------------------------------------
    def forward(self, method, path, body, headers,
                entity_id=None, priority=""):  # pio: hotpath=zerocopy
        """Relay one request, retrying once on the next replica after a
        transport error.  ``body`` goes through untouched — on the
        packed int8 wire that is the zero-copy contract end to end.
        With ``PIO_TPU_ROUTER_HEDGE_MS`` set, interactive requests that
        outlive the hedge budget race the next replica instead."""
        plan = self.pick(entity_id, priority)
        hdrs = forward_headers(headers)
        if (self.hedge_s > 0.0 and len(plan) >= 2
                and priority_floor(priority) == 0.0):
            return self._forward_hedged(
                method, path, body, hdrs, plan, entity_id, priority
            )
        last_exc = None
        for attempt, ms in enumerate(plan[:2]):
            failpoint("router.forward")
            t0 = monotonic_s()
            try:
                status, reply, out = self._pools[ms.name].request(
                    method, path, body, hdrs
                )
            except Exception as e:
                self.note_failure(ms.name)
                last_exc = e
                continue
            self._forwarded.inc(member=ms.name)
            if attempt:
                self._retried.inc(member=ms.name)
            self._observe_relay(method, path, body, hdrs, entity_id,
                                priority, ms.name, status, out,
                                monotonic_s() - t0)
            return status, reply, out, ms.name
        self._shed.inc(reason="upstream_unreachable")
        raise Shed(503, "upstream_unreachable", self.forced_down_s) \
            from last_exc

    def _forward_hedged(self, method, path, body, hdrs, plan,
                        entity_id, priority):
        """Tail-latency hedge: the primary gets ``hedge_s`` to answer;
        then (or immediately on a primary transport error) the same
        request fires at the next replica and the first answer wins —
        the loser finishes in the background against its own pool."""
        cond = threading.Condition()
        results: List[Tuple[MemberState, Tuple]] = []
        errors: List[MemberState] = []

        def attempt(ms):
            try:
                got = self._pools[ms.name].request(method, path, body, hdrs)
            except Exception:
                self.note_failure(ms.name)
                with cond:
                    errors.append(ms)
                    cond.notify_all()
                return
            with cond:
                results.append((ms, got))
                cond.notify_all()

        primary, backup = plan[0], plan[1]
        t0 = monotonic_s()
        threading.Thread(
            target=attempt, args=(primary,), daemon=True
        ).start()
        with cond:
            # the hedge budget itself — an intentional bounded wait,
            # the whole point of the opt-in knob
            # pio: disable=hotpath-blocking
            cond.wait_for(lambda: results or errors,
                          timeout=self.hedge_s)
            need_hedge = not results
        if not need_hedge:
            ms, (status, reply, out) = results[0]
            self._forwarded.inc(member=ms.name)
            self._observe_relay(method, path, body, hdrs, entity_id,
                                priority, ms.name, status, out,
                                monotonic_s() - t0)
            return status, reply, out, ms.name
        failpoint("router.forward.hedge")
        threading.Thread(
            target=attempt, args=(backup,), daemon=True
        ).start()
        deadline = monotonic_s() + self.timeout_s + 1.0
        with cond:
            while not results and len(errors) < 2:
                remaining = deadline - monotonic_s()
                if remaining <= 0.0:
                    break
                # racing two in-flight upstreams; bounded by the pool
                # timeout either way
                cond.wait(remaining)  # pio: disable=hotpath-blocking
            got = list(results)
        if not got:
            self._hedged.inc(outcome="error")
            self._shed.inc(reason="upstream_unreachable")
            raise Shed(503, "upstream_unreachable", self.forced_down_s)
        ms, (status, reply, out) = got[0]
        won = "primary_won" if ms.name == primary.name else "hedge_won"
        self._hedged.inc(outcome=won)
        self._forwarded.inc(member=ms.name)
        if won == "hedge_won":
            self._retried.inc(member=ms.name)
        self._observe_relay(method, path, body, hdrs, entity_id, priority,
                            ms.name, status, out, monotonic_s() - t0)
        return status, reply, out, ms.name

    def _observe_relay(self, method, path, body, hdrs, entity_id,
                       priority, member, status, out, elapsed_s) -> None:
        observer = self._observer
        if observer is None:
            return
        try:
            observer(method, path, body, hdrs, entity_id, priority,
                     member, status, out, elapsed_s)
        except Exception:
            pass

    # -- introspection -----------------------------------------------------
    # pio: endpoint=/router.json
    def snapshot(self) -> dict:
        """The ``/router.json`` member/ring view (schema documented in
        docs/observability.md)."""
        now = monotonic_s()
        members = []
        for ms in list(self._members.values()):
            members.append({
                "member": ms.name,
                "url": ms.base_url,
                "status": ms.status,
                "routable": self._routable(ms, now),
                "aux": ms.aux,
                "worstBurn": round(ms.burn, 4),
                "lagBytes": ms.lag_bytes,
                "headroomBytes": ms.headroom_bytes,
                "generation": ms.generation,
                "forwarded": int(self._forwarded.value(ms.name)),
                "retried": int(self._retried.value(ms.name)),
                "errors": int(self._forward_errors.value(ms.name)),
            })
        routable = [
            m["member"] for m in members if m["routable"] and not m["aux"]
        ]
        return {
            "ring": {
                "members": list(self.ring.members),
                "partitions": self.ring.partitions,
                "routable": routable,
                "size": len(routable),
            },
            "policy": {
                "burnLimit": self.burn_limit,
                "lagSoftBytes": self.lag_soft_bytes,
                "forcedDownSeconds": self.forced_down_s,
                "hedgeMs": round(self.hedge_s * 1e3, 3),
            },
            "members": members,
        }

    def close(self) -> None:
        for pool in self._pools.values():
            pool.close()
