"""Two-tower retrieval template — neural personalized recommendation.

BASELINE.json config #5 ("Two-tower / Wide&Deep recommender template") —
capability-forward: the reference's recommenders are ALS-factor based
(examples/scala-parallel-{recommendation,similarproduct} — UNVERIFIED
paths; SURVEY.md §2.5); this template serves the same query shape from a
learned two-tower model (pio_tpu/models/two_tower.py) whose training step
shards dp × tp × ep over the device mesh.

engine.json:

    {
      "id": "twotower",
      "engineFactory": "templates.twotower",
      "datasource": {"params": {"app_name": "myapp"}},
      "algorithms": [{"name": "twotower", "params":
          {"out_dim": 64, "steps": 500, "model_parallel": 1}}]
    }

Query ``{"user": "u1", "num": 4}`` →
``{"itemScores": [{"item": "i5", "score": 0.93}, ...]}`` — identical wire
shape to the recommendation template, so clients can switch engines without
code changes.
"""

from __future__ import annotations

import dataclasses

from pio_tpu.controller import (
    Algorithm,
    Engine,
    FirstServing,
    Params,
    register_engine,
)
from pio_tpu.data.bimap import BiMap
from pio_tpu.models.two_tower import (
    TwoTowerConfig,
    TwoTowerModel,
    train_two_tower,
)
from pio_tpu.parallel.context import ComputeContext
from pio_tpu.parallel.mesh import MeshSpec, build_mesh
from pio_tpu.templates.common import DeviceScorerModel, PredictedResult
from pio_tpu.workflow.shard_store import ShardableModel
from pio_tpu.templates.recommendation import (
    PreparedData,
    Query,
    RecommendationDataSource,
    RecommendationPreparator,
    batched_user_topn,
    predict_user_topn,
)


@dataclasses.dataclass(frozen=True)
class TwoTowerParams(Params):
    embed_dim: int = 64
    hidden: int = 128
    out_dim: int = 64
    temperature: float = 20.0
    learning_rate: float = 1e-3
    steps: int = 500
    batch_size: int = 256
    seed: int = 0
    #: epoch feed: "off" stages the batches on device, "on" streams batch
    #: spans through parallel/stream.py, "auto" streams only when staging
    #: would exceed PIO_TPU_DEVICE_BUDGET_BYTES
    stream: str = "auto"
    #: mesh split: model axis size (tp/ep); remaining devices ride data (dp)
    model_parallel: int = 1


@dataclasses.dataclass
class TwoTowerEngineModel(DeviceScorerModel, ShardableModel):
    model: TwoTowerModel
    user_index: BiMap
    item_index: BiMap

    shard_template = "two_tower"

    def _scorer_factors(self):
        return self.model.user_vectors, self.model.item_vectors

    def shard_arrays(self):
        return {
            "user_vectors": self.model.user_vectors,
            "item_vectors": self.model.item_vectors,
        }

    def replace_shard_arrays(self, arrays):
        return dataclasses.replace(
            self,
            model=dataclasses.replace(
                self.model,
                user_vectors=arrays["user_vectors"],
                item_vectors=arrays["item_vectors"],
            ),
        )


class TwoTowerAlgorithm(Algorithm):
    """Contrastive two-tower training on the interaction pairs."""

    params_class = TwoTowerParams
    query_class = Query

    def _mesh(self, ctx: ComputeContext):
        p: TwoTowerParams = self.params
        if ctx.mesh is None:
            return None
        devices = list(ctx.mesh.devices.flat)
        mp = max(1, min(p.model_parallel, len(devices)))
        return build_mesh(
            MeshSpec(data=-1, model=mp), devices=devices
        )

    def train(
        self, ctx: ComputeContext, pd: PreparedData
    ) -> TwoTowerEngineModel:
        p: TwoTowerParams = self.params
        model = train_two_tower(
            self._mesh(ctx),
            pd.user_codes,
            pd.item_codes,
            n_users=len(pd.user_index),
            n_items=len(pd.item_index),
            config=TwoTowerConfig(
                embed_dim=p.embed_dim,
                hidden=p.hidden,
                out_dim=p.out_dim,
                temperature=p.temperature,
                learning_rate=p.learning_rate,
                steps=p.steps,
                batch_size=p.batch_size,
                seed=p.seed,
                stream=p.stream,
            ),
            checkpoint=ctx.checkpoint,
            checkpoint_every=ctx.checkpoint_every,
        )
        return TwoTowerEngineModel(model, pd.user_index, pd.item_index)

    def prepare_for_serving(
        self, model: TwoTowerEngineModel
    ) -> TwoTowerEngineModel:
        """Upload both tower-output tables to the accelerator once at
        deploy and pre-compile the single-query bucket."""
        model.scorer(warmup=True)
        return model

    def predict(
        self, model: TwoTowerEngineModel, query: Query
    ) -> PredictedResult:
        return predict_user_topn(
            model, query, model.user_index, model.item_index
        )

    def warmup_query(self, model: TwoTowerEngineModel):
        """Any known user exercises the batched top-N program — enough
        to compile each serving shape bucket at deploy."""
        if len(model.user_index) == 0:
            return None
        return Query(user=model.user_index.inverse[0])

    def batch_predict(self, model: TwoTowerEngineModel, queries):
        """Vectorized offline scoring: one device dispatch per chunk of
        known-user top-N queries (shared routing with the ALS template)."""
        return batched_user_topn(
            self, model, queries, model.user_index, model.item_index,
            model.scorer,
        )


class TwoTowerServing(FirstServing):
    pass


@register_engine("templates.twotower")
def twotower_engine() -> Engine:
    return Engine(
        RecommendationDataSource,
        RecommendationPreparator,
        {"twotower": TwoTowerAlgorithm},
        TwoTowerServing,
    )


# -------------------------------------------------------------- evaluation
def twotower_evaluation(
    app_name: str = "",
    eval_k: int = 3,
    eval_num: int = 10,
    out_dims=(32, 64),
    steps: int = 300,
    batch_size: int = 256,
):
    """Ready-made `pio eval` sweep: k-fold HitRate@``eval_num`` on held-out
    interactions over an output-dimension grid (retrieval quality is what
    a contrastive model optimizes — rating regression would be
    meaningless for it).

    Zero-arg CLI use reads the app from ``$PIO_TPU_EVAL_APP``:

        PIO_TPU_EVAL_APP=myapp python -m pio_tpu eval \\
            pio_tpu.templates.twotower:twotower_evaluation
    """
    from pio_tpu.controller.engine import EngineParams
    from pio_tpu.controller.evaluation import (
        EngineParamsGenerator, Evaluation,
    )
    from pio_tpu.templates.common import eval_app_name
    from pio_tpu.templates.recommendation import DataSourceParams
    from pio_tpu.templates.similarproduct import HitRateMetric

    if eval_k < 2:
        raise ValueError("k-fold evaluation needs eval_k >= 2")
    ds = DataSourceParams(
        app_name=eval_app_name(app_name), eval_k=eval_k,
        eval_mode="hitrate", eval_num=eval_num,
    )
    grid = [
        EngineParams(
            data_source_params=ds,
            algorithm_params_list=(
                ("twotower", TwoTowerParams(
                    out_dim=d, steps=steps, batch_size=batch_size,
                )),
            ),
        )
        for d in out_dims
    ]
    return Evaluation(
        twotower_engine(), HitRateMetric(),
        engine_params_generator=EngineParamsGenerator(grid),
    )
