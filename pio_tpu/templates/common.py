"""Shared template plumbing.

The reference templates each re-declare app-name lookup inside their
DataSource (``examples/scala-parallel-*/DataSource.scala``, UNVERIFIED;
SURVEY.md §2.5); here it is one helper shared by every bundled template.
"""

from __future__ import annotations

from typing import Optional, Tuple

from pio_tpu.storage import Storage


def resolve_app(params) -> Tuple[int, Optional[int]]:
    """(app_id, channel_id) from datasource params.

    ``params`` needs ``app_name``/``app_id`` and optionally ``channel``
    attributes (every bundled DataSourceParams has them).
    """
    app_id = params.app_id
    if params.app_name:
        app = Storage.get_meta_data_apps().get_by_name(params.app_name)
        if app is None:
            raise ValueError(f"app {params.app_name!r} not found")
        app_id = app.id
    if not app_id:
        raise ValueError("datasource params need app_name or app_id")
    channel_id = None
    channel = getattr(params, "channel", "")
    if channel:
        chans = Storage.get_meta_data_channels().get_by_app_id(app_id)
        match = [c for c in chans if c.name == channel]
        if not match:
            raise ValueError(f"channel {channel!r} not found")
        channel_id = match[0].id
    return app_id, channel_id
