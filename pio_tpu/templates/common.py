"""Shared template plumbing.

The reference templates each re-declare app-name lookup inside their
DataSource (``examples/scala-parallel-*/DataSource.scala``, UNVERIFIED;
SURVEY.md §2.5); here it is one helper shared by every bundled template.
"""

from __future__ import annotations

import dataclasses
import threading as _threading
from typing import FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from pio_tpu.utils import knobs
from pio_tpu.storage import Storage


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...] = ()

    def to_dict(self) -> dict:
        return {
            "itemScores": [
                {"item": s.item, "score": s.score} for s in self.item_scores
            ]
        }


class DeviceScorerModel:
    """Lazy per-model :class:`DeviceTopNScorer` cache with pickle-drop —
    one home for the serving-cache discipline shared by the factor-serving
    engine models (ALS recommendation, two-tower). Subclasses return the
    (row_factors, col_factors) pair from :meth:`_scorer_factors`."""

    def _scorer_factors(self) -> Tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError

    def scorer(self, warmup: bool = False):
        """Device-resident factor scorer, built once per deploy lifetime
        (factors upload on first use / at prepare_for_serving and stay on
        the accelerator; queries ship only integer codes). Lock-guarded:
        concurrent first requests in the threaded query server must not
        each upload the factor tables and re-run the link probes."""
        s = self.__dict__.get("_scorer")
        if s is None:
            with self.__dict__.setdefault("_scorer_lock", _threading.Lock()):
                s = self.__dict__.get("_scorer")
                if s is None:
                    from pio_tpu.ops.topn import DeviceTopNScorer

                    rows, cols = self._scorer_factors()
                    s = DeviceTopNScorer(
                        rows, cols, warmup=warmup,
                        mesh=self.__dict__.get("_serve_mesh"),
                    )
                    self.__dict__["_scorer"] = s
        return s

    def __getstate__(self):
        # device handles and jitted closures never serialize
        d = dict(self.__dict__)
        d.pop("_scorer", None)
        d.pop("_scorer_lock", None)
        d.pop("_serve_mesh", None)
        return d


def dedup_pair_indices(a, b) -> np.ndarray:
    """Indices of the first occurrence of each ``(a[i], b[i])`` pair, in
    order. K-fold holdouts dedupe interactions first so a repeated pair
    split across folds can't leak the held-out interaction into the
    training fold."""
    seen = set()
    keep = []
    for idx, pair in enumerate(zip(a, b)):
        if pair not in seen:
            seen.add(pair)
            keep.append(idx)
    return np.asarray(keep, np.int64)


def fold_assignments(n: int, k: int, seed: int = 0) -> np.ndarray:
    """Deterministic randomized fold labels ``[n] → {0..k-1}``.

    A sequential ``arange(n) % k`` is hazardous on time-ordered event
    frames: all users' minute-0 events come first, so index parity can
    systematically place entire users in one fold (observed: a 2-fold
    split training on only the odd users). A seeded permutation keeps
    folds reproducible without that structure."""
    rng = np.random.default_rng(seed)
    return rng.permutation(n) % k


def seen_exclusion_holdout(train_users, train_items, test_users,
                           test_items, make_query):
    """One home for the hitrate holdout protocol (recommendation/two-tower
    and e-commerce evaluations): per held-out (user, item) pair, build a
    query black-listing the user's training-fold items — a recommender
    ranks items it memorized first, so without the exclusion the held-out
    item is structurally disadvantaged. User-cold and item-cold pairs are
    unanswerable in that fold and skipped. ``make_query(user, black_list)``
    returns the template's query object; returns ``[(query, actual)]``."""
    seen: dict = {}
    for u, i in zip(train_users, train_items):
        seen.setdefault(str(u), []).append(str(i))
    known_items = {str(i) for i in train_items}
    return [
        (make_query(str(u), tuple(seen[str(u)])), str(i))
        for u, i in zip(test_users, test_items)
        if str(u) in seen and str(i) in known_items
    ]


def eval_app_name(app_name: str) -> str:
    """App for a bundled `pio eval` sweep: the explicit argument, or the
    ``$PIO_TPU_EVAL_APP`` environment fallback for zero-arg CLI use —
    one contract shared by every template's evaluation factory."""
    import os

    return app_name or knobs.knob_str("PIO_TPU_EVAL_APP")


def resolve_app(params) -> Tuple[int, Optional[int]]:
    """(app_id, channel_id) from datasource params.

    ``params`` needs ``app_name``/``app_id`` and optionally ``channel``
    attributes (every bundled DataSourceParams has them).
    """
    from pio_tpu.data.store import resolve_channel

    app_id = params.app_id
    if params.app_name:
        app = Storage.get_meta_data_apps().get_by_name(params.app_name)
        if app is None:
            raise ValueError(f"app {params.app_name!r} not found")
        app_id = app.id
    if not app_id:
        raise ValueError("datasource params need app_name or app_id")
    return app_id, resolve_channel(app_id, getattr(params, "channel", ""))


# ------------------------------------------------ shared item-scoring rules
def l2_normalize_rows(f: np.ndarray) -> np.ndarray:
    """Row-normalize factors for cosine scoring; zero rows stay zero."""
    norms = np.linalg.norm(f, axis=1, keepdims=True)
    return np.where(norms > 0, f / np.where(norms > 0, norms, 1), 0.0).astype(
        np.float32
    )


def business_rule_mask(
    n_items: int,
    item_index,
    categories_per_item: Sequence[FrozenSet[str]],
    categories: Tuple[str, ...] = (),
    white_list: Tuple[str, ...] = (),
    black_list: Tuple[str, ...] = (),
) -> np.ndarray:
    """Boolean keep-mask from the standard template filters
    (≙ the reference templates' categories/whiteList/blackList handling)."""
    mask = np.ones(n_items, bool)
    if categories:
        wanted = set(categories)
        mask &= np.fromiter(
            (bool(wanted & c) for c in categories_per_item),
            bool,
            len(categories_per_item),
        )
    if white_list:
        white = np.zeros(n_items, bool)
        for i in white_list:
            c = item_index.get(i)
            if c is not None:
                white[c] = True
        mask &= white
    for i in black_list:
        c = item_index.get(i)
        if c is not None:
            mask[c] = False
    return mask


def top_item_scores(
    scores: np.ndarray, mask: np.ndarray, num: int, item_index
) -> PredictedResult:
    """Masked top-N → PredictedResult (argpartition, not full sort)."""
    scores = np.where(mask, scores, -np.inf)
    n = min(num, int(mask.sum()))
    if n <= 0:
        return PredictedResult()
    idx = np.argpartition(-scores, n - 1)[:n]
    idx = idx[np.argsort(-scores[idx])]
    inv = item_index.inverse
    return PredictedResult(
        tuple(
            ItemScore(inv[int(i)], float(scores[i]))
            for i in idx
            if np.isfinite(scores[i])
        )
    )
