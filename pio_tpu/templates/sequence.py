"""Sequence-recommendation template — next-item prediction over histories.

Long-context, first-class: the DataSource assembles each user's **full
time-ordered event stream** (view/buy/rate events sorted by eventTime —
the reference's nearest concept is Spark partitioning of the event RDD
along time; SURVEY.md §5 "long-context: ABSENT") and the algorithm trains
the causal transformer of pio_tpu/models/seqrec.py, whose training step
shards dp × sp (ring attention) × tp × ep × pp over the mesh.

engine.json:

    {
      "id": "seqrec",
      "engineFactory": "templates.sequence",
      "datasource": {"params": {"app_name": "myapp"}},
      "algorithms": [{"name": "seqrec", "params":
          {"d_model": 64, "n_layers": 2, "max_len": 64,
           "seq_parallel": 1, "pipe_parallel": 1}}]
    }

Query ``{"user": "u1", "num": 4}`` (or ``{"history": ["i1", "i2"], ...}``)
→ ``{"itemScores": [{"item": "i5", "score": 3.1}, ...]}``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from pio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Params,
    Preparator,
    SanityCheck,
    register_engine,
)
from pio_tpu.data.bimap import BiMap
from pio_tpu.models.als import top_n
from pio_tpu.models.seqrec import SeqRecConfig, SeqRecModel, train_seqrec
from pio_tpu.parallel.context import ComputeContext
from pio_tpu.parallel.mesh import MeshSpec, build_mesh
from pio_tpu.storage import Storage
from pio_tpu.templates.common import (
    ItemScore,
    PredictedResult,
    fold_assignments,
    resolve_app,
)
from pio_tpu.workflow.shard_store import ShardableModel


# --------------------------------------------------------------- data source
@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    app_id: int = 0
    channel: str = ""
    #: events whose target entity enters the user's history, in time order
    event_names: Tuple[str, ...] = ("view", "buy", "rate")
    min_history: int = 2
    eval_k: int = 0  # >0 enables k-fold leave-last-out read_eval
    eval_num: int = 10


@dataclasses.dataclass
class TrainingData(SanityCheck):
    #: per user: time-ordered item-id history
    histories: Dict[str, List[str]]

    def sanity_check(self) -> None:
        if not self.histories:
            raise ValueError(
                "TrainingData is empty - no user event streams found. "
                "Did you import events for this app?"
            )

    def __len__(self):
        return len(self.histories)


class SequenceDataSource(DataSource):
    """Full event streams per user, ordered by eventTime."""

    params_class = DataSourceParams

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        p: DataSourceParams = self.params
        app_id, channel_id = resolve_app(p)
        frame = Storage.get_pevents().find_frame(
            app_id,
            channel_id=channel_id,
            event_names=list(p.event_names),
            entity_type="user",
            target_entity_type="item",
        )
        order = np.argsort(frame.event_time_us, kind="stable")
        histories: Dict[str, List[str]] = {}
        for i in order:
            histories.setdefault(str(frame.entity_id[i]), []).append(
                str(frame.target_entity_id[i])
            )
        histories = {
            u: h for u, h in histories.items() if len(h) >= p.min_history
        }
        return TrainingData(histories=histories)

    def read_eval(self, ctx: ComputeContext):
        """k-fold leave-last-out next-item protocol: users split into k
        folds; a fold's users train on their history MINUS the last item
        and are queried with that prefix, the actual being the held-out
        last item (HitRate@eval_num ≡ next-item accuracy when
        eval_num=1). Other folds' users train on their full history."""
        p: DataSourceParams = self.params
        if p.eval_k <= 0:
            return []
        if p.eval_k == 1:
            raise ValueError("k-fold cross-validation needs eval_k >= 2")
        td = self.read_training(ctx)
        users = sorted(td.histories)
        # randomized (seeded) user folds: sorted user ids often encode
        # signup order, so sequential r % k would correlate folds with
        # user cohorts (see common.fold_assignments)
        fold_of = fold_assignments(len(users), p.eval_k)
        folds = []
        for k in range(p.eval_k):
            train_h: Dict[str, List[str]] = {}
            qa = []
            for r, u in enumerate(users):
                h = td.histories[u]
                if fold_of[r] == k and len(h) > p.min_history:
                    train_h[u] = h[:-1]
                    qa.append(
                        (Query(history=tuple(h[:-1]), num=p.eval_num),
                         str(h[-1]))
                    )
                else:
                    train_h[u] = h
            folds.append((TrainingData(histories=train_h), {"fold": k}, qa))
        return folds


# --------------------------------------------------------------- preparator
@dataclasses.dataclass
class PreparedData:
    item_index: BiMap  # code 0 is reserved for padding
    sequences: np.ndarray  # [n_users, T] int32, right-padded with 0
    user_rows: Dict[str, int]  # user id → row in sequences


class SequencePreparator(Preparator):
    """Index items (code 0 = pad) and pack histories into a dense matrix."""

    def prepare(self, ctx: ComputeContext, td: TrainingData) -> PreparedData:
        all_items: List[str] = []
        for h in td.histories.values():
            all_items.extend(h)
        # BiMap codes start at 0; shift by +1 so 0 stays the pad id.
        # Popularity ordering clusters hot embedding rows (the
        # vocab-sharded gather's locality) — codes stay deterministic.
        item_index = BiMap.string_int_by_frequency(all_items)
        fwd = item_index.to_dict()
        users = sorted(td.histories)
        t = max(len(td.histories[u]) for u in users)
        seqs = np.zeros((len(users), t), np.int32)
        for r, u in enumerate(users):
            h = td.histories[u]
            seqs[r, : len(h)] = [fwd[i] + 1 for i in h]
        return PreparedData(
            item_index=item_index,
            sequences=seqs,
            user_rows={u: r for r, u in enumerate(users)},
        )


# ----------------------------------------------------------------- algorithm
@dataclasses.dataclass(frozen=True)
class Query:
    user: str = ""
    history: Tuple[str, ...] = ()  # anonymous/session queries
    num: int = 10


@dataclasses.dataclass(frozen=True)
class SeqRecParams(Params):
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    ffn: int = 128
    max_len: int = 64
    learning_rate: float = 1e-3
    steps: int = 300
    seed: int = 0
    #: sequence-parallel attention mode: "ring" or "ulysses" (all-to-all)
    attention: str = "ring"
    #: rows per optimizer step; 0 = full-batch (historical path),
    #: > 0 enables minibatch SGD and the streamed epoch feed
    batch_size: int = 0
    #: epoch feed: "off" stages on device, "on" streams row spans,
    #: "auto" streams only past PIO_TPU_DEVICE_BUDGET_BYTES
    stream: str = "auto"
    #: mesh splits; remaining devices ride the data axis
    seq_parallel: int = 1
    pipe_parallel: int = 1
    model_parallel: int = 1


@dataclasses.dataclass
class SeqRecEngineModel(ShardableModel):
    model: SeqRecModel
    item_index: BiMap
    #: training-time histories for user-id queries
    user_histories: Dict[str, List[int]]

    shard_template = "seqrec"

    def shard_arrays(self):
        # flatten the layer-stacked params pytree with the same "/"
        # paths the partition rules match against
        out = {}
        for k, v in self.model.params.items():
            if isinstance(v, dict):
                for k2, v2 in v.items():
                    out[f"{k}/{k2}"] = v2
            else:
                out[k] = v
        return out

    def replace_shard_arrays(self, arrays):
        params: Dict = {}
        for name, arr in arrays.items():
            if "/" in name:
                outer, inner = name.split("/", 1)
                params.setdefault(outer, {})[inner] = arr
            else:
                params[name] = arr
        return dataclasses.replace(
            self, model=dataclasses.replace(self.model, params=params)
        )


class SeqRecAlgorithm(Algorithm):
    """Causal-transformer next-item training over the packed histories."""

    params_class = SeqRecParams
    query_class = Query

    def _mesh(self, ctx: ComputeContext):
        p: SeqRecParams = self.params
        if ctx.mesh is None:
            return None
        devices = list(ctx.mesh.devices.flat)
        n = len(devices)
        sp = max(1, min(p.seq_parallel, n))
        pp = max(1, min(p.pipe_parallel, n // sp))
        mp = max(1, min(p.model_parallel, n // (sp * pp)))
        return build_mesh(
            MeshSpec(data=-1, seq=sp, pipe=pp, model=mp), devices=devices
        )

    def train(
        self, ctx: ComputeContext, pd: PreparedData
    ) -> SeqRecEngineModel:
        p: SeqRecParams = self.params
        mesh = self._mesh(ctx)
        # train_seqrec keeps each row's NEWEST max_len events (tail), the
        # same window predict scores
        model = train_seqrec(
            mesh,
            pd.sequences,
            n_items=len(pd.item_index),
            config=SeqRecConfig(
                d_model=p.d_model,
                n_heads=p.n_heads,
                n_layers=p.n_layers,
                ffn=p.ffn,
                max_len=p.max_len,
                learning_rate=p.learning_rate,
                steps=p.steps,
                attention=p.attention,
                seed=p.seed,
                batch_size=p.batch_size,
                stream=p.stream,
            ),
            checkpoint=ctx.checkpoint,
            checkpoint_every=ctx.checkpoint_every,
        )
        user_histories = {
            u: [int(x) for x in pd.sequences[r] if x > 0]
            for u, r in pd.user_rows.items()
        }
        return SeqRecEngineModel(model, pd.item_index, user_histories)

    def _history_codes(
        self, model: SeqRecEngineModel, query: Query
    ) -> Optional[List[int]]:
        if query.history:
            # O(1) lookups — to_dict() would copy the whole index per query
            codes = [
                c + 1
                for c in (
                    model.item_index.get(i) for i in query.history
                )
                if c is not None
            ]
            return codes or None
        return model.user_histories.get(query.user)

    def warmup_query(self, model: SeqRecEngineModel) -> Optional[Query]:
        """Any user with a training-time history drives the [B, T]
        transformer forward — enough to compile each serving bucket."""
        for u, hist in model.user_histories.items():
            if hist:
                return Query(user=u)
        return None

    def predict(
        self, model: SeqRecEngineModel, query: Query
    ) -> PredictedResult:
        codes = self._history_codes(model, query)
        if not codes:
            return PredictedResult()  # unknown user / empty history
        scores = model.model.next_item_scores(
            _history_rows([codes], model.model.config.max_len)
        )[0]
        return _seq_top_result(scores, query.num, model.item_index)

    def batch_predict(self, model: SeqRecEngineModel, queries):
        """Vectorized offline scoring: the transformer forward already
        takes a [B, T] batch — stack every resolvable history and run
        ONE device call instead of B."""
        out = []
        bidx, bq, bcodes = [], [], []
        for i, q in queries:
            codes = self._history_codes(model, q)
            if not codes:
                out.append((i, PredictedResult()))
                continue
            bidx.append(i)
            bq.append(q)
            bcodes.append(codes)
        if bidx:
            rows = _history_rows(bcodes, model.model.config.max_len)
            scores = model.model.next_item_scores(rows)
            for i, q, row in zip(bidx, bq, scores):
                out.append(
                    (i, _seq_top_result(row, q.num, model.item_index))
                )
        return out


def _history_rows(code_lists, max_len: int) -> np.ndarray:
    """Right-truncated, zero-padded [B, max_len] history batch."""
    rows = np.zeros((len(code_lists), max_len), np.int32)
    for r, codes in enumerate(code_lists):
        tail = codes[-max_len:]
        rows[r, : len(tail)] = tail
    return rows


def _seq_top_result(scores, num: int, item_index) -> PredictedResult:
    """Shared top-N tail (scores[0] is the pad row, shifted off here) so
    predict and batch_predict cannot diverge."""
    idx, vals = top_n(scores[1:], num)
    inv = item_index.inverse
    return PredictedResult(
        tuple(ItemScore(inv[int(i)], float(v)) for i, v in zip(idx, vals))
    )


class SequenceServing(FirstServing):
    pass


@register_engine("templates.sequence")
def sequence_engine() -> Engine:
    return Engine(
        SequenceDataSource,
        SequencePreparator,
        {"seqrec": SeqRecAlgorithm},
        SequenceServing,
    )


# -------------------------------------------------------------- evaluation
def sequence_evaluation(
    app_name: str = "",
    eval_k: int = 3,
    eval_num: int = 10,
    layer_grid=(1, 2),
    steps: int = 200,
    d_model: int = 32,
    max_len: int = 32,
):
    """Ready-made `pio eval` sweep: k-fold leave-last-out
    HitRate@``eval_num`` (next-item accuracy at eval_num=1) over a
    transformer-depth grid.

    Zero-arg CLI use reads the app from ``$PIO_TPU_EVAL_APP``:

        PIO_TPU_EVAL_APP=myapp python -m pio_tpu eval \\
            pio_tpu.templates.sequence:sequence_evaluation
    """
    from pio_tpu.controller.engine import EngineParams
    from pio_tpu.controller.evaluation import (
        EngineParamsGenerator, Evaluation,
    )
    from pio_tpu.templates.common import eval_app_name
    from pio_tpu.templates.similarproduct import HitRateMetric

    if eval_k < 2:
        raise ValueError("k-fold evaluation needs eval_k >= 2")
    ds = DataSourceParams(
        app_name=eval_app_name(app_name), eval_k=eval_k, eval_num=eval_num
    )
    grid = [
        EngineParams(
            data_source_params=ds,
            algorithm_params_list=(
                ("seqrec", SeqRecParams(
                    d_model=d_model, n_layers=n, steps=steps,
                    max_len=max_len,
                )),
            ),
        )
        for n in layer_grid
    ]
    return Evaluation(
        sequence_engine(), HitRateMetric(),
        engine_params_generator=EngineParamsGenerator(grid),
    )
