"""Classification template — label prediction from entity attributes.

Rebuild of the reference's ``examples/scala-parallel-classification``
(DataSource.scala reads ``$set`` user properties ``attr0..attr2`` + ``plan``
label via ``PEventStore.aggregateProperties``; NaiveBayesAlgorithm.scala
trains MLlib multinomial NB — UNVERIFIED paths; see SURVEY.md §2.5).

Two algorithms, selectable in engine.json (≙ the template's NB default and
its documented LogisticRegressionWithLBFGS variant):

- ``naivebayes`` — multinomial NB; counting is a segment-sum, scoring one
  MXU matmul (pio_tpu/models/naive_bayes.py).
- ``logreg`` — softmax regression, full-batch Adam over the mesh ``data``
  axis; the treeAggregate gradient reduction becomes an XLA psum
  (pio_tpu/models/logreg.py).

engine.json:

    {
      "id": "classification",
      "engineFactory": "templates.classification",
      "datasource": {"params": {"app_name": "myapp"}},
      "algorithms": [{"name": "naivebayes", "params": {"lambda_": 1.0}}]
    }

Query ``{"attr0": 2, "attr1": 0, "attr2": 0}`` → ``{"label": "..."}``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from pio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Params,
    Preparator,
    SanityCheck,
    register_engine,
)
from pio_tpu.controller.cross_validation import split_data
from pio_tpu.controller.metrics import AverageMetric
from pio_tpu.data.bimap import BiMap
from pio_tpu.models.logreg import LogRegConfig, LogRegModel, train_logreg
from pio_tpu.models.naive_bayes import (
    MultinomialNBModel,
    train_multinomial_nb,
)
from pio_tpu.parallel.context import ComputeContext
from pio_tpu.storage import Storage
from pio_tpu.templates.common import resolve_app


# --------------------------------------------------------------- data source
@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    app_id: int = 0
    channel: str = ""
    entity_type: str = "user"
    #: numeric feature attributes read off each entity's PropertyMap
    attrs: Tuple[str, ...] = ("attr0", "attr1", "attr2")
    #: label attribute (reference template's "plan")
    label_attr: str = "plan"
    eval_k: int = 0


@dataclasses.dataclass
class TrainingData(SanityCheck):
    features: np.ndarray  # [n, d] float32
    labels: np.ndarray  # [n] str objects

    def sanity_check(self) -> None:
        if len(self.labels) == 0:
            raise ValueError(
                "TrainingData is empty - no entities with the required "
                "attributes. Did you $set properties for this app?"
            )

    def __len__(self):
        return len(self.labels)


class ClassificationDataSource(DataSource):
    """aggregateProperties → dense feature matrix + label column
    (≙ reference DataSource.readTraining)."""

    params_class = DataSourceParams

    def _read(self) -> TrainingData:
        p: DataSourceParams = self.params
        app_id, channel_id = resolve_app(p)
        required = list(p.attrs) + [p.label_attr]
        props = Storage.get_pevents().aggregate_properties(
            app_id,
            entity_type=p.entity_type,
            channel_id=channel_id,
            required=required,
        )
        feats = np.zeros((len(props), len(p.attrs)), np.float32)
        labels = np.empty(len(props), object)
        for i, (eid, pm) in enumerate(sorted(props.items())):
            for j, a in enumerate(p.attrs):
                feats[i, j] = float(pm.get(a))
            labels[i] = str(pm.get(p.label_attr))
        return TrainingData(features=feats, labels=labels)

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        return self._read()

    def read_eval(self, ctx: ComputeContext):
        p: DataSourceParams = self.params
        if p.eval_k <= 0:
            return []
        td = self._read()
        rows = list(zip(td.features, td.labels))
        return split_data(
            p.eval_k,
            rows,
            to_training_data=lambda rs: TrainingData(
                features=np.array([f for f, _ in rs], np.float32).reshape(
                    len(rs), td.features.shape[1]
                ),
                labels=np.array([l for _, l in rs], object),
            ),
            to_query_actual=lambda r: (
                Query(attrs=tuple(float(x) for x in r[0])),
                str(r[1]),
            ),
        )


# --------------------------------------------------------------- preparator
@dataclasses.dataclass
class PreparedData:
    features: np.ndarray  # [n, d] float32
    label_codes: np.ndarray  # [n] int32
    label_index: BiMap


class ClassificationPreparator(Preparator):
    """String labels → dense codes (BiMap); features pass through."""

    def prepare(self, ctx: ComputeContext, td: TrainingData) -> PreparedData:
        label_index = BiMap.string_int(td.labels.tolist())
        fwd = label_index.to_dict()
        codes = np.fromiter(
            (fwd[l] for l in td.labels.tolist()), np.int32, len(td)
        )
        return PreparedData(td.features, codes, label_index)


# ----------------------------------------------------------------- algorithm
@dataclasses.dataclass(frozen=True)
class Query:
    attrs: Tuple[float, ...] = ()
    # individual attr fields for engine.json-style queries
    attr0: Optional[float] = None
    attr1: Optional[float] = None
    attr2: Optional[float] = None

    def vector(self, dim: int) -> np.ndarray:
        if self.attrs:
            vals = self.attrs
        else:
            vals = tuple(
                v for v in (self.attr0, self.attr1, self.attr2)
                if v is not None
            )
        if len(vals) != dim:
            raise ValueError(
                f"query has {len(vals)} attrs, model expects {dim}"
            )
        return np.asarray(vals, np.float32)[None, :]


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    label: str = ""

    def to_dict(self) -> dict:
        return {"label": self.label}


@dataclasses.dataclass(frozen=True)
class NaiveBayesParams(Params):
    lambda_: float = 1.0  # Laplace smoothing (reference param "lambda")


@dataclasses.dataclass
class NBClassifierModel:
    nb: MultinomialNBModel
    label_index: BiMap
    dim: int


class NaiveBayesAlgorithm(Algorithm):
    """Multinomial NB (≙ reference NaiveBayesAlgorithm → MLlib NaiveBayes)."""

    params_class = NaiveBayesParams
    query_class = Query

    def train(self, ctx: ComputeContext, pd: PreparedData) -> NBClassifierModel:
        p: NaiveBayesParams = self.params
        nb = train_multinomial_nb(
            pd.features,
            pd.label_codes,
            n_classes=len(pd.label_index),
            lambda_=p.lambda_,
        )
        return NBClassifierModel(nb, pd.label_index, pd.features.shape[1])

    def predict(self, model: NBClassifierModel, query: Query) -> PredictedResult:
        x = query.vector(model.dim)
        scorer = _resident_of(model)
        if scorer is not None:
            code = int(scorer.score_codes(x)[0])
        else:
            code = int(model.nb.predict(x)[0])
        return PredictedResult(label=model.label_index.inverse[code])

    def batch_predict(self, model: NBClassifierModel, queries):
        """One batched scoring call for the whole query file (the model
        predict already takes [B, d])."""
        scorer = _resident_of(model)
        return _batch_label_results(
            model,
            queries,
            scorer.score_codes if scorer is not None
            else lambda X: model.nb.predict(X),
        )

    def warmup_query(self, model: NBClassifierModel) -> Query:
        return Query(attrs=(0.0,) * model.dim)

    def resident_scorer(self, model: NBClassifierModel):
        return _linear_resident(
            "naivebayes",
            model,
            weights=model.nb.log_theta.T,
            bias=model.nb.log_prior,
            scales=getattr(model.nb, "feature_scales", None),
        )


@dataclasses.dataclass(frozen=True)
class LogRegParams(Params):
    iterations: int = 200
    learning_rate: float = 0.1
    reg: float = 0.0
    seed: int = 0
    #: feature wire/matmul dtype — "float32" (default, exact arithmetic),
    #: opt-in "bfloat16" (MXU-native, half the host→device bytes), or
    #: "int8" (quarter the bytes: per-column scales fold into the weights
    #: on device, so the learned model still serves raw float features)
    input_dtype: str = "float32"


@dataclasses.dataclass
class LogRegClassifierModel:
    lr: LogRegModel
    label_index: BiMap
    dim: int


class LogisticRegressionAlgorithm(Algorithm):
    """Sharded softmax regression (≙ LogisticRegressionWithLBFGS variant)."""

    params_class = LogRegParams
    query_class = Query

    def train(
        self, ctx: ComputeContext, pd: PreparedData
    ) -> LogRegClassifierModel:
        p: LogRegParams = self.params
        lr = train_logreg(
            ctx,
            pd.features,
            pd.label_codes,
            n_classes=len(pd.label_index),
            config=LogRegConfig(
                iterations=p.iterations,
                learning_rate=p.learning_rate,
                reg=p.reg,
                seed=p.seed,
                input_dtype=p.input_dtype,
            ),
        )
        return LogRegClassifierModel(lr, pd.label_index, pd.features.shape[1])

    def predict(
        self, model: LogRegClassifierModel, query: Query
    ) -> PredictedResult:
        x = query.vector(model.dim)
        scorer = _resident_of(model)
        if scorer is not None:
            code = int(scorer.score_codes(x)[0])
        else:
            code = int(model.lr.predict(x)[0])
        return PredictedResult(label=model.label_index.inverse[code])

    def batch_predict(self, model: LogRegClassifierModel, queries):
        """One batched scoring call for the whole query file."""
        scorer = _resident_of(model)
        return _batch_label_results(
            model,
            queries,
            scorer.score_codes if scorer is not None
            else lambda X: model.lr.predict(X),
        )

    def warmup_query(self, model: LogRegClassifierModel) -> Query:
        return Query(attrs=(0.0,) * model.dim)

    def resident_scorer(self, model: LogRegClassifierModel):
        return _linear_resident(
            "logreg",
            model,
            weights=model.lr.weights,
            bias=model.lr.bias,
            scales=getattr(model.lr, "feature_scales", None),
        )


def _resident_of(model):
    """The model's live device-resident scorer, or None.

    The query server attaches ``model._resident`` at deploy/hot-swap
    (behind the swap lock); a retired scorer means a swap landed between
    the attribute read and the dispatch — fall back to the host mirror,
    which the swap already replaced."""
    scorer = getattr(model, "_resident", None)
    if scorer is not None and not scorer.retired:
        return scorer
    return None


def _linear_resident(algo_name, model, weights, bias, scales):
    """Shared resident-scorer builder for the two linear classifiers:
    both serve ``argmax(X @ W + b)``, so they differ only in where W/b
    live on the host model."""
    from pio_tpu.server.residency import ResidentLinearScorer

    return ResidentLinearScorer(
        weights=weights,
        bias=bias,
        scales=scales,
        name=algo_name,
        mesh=getattr(model, "_serve_mesh", None),
        query_factory=lambda x: Query(
            attrs=tuple(float(v) for v in np.asarray(x).reshape(-1))
        ),
        # both linear classifiers serve through FirstServing (identity
        # supplement), so a wire-codes dispatch is result-equivalent
        result_factory=lambda c: PredictedResult(
            label=model.label_index.inverse[int(c)]
        ),
    )


def _batch_label_results(model, queries, predict_codes):
    """Shared batch tail for the attribute classifiers: stack the query
    vectors, one model call, map codes back to labels. An invalid query
    (wrong attr arity) raises exactly as the per-query path would."""
    if not queries:
        return []
    # vector() yields [1, d] rows; concatenate → [B, d]
    X = np.concatenate([q.vector(model.dim) for _, q in queries])
    inv = model.label_index.inverse
    return [
        (i, PredictedResult(label=inv[int(c)]))
        for (i, _), c in zip(queries, predict_codes(X))
    ]


class ClassificationServing(FirstServing):
    pass


@register_engine("templates.classification")
def classification_engine() -> Engine:
    return Engine(
        ClassificationDataSource,
        ClassificationPreparator,
        {
            "naivebayes": NaiveBayesAlgorithm,
            "logreg": LogisticRegressionAlgorithm,
        },
        ClassificationServing,
    )


# -------------------------------------------------------------- evaluation
class AccuracyMetric(AverageMetric):
    """Fraction of held-out entities whose predicted label matches
    (the reference classification template's Evaluation.scala metric)."""

    def calculate_one(self, query, prediction, actual):
        return 1.0 if prediction.label == actual else 0.0


def classification_evaluation(
    app_name: str = "",
    eval_k: int = 3,
    lambdas=(0.5, 1.0, 2.0),
):
    """Ready-made `pio eval` sweep: k-fold accuracy over the naive-Bayes
    smoothing grid (the reference template's quickstart evaluation).

    Zero-arg CLI use reads the app from ``$PIO_TPU_EVAL_APP``:

        PIO_TPU_EVAL_APP=myapp python -m pio_tpu eval \\
            pio_tpu.templates.classification:classification_evaluation
    """
    from pio_tpu.controller.engine import EngineParams
    from pio_tpu.controller.evaluation import (
        EngineParamsGenerator, Evaluation,
    )
    from pio_tpu.templates.common import eval_app_name

    if eval_k < 2:
        raise ValueError("k-fold evaluation needs eval_k >= 2")
    ds = DataSourceParams(app_name=eval_app_name(app_name), eval_k=eval_k)
    grid = [
        EngineParams(
            data_source_params=ds,
            algorithm_params_list=(
                ("naivebayes", NaiveBayesParams(lambda_=lam)),
            ),
        )
        for lam in lambdas
    ]
    return Evaluation(
        classification_engine(), AccuracyMetric(),
        engine_params_generator=EngineParamsGenerator(grid),
    )
