"""E-Commerce Recommendation template — personalized recs with business rules.

Rebuild of the reference's ``examples/scala-parallel-ecommercerecommendation``
(ECommAlgorithm.scala — UNVERIFIED paths; SURVEY.md §2.5): implicit ALS on
view events plus serve-time business logic:

- known user  → personalized scores (user factor · item factors);
- unknown/cold user → fallback to the user's most recent views (queried from
  the *live* event store at predict time, like the reference's LEventStore
  lookup), scored by cosine similarity;
- ``unseen_only`` → exclude items the user has already seen (recent
  view/buy events, live lookup);
- "unavailable items" constraint entity: the latest ``$set`` on
  ``constraint/unavailableItems`` (property ``items``) is honored at serve
  time, so ops can pull items without retraining;
- category / whiteList / blackList masks as in the Similar-Product template.

TPU-first serving: all rules are boolean masks over one scores vector from a
single matvec against the item-factor matrix.

Query ``{"user": "u1", "num": 4, "categories": [...], "whiteList": [...],
"blackList": [...]}`` → ``{"itemScores": [...]}``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

import numpy as np

from pio_tpu.controller import (
    Algorithm,
    Engine,
    FirstServing,
    Params,
    register_engine,
)
from pio_tpu.data.bimap import BiMap
from pio_tpu.models.als import ALSConfig, train_als
from pio_tpu.parallel.context import ComputeContext
from pio_tpu.storage import Storage
from pio_tpu.templates.common import (
    PredictedResult,
    business_rule_mask,
    l2_normalize_rows,
    top_item_scores,
)
from pio_tpu.templates.similarproduct import (
    PreparedData,
    SimilarProductDataSource,
    SimilarProductPreparator,
)


# ------------------------------------------------- data source / preparator
# The e-commerce template reads the same training inputs as Similar-Product
# (view edges + item categories); buy/seen handling happens at serve time
# against the live event store, mirroring the reference's split.
@dataclasses.dataclass(frozen=True)
class DataSourceParams(
    SimilarProductDataSource.params_class  # type: ignore[misc]
):
    pass


class ECommerceDataSource(SimilarProductDataSource):
    params_class = DataSourceParams


class ECommercePreparator(SimilarProductPreparator):
    pass


# ----------------------------------------------------------------- algorithm
@dataclasses.dataclass(frozen=True)
class Query:
    user: str = ""
    num: int = 10
    categories: Tuple[str, ...] = ()
    white_list: Tuple[str, ...] = ()
    black_list: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ECommAlgorithmParams(Params):
    app_name: str = ""  # live event-store lookups at serve time
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int = 3
    #: exclude items the user has recently seen (view/buy)
    unseen_only: bool = False
    seen_events: Tuple[str, ...] = ("buy", "view")
    #: events used for the cold-user fallback basket
    similar_events: Tuple[str, ...] = ("view",)
    #: how many recent events the serve-time lookups read
    num_recent_events: int = 10


@dataclasses.dataclass
class ECommModel:
    user_factors: np.ndarray  # [n_users, rank]
    norm_item_factors: np.ndarray  # [n_items, rank], L2-normalized
    item_factors: np.ndarray  # [n_items, rank], raw (personalized scores)
    user_index: BiMap
    item_index: BiMap
    categories: List[FrozenSet[str]]
    app_id: int


class ECommAlgorithm(Algorithm):
    """Implicit ALS + serve-time business rules
    (≙ reference ECommAlgorithm)."""

    params_class = ECommAlgorithmParams
    query_class = Query

    def train(self, ctx: ComputeContext, pd: PreparedData) -> ECommModel:
        p: ECommAlgorithmParams = self.params
        app = Storage.get_meta_data_apps().get_by_name(p.app_name)
        if app is None:
            raise ValueError(
                f"ECommAlgorithm params need app_name (got {p.app_name!r})"
            )
        factors = train_als(
            ctx,
            pd.user_codes,
            pd.item_codes,
            np.ones(len(pd.item_codes), np.float32),
            n_users=len(pd.user_index),
            n_items=len(pd.item_index),
            config=ALSConfig(
                rank=p.rank,
                iterations=p.num_iterations,
                reg=p.lambda_,
                implicit=True,
                alpha=p.alpha,
                seed=p.seed,
            ),
        )
        f = factors.item_factors
        return ECommModel(
            user_factors=factors.user_factors,
            norm_item_factors=l2_normalize_rows(f),
            item_factors=f.astype(np.float32),
            user_index=pd.user_index,
            item_index=pd.item_index,
            categories=pd.categories,
            app_id=app.id,
        )

    # ------------------------------------------------ live event-store reads
    def _recent_items(
        self, model: ECommModel, user: str, event_names: Tuple[str, ...],
        limit: int,
    ) -> List[str]:
        events = Storage.get_levents().find(
            model.app_id,
            entity_type="user",
            entity_id=user,
            event_names=list(event_names),
            limit=limit,
            reversed_order=True,
        )
        return [
            e.target_entity_id for e in events if e.target_entity_id
        ]

    def _unavailable_items(self, model: ECommModel) -> Set[str]:
        props = Storage.get_levents().aggregate_properties(
            model.app_id, entity_type="constraint"
        )
        pm = props.get("unavailableItems")
        if pm is None:
            return set()
        return set(pm.get_opt("items") or [])

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        p: ECommAlgorithmParams = self.params
        ucode = model.user_index.get(query.user)
        if ucode is not None:
            scores = model.item_factors @ model.user_factors[ucode]
        else:
            # cold user: basket = recent views from the live event store
            recent = self._recent_items(
                model, query.user, p.similar_events, p.num_recent_events
            )
            codes = [
                c
                for c in (model.item_index.get(i) for i in recent)
                if c is not None
            ]
            if not codes:
                return PredictedResult()
            basket = model.norm_item_factors[np.asarray(codes, np.int32)]
            scores = model.norm_item_factors @ basket.mean(axis=0)

        mask = business_rule_mask(
            len(scores),
            model.item_index,
            model.categories,
            categories=query.categories,
            white_list=query.white_list,
            black_list=query.black_list,
        )
        for i in self._unavailable_items(model):
            c = model.item_index.get(i)
            if c is not None:
                mask[c] = False
        if p.unseen_only:
            for i in self._recent_items(
                model, query.user, p.seen_events, p.num_recent_events
            ):
                c = model.item_index.get(i)
                if c is not None:
                    mask[c] = False

        return top_item_scores(scores, mask, query.num, model.item_index)


class ECommerceServing(FirstServing):
    pass


@register_engine("templates.ecommerce")
def ecommerce_engine() -> Engine:
    return Engine(
        ECommerceDataSource,
        ECommercePreparator,
        {"ecomm": ECommAlgorithm},
        ECommerceServing,
    )
