"""E-Commerce Recommendation template — personalized recs with business rules.

Rebuild of the reference's ``examples/scala-parallel-ecommercerecommendation``
(ECommAlgorithm.scala — UNVERIFIED paths; SURVEY.md §2.5): implicit ALS on
view events plus serve-time business logic:

- known user  → personalized scores (user factor · item factors);
- unknown/cold user → fallback to the user's most recent views (queried from
  the *live* event store at predict time, like the reference's LEventStore
  lookup), scored by cosine similarity;
- ``unseen_only`` → exclude items the user has already seen (recent
  view/buy events, live lookup);
- "unavailable items" constraint entity: the latest ``$set`` on
  ``constraint/unavailableItems`` (property ``items``) is honored at serve
  time, so ops can pull items without retraining;
- category / whiteList / blackList masks as in the Similar-Product template.

TPU-first serving: all rules are boolean masks over one scores vector from a
single matvec against the item-factor matrix.

Query ``{"user": "u1", "num": 4, "categories": [...], "whiteList": [...],
"blackList": [...]}`` → ``{"itemScores": [...]}``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

import numpy as np

from pio_tpu.controller import (
    Algorithm,
    Engine,
    FirstServing,
    Params,
    register_engine,
)
from pio_tpu.data.bimap import BiMap
from pio_tpu.models.als import ALSConfig, train_als
from pio_tpu.parallel.context import ComputeContext
from pio_tpu.storage import Storage
from pio_tpu.templates.common import (
    PredictedResult,
    business_rule_mask,
    dedup_pair_indices,
    fold_assignments,
    l2_normalize_rows,
    seen_exclusion_holdout,
    top_item_scores,
)
from pio_tpu.templates.similarproduct import (
    PreparedData,
    SimilarProductDataSource,
    SimilarProductPreparator,
)


# ------------------------------------------------- data source / preparator
# The e-commerce template reads the same training inputs as Similar-Product
# (view edges + item categories); buy/seen handling happens at serve time
# against the live event store, mirroring the reference's split.
@dataclasses.dataclass(frozen=True)
class DataSourceParams(
    SimilarProductDataSource.params_class  # type: ignore[misc]
):
    pass


class ECommerceDataSource(SimilarProductDataSource):
    params_class = DataSourceParams

    def read_eval(self, ctx: ComputeContext):
        """k-fold held-out-view protocol, personalized: the query asks
        top-``eval_num`` recs for the USER (this template's query shape),
        the actual is a held-out viewed item — scored by HitRate@eval_num.
        (The parent's basket-shaped protocol doesn't fit e-commerce
        queries.)"""
        p = self.params
        if p.eval_k <= 0:
            return []
        if p.eval_k == 1:
            raise ValueError("k-fold cross-validation needs eval_k >= 2")
        td = self.read_training(ctx)
        keep = dedup_pair_indices(td.user_ids, td.item_ids)
        users, items = td.user_ids[keep], td.item_ids[keep]
        fold_of = fold_assignments(len(users), p.eval_k)
        folds = []
        for k in range(p.eval_k):
            train = fold_of != k
            td_k = type(td)(
                user_ids=users[train],
                item_ids=items[train],
                item_categories=td.item_categories,
            )
            # seen-exclusion protocol, expressed through the template's
            # own black_list business rule (one home for the protocol:
            # common.seen_exclusion_holdout)
            qa = seen_exclusion_holdout(
                users[train], items[train],
                users[~train], items[~train],
                lambda u, bl: Query(
                    user=u, num=p.eval_num, black_list=bl
                ),
            )
            folds.append((td_k, {"fold": k}, qa))
        return folds


class ECommercePreparator(SimilarProductPreparator):
    pass


# ----------------------------------------------------------------- algorithm
@dataclasses.dataclass(frozen=True)
class Query:
    user: str = ""
    num: int = 10
    categories: Tuple[str, ...] = ()
    white_list: Tuple[str, ...] = ()
    black_list: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ECommAlgorithmParams(Params):
    app_name: str = ""  # live event-store lookups at serve time
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int = 3
    #: exclude items the user has recently seen (view/buy)
    unseen_only: bool = False
    seen_events: Tuple[str, ...] = ("buy", "view")
    #: events used for the cold-user fallback basket
    similar_events: Tuple[str, ...] = ("view",)
    #: how many recent events the serve-time lookups read
    num_recent_events: int = 10


@dataclasses.dataclass
class ECommModel:
    user_factors: np.ndarray  # [n_users, rank]
    norm_item_factors: np.ndarray  # [n_items, rank], L2-normalized
    item_factors: np.ndarray  # [n_items, rank], raw (personalized scores)
    user_index: BiMap
    item_index: BiMap
    categories: List[FrozenSet[str]]
    app_id: int


class ECommAlgorithm(Algorithm):
    """Implicit ALS + serve-time business rules
    (≙ reference ECommAlgorithm)."""

    params_class = ECommAlgorithmParams
    query_class = Query

    def train(self, ctx: ComputeContext, pd: PreparedData) -> ECommModel:
        p: ECommAlgorithmParams = self.params
        app = Storage.get_meta_data_apps().get_by_name(p.app_name)
        if app is None:
            raise ValueError(
                f"ECommAlgorithm params need app_name (got {p.app_name!r})"
            )
        factors = train_als(
            ctx,
            pd.user_codes,
            pd.item_codes,
            np.ones(len(pd.item_codes), np.float32),
            n_users=len(pd.user_index),
            n_items=len(pd.item_index),
            config=ALSConfig(
                rank=p.rank,
                iterations=p.num_iterations,
                reg=p.lambda_,
                implicit=True,
                alpha=p.alpha,
                seed=p.seed,
            ),
        )
        f = factors.item_factors
        return ECommModel(
            user_factors=factors.user_factors,
            norm_item_factors=l2_normalize_rows(f),
            item_factors=f.astype(np.float32),
            user_index=pd.user_index,
            item_index=pd.item_index,
            categories=pd.categories,
            app_id=app.id,
        )

    # ------------------------------------------------ live event-store reads
    def _recent_items(
        self, model: ECommModel, user: str, event_names: Tuple[str, ...],
        limit: int,
    ) -> List[str]:
        events = Storage.get_levents().find(
            model.app_id,
            entity_type="user",
            entity_id=user,
            event_names=list(event_names),
            limit=limit,
            reversed_order=True,
        )
        return [
            e.target_entity_id for e in events if e.target_entity_id
        ]

    def _unavailable_items(self, model: ECommModel) -> Set[str]:
        props = Storage.get_levents().aggregate_properties(
            model.app_id, entity_type="constraint"
        )
        pm = props.get("unavailableItems")
        if pm is None:
            return set()
        return set(pm.get_opt("items") or [])

    def _cold_scores(
        self, model: ECommModel, query: Query
    ) -> Optional[np.ndarray]:
        """Cold user: basket = recent views from the live event store."""
        p: ECommAlgorithmParams = self.params
        recent = self._recent_items(
            model, query.user, p.similar_events, p.num_recent_events
        )
        codes = [
            c
            for c in (model.item_index.get(i) for i in recent)
            if c is not None
        ]
        if not codes:
            return None
        basket = model.norm_item_factors[np.asarray(codes, np.int32)]
        return model.norm_item_factors @ basket.mean(axis=0)

    def _apply_rules(
        self,
        model: ECommModel,
        query: Query,
        scores: np.ndarray,
        unavailable: Set[str],
    ) -> PredictedResult:
        """Business-rule masks + top-N tail, shared by predict and
        batch_predict so online and offline scoring cannot diverge.
        ``unavailable`` is the constraint snapshot (fresh per predict,
        one snapshot per batch_predict call)."""
        p: ECommAlgorithmParams = self.params
        mask = business_rule_mask(
            len(scores),
            model.item_index,
            model.categories,
            categories=query.categories,
            white_list=query.white_list,
            black_list=query.black_list,
        )
        for i in unavailable:
            c = model.item_index.get(i)
            if c is not None:
                mask[c] = False
        if p.unseen_only:
            # per-user live lookup stays per query — it IS the semantic
            # point of this template's serve-time freshness
            for i in self._recent_items(
                model, query.user, p.seen_events, p.num_recent_events
            ):
                c = model.item_index.get(i)
                if c is not None:
                    mask[c] = False
        return top_item_scores(scores, mask, query.num, model.item_index)

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        ucode = model.user_index.get(query.user)
        if ucode is not None:
            scores = model.item_factors @ model.user_factors[ucode]
        else:
            scores = self._cold_scores(model, query)
            if scores is None:
                return PredictedResult()
        return self._apply_rules(
            model, query, scores, self._unavailable_items(model)
        )

    def batch_predict(self, model: ECommModel, queries):
        """Vectorized offline scoring: known-user queries batch into ONE
        [B, K] @ [K, N] matmul and the unavailable-items constraint is
        snapshotted once per call; per-user freshness lookups (cold-user
        baskets, unseen_only) stay live per query — those live reads are
        this template's semantic point."""
        unavailable = self._unavailable_items(model)
        out = []
        bidx, bq, bcodes = [], [], []
        for i, q in queries:
            code = model.user_index.get(q.user)
            if code is None:
                scores = self._cold_scores(model, q)
                out.append((
                    i,
                    PredictedResult() if scores is None
                    else self._apply_rules(model, q, scores, unavailable),
                ))
            else:
                bidx.append(i)
                bq.append(q)
                bcodes.append(code)
        if bidx:
            mat = (
                model.user_factors[np.asarray(bcodes, np.int32)]
                @ model.item_factors.T
            )  # [B, n_items]
            for i, q, scores in zip(bidx, bq, mat):
                out.append(
                    (i, self._apply_rules(model, q, scores, unavailable))
                )
        return out


class ECommerceServing(FirstServing):
    pass


@register_engine("templates.ecommerce")
def ecommerce_engine() -> Engine:
    return Engine(
        ECommerceDataSource,
        ECommercePreparator,
        {"ecomm": ECommAlgorithm},
        ECommerceServing,
    )


# -------------------------------------------------------------- evaluation
def ecommerce_evaluation(
    app_name: str = "",
    eval_k: int = 3,
    eval_num: int = 10,
    ranks=(8, 16),
    num_iterations: int = 10,
):
    """Ready-made `pio eval` sweep: k-fold HitRate@``eval_num`` on
    held-out views, personalized queries, over the rank grid. Each eval
    query black-lists the user's training-fold items (the seen-exclusion
    protocol read_eval builds); otherwise business rules run exactly as
    in serving, including the unavailable-items constraint.

    Zero-arg CLI use reads the app from ``$PIO_TPU_EVAL_APP``:

        PIO_TPU_EVAL_APP=myapp python -m pio_tpu eval \\
            pio_tpu.templates.ecommerce:ecommerce_evaluation
    """
    from pio_tpu.controller.engine import EngineParams
    from pio_tpu.controller.evaluation import (
        EngineParamsGenerator, Evaluation,
    )
    from pio_tpu.templates.common import eval_app_name
    from pio_tpu.templates.similarproduct import HitRateMetric

    if eval_k < 2:
        raise ValueError("k-fold evaluation needs eval_k >= 2")
    app = eval_app_name(app_name)
    ds = DataSourceParams(app_name=app, eval_k=eval_k, eval_num=eval_num)
    grid = [
        EngineParams(
            data_source_params=ds,
            algorithm_params_list=(
                ("ecomm", ECommAlgorithmParams(
                    app_name=app, rank=r, num_iterations=num_iterations,
                )),
            ),
        )
        for r in ranks
    ]
    return Evaluation(
        ecommerce_engine(), HitRateMetric(),
        engine_params_generator=EngineParamsGenerator(grid),
    )
