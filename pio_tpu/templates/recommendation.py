"""Recommendation template — ALS on rate/buy events.

Rebuild of the reference's ``examples/scala-parallel-recommendation``
(DataSource.scala, Preparator.scala, ALSAlgorithm.scala, Serving.scala —
UNVERIFIED paths; see SURVEY.md): read ``rate``/``buy`` events, index string
ids densely, factorize with ALS, serve top-N item scores per user.

engine.json:

    {
      "id": "recommendation",
      "engineFactory": "templates.recommendation",
      "datasource": {"params": {"app_name": "myapp"}},
      "algorithms": [{"name": "als", "params":
          {"rank": 10, "num_iterations": 10, "lambda_": 0.01, "seed": 3}}]
    }

Query ``{"user": "u1", "num": 4}`` →
``{"itemScores": [{"item": "i5", "score": 3.2}, ...]}``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from pio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Params,
    Preparator,
    SanityCheck,
    register_engine,
)
from pio_tpu.controller.engine import EngineParams
from pio_tpu.controller.metrics import OptionAverageMetric
from pio_tpu.data.bimap import BiMap
from pio_tpu.models.als import ALSConfig, ALSFactors, train_als
from pio_tpu.parallel.context import ComputeContext
from pio_tpu.storage import Storage
from pio_tpu.storage.frame import EventFrame
from pio_tpu.templates.common import (
    DeviceScorerModel,
    ItemScore,
    PredictedResult,
    dedup_pair_indices,
    fold_assignments,
    seen_exclusion_holdout,
    resolve_app,
)
from pio_tpu.workflow.shard_store import ShardableModel


# --------------------------------------------------------------- data source
@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    app_id: int = 0  # alternative to app_name
    channel: str = ""  # optional named channel
    #: events read as ratings; ``buy`` is treated as an implicit 4.0 rating
    #: (parity with the reference template's buyEvent handling)
    rate_event: str = "rate"
    buy_event: str = "buy"
    buy_rating: float = 4.0
    eval_k: int = 0  # >0 enables k-fold read_eval
    #: eval protocol: "rating" scores held-out ratings (MSE-style metrics);
    #: "hitrate" asks top-``eval_num`` recs and scores held-out item hits
    #: (the two-tower template's protocol — rating regression is
    #: meaningless for a contrastive retrieval model)
    eval_mode: str = "rating"
    eval_num: int = 10


@dataclasses.dataclass
class TrainingData(SanityCheck):
    user_ids: np.ndarray  # [n] str objects
    item_ids: np.ndarray  # [n] str objects
    ratings: np.ndarray  # [n] float32

    def sanity_check(self) -> None:
        if len(self.ratings) == 0:
            raise ValueError(
                "TrainingData is empty - no rate/buy events found. "
                "Did you import events for this app?"
            )

    def __len__(self):
        return len(self.ratings)


class RecommendationDataSource(DataSource):
    """PEvents bulk read → columnar ratings
    (≙ reference DataSource.readTraining via PEventStore.find)."""

    params_class = DataSourceParams

    def _read_frame(self) -> Tuple[EventFrame, "DataSourceParams"]:
        p: DataSourceParams = self.params
        app_id, channel_id = resolve_app(p)
        frame = Storage.get_pevents().find_frame(
            app_id,
            channel_id=channel_id,
            event_names=[p.rate_event, p.buy_event],
            entity_type="user",
            target_entity_type="item",
        )
        return frame, p

    def _to_training_data(self, frame: EventFrame) -> TrainingData:
        p: DataSourceParams = self.params
        ratings = frame.property_column("rating", default=np.nan)
        is_buy = frame.event == p.buy_event
        ratings = np.where(is_buy, np.float32(p.buy_rating), ratings)
        # drop rate events with no rating property
        keep = ~np.isnan(ratings)
        return TrainingData(
            user_ids=frame.entity_id[keep],
            item_ids=frame.target_entity_id[keep],
            ratings=ratings[keep].astype(np.float32),
        )

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        frame, _ = self._read_frame()
        return self._to_training_data(frame)

    def read_eval(self, ctx: ComputeContext):
        """k-fold split by rating index (≙ e2 CommonHelperFunctions.splitData)."""
        p: DataSourceParams = self.params
        if p.eval_k <= 0:
            return []
        if p.eval_k == 1:
            # k=1 would make every training fold empty and fail deep in
            # ALS with a misleading "no ratings" error
            raise ValueError("k-fold cross-validation needs eval_k >= 2")
        if p.eval_mode not in ("rating", "hitrate"):
            raise ValueError(
                f"eval_mode must be 'rating' or 'hitrate', got {p.eval_mode!r}"
            )
        frame, _ = self._read_frame()
        td_all = self._to_training_data(frame)
        if p.eval_mode == "hitrate":
            # dedupe (user, item) pairs — a repeat interaction split
            # across folds would leak the held-out pair into training
            # (rating mode keeps duplicates: they are distinct
            # observations for a regression metric)
            keep = dedup_pair_indices(td_all.user_ids, td_all.item_ids)
            td_all = TrainingData(
                user_ids=td_all.user_ids[keep],
                item_ids=td_all.item_ids[keep],
                ratings=td_all.ratings[keep],
            )
        n = len(td_all)
        fold_of = fold_assignments(n, p.eval_k)
        folds = []
        for k in range(p.eval_k):
            train = fold_of != k
            test = ~train
            td = TrainingData(
                user_ids=td_all.user_ids[train],
                item_ids=td_all.item_ids[train],
                ratings=td_all.ratings[train],
            )
            if p.eval_mode == "hitrate":
                # held-out interaction retrieval, scored by HitRateMetric
                # (see common.seen_exclusion_holdout for the protocol)
                qa = seen_exclusion_holdout(
                    td.user_ids, td.item_ids,
                    td_all.user_ids[test], td_all.item_ids[test],
                    lambda u, bl: Query(
                        user=u, num=p.eval_num, black_list=bl
                    ),
                )
            else:
                qa = [
                    (
                        Query(user=str(u), num=1, item=str(i)),
                        float(r),
                    )
                    for u, i, r in zip(
                        td_all.user_ids[test],
                        td_all.item_ids[test],
                        td_all.ratings[test],
                    )
                ]
            folds.append((td, {"fold": k}, qa))
        return folds


# --------------------------------------------------------------- preparator
@dataclasses.dataclass
class PreparedData:
    user_index: BiMap
    item_index: BiMap
    user_codes: np.ndarray  # [n] int32
    item_codes: np.ndarray  # [n] int32
    ratings: np.ndarray  # [n] float32


class RecommendationPreparator(Preparator):
    """String ids → dense codes (≙ reference Preparator + BiMap.stringInt).

    Items are indexed by DESCENDING popularity: hot rows cluster at the
    low end of the factor table (gather locality on device) and the ALS
    delta item wire gets denser gaps. Code assignment is deterministic;
    results only depend on the mapping being a bijection."""

    def prepare(self, ctx: ComputeContext, td: TrainingData) -> PreparedData:
        user_index = BiMap.string_int(td.user_ids.tolist())
        item_index = BiMap.string_int_by_frequency(td.item_ids.tolist())
        ufwd, ifwd = user_index.to_dict(), item_index.to_dict()
        user_codes = np.fromiter(
            (ufwd[u] for u in td.user_ids.tolist()), np.int32, len(td)
        )
        item_codes = np.fromiter(
            (ifwd[i] for i in td.item_ids.tolist()), np.int32, len(td)
        )
        return PreparedData(
            user_index, item_index, user_codes, item_codes, td.ratings
        )


# --------------------------------------------------------------- algorithm
@dataclasses.dataclass(frozen=True)
class Query:
    user: str
    num: int = 10
    item: str = ""  # when set, score just this item (used by eval)
    #: items to exclude from the top-N (already-purchased exclusion; the
    #: hitrate eval's seen-item protocol) — applied ON DEVICE via the
    #: scorer's masked top-k, not by post-filtering
    black_list: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01  # engine.json key "lambda_" (lambda is reserved)
    seed: int = 3
    implicit_prefs: bool = False
    alpha: float = 1.0


@dataclasses.dataclass
class ALSModel(DeviceScorerModel, ShardableModel):
    factors: ALSFactors
    user_index: BiMap
    item_index: BiMap

    shard_template = "als"

    def _scorer_factors(self):
        return self.factors.user_factors, self.factors.item_factors

    def shard_arrays(self):
        return {
            "user_factors": self.factors.user_factors,
            "item_factors": self.factors.item_factors,
        }

    def replace_shard_arrays(self, arrays):
        return dataclasses.replace(
            self,
            factors=ALSFactors(
                user_factors=arrays["user_factors"],
                item_factors=arrays["item_factors"],
            ),
        )


class ALSAlgorithm(Algorithm):
    """pjit ALS (≙ reference ALSAlgorithm.train → MLlib ALS.train)."""

    params_class = ALSAlgorithmParams
    query_class = Query

    def train(self, ctx: ComputeContext, pd: PreparedData) -> ALSModel:
        p: ALSAlgorithmParams = self.params
        factors = train_als(
            ctx,
            pd.user_codes,
            pd.item_codes,
            pd.ratings,
            n_users=len(pd.user_index),
            n_items=len(pd.item_index),
            config=ALSConfig(
                rank=p.rank,
                iterations=p.num_iterations,
                reg=p.lambda_,
                implicit=p.implicit_prefs,
                alpha=p.alpha,
                seed=p.seed,
            ),
        )
        return ALSModel(factors, pd.user_index, pd.item_index)

    def prepare_for_serving(self, model: ALSModel) -> ALSModel:
        """Upload the factor matrices to the accelerator once at deploy and
        pre-compile the single-query bucket (SURVEY.md §7 hard part (d):
        amortize host↔device transfer across the serving lifetime)."""
        model.scorer(warmup=True)
        return model

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        return predict_user_topn(
            model, query, model.user_index, model.item_index
        )

    def warmup_query(self, model: ALSModel) -> Optional[Query]:
        """Any known user exercises the batched top-N program — enough
        to compile each serving shape bucket at deploy."""
        if len(model.user_index) == 0:
            return None
        return Query(user=model.user_index.inverse[0])

    def batch_predict(self, model: ALSModel, queries):
        """Vectorized offline scoring (reference ``batchPredictBase``):
        known-user top-N queries batch into ONE device dispatch per chunk
        ([B, K] @ [K, N] matmul + top-k on the accelerator); unknown users
        and single-item queries take the per-query path."""
        return batched_user_topn(
            self, model, queries, model.user_index, model.item_index,
            model.scorer,
        )


def _result_from_topn(idx, vals, item_index: BiMap) -> PredictedResult:
    """(top-n indices, scores) → PredictedResult — the only step that
    touches host Python: mapping integer codes back to string item ids.
    Non-finite scores are dropped: when a black_list leaves fewer than n
    items, the excluded slots surface from top-k as -inf and must not be
    served (nor serialized as non-standard JSON Infinity)."""
    inv = item_index.inverse
    return PredictedResult(
        tuple(
            ItemScore(inv[int(i)], float(v))
            for i, v in zip(idx, vals)
            if np.isfinite(v)
        )
    )


def predict_user_topn(model, query, user_index: BiMap,
                      item_index: BiMap) -> PredictedResult:
    """Shared online predict for user→top-N recommenders (ALS, two-tower):
    one home for the unknown-user guard, the single-item pair branch, the
    num<=0 guard, and the scorer dispatch — so the two templates (and the
    batched path below) cannot diverge. ``model`` is any DeviceScorerModel."""
    code = user_index.get(query.user)
    if code is None:
        return PredictedResult()  # unknown user (parity: empty result)
    if query.item:
        icode = item_index.get(query.item)
        if icode is None:
            return PredictedResult()
        score = model.scorer().score_pairs([code], [icode])[0]
        return PredictedResult((ItemScore(query.item, float(score)),))
    if query.num <= 0:
        return PredictedResult()
    scorer = model.scorer()
    idx, vals = scorer.top_n_batch(
        np.asarray([code], np.int32), query.num,
        exclude=_exclude_rows([query], item_index, scorer.n_cols),
    )
    return _result_from_topn(idx[0], vals[0], item_index)


def _exclude_rows(queries, item_index: BiMap, sentinel: int):
    """Per-query black_list item ids → padded ``[B, E]`` exclusion codes
    for the scorer (sentinel-filled; None when no query excludes
    anything). One home shared by the online and batched paths."""
    lists = [
        [
            c for c in (item_index.get(i) for i in q.black_list)
            if c is not None
        ]
        for q in queries
    ]
    width = max((len(ls) for ls in lists), default=0)
    if width == 0:
        return None
    out = np.full((len(lists), width), sentinel, np.int32)
    for r, ls in enumerate(lists):
        out[r, : len(ls)] = ls
    return out


def batched_user_topn(algo, model, queries, user_index, item_index, scorer):
    """Shared batch_predict routing for user→top-N recommenders (ALS,
    two-tower): known-user top-N queries batch through the device scorer
    (one matmul + top-k dispatch per chunk); unknown users and single-item
    queries fall back to ``algo.predict``. ``scorer`` may be a zero-arg
    callable (``model.scorer``) — it is then resolved only when a
    batchable query actually exists, so an all-fallback query file never
    pays the factor upload."""
    out = []
    bidx, bcodes, bq = [], [], []
    for i, q in queries:
        code = user_index.get(q.user)
        # num <= 0 rides the online path too: predict_user_topn owns that
        # empty-result contract (a negative num must not slice kmax+num
        # items off the batched result)
        if code is None or q.item or q.num <= 0:
            out.append((i, algo.predict(model, q)))
        else:
            bidx.append(i)
            bcodes.append(code)
            bq.append(q)
    if bcodes:
        if callable(scorer):
            scorer = scorer()
        kmax = max(q.num for q in bq)
        idx, vals = scorer.top_n_batch(
            np.asarray(bcodes, np.int32), kmax,
            exclude=_exclude_rows(bq, item_index, scorer.n_cols),
        )
        for i, q, ri, rv in zip(bidx, bq, idx, vals):
            out.append(
                (i, _result_from_topn(ri[:q.num], rv[:q.num], item_index))
            )
    return out


class RecommendationServing(FirstServing):
    pass


@register_engine("templates.recommendation")
def recommendation_engine() -> Engine:
    return Engine(
        RecommendationDataSource,
        RecommendationPreparator,
        {"als": ALSAlgorithm},
        RecommendationServing,
    )


# -------------------------------------------------------------- evaluation
class SquaredErrorMetric(OptionAverageMetric):
    """MSE on held-out (user, item) ratings; queries whose user/item were
    unseen in the training fold are skipped (the reference template's
    Evaluation.scala RMSE analog). Lower is better."""

    higher_is_better = False

    def calculate_one(self, query, prediction, actual):
        if not prediction.item_scores:
            return None
        return (prediction.item_scores[0].score - float(actual)) ** 2


def recommendation_evaluation(
    app_name: str = "",
    eval_k: int = 3,
    rate_event: str = "rate",
    ranks=(8, 16),
    lambdas=(0.05, 0.1),
    num_iterations: int = 10,
):
    """Ready-made `pio eval` sweep: k-fold MSE over a rank × lambda grid.

    Zero-arg CLI use reads the app from ``$PIO_TPU_EVAL_APP``:

        PIO_TPU_EVAL_APP=myapp python -m pio_tpu eval \\
            pio_tpu.templates.recommendation:recommendation_evaluation

    or wrap it in your own module to pin parameters.
    """
    from pio_tpu.controller.evaluation import (
        EngineParamsGenerator, Evaluation,
    )
    from pio_tpu.templates.common import eval_app_name

    if eval_k < 2:
        raise ValueError("k-fold evaluation needs eval_k >= 2")
    ds = DataSourceParams(
        app_name=eval_app_name(app_name), rate_event=rate_event,
        eval_k=eval_k,
    )
    grid = [
        EngineParams(
            data_source_params=ds,
            algorithm_params_list=(
                ("als", ALSAlgorithmParams(
                    rank=r, lambda_=lam, num_iterations=num_iterations
                )),
            ),
        )
        for r in ranks
        for lam in lambdas
    ]
    return Evaluation(
        recommendation_engine(), SquaredErrorMetric(),
        engine_params_generator=EngineParamsGenerator(grid),
    )
