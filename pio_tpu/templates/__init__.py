"""Bundled engine templates (reference ``examples/scala-parallel-*``).

Importing this package registers every bundled engine factory:

- ``templates.recommendation`` — explicit ALS recommender
  (≙ examples/scala-parallel-recommendation)
"""

from pio_tpu.templates import recommendation  # noqa: F401  (registers factory)

__all__ = ["recommendation"]
