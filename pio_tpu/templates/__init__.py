"""Bundled engine templates (reference ``examples/scala-parallel-*``).

Importing this package registers every bundled engine factory:

- ``templates.recommendation`` — explicit ALS recommender
  (≙ examples/scala-parallel-recommendation)
- ``templates.classification`` — NB / logreg attribute classifier
  (≙ examples/scala-parallel-classification)
"""

from pio_tpu.templates import classification  # noqa: F401  (registers factory)
from pio_tpu.templates import recommendation  # noqa: F401  (registers factory)

__all__ = ["classification", "recommendation"]
