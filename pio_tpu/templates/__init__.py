"""Bundled engine templates (reference ``examples/scala-parallel-*``).

Importing this package registers every bundled engine factory:

- ``templates.recommendation`` — explicit ALS recommender
  (≙ examples/scala-parallel-recommendation)
- ``templates.classification`` — NB / logreg attribute classifier
  (≙ examples/scala-parallel-classification)
- ``templates.similarproduct`` — implicit-ALS cosine similar items
  (≙ examples/scala-parallel-similarproduct)
- ``templates.ecommerce`` — personalized recs + business rules
  (≙ examples/scala-parallel-ecommercerecommendation)
- ``templates.textclassification`` — TF-IDF + sparse-input MLP / NB
  (≙ upstream text-classification template; BASELINE.json config #4)
- ``templates.twotower`` — neural two-tower retrieval, dp×tp×ep sharded
  (BASELINE.json config #5; capability-forward, no reference analog)
- ``templates.sequence`` — next-item transformer over full event
  histories, dp×sp×tp×ep×pp sharded (capability-forward)
"""

from pio_tpu.templates import classification  # noqa: F401  (registers factory)
from pio_tpu.templates import ecommerce  # noqa: F401  (registers factory)
from pio_tpu.templates import recommendation  # noqa: F401  (registers factory)
from pio_tpu.templates import sequence  # noqa: F401  (registers factory)
from pio_tpu.templates import similarproduct  # noqa: F401  (registers factory)
from pio_tpu.templates import textclassification  # noqa: F401  (registers factory)
from pio_tpu.templates import twotower  # noqa: F401  (registers factory)

__all__ = [
    "classification",
    "ecommerce",
    "recommendation",
    "sequence",
    "similarproduct",
    "textclassification",
    "twotower",
]
