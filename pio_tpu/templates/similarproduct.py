"""Similar-Product template — items similar to a basket of query items.

Rebuild of the reference's ``examples/scala-parallel-similarproduct``
(DataSource.scala reads ``$set item`` entities with ``categories`` + user
``view`` events; ALSAlgorithm.scala calls MLlib ``ALS.trainImplicit`` and
answers queries by cosine similarity over ``productFeatures`` with
category/whiteList/blackList filters — UNVERIFIED paths; SURVEY.md §2.5).

TPU-first serving: item factors are L2-normalized once at train time, so a
query is ``mean(normalized factors of basket) @ normalized_factorsᵀ`` — one
MXU matvec over all items — followed by masked top-N. Business-rule filters
(categories, white/black lists, the basket itself) become boolean masks on
the score vector, not per-item Python loops.

engine.json:

    {
      "id": "similarproduct",
      "engineFactory": "templates.similarproduct",
      "datasource": {"params": {"app_name": "myapp"}},
      "algorithms": [{"name": "als", "params":
          {"rank": 10, "num_iterations": 10, "lambda_": 0.01, "seed": 3}}]
    }

Query ``{"items": ["i1"], "num": 4, "categories": ["c"], "whiteList": [],
"blackList": []}`` → ``{"itemScores": [{"item": "i5", "score": 0.9}, ...]}``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

from pio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Params,
    Preparator,
    SanityCheck,
    register_engine,
)
from pio_tpu.controller.metrics import AverageMetric
from pio_tpu.data.bimap import BiMap
from pio_tpu.models.als import ALSConfig, train_als
from pio_tpu.parallel.context import ComputeContext
from pio_tpu.storage import Storage
from pio_tpu.templates.common import (
    ItemScore,
    PredictedResult,
    business_rule_mask,
    dedup_pair_indices,
    fold_assignments,
    l2_normalize_rows,
    resolve_app,
    top_item_scores,
)


# --------------------------------------------------------------- data source
@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    app_id: int = 0
    channel: str = ""
    view_event: str = "view"
    eval_k: int = 0  # >0 enables k-fold read_eval
    #: eval: context items per query / top-k window scored by HitRate
    eval_query_items: int = 3
    eval_num: int = 10


@dataclasses.dataclass
class TrainingData(SanityCheck):
    user_ids: np.ndarray  # [n] str objects (view edge sources)
    item_ids: np.ndarray  # [n] str objects (view edge targets)
    #: item entity id → categories (from $set item events)
    item_categories: Dict[str, FrozenSet[str]] = dataclasses.field(
        default_factory=dict
    )

    def sanity_check(self) -> None:
        if len(self.item_ids) == 0:
            raise ValueError(
                "TrainingData is empty - no view events found. "
                "Did you import events for this app?"
            )

    def __len__(self):
        return len(self.item_ids)


class SimilarProductDataSource(DataSource):
    """View edges + item category properties
    (≙ reference DataSource.readTraining)."""

    params_class = DataSourceParams

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        p: DataSourceParams = self.params
        app_id, channel_id = resolve_app(p)
        pe = Storage.get_pevents()
        frame = pe.find_frame(
            app_id,
            channel_id=channel_id,
            event_names=[p.view_event],
            entity_type="user",
            target_entity_type="item",
        )
        props = pe.aggregate_properties(
            app_id, entity_type="item", channel_id=channel_id
        )
        cats = {
            eid: frozenset(pm.get_opt("categories") or [])
            for eid, pm in props.items()
        }
        return TrainingData(
            user_ids=frame.entity_id,
            item_ids=frame.target_entity_id,
            item_categories=cats,
        )

    def read_eval(self, ctx: ComputeContext):
        """k-fold co-view holdout: the query carries a few items the user
        viewed in the training fold, the actual is a held-out co-viewed
        item — scored by HitRate@eval_num."""
        p: DataSourceParams = self.params
        if p.eval_k <= 0:
            return []
        if p.eval_k == 1:
            raise ValueError("k-fold cross-validation needs eval_k >= 2")
        td = self.read_training(ctx)
        keep = dedup_pair_indices(td.user_ids, td.item_ids)
        td = TrainingData(
            user_ids=td.user_ids[keep],
            item_ids=td.item_ids[keep],
            item_categories=td.item_categories,
        )
        n = len(td)
        fold_of = fold_assignments(n, p.eval_k)
        folds = []
        for k in range(p.eval_k):
            train = fold_of != k
            td_k = TrainingData(
                user_ids=td.user_ids[train],
                item_ids=td.item_ids[train],
                item_categories=td.item_categories,
            )
            by_user: Dict[str, List[str]] = {}
            for u, i in zip(td_k.user_ids, td_k.item_ids):
                by_user.setdefault(u, []).append(i)
            qa = []
            for u, i in zip(td.user_ids[~train], td.item_ids[~train]):
                ctx_items = [
                    x for x in by_user.get(u, ()) if x != i
                ][: p.eval_query_items]
                if not ctx_items:
                    continue  # cold user in this fold — unanswerable
                qa.append(
                    (Query(items=tuple(ctx_items), num=p.eval_num), str(i))
                )
            folds.append((td_k, {"fold": k}, qa))
        return folds


# --------------------------------------------------------------- preparator
@dataclasses.dataclass
class PreparedData:
    user_index: BiMap
    item_index: BiMap
    user_codes: np.ndarray  # [n] int32
    item_codes: np.ndarray  # [n] int32
    #: per item code, the item's categories
    categories: List[FrozenSet[str]] = dataclasses.field(default_factory=list)


class SimilarProductPreparator(Preparator):
    def prepare(self, ctx: ComputeContext, td: TrainingData) -> PreparedData:
        user_index = BiMap.string_int(td.user_ids.tolist())
        # include items that only appear as $set entities so category-only
        # items still get factor rows (cold but filterable); popularity
        # ordering clusters hot factor rows (gather locality + denser
        # delta wire)
        all_items = td.item_ids.tolist() + sorted(td.item_categories)
        item_index = BiMap.string_int_by_frequency(all_items)
        ufwd, ifwd = user_index.to_dict(), item_index.to_dict()
        user_codes = np.fromiter(
            (ufwd[u] for u in td.user_ids.tolist()), np.int32, len(td)
        )
        item_codes = np.fromiter(
            (ifwd[i] for i in td.item_ids.tolist()), np.int32, len(td)
        )
        inv = item_index.inverse
        categories = [
            td.item_categories.get(inv[c], frozenset())
            for c in range(len(item_index))
        ]
        return PreparedData(
            user_index, item_index, user_codes, item_codes, categories
        )


# ----------------------------------------------------------------- algorithm
@dataclasses.dataclass(frozen=True)
class Query:
    items: Tuple[str, ...] = ()
    num: int = 10
    categories: Tuple[str, ...] = ()
    white_list: Tuple[str, ...] = ()
    black_list: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int = 3


@dataclasses.dataclass
class SimilarProductModel:
    #: L2-normalized item factors [n_items, rank]
    norm_factors: np.ndarray
    item_index: BiMap
    categories: List[FrozenSet[str]]


class SimilarProductAlgorithm(Algorithm):
    """Implicit ALS + cosine over item factors
    (≙ reference ALSAlgorithm.train → MLlib ALS.trainImplicit)."""

    params_class = ALSAlgorithmParams
    query_class = Query

    def train(
        self, ctx: ComputeContext, pd: PreparedData
    ) -> SimilarProductModel:
        p: ALSAlgorithmParams = self.params
        factors = train_als(
            ctx,
            pd.user_codes,
            pd.item_codes,
            np.ones(len(pd.item_codes), np.float32),  # implicit: r=1 per view
            n_users=len(pd.user_index),
            n_items=len(pd.item_index),
            config=ALSConfig(
                rank=p.rank,
                iterations=p.num_iterations,
                reg=p.lambda_,
                implicit=True,
                alpha=p.alpha,
                seed=p.seed,
            ),
        )
        return SimilarProductModel(
            l2_normalize_rows(factors.item_factors),
            pd.item_index,
            pd.categories,
        )

    def predict(
        self, model: SimilarProductModel, query: Query
    ) -> PredictedResult:
        codes = [
            c
            for c in (model.item_index.get(i) for i in query.items)
            if c is not None
        ]
        if not codes:
            return PredictedResult()  # all query items unknown
        basket = model.norm_factors[np.asarray(codes, np.int32)]
        scores = model.norm_factors @ basket.mean(axis=0)

        return _masked_top_result(model, codes, scores, query)

    def batch_predict(self, model: SimilarProductModel, queries):
        """Vectorized offline scoring: one [B, K] @ [K, N] matmul over all
        resolvable baskets; business-rule masks stay per query (they
        depend on each query's category/white/black lists)."""
        out = []
        bidx, bq, bcodes = [], [], []
        for i, q in queries:
            codes = [
                c
                for c in (model.item_index.get(x) for x in q.items)
                if c is not None
            ]
            if not codes:
                out.append((i, PredictedResult()))
                continue
            bidx.append(i)
            bq.append(q)
            bcodes.append(codes)
        if bidx:
            baskets = np.stack([
                model.norm_factors[np.asarray(c, np.int32)].mean(axis=0)
                for c in bcodes
            ])
            scores = baskets @ model.norm_factors.T  # [B, n_items]
            for i, q, codes, row in zip(bidx, bq, bcodes, scores):
                out.append((i, _masked_top_result(model, codes, row, q)))
        return out


def _masked_top_result(
    model: SimilarProductModel, codes, scores, query: Query
) -> PredictedResult:
    """Shared business-rule mask + top-N tail for predict/batch_predict
    (one home, so online and offline scoring cannot diverge)."""
    mask = business_rule_mask(
        len(scores),
        model.item_index,
        model.categories,
        categories=query.categories,
        white_list=query.white_list,
        black_list=query.black_list,
    )
    mask[np.asarray(codes, np.int32)] = False  # never return the basket
    return top_item_scores(scores, mask, query.num, model.item_index)


class SimilarProductServing(FirstServing):
    pass


@register_engine("templates.similarproduct")
def similarproduct_engine() -> Engine:
    return Engine(
        SimilarProductDataSource,
        SimilarProductPreparator,
        {"als": SimilarProductAlgorithm},
        SimilarProductServing,
    )


# -------------------------------------------------------------- evaluation
class HitRateMetric(AverageMetric):
    """Fraction of held-out co-viewed items appearing in the top-k similars
    (HitRate@k; the reference similar-product eval pattern)."""

    def calculate_one(self, query, prediction, actual):
        return 1.0 if any(
            s.item == actual for s in prediction.item_scores
        ) else 0.0


def similarproduct_evaluation(
    app_name: str = "",
    eval_k: int = 3,
    ranks=(8, 16),
    num_iterations: int = 10,
    eval_num: int = 10,
):
    """Ready-made `pio eval` sweep: k-fold HitRate@``eval_num`` over the
    rank grid. Keep ``eval_num`` well below the catalog size or the
    metric saturates (every item fits in the window).

    Zero-arg CLI use reads the app from ``$PIO_TPU_EVAL_APP``:

        PIO_TPU_EVAL_APP=myapp python -m pio_tpu eval \\
            pio_tpu.templates.similarproduct:similarproduct_evaluation
    """
    from pio_tpu.controller.engine import EngineParams
    from pio_tpu.controller.evaluation import (
        EngineParamsGenerator, Evaluation,
    )
    from pio_tpu.templates.common import eval_app_name

    if eval_k < 2:
        raise ValueError("k-fold evaluation needs eval_k >= 2")
    ds = DataSourceParams(
        app_name=eval_app_name(app_name), eval_k=eval_k, eval_num=eval_num
    )
    grid = [
        EngineParams(
            data_source_params=ds,
            algorithm_params_list=(
                ("als", ALSAlgorithmParams(
                    rank=r, num_iterations=num_iterations
                )),
            ),
        )
        for r in ranks
    ]
    return Evaluation(
        similarproduct_engine(), HitRateMetric(),
        engine_params_generator=EngineParamsGenerator(grid),
    )
