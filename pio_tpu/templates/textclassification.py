"""Text-classification template — label prediction from raw text.

Rebuild of the upstream text-classification engine template (MLlib
``HashingTF``/``IDF`` featurization + NaiveBayes/LogisticRegression;
UNVERIFIED — the in-repo reference bundles no text template, but
BASELINE.json config #4 names "Text-Classification template (TF-IDF + MLP)
with Pallas embedding lookup" as a required measurement config).

TPU-first design: documents stay sparse end-to-end. The Preparator fits a
learned-vocabulary TF-IDF vectorizer (pio_tpu/models/tfidf.py) and packs
each document into a (token-id, weight) bag; the algorithms consume bags
through :func:`pio_tpu.ops.embedding_bag` — the Pallas streamed
sparse×dense kernel — so no ``[B, V]`` one-hot matrix ever exists.

Two algorithms, selectable in engine.json:

- ``mlp`` — sparse-input MLP (pio_tpu/models/mlp.py), data-parallel Adam.
- ``nb``  — multinomial NB over the tf-idf bags (densified per class
  via segment sums; pio_tpu/models/naive_bayes.py).

engine.json:

    {
      "id": "textclass",
      "engineFactory": "templates.textclassification",
      "datasource": {"params": {"app_name": "myapp"}},
      "algorithms": [{"name": "mlp", "params": {"hidden": 128}}]
    }

Query ``{"text": "..."}`` → ``{"label": "...", "confidence": 0.93}``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from pio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Params,
    Preparator,
    SanityCheck,
    register_engine,
)
from pio_tpu.controller.cross_validation import split_data
from pio_tpu.controller.metrics import AverageMetric
from pio_tpu.data.bimap import BiMap
from pio_tpu.models.mlp import MLPConfig, MLPModel, train_mlp
from pio_tpu.models.naive_bayes import (
    MultinomialNBModel,
    train_multinomial_nb_bags,
)
from pio_tpu.models.tfidf import TfIdfVectorizer
from pio_tpu.ops.embedding import pack_bags
from pio_tpu.parallel.context import ComputeContext
from pio_tpu.storage import Storage
from pio_tpu.templates.common import resolve_app


# --------------------------------------------------------------- data source
@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    app_id: int = 0
    channel: str = ""
    #: documents are $set properties on this entity type
    entity_type: str = "content"
    text_attr: str = "text"
    label_attr: str = "label"
    eval_k: int = 0


@dataclasses.dataclass
class TrainingData(SanityCheck):
    texts: list  # [n] str
    labels: list  # [n] str

    def sanity_check(self) -> None:
        if not self.texts:
            raise ValueError(
                "TrainingData is empty - no entities with text + label "
                "properties. Did you $set documents for this app?"
            )

    def __len__(self):
        return len(self.texts)


class TextDataSource(DataSource):
    """aggregateProperties → (text, label) rows."""

    params_class = DataSourceParams

    def _read(self) -> TrainingData:
        p: DataSourceParams = self.params
        app_id, channel_id = resolve_app(p)
        props = Storage.get_pevents().aggregate_properties(
            app_id,
            entity_type=p.entity_type,
            channel_id=channel_id,
            required=[p.text_attr, p.label_attr],
        )
        texts, labels = [], []
        for _eid, pm in sorted(props.items()):
            texts.append(str(pm.get(p.text_attr)))
            labels.append(str(pm.get(p.label_attr)))
        return TrainingData(texts=texts, labels=labels)

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        return self._read()

    def read_eval(self, ctx: ComputeContext):
        p: DataSourceParams = self.params
        if p.eval_k <= 0:
            return []
        td = self._read()
        rows = list(zip(td.texts, td.labels))
        return split_data(
            p.eval_k,
            rows,
            to_training_data=lambda rs: TrainingData(
                texts=[t for t, _ in rs], labels=[l for _, l in rs]
            ),
            to_query_actual=lambda r: (Query(text=r[0]), r[1]),
        )


# --------------------------------------------------------------- preparator
@dataclasses.dataclass(frozen=True)
class PreparatorParams(Params):
    max_features: int = 65536
    #: cap on tokens per document bag (rounded up to a multiple of 8)
    max_doc_tokens: int = 256


@dataclasses.dataclass
class PreparedData:
    vectorizer: TfIdfVectorizer
    ids: np.ndarray  # [n, L] int32 bags
    weights: np.ndarray  # [n, L] float32
    label_codes: np.ndarray  # [n] int32
    label_index: BiMap
    token_cap: int = 0  # per-doc truncation cap applied at train time


class TextPreparator(Preparator):
    """Fit TF-IDF vocab + label index; documents → packed sparse bags."""

    params_class = PreparatorParams

    def prepare(self, ctx: ComputeContext, td: TrainingData) -> PreparedData:
        p: PreparatorParams = self.params
        vec = TfIdfVectorizer.fit(td.texts, max_features=p.max_features)
        bags = [
            _truncate_bag(*vec.transform_doc(t), p.max_doc_tokens)
            for t in td.texts
        ]
        longest = max((len(b[0]) for b in bags), default=1)
        ids, w = pack_bags(
            [b[0] for b in bags],
            [b[1] for b in bags],
            max_len=min(max(longest, 1), p.max_doc_tokens),
        )
        label_index = BiMap.string_int(td.labels)
        fwd = label_index.to_dict()
        codes = np.fromiter(
            (fwd[l] for l in td.labels), np.int32, len(td.labels)
        )
        return PreparedData(
            vec, ids, w, codes, label_index, token_cap=p.max_doc_tokens
        )


# ----------------------------------------------------------------- algorithm
@dataclasses.dataclass(frozen=True)
class Query:
    text: str = ""


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    label: str = ""
    confidence: float = 0.0

    def to_dict(self) -> dict:
        return {"label": self.label, "confidence": self.confidence}


def _truncate_bag(ids, w, width: int):
    """Cut a bag to ``width`` tokens keeping the *highest-weight* ones.

    transform_doc returns ids ascending (≈ descending document frequency),
    so a head-slice would keep the common low-idf tokens and drop the rare
    discriminative ones.
    """
    if len(ids) <= width:
        return ids, w
    keep = np.argsort(-np.asarray(w))[:width]
    keep.sort()  # preserve id order within the kept set
    return np.asarray(ids)[keep], np.asarray(w)[keep]


def _query_bag(vec: TfIdfVectorizer, text: str, width: int, cap: int = 0):
    """Pack one query doc to the training bag width.

    ``cap`` is the train-time truncation cap: pack_bags rounds the packed
    width up (kernel alignment), so truncating at ``width`` would keep more
    tokens for queries than training docs got — train/serve skew.
    """
    cap = min(width, cap) if cap else width
    ids, w = _truncate_bag(*vec.transform_doc(text), cap)
    out_i = np.zeros((1, width), np.int32)
    out_w = np.zeros((1, width), np.float32)
    n = len(ids)
    out_i[0, :n] = ids
    out_w[0, :n] = w
    return out_i, out_w


@dataclasses.dataclass(frozen=True)
class MLPParams(Params):
    hidden: int = 128
    iterations: int = 200
    learning_rate: float = 0.01
    reg: float = 0.0
    seed: int = 0


@dataclasses.dataclass
class TextMLPModel:
    mlp: MLPModel
    vectorizer: TfIdfVectorizer
    label_index: BiMap
    bag_width: int  # packed width (rounded up for kernel alignment)
    token_cap: int = 0  # truncation cap used at train time (0 = bag_width)


class MLPAlgorithm(Algorithm):
    """Sparse-input MLP over TF-IDF bags (Pallas embedding-bag hot path)."""

    params_class = MLPParams
    query_class = Query

    def train(self, ctx: ComputeContext, pd: PreparedData) -> TextMLPModel:
        p: MLPParams = self.params
        mlp = train_mlp(
            ctx,
            pd.ids,
            pd.weights,
            pd.label_codes,
            n_features=pd.vectorizer.n_features,
            n_classes=len(pd.label_index),
            config=MLPConfig(
                hidden=p.hidden,
                iterations=p.iterations,
                learning_rate=p.learning_rate,
                reg=p.reg,
                seed=p.seed,
            ),
        )
        return TextMLPModel(
            mlp, pd.vectorizer, pd.label_index, pd.ids.shape[1],
            token_cap=pd.token_cap,
        )

    def predict(self, model: TextMLPModel, query: Query) -> PredictedResult:
        ids, w = _query_bag(
            model.vectorizer, query.text, model.bag_width, model.token_cap
        )
        return _proba_result(
            model.mlp.predict_proba(ids, w)[0], model.label_index
        )

    def warmup_query(self, model: TextMLPModel) -> Query:
        """Bag width is fixed per model, so any text (even empty)
        produces the serving input shape — enough to warm each bucket."""
        return Query(text="warmup")

    def batch_predict(self, model: TextMLPModel, queries):
        """Tokenize per query on host, then one device forward per
        bounded chunk of stacked [B, L] bags."""
        out = []
        for chunk in _chunks(queries):
            ids, w = _stack_bags(model, chunk)
            proba = model.mlp.predict_proba(ids, w)
            out.extend(
                (i, _proba_result(p, model.label_index))
                for (i, _), p in zip(chunk, proba)
            )
        return out


@dataclasses.dataclass(frozen=True)
class NBParams(Params):
    lambda_: float = 1.0


@dataclasses.dataclass
class TextNBModel:
    nb: MultinomialNBModel
    vectorizer: TfIdfVectorizer
    label_index: BiMap
    bag_width: int  # packed width (rounded up for kernel alignment)
    token_cap: int = 0  # truncation cap used at train time (0 = bag_width)


class NBAlgorithm(Algorithm):
    """Multinomial NB over the sparse tf-idf bags (segment-sum training)."""

    params_class = NBParams
    query_class = Query

    def train(self, ctx: ComputeContext, pd: PreparedData) -> TextNBModel:
        p: NBParams = self.params
        nb = train_multinomial_nb_bags(
            pd.ids,
            pd.weights,
            pd.label_codes,
            n_features=pd.vectorizer.n_features,
            n_classes=len(pd.label_index),
            lambda_=p.lambda_,
        )
        return TextNBModel(
            nb, pd.vectorizer, pd.label_index, pd.ids.shape[1],
            token_cap=pd.token_cap,
        )

    def predict(self, model: TextNBModel, query: Query) -> PredictedResult:
        ids, w = _query_bag(
            model.vectorizer, query.text, model.bag_width, model.token_cap
        )
        log_p = model.nb.scores_bags(ids, w)[0]
        return _proba_result(_softmax(log_p), model.label_index)

    def warmup_query(self, model: TextNBModel) -> Query:
        """Bag width is fixed per model, so any text (even empty)
        produces the serving input shape — enough to warm each bucket."""
        return Query(text="warmup")

    def batch_predict(self, model: TextNBModel, queries):
        """Tokenize per query on host, then one scores_bags call per
        bounded chunk (its [C, B, L] gather scales with the chunk, so an
        arbitrarily large query file must not ride one dispatch)."""
        out = []
        for chunk in _chunks(queries):
            ids, w = _stack_bags(model, chunk)
            log_p = model.nb.scores_bags(ids, w)
            out.extend(
                (i, _proba_result(_softmax(lp), model.label_index))
                for (i, _), lp in zip(chunk, log_p)
            )
        return out


#: batch-scoring chunk: bounds the [B, L] bags (and NB's [C, B, L]
#: gather) regardless of query-file size, and keeps jit shape
#: specialization to at most two variants (full chunks + the remainder)
_BATCH_CHUNK = 1024


def _chunks(queries, n: int = _BATCH_CHUNK):
    for k in range(0, len(queries), n):
        yield queries[k:k + n]


def _stack_bags(model, queries):
    """[B, L] id/weight bags from the queries' texts (host tokenize)."""
    bags = [
        _query_bag(
            model.vectorizer, q.text, model.bag_width, model.token_cap
        )
        for _, q in queries
    ]
    return (
        np.concatenate([b[0] for b in bags]),
        np.concatenate([b[1] for b in bags]),
    )


def _softmax(log_p: np.ndarray) -> np.ndarray:
    p = np.exp(log_p - log_p.max())
    return p / p.sum()


def _proba_result(proba: np.ndarray, label_index) -> PredictedResult:
    """Shared argmax+confidence tail so predict/batch_predict agree."""
    code = int(np.argmax(proba))
    return PredictedResult(
        label=label_index.inverse[code], confidence=float(proba[code])
    )


class TextServing(FirstServing):
    pass


@register_engine("templates.textclassification")
def textclassification_engine() -> Engine:
    return Engine(
        TextDataSource,
        TextPreparator,
        {"mlp": MLPAlgorithm, "nb": NBAlgorithm},
        TextServing,
    )


# -------------------------------------------------------------- evaluation
class TextAccuracyMetric(AverageMetric):
    """Fraction of held-out documents labeled correctly."""

    def calculate_one(self, query, prediction, actual):
        return 1.0 if prediction.label == actual else 0.0


def textclassification_evaluation(
    app_name: str = "",
    eval_k: int = 3,
    hiddens=(64, 128),
):
    """Ready-made `pio eval` sweep: k-fold accuracy over the MLP hidden
    width grid.

    Zero-arg CLI use reads the app from ``$PIO_TPU_EVAL_APP``:

        PIO_TPU_EVAL_APP=myapp python -m pio_tpu eval \\
            pio_tpu.templates.textclassification:textclassification_evaluation
    """
    from pio_tpu.controller.engine import EngineParams
    from pio_tpu.controller.evaluation import (
        EngineParamsGenerator, Evaluation,
    )
    from pio_tpu.templates.common import eval_app_name

    if eval_k < 2:
        raise ValueError("k-fold evaluation needs eval_k >= 2")
    ds = DataSourceParams(app_name=eval_app_name(app_name), eval_k=eval_k)
    grid = [
        EngineParams(
            data_source_params=ds,
            preparator_params=PreparatorParams(),
            algorithm_params_list=(("mlp", MLPParams(hidden=h)),),
        )
        for h in hiddens
    ]
    return Evaluation(
        textclassification_engine(), TextAccuracyMetric(),
        engine_params_generator=EngineParamsGenerator(grid),
    )
