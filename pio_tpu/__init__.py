"""pio_tpu — a TPU-native machine-learning server.

A from-scratch rebuild of the capabilities of Apache PredictionIO
(reference: TharinduDG/incubator-predictionio) on a JAX/XLA substrate:

- ``pio_tpu.data``       — event data model (Event, DataMap, PropertyMap, BiMap)
                           [ref: data/src/main/scala/o/a/p/data/storage/Event.scala etc.]
- ``pio_tpu.storage``    — storage SPI + backends (memory, SQLite, Parquet)
                           [ref: data/.../storage/Storage.scala + storage/* subprojects]
- ``pio_tpu.server``     — Event Server + per-engine Query Server (HTTP)
                           [ref: data/.../api/EventServer.scala, core/.../workflow/CreateServer.scala]
- ``pio_tpu.controller`` — DASE framework: DataSource, Preparator, Algorithm,
                           Serving, Evaluation/Metric [ref: core/.../controller/*]
- ``pio_tpu.workflow``   — train/eval/deploy workflow + engine registry
                           [ref: core/.../workflow/CreateWorkflow.scala, CoreWorkflow.scala]
- ``pio_tpu.models``     — JAX/TPU algorithm implementations (ALS, LogReg, ...)
                           replacing Spark MLlib
- ``pio_tpu.ops``        — Pallas kernels and TPU-friendly primitive ops
- ``pio_tpu.parallel``   — mesh / sharding / collective helpers replacing Spark
                           shuffle + treeAggregate
- ``pio_tpu.templates``  — bundled engines (recommendation, classification,
                           similar-product, e-commerce, text classification,
                           two-tower, sequence) [ref: examples/scala-parallel-*]
- ``pio_tpu.native``     — C++ runtime components (event-log storage engine,
                           ALS data packer), built with g++ on first use
- ``pio_tpu.tools``      — the ``pio`` CLI equivalent

Where the reference dispatches work to Spark executors, this package runs
sharded JAX programs over a ``jax.sharding.Mesh``; XLA collectives over
ICI/DCN replace Spark shuffles and tree-aggregations.
"""

__version__ = "0.1.0"
