"""Workflow engine: engine.json loading, train/eval drivers, bookkeeping.

Rebuild of the reference's ``core/.../workflow/`` (CreateWorkflow,
CoreWorkflow, EvaluationWorkflow — UNVERIFIED paths; see SURVEY.md).
"""

from pio_tpu.workflow.core_workflow import (
    deserialize_models,
    load_models_for_instance,
    run_evaluation,
    run_train,
    serialize_models,
)
from pio_tpu.workflow.engine_json import (
    EngineJsonError,
    EngineVariant,
    build_engine,
    load_variant,
    variant_from_dict,
)
from pio_tpu.workflow.params import WorkflowParams

__all__ = [
    "EngineJsonError",
    "EngineVariant",
    "WorkflowParams",
    "build_engine",
    "deserialize_models",
    "load_models_for_instance",
    "load_variant",
    "run_evaluation",
    "run_train",
    "serialize_models",
    "variant_from_dict",
]
