"""Batch predict — bulk offline scoring from a query file.

Rebuild of the reference's ``BatchPredict.main``
(``tools/src/main/scala/o/a/p/workflow/BatchPredict.scala`` [v0.12],
UNVERIFIED path; see SURVEY.md): input file of JSON-lines queries → load the
deployed model → ``Algorithm.batch_predict`` → serving per query → JSON-lines
output. Where the reference distributes via an RDD of queries, algorithms
here can override ``batch_predict`` with one vectorized device program.
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from pio_tpu.controller.params import ParamsError, params_from_dict
from pio_tpu.parallel.context import ComputeContext
from pio_tpu.workflow.core_workflow import load_models_for_instance
from pio_tpu.workflow.deploy_common import (
    resolve_instance_id,
    resolve_query_class,
    to_jsonable,
)
from pio_tpu.workflow.engine_json import EngineVariant, build_engine

log = logging.getLogger("pio_tpu.batchpredict")


def run_batch_predict(
    variant: EngineVariant,
    input_path: str,
    output_path: str,
    instance_id: Optional[str] = None,
    ctx: Optional[ComputeContext] = None,
) -> int:
    """Score every query line; returns the number scored.

    Output lines: ``{"query": ..., "prediction": ...}`` — malformed query
    lines produce ``{"query": ..., "error": ...}`` instead of aborting the
    run (parity with batch ingestion's per-item statuses).
    """
    ctx = ctx or ComputeContext.create()
    engine, engine_params = build_engine(variant)
    instance_id = resolve_instance_id(variant, instance_id)
    models = load_models_for_instance(
        instance_id, engine, engine_params, ctx, variant=variant
    )
    pairs = engine.algorithms_with_models(engine_params, models)
    serving = engine.make_serving(engine_params)
    qc = resolve_query_class(pairs)

    n = 0
    with open(input_path) as fin, open(output_path, "w") as fout:
        # Stage 1: parse queries (keeping raw line pairing for errors)
        parsed = []
        for line in fin:
            line = line.strip()
            if not line:
                continue
            try:
                raw = json.loads(line)
                query = params_from_dict(qc, raw) if qc else raw
                parsed.append((raw, query, None))
            except (json.JSONDecodeError, ParamsError) as e:
                parsed.append((line, None, str(e)))

        # Stage 2: supplement ONCE per query (same semantics as the query
        # server), then batch predict per algorithm (vectorized when
        # overridden)
        supplemented = {
            i: serving.supplement(q)
            for i, (_, q, err) in enumerate(parsed)
            if err is None
        }
        supplied = list(supplemented.items())  # built in ascending-i order
        per_algo = [
            dict(algo.batch_predict(model, supplied)) for algo, model in pairs
        ]

        # Stage 3: serve + write
        for i, (raw, _, err) in enumerate(parsed):
            if err is not None:
                fout.write(json.dumps({"query": raw, "error": err}) + "\n")
                continue
            predictions = [p[i] for p in per_algo]
            result = serving.serve(supplemented[i], predictions)
            fout.write(
                json.dumps(
                    {"query": raw, "prediction": to_jsonable(result)}
                )
                + "\n"
            )
            n += 1
    log.info("batch predict: %d queries scored -> %s", n, output_path)
    return n
