"""Sharded model persistence: per-shard records + a shard manifest.

A model whose parameters exceed one chip's memory can't round-trip
through the single pickled blob ``run_train`` writes: the blob is one
host allocation, and deploy would re-place it whole. Instead, models
that implement the :class:`ShardableModel` protocol persist their large
arrays as **per-shard records** in the Models store (row-slices along
dim 0, one per training device) plus a **shard manifest** recording the
saved mesh shape, every array's shape/dtype/partition spec, and a
sha256 per shard. The pickled blob keeps only lightweight state with
:class:`ShardPlaceholder` markers where the arrays were.

Write order is crash-safe by construction: shards → shard manifest →
blob → blob manifest. A crash anywhere leaves either a previous
generation intact or a stripped blob whose manifest is missing /
unverifiable — both raise at load and ride the existing
last-known-good fallback (``pio_tpu_model_fallback_total``).

Because shards are plain row-slices, loading on a *different* mesh
shape is just concat + re-place: a checkpoint saved on ``(8,)`` deploys
on ``(4,)`` or ``(1,)`` unchanged (counted by
``pio_tpu_shard_reshard_total``).

Gate: ``PIO_TPU_SHARDED_PERSIST=1`` (default off — the single-blob path
stays byte-identical to prior releases).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json as _json
import logging
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pio_tpu.utils import knobs
from pio_tpu.faults import failpoint
from pio_tpu.obs import REGISTRY
from pio_tpu.storage import Model

log = logging.getLogger("pio_tpu.workflow")

#: Models-store id suffix of the per-instance shard manifest.
SHARD_MANIFEST_SUFFIX = ".shards"

_SHARD_RESHARD = REGISTRY.counter(
    "pio_tpu_shard_reshard_total",
    "Sharded checkpoint loads whose device count differed from the "
    "mesh shape the shards were saved on (concat + re-place)",
)


def _env_on() -> bool:
    return knobs.knob_str("PIO_TPU_SHARDED_PERSIST") == "1"


@dataclasses.dataclass(frozen=True)
class ShardPlaceholder:
    """Marks a stripped array inside a pickled blob; the real bytes live
    in shard records named by the shard manifest."""

    name: str
    shape: Tuple[int, ...]
    dtype: str


class ShardableModel:
    """Protocol mixin for models whose big arrays persist sharded.

    Subclasses set ``shard_template`` (a partition-rule registry name)
    and implement :meth:`shard_arrays` (name → host array of every
    tensor to persist sharded) and :meth:`replace_shard_arrays`
    (returns a copy with those arrays swapped — used both to strip
    placeholders in and to install restored arrays).
    """

    # plain class attribute, not an annotated field: dataclass subclasses
    # must not inherit it as a defaulted field ahead of their own
    shard_template = ""

    def shard_arrays(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def replace_shard_arrays(self, arrays: Dict[str, Any]):
        raise NotImplementedError


def sharded_persist_enabled() -> bool:
    """True when ``PIO_TPU_SHARDED_PERSIST=1``."""
    return _env_on()


def is_stripped(model: Any) -> bool:
    """True if ``model`` carries :class:`ShardPlaceholder` leaves."""
    if not isinstance(model, ShardableModel):
        return False
    return any(
        isinstance(v, ShardPlaceholder) for v in model.shard_arrays().values()
    )


def _spec_to_json(spec) -> List[Any]:
    out: List[Any] = []
    for entry in spec:
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append(list(entry))
    return out


def _spec_entries(model: ShardableModel, arrays: Dict[str, np.ndarray]):
    """name → partition spec (JSON-ready) from the model's rule template."""
    from pio_tpu.parallel.partition import match_partition_rules, rules_for

    try:
        rules = rules_for(model.shard_template)
    except KeyError:
        rules = []
    specs = match_partition_rules(rules, arrays)
    return {name: _spec_to_json(specs[name]) for name in arrays}


def save_sharded(
    models_store,
    instance_id: str,
    models: List[Any],
    n_shards: int,
    mesh_shape: Optional[List[int]] = None,
) -> List[Any]:
    """Persist every ShardableModel's arrays as shard records; returns
    the blob-ready model list with those arrays stripped to placeholders.

    Writes shard records first and the shard manifest last, so a partial
    write never yields a manifest naming missing bytes.
    """
    n_shards = max(1, int(n_shards))
    manifest: Dict[str, Any] = {
        "version": 1,
        "n_shards": n_shards,
        "mesh_shape": list(mesh_shape or [n_shards]),
        "algos": [],
    }
    stripped: List[Any] = list(models)
    any_sharded = False
    for algo_idx, model in enumerate(models):
        if not isinstance(model, ShardableModel):
            manifest["algos"].append(None)
            continue
        arrays = {
            k: np.asarray(v) for k, v in model.shard_arrays().items()
        }
        entries = []
        specs = _spec_entries(model, arrays)
        placeholders: Dict[str, Any] = {}
        for arr_idx, (name, arr) in enumerate(sorted(arrays.items())):
            shards = []
            row = 0
            for shard_idx, piece in enumerate(
                np.array_split(arr, n_shards, axis=0)
            ):
                piece = np.ascontiguousarray(piece)
                payload = piece.tobytes()
                shard_id = (
                    f"{instance_id}.shard.{algo_idx}.{arr_idx}.{shard_idx}"
                )
                models_store.insert(Model(id=shard_id, models=payload))
                shards.append(
                    {
                        "id": shard_id,
                        "sha256": hashlib.sha256(payload).hexdigest(),
                        "size": len(payload),
                        "rows": [row, row + len(piece)],
                    }
                )
                row += len(piece)
            entries.append(
                {
                    "name": name,
                    "shape": list(arr.shape),
                    "dtype": str(arr.dtype),
                    "spec": specs[name],
                    "shards": shards,
                }
            )
            placeholders[name] = ShardPlaceholder(
                name, tuple(arr.shape), str(arr.dtype)
            )
        manifest["algos"].append(
            {"template": model.shard_template, "arrays": entries}
        )
        stripped[algo_idx] = model.replace_shard_arrays(placeholders)
        any_sharded = True
    if any_sharded:
        models_store.insert(
            Model(
                id=instance_id + SHARD_MANIFEST_SUFFIX,
                models=_json.dumps(manifest, sort_keys=True).encode(),
            )
        )
    return stripped


def restore_sharded(
    models_store,
    instance_id: str,
    models: List[Any],
    n_devices: Optional[int] = None,
) -> List[Any]:
    """Reassemble stripped models from verified shard records.

    Every shard is checksummed against the shard manifest before any
    byte is interpreted; a missing manifest, missing shard, or checksum
    mismatch raises RuntimeError — the caller's last-known-good fallback
    handles it exactly like a torn blob.
    """
    if not any(is_stripped(m) for m in models):
        return models
    record = models_store.get(instance_id + SHARD_MANIFEST_SUFFIX)
    if record is None:
        raise RuntimeError(
            f"instance {instance_id!r}: blob is shard-stripped but no "
            f"shard manifest exists (torn sharded persist)"
        )
    try:
        manifest = _json.loads(record.models.decode("utf-8"))
    except Exception as e:
        raise RuntimeError(
            f"unreadable shard manifest for instance {instance_id!r}: {e}"
        ) from e
    algos = manifest.get("algos", [])
    saved_shape = manifest.get("mesh_shape") or [manifest.get("n_shards", 1)]
    if n_devices is not None and int(np.prod(saved_shape)) != int(n_devices):
        failpoint("shard.reshard")
        _SHARD_RESHARD.inc()
        log.info(
            "resharding instance %s: saved on mesh %s, loading on %d "
            "device(s)", instance_id, saved_shape, n_devices,
        )
    out = list(models)
    for algo_idx, model in enumerate(models):
        if not is_stripped(model):
            continue
        if algo_idx >= len(algos) or algos[algo_idx] is None:
            raise RuntimeError(
                f"instance {instance_id!r}: algorithm {algo_idx} is "
                f"shard-stripped but absent from the shard manifest"
            )
        arrays: Dict[str, np.ndarray] = {}
        for entry in algos[algo_idx]["arrays"]:
            pieces = []
            for shard in entry["shards"]:
                rec = models_store.get(shard["id"])
                if rec is None:
                    raise RuntimeError(
                        f"missing shard record {shard['id']!r} for "
                        f"instance {instance_id!r}"
                    )
                got = hashlib.sha256(rec.models).hexdigest()
                if got != shard["sha256"] or len(rec.models) != shard["size"]:
                    raise RuntimeError(
                        f"shard {shard['id']!r} failed checksum "
                        f"verification (manifest {shard['sha256']}, "
                        f"got {got})"
                    )
                lo, hi = shard["rows"]
                # bytearray: one copy, writable result (frombuffer over
                # the record bytes would alias an immutable buffer)
                pieces.append(
                    np.frombuffer(
                        bytearray(rec.models), dtype=entry["dtype"]
                    ).reshape([hi - lo] + list(entry["shape"][1:]))
                )
            arr = (
                np.concatenate(pieces, axis=0)
                if len(pieces) > 1
                else pieces[0]
            )
            if list(arr.shape) != list(entry["shape"]):
                raise RuntimeError(
                    f"shard set for {entry['name']!r} reassembles to "
                    f"{list(arr.shape)}, manifest says {entry['shape']}"
                )
            arrays[entry["name"]] = arr
        out[algo_idx] = model.replace_shard_arrays(arrays)
    return out
