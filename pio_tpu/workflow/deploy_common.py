"""Helpers shared by the query server and batch predict — one copy of the
serve-path plumbing so online and offline scoring can't drift apart."""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

from pio_tpu.storage import Storage
from pio_tpu.workflow.engine_json import EngineVariant


def to_jsonable(obj: Any) -> Any:
    """Prediction object → JSON-able structure (to_dict > dataclass > raw)."""
    if hasattr(obj, "to_dict"):
        return obj.to_dict()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return dataclasses.asdict(obj)
    return obj


def resolve_instance_id(
    variant: EngineVariant, instance_id: Optional[str]
) -> str:
    """Explicit id, or the latest COMPLETED instance for this variant."""
    if instance_id is not None:
        return instance_id
    latest = Storage.get_meta_data_engine_instances().get_latest_completed(
        variant.engine_id,
        variant.engine_version,
        variant.path or variant.engine_id,
    )
    if latest is None:
        raise RuntimeError(
            f"no COMPLETED engine instance for engine "
            f"{variant.engine_id!r} - run train first"
        )
    return latest.id


def resolve_query_class(pairs: Sequence[Tuple[Any, Any]]) -> Optional[type]:
    """The single query dataclass declared by the algorithms (None = raw
    dict queries). Conflicting declarations are an engine bug."""
    query_classes = {getattr(algo, "query_class", None) for algo, _ in pairs}
    query_classes.discard(None)
    if not query_classes:
        return None
    if len(query_classes) > 1:
        raise ValueError(
            "algorithms declare conflicting query classes: "
            + ", ".join(sorted(c.__name__ for c in query_classes))
        )
    (qc,) = query_classes
    return qc
