"""WorkflowParams (reference ``workflow/WorkflowParams.scala``, UNVERIFIED)."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class WorkflowParams:
    """Debug/controls for a train/eval run (reference fields: batch, verbose,
    skipSanityCheck, stopAfterRead, stopAfterPrepare, sparkEnv→jax_conf)."""

    batch: str = ""
    verbose: int = 2
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    seed: int = 0
    #: >0 → snapshot train state every N steps (capability beyond the
    #: reference; SURVEY.md §5). Algorithms that support it read the
    #: manager off the ComputeContext.
    checkpoint_every: int = 0
    #: explicit snapshot dir; default is per-engine-instance (set this to
    #: resume a preempted run under a NEW instance id)
    checkpoint_dir: str = ""
    #: non-empty → capture a jax.profiler trace of the whole train into
    #: this directory (viewable with tensorboard/xprof). The rebuild's
    #: answer to the reference's Spark UI (SURVEY.md §5 tracing).
    profile_dir: str = ""
