"""engine.json variant loading (reference ``WorkflowUtils.getEngine`` +
``Engine.jValueToEngineParams``, UNVERIFIED paths; see SURVEY.md).

Format (parity with the reference's engine.json):

    {
      "id": "default",
      "version": "1",
      "description": "...",
      "engineFactory": "org.example.RecommendationEngine",
      "datasource": {"params": {...}},
      "preparator": {"params": {...}},
      "algorithms": [{"name": "als", "params": {...}}],
      "serving": {"params": {...}},
      "jaxConf": {"mesh_axes": ["data"], ...}
    }

``engineFactory`` resolves through the engine registry (or a
``module:attr`` path) instead of JVM reflection.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional, Tuple

from pio_tpu.controller.engine import Engine, EngineParams, get_engine_factory


class EngineJsonError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class EngineVariant:
    """Parsed engine.json metadata + raw variant dict."""

    engine_id: str
    engine_version: str
    engine_factory: str
    variant: Dict[str, Any]
    path: str = ""

    @property
    def variant_json(self) -> str:
        return json.dumps(self.variant, sort_keys=True)

    @property
    def jax_conf(self) -> Dict[str, Any]:
        return self.variant.get("jaxConf", {})


def load_variant(path: str) -> EngineVariant:
    if not os.path.exists(path):
        raise EngineJsonError(f"engine variant file not found: {path}")
    with open(path) as f:
        try:
            variant = json.load(f)
        except json.JSONDecodeError as e:
            raise EngineJsonError(f"{path}: invalid JSON: {e}") from None
    return variant_from_dict(variant, path=path)


def variant_from_dict(variant: Dict[str, Any], path: str = "") -> EngineVariant:
    if "engineFactory" not in variant:
        raise EngineJsonError("engine.json must declare 'engineFactory'")
    return EngineVariant(
        engine_id=str(variant.get("id", "default")),
        engine_version=str(variant.get("version", "1")),
        engine_factory=variant["engineFactory"],
        variant=variant,
        path=path,
    )


def build_engine(variant: EngineVariant) -> Tuple[Engine, EngineParams]:
    """Factory lookup + params binding — the ``pio train`` front half."""
    factory = get_engine_factory(variant.engine_factory)
    engine = factory()
    engine_params = engine.params_from_variant(variant.variant)
    return engine, engine_params
