"""CoreWorkflow — train/eval drivers with run bookkeeping.

Rebuild of the reference's ``workflow/CoreWorkflow.scala`` +
``workflow/CreateWorkflow.scala`` + ``workflow/EvaluationWorkflow.scala``
(UNVERIFIED paths; see SURVEY.md): set the EngineInstance status to RUNNING,
run ``Engine.train``, persist models (pickled blob ≙ reference Kryo blob, or
``PersistentModel`` custom path), mark COMPLETED — or FAILED with the error
recorded, so ``pio status``/dashboard surface crashed runs.

Upgrade over the reference: per-phase wall-time is recorded into the
instance env (the reference has no tracing at all — SURVEY.md §5).
"""

from __future__ import annotations

import contextlib
import dataclasses
import datetime as _dt
import hashlib
import json as _json
import logging
import pickle
import traceback
from typing import Any, List, Optional, Sequence

import numpy as np

from pio_tpu.controller.components import PersistentModel
from pio_tpu.obs import REGISTRY, Tracer, monotonic_s
from pio_tpu.controller.engine import Engine, EngineParams
from pio_tpu.controller.evaluation import (
    EngineParamsGenerator,
    Evaluation,
    MetricEvaluator,
    MetricEvaluatorResult,
)
from pio_tpu.controller.params import params_to_dict, params_to_json
from pio_tpu.parallel.context import ComputeContext
from pio_tpu.storage import (
    EngineInstance,
    EvaluationInstance,
    Model,
    RunStatus,
    Storage,
)
from pio_tpu.obs import devicewatch, slog, trainwatch
from pio_tpu.workflow import shard_store
from pio_tpu.workflow.engine_json import EngineVariant
from pio_tpu.workflow.params import WorkflowParams

log = logging.getLogger("pio_tpu.workflow")

#: training-run tracer (process-global registry): every run lands in the
#: ring (inspectable in-process) and feeds pio_tpu_train_stage_seconds
#: histograms — stage labels are the engine.train timing keys
#: (read / prepare / train:<algo>) plus "persist". Wide buckets: reads
#: are milliseconds, ALS on a real corpus is minutes.
TRAIN_TRACER = Tracer(
    "train", registry=REGISTRY,
    stages=("read", "prepare", "persist"),
    buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0,
             300.0, 1800.0, 7200.0),
)


#: Models-store id suffix of the checksum manifest written next to each
#: pickled blob: {"sha256": ..., "size": ...}. Blob first, manifest
#: second — a crash between the two leaves a blob without a manifest,
#: which loads unverified (the pre-manifest behavior), never a manifest
#: promising bytes that don't exist.
MANIFEST_SUFFIX = ".manifest"

_MODEL_FALLBACK = REGISTRY.counter(
    "pio_tpu_model_fallback_total",
    "Deploys that fell back to an older COMPLETED instance's model "
    "after the requested instance's blob failed verification",
)


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


def _to_host(obj: Any) -> Any:
    """Pull device arrays in a model pytree back to host numpy for pickling.

    jax.Array leaves (possibly sharded) become np.ndarray; anything jax
    doesn't recognize passes through untouched.
    """
    import jax

    def leaf(x):
        return np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x

    return jax.tree_util.tree_map(leaf, obj)


def serialize_models(models: Sequence[Any]) -> bytes:
    """Default model persistence (≙ reference Kryo blob via KryoInjection)."""
    return pickle.dumps([_to_host(m) for m in models], protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_models(blob: bytes) -> List[Any]:
    return pickle.loads(blob)


def run_train(
    engine: Engine,
    engine_params: EngineParams,
    variant: EngineVariant,
    workflow_params: WorkflowParams = WorkflowParams(),
    ctx: Optional[ComputeContext] = None,
) -> str:
    """Train + persist; returns the engine-instance id
    (reference ``CoreWorkflow.runTrain``)."""
    if ctx is None:
        ctx = ComputeContext.create(seed=workflow_params.seed)
    instances = Storage.get_meta_data_engine_instances()
    now = _utcnow()
    instance = EngineInstance(
        id="",
        status=RunStatus.RUNNING,
        start_time=now,
        end_time=now,
        engine_id=variant.engine_id,
        engine_version=variant.engine_version,
        engine_variant=variant.path or variant.engine_id,
        engine_factory=variant.engine_factory,
        batch=workflow_params.batch,
        env={},
        jax_conf=variant.jax_conf,
        data_source_params=params_to_json(engine_params.data_source_params),
        preparator_params=params_to_json(engine_params.preparator_params),
        algorithms_params=_json.dumps(
            [
                {"name": n, "params": params_to_dict(p)}
                for n, p in engine_params.algorithm_params_list
            ],
            sort_keys=True,
        ),
        serving_params=params_to_json(engine_params.serving_params),
    )
    instance_id = instances.insert(instance)
    instance = instances.get(instance_id)
    # JSON log ring + volume counter for the train path too — `pio
    # train` is a daemonless run, so the ring is its only /logs.json
    # analog (dumped on failure, queryable in-process by tests)
    slog.install()
    log.info("training started: instance %s", instance_id)

    if workflow_params.checkpoint_every > 0:
        from pio_tpu.workflow.checkpoint import (
            default_checkpoint_dir,
            state_fingerprint,
        )

        # Default dir keys on the engine variant + params (NOT the per-run
        # instance id): a preempted run restarted with the same config
        # finds its snapshots; the data fingerprint recorded inside guards
        # against resuming across a data change.
        stable_key = state_fingerprint(
            variant.engine_id,
            variant.engine_factory,
            instance.data_source_params,
            instance.preparator_params,
            instance.algorithms_params,
        )
        ckpt_dir = workflow_params.checkpoint_dir or default_checkpoint_dir(
            stable_key
        )
        ctx = dataclasses.replace(
            ctx,
            checkpoint_base=ckpt_dir,
            checkpoint_every=workflow_params.checkpoint_every,
        )

    # telemetry plane (ISSUE 16): the recorder collects step-stream
    # progress from the training loops, renders /train.json for the
    # status sidecar, and lands in the run ledger on exit
    recorder = trainwatch.StepRecorder(instance_id, variant.engine_id)
    params_hash = hashlib.sha256(
        "\n".join([
            instance.data_source_params or "",
            instance.preparator_params or "",
            instance.algorithms_params or "",
            instance.serving_params or "",
        ]).encode()
    ).hexdigest()[:16]

    def _append_run_record(status: str, train_s: float,
                           timings: dict, *,
                           shard_manifest: Optional[str] = None,
                           error: Optional[str] = None) -> None:
        # ledger append is best-effort by design: a full disk or torn
        # runs dir must never fail (or un-fail) the run itself
        try:
            rec = trainwatch.run_record(
                run_id=instance_id,
                engine_id=variant.engine_id,
                status=status,
                train_seconds=train_s,
                phases={
                    k.replace(":", "."): float(v)
                    for k, v in recorder.phases.items()
                } or {
                    k.replace(":", "."): float(v)
                    for k, v in timings.items()
                },
                params_hash=params_hash,
                step_summary=recorder.summary(),
                num_devices=ctx.num_devices,
                shard_manifest=shard_manifest,
                error=error,
            )
            path = trainwatch.append_run(rec)
            log.info("run record appended to %s", path)
        except Exception as exc:
            log.warning("run-ledger append failed: %s", exc)

    t0 = monotonic_s()
    timings: dict = {}
    try:
        # the device watch samples memory + attributes trainer compiles
        # for the run's duration; the status sidecar serves its payload
        # as /device.json while steps stream
        with trainwatch.recording(recorder), devicewatch.watching(
            devicewatch.DeviceWatch()
        ), TRAIN_TRACER.trace(
            "train", instanceId=instance_id, engineId=variant.engine_id
        ) as tr:
            with contextlib.ExitStack() as stack:
                if workflow_params.profile_dir:
                    # jax.profiler trace of the whole train — the rebuild's
                    # Spark UI equivalent; view with tensorboard/xprof
                    import jax as _jax

                    stack.enter_context(
                        _jax.profiler.trace(workflow_params.profile_dir)
                    )
                models = engine.train(
                    ctx,
                    engine_params,
                    skip_sanity_check=workflow_params.skip_sanity_check,
                    stop_after_read=workflow_params.stop_after_read,
                    stop_after_prepare=workflow_params.stop_after_prepare,
                    timings=timings,
                )
            train_s = monotonic_s() - t0
            # the phases already ran inside LIVE tr.span()s (engine.train
            # opens one per phase since ISSUE 16), so the stage
            # histograms (pio_tpu_train_stage_seconds) and the trace ring
            # saw them as they happened and every in-phase log line
            # carries (trace_id, span) — /logs.json?trace_id= reassembles
            # one run's full story. Here we only log the summary.
            for phase, dur in timings.items():
                log.info(
                    "train phase %s done in %.3fs (instance %s)",
                    phase, float(dur), instance_id,
                )
            if (workflow_params.stop_after_read
                    or workflow_params.stop_after_prepare):
                instances.update(instance.with_status(RunStatus.ABORTED))
                log.info(
                    "run %s aborted early by stop-after flag", instance_id
                )
                return instance_id

            # Persist: PersistentModel handles itself; everything else goes
            # into the Models store as one pickled blob.
            recorder.set_phase("persist")
            t_persist = monotonic_s()
            shard_manifest_id = None
            with tr.span("persist"):
                persisted_externally = []
                for (name, algo_params), model in zip(
                    engine_params.algorithm_params_list, models
                ):
                    if isinstance(model, PersistentModel):
                        persisted_externally.append(
                            model.save(instance_id, algo_params, ctx)
                        )
                    else:
                        persisted_externally.append(False)
                blob_models = [
                    None if ext else m
                    for ext, m in zip(persisted_externally, models)
                ]
                models_store = Storage.get_model_data_models()
                if shard_store.sharded_persist_enabled():
                    # ShardableModel arrays go out as per-shard records +
                    # a shard manifest (written BEFORE the blob: a torn
                    # persist leaves a blob-less shard set, never a blob
                    # naming missing shards); the blob keeps placeholders
                    mesh_shape = (
                        [int(s) for s in ctx.mesh.devices.shape]
                        if ctx.mesh is not None
                        else [1]
                    )
                    blob_models = shard_store.save_sharded(
                        models_store,
                        instance_id,
                        blob_models,
                        n_shards=ctx.num_devices,
                        mesh_shape=mesh_shape,
                    )
                    shard_manifest_id = (
                        instance_id + shard_store.SHARD_MANIFEST_SUFFIX
                    )
                blob = serialize_models(blob_models)
                models_store.insert(Model(id=instance_id, models=blob))
                manifest = _json.dumps(
                    {
                        "sha256": hashlib.sha256(blob).hexdigest(),
                        "size": len(blob),
                    },
                    sort_keys=True,
                ).encode()
                models_store.insert(
                    Model(id=instance_id + MANIFEST_SUFFIX, models=manifest)
                )
            recorder.set_phase_seconds(
                "persist", monotonic_s() - t_persist
            )
            recorder.set_phase("done")

            done = dataclasses.replace(
                instance.with_status(RunStatus.COMPLETED),
                env={
                    "train_seconds": f"{train_s:.3f}",
                    "num_devices": str(ctx.num_devices),
                    # per-phase wall seconds (read / prepare / train:<algo>)
                    **{f"phase_{k}": str(v) for k, v in timings.items()},
                },
            )
            instances.update(done)
            _append_run_record(
                "COMPLETED", train_s, timings,
                shard_manifest=shard_manifest_id,
            )
            log.info(
                "training finished: instance %s (%.2fs, %d model(s))",
                instance_id, train_s, len(models),
            )
            return instance_id
    except Exception:
        err = traceback.format_exc()
        failed = dataclasses.replace(
            instance.with_status(RunStatus.FAILED), env={"error": err[-4000:]}
        )
        instances.update(failed)
        # failed runs land in the ledger too — a crash IS trend data
        _append_run_record(
            "FAILED", monotonic_s() - t0, timings, error=err,
        )
        log.error("training FAILED: instance %s\n%s", instance_id, err)
        raise


def _verified_blob_models(
    models_store, instance_id: str, ctx: Optional[ComputeContext] = None
) -> List[Any]:
    """Fetch + checksum-verify + deserialize one instance's model blob.

    Raises RuntimeError on a missing record, a checksum mismatch against
    the instance's manifest, or a blob that fails to unpickle. A missing
    manifest (pre-manifest instance, or crash between blob and manifest
    writes) loads unverified. Shard-stripped models (sharded persist)
    reassemble from checksum-verified shard records; a missing/torn
    shard set raises like a torn blob, so the same last-known-good
    fallback applies.
    """
    record = models_store.get(instance_id)
    if record is None:
        raise RuntimeError(f"no models stored for instance {instance_id!r}")
    manifest = models_store.get(instance_id + MANIFEST_SUFFIX)
    if manifest is not None:
        try:
            want = _json.loads(manifest.models.decode("utf-8"))["sha256"]
        except Exception as e:
            raise RuntimeError(
                f"unreadable model manifest for instance {instance_id!r}: {e}"
            ) from e
        got = hashlib.sha256(record.models).hexdigest()
        if got != want:
            raise RuntimeError(
                f"model blob for instance {instance_id!r} failed checksum "
                f"verification (manifest {want}, blob {got})"
            )
    try:
        models = deserialize_models(record.models)
    except Exception as e:
        raise RuntimeError(
            f"model blob for instance {instance_id!r} failed to "
            f"deserialize: {e}"
        ) from e
    return shard_store.restore_sharded(
        models_store,
        instance_id,
        models,
        n_devices=ctx.num_devices if ctx is not None else None,
    )


def load_models_for_instance(
    instance_id: str,
    engine: Engine,
    engine_params: EngineParams,
    ctx: ComputeContext,
    variant: Optional[EngineVariant] = None,
) -> List[Any]:
    """Models-store blob + PersistentModel loads
    (reference ``Engine.prepareDeploy``).

    With ``variant`` given, a blob that fails verification (torn write,
    bit rot, half-persisted crash) does not fail the deploy: the loader
    falls back to the newest older COMPLETED instance of the same variant
    whose blob verifies — last known good — and serves that instead.
    """
    models_store = Storage.get_model_data_models()
    try:
        blob_models = _verified_blob_models(models_store, instance_id, ctx)
    except RuntimeError as primary_err:
        if variant is None:
            raise
        log.error(
            "model load for instance %s failed (%s); searching for last "
            "known good", instance_id, primary_err,
        )
        blob_models = None
        candidates = Storage.get_meta_data_engine_instances().get_completed(
            variant.engine_id,
            variant.engine_version,
            variant.path or variant.engine_id,
        )
        for cand in candidates:
            if cand.id == instance_id:
                continue
            try:
                blob_models = _verified_blob_models(
                    models_store, cand.id, ctx
                )
            except RuntimeError as e:
                log.warning("fallback candidate %s also bad: %s", cand.id, e)
                continue
            _MODEL_FALLBACK.inc()
            log.warning(
                "serving last known good instance %s in place of %s",
                cand.id, instance_id,
            )
            # PersistentModel loads below must come from the SAME instance
            # as the blob, or externally-persisted algorithms would mix
            # generations
            instance_id = cand.id
            break
        if blob_models is None:
            raise primary_err
    out = []
    for (name, algo_params), blob_model in zip(
        engine_params.algorithm_params_list, blob_models
    ):
        if blob_model is not None:
            out.append(blob_model)
            continue
        algo_cls = engine.algorithm_class_map[name]
        model_cls = getattr(algo_cls, "model_class", None)
        if model_cls is None or not issubclass(model_cls, PersistentModel):
            raise RuntimeError(
                f"algorithm {name!r}: model was persisted externally but "
                f"{algo_cls.__name__} declares no PersistentModel model_class"
            )
        out.append(model_cls.load(instance_id, algo_params, ctx))
    return out


def run_evaluation(
    evaluation: Evaluation,
    generator: EngineParamsGenerator,
    workflow_params: WorkflowParams = WorkflowParams(),
    ctx: Optional[ComputeContext] = None,
    evaluation_class: str = "",
    generator_class: str = "",
) -> MetricEvaluatorResult:
    """Sweep params, record the winner (reference
    ``EvaluationWorkflow.runEvaluation``). Returns the result; the
    EvaluationInstance row carries its JSON for the dashboard."""
    if ctx is None:
        ctx = ComputeContext.create(seed=workflow_params.seed)
    instances = Storage.get_meta_data_evaluation_instances()
    now = _utcnow()
    instance = EvaluationInstance(
        id="",
        status=RunStatus.RUNNING,
        start_time=now,
        end_time=now,
        evaluation_class=evaluation_class or type(evaluation).__name__,
        engine_params_generator_class=generator_class or type(generator).__name__,
        batch=workflow_params.batch,
    )
    instance_id = instances.insert(instance)
    instance = instances.get(instance_id)
    try:
        evaluator = MetricEvaluator(evaluation.metric, evaluation.other_metrics)
        result = evaluator.evaluate(
            ctx, evaluation.engine, generator.engine_params_list
        )
        done = dataclasses.replace(
            instance.with_status(RunStatus.COMPLETED),
            evaluator_results=f"{result.metric_header}: {result.best_score}",
            evaluator_results_json=result.to_json(),
        )
        instances.update(done)
        return result
    except Exception:
        err = traceback.format_exc()
        failed = dataclasses.replace(
            instance.with_status(RunStatus.FAILED), evaluator_results=err[-4000:]
        )
        instances.update(failed)
        raise
