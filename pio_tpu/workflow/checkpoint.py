"""Mid-training checkpoint/resume — a capability the reference lacks.

The reference persists only *finished* models (Kryo blob in the Models
store, or ``PersistentModel.save`` — ``core/.../controller/Engine.scala``,
UNVERIFIED; SURVEY.md §5 "no mid-training checkpointing; lineage is the
recovery story"). On TPU, preemption is routine and training steps are the
expensive resource, so the rebuild adds real checkpointing: orbax-backed
snapshots of the (possibly sharded) train state every N steps, with
restore-on-restart.

Layout: ``$PIO_TPU_HOME/checkpoints/<engine-instance-id>/<step>/…`` —
one orbax step dir per snapshot, pruned to ``keep`` newest. Sharded
``jax.Array`` leaves save/restore with their shardings (orbax writes per-
shard; on restore the arrays land back on the same mesh).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, Optional, Tuple

from pio_tpu.utils import knobs

log = logging.getLogger("pio_tpu.workflow.checkpoint")


def default_checkpoint_dir(instance_id: str) -> str:
    home = knobs.knob_str("PIO_TPU_HOME") or os.path.expanduser("~/.pio_tpu")
    return os.path.join(home, "checkpoints", instance_id)


class CheckpointManager:
    """Thin orbax CheckpointManager wrapper with a stable, tiny surface.

    Deliberately minimal so algorithm code stays readable:
    ``save(step, state)`` / ``restore(template) -> (step, state) | None`` /
    ``latest_step()``.
    """

    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self._keep = keep
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = self._open()

    def _open(self):
        import orbax.checkpoint as ocp

        return ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self._keep, create=True
            ),
        )

    def _purge(self) -> None:
        """Wipe the directory: a stale run's snapshots are unusable, and
        leaving them would both poison the recorded fingerprint and make
        orbax silently skip saves at steps ≤ the stale latest step."""
        import shutil

        self._mgr.close()
        shutil.rmtree(self.directory, ignore_errors=True)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = self._open()

    @property
    def _fingerprint_path(self) -> str:
        return os.path.join(self.directory, "fingerprint.json")

    def save(
        self, step: int, state: Any, fingerprint: Optional[str] = None
    ) -> None:
        """Snapshot asynchronously (orbax writes in the background; the
        next save/restore/close waits). ``fingerprint`` tags the directory
        with the run identity so a different run never resumes it."""
        import orbax.checkpoint as ocp

        if fingerprint is not None and not os.path.exists(
            self._fingerprint_path
        ):
            with open(self._fingerprint_path, "w") as f:
                json.dump({"fingerprint": fingerprint}, f)
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        log.info("checkpoint saving: %s step %d", self.directory, step)

    def latest_step(self) -> Optional[int]:
        self._mgr.wait_until_finished()
        return self._mgr.latest_step()

    def restore(
        self, template: Any, fingerprint: Optional[str] = None
    ) -> Optional[Tuple[int, Any]]:
        """Restore the newest snapshot shaped like ``template``.

        Returns None when no snapshot exists, or when ``fingerprint``
        doesn't match the directory's recorded run identity (stale
        snapshots from a different config/dataset are never resumed).
        """
        import orbax.checkpoint as ocp

        self._mgr.wait_until_finished()
        step = self._mgr.latest_step()
        if step is None:
            return None
        if fingerprint is not None and os.path.exists(
            self._fingerprint_path
        ):
            with open(self._fingerprint_path) as f:
                recorded = json.load(f).get("fingerprint")
            if recorded != fingerprint:
                log.warning(
                    "checkpoint dir %s belongs to a different run "
                    "(fingerprint mismatch) - purging and starting fresh",
                    self.directory,
                )
                self._purge()
                return None
        state = self._mgr.restore(
            step, args=ocp.args.StandardRestore(template)
        )
        log.info("checkpoint restored: %s step %d", self.directory, step)
        return step, state

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


def state_fingerprint(*parts: Any) -> str:
    """Cheap run-identity digest from config reprs / shapes / data sums."""
    h = hashlib.sha256()
    for part in parts:
        h.update(repr(part).encode())
        h.update(b"\x00")
    return h.hexdigest()[:32]


def run_chunked_steps(
    state: Any,
    total_steps: int,
    run_chunk,  # (state, n_steps:int) -> state   (jit-compiled inside)
    checkpoint: Optional[CheckpointManager] = None,
    checkpoint_every: int = 0,
    fingerprint: Optional[str] = None,
) -> Any:
    """Drive a step loop in checkpointable chunks, resuming if possible.

    The training-loop shape shared by the iterative trainers: the whole
    loop is ONE compiled scan when checkpointing is off (zero overhead);
    with ``checkpoint_every`` it becomes ⌈total/every⌉ scan calls (at most
    two distinct chunk lengths → at most two compilations) with an orbax
    snapshot between chunks. On restart with the same manager directory,
    training resumes from the newest snapshot instead of step 0.
    """
    start = 0
    if checkpoint is not None:
        restored = checkpoint.restore(template=state, fingerprint=fingerprint)
        if restored is not None:
            start, state = restored
            if start >= total_steps:
                return state
    if checkpoint is None or checkpoint_every <= 0:
        return run_chunk(state, total_steps - start)

    done = start
    while done < total_steps:
        n = min(checkpoint_every, total_steps - done)
        state = run_chunk(state, n)
        done += n
        checkpoint.save(done, state, fingerprint=fingerprint)
    return state
