"""The failpoint registry: spec grammar, matching, actions, counters.

Design constraints, in priority order:

1. **Inert means free.** With no spec installed, :func:`failpoint` must
   cost one dict lookup on the serving hot path (acceptance criterion:
   bench serving stages regress < 2%). So the disarmed fast path is a
   single ``dict.get`` against an empty resolution cache — no locks, no
   string formatting, no allocation.
2. **Fail loudly on bad specs.** The grammar errors (:class:`FaultError`,
   a ``ValueError`` like ``QoSError``) are raised at parse time —
   ``pio deploy --faults`` validates before exporting the env var, so a
   typo'd action name never ships to spawned workers as a silent no-op.
3. **Deterministic bookkeeping.** Every trigger is counted under a lock
   BEFORE the action runs: a ``crash`` that kills the process mid-flush
   still leaves the count observable in the parent's assertions via the
   pre-crash stderr line.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from pio_tpu.utils import knobs
from pio_tpu.obs import parse_duration_s

#: spawned workers / subprocesses inherit the armed spec through this
ENV_VAR = "PIO_TPU_FAULTS"

_ACTIONS = ("error", "latency", "torn-write", "crash")

#: exit status for the ``crash`` action — the conventional 128+SIGKILL,
#: so a supervisor reading the code cannot tell it from a real kill -9
CRASH_EXIT_CODE = 137


class FaultError(ValueError):
    """A faults spec that does not parse (bad point/action/modifier)."""


class FaultInjected(Exception):
    """Raised by an armed ``error`` (or siteless ``torn-write``)
    failpoint. The storage retry layer classifies this transient, so
    low-rate injected errors exercise retries instead of surfacing."""

    def __init__(self, point: str, action: str = "error"):
        super().__init__(f"injected {action} at failpoint {point!r}")
        self.point = point
        self.action = action


@dataclasses.dataclass
class FaultRule:
    """One armed spec item. ``pattern`` may be an exact point name or a
    glob (``eventlog.flush.*``); first matching rule in spec order wins."""

    pattern: str
    action: str
    delay_s: Optional[float] = None  # latency only
    probability: float = 1.0
    once: bool = False
    triggered: int = 0
    disarmed: bool = False

    def to_dict(self) -> dict:
        d = {
            "pattern": self.pattern,
            "action": self.action,
            "probability": self.probability,
            "once": self.once,
            "triggered": self.triggered,
            "disarmed": self.disarmed,
        }
        if self.delay_s is not None:
            d["delay_ms"] = self.delay_s * 1000.0
        return d


def parse_faults(spec: str) -> List[FaultRule]:
    """Parse ``point=action[:arg[:modifier]],...`` into rules.

    Examples: ``eventlog.flush.*=error:0.1`` (10% of matching hits),
    ``storage.sqlite.commit=latency:200ms``, ``worker.serve=crash:once``.
    ``latency`` requires a leading duration; every action then takes an
    optional modifier — a probability in ``(0, 1]`` or ``once``.
    """
    rules: List[FaultRule] = []
    for item in str(spec).split(","):
        item = item.strip()
        if not item:
            continue
        point, sep, raw = item.partition("=")
        point, raw = point.strip(), raw.strip()
        if not sep or not raw or not point:
            raise FaultError(
                f"faults spec item {item!r} is not point=action"
            )
        parts = [p.strip() for p in raw.split(":")]
        # torn_write accepted for shells where '-' invites quoting issues
        action = parts[0].lower().replace("_", "-")
        if action not in _ACTIONS:
            raise FaultError(
                f"unknown fault action {parts[0]!r} in {item!r} "
                f"(expected one of: {', '.join(_ACTIONS)})"
            )
        mods = parts[1:]
        delay_s = None
        if action == "latency":
            if not mods or not mods[0]:
                raise FaultError(
                    f"latency needs a duration in {item!r} "
                    "(e.g. latency:200ms)"
                )
            try:
                delay_s = parse_duration_s(mods.pop(0))
            except (TypeError, ValueError) as e:
                raise FaultError(f"bad latency in {item!r}: {e}") from None
        probability, once = 1.0, False
        if mods:
            if len(mods) > 1:
                raise FaultError(
                    f"too many modifiers in {item!r} (one of: a "
                    "probability in (0, 1], or 'once')"
                )
            m = mods[0].lower()
            if m == "once":
                once = True
            else:
                try:
                    probability = float(m)
                except ValueError:
                    raise FaultError(
                        f"bad modifier {mods[0]!r} in {item!r} (expected "
                        "a probability in (0, 1], or 'once')"
                    ) from None
                if not (0.0 < probability <= 1.0):
                    raise FaultError(
                        f"fault probability must be in (0, 1], got "
                        f"{probability} in {item!r}"
                    )
        rules.append(
            FaultRule(point, action, delay_s, probability, once)
        )
    return rules


# -- registry state ----------------------------------------------------------
_lock = threading.Lock()
_rules: List[FaultRule] = []
_spec: str = ""
#: point name → first matching rule (or None = no match). THE hot-path
#: structure: disarmed processes see an empty dict, and .get() on it is
#: the entire failpoint cost. Entries are only ever added under _lock;
#: dict reads are safe against concurrent insertion in CPython.
_resolved: Dict[str, Optional[FaultRule]] = {}
_counts: Dict[Tuple[str, str], int] = {}


def install(spec: Optional[str] = None) -> List[FaultRule]:
    """Arm the registry. ``spec=None`` reads :data:`ENV_VAR`; an empty
    resolved spec disarms (every failpoint back to inert). Trigger
    counts survive re-installs — only :func:`uninstall` clears them."""
    if spec is None:
        spec = knobs.knob_str(ENV_VAR)
    rules = parse_faults(spec) if spec else []
    global _rules, _spec
    with _lock:
        _rules = rules
        _spec = spec if rules else ""
        # resolution is lazy (first hit per point) so first-match-wins
        # follows SPEC order even when a glob precedes an exact pattern
        _resolved.clear()
    return rules


def uninstall() -> None:
    """Disarm and forget everything, counts included (test isolation)."""
    global _rules, _spec
    with _lock:
        _rules = []
        _spec = ""
        _resolved.clear()
        _counts.clear()


def _match(point: str) -> Optional[FaultRule]:
    rule = _resolved.get(point)
    if rule is not None or point in _resolved:
        return rule
    with _lock:
        rule = None
        for r in _rules:
            if r.pattern == point or fnmatch.fnmatchcase(point, r.pattern):
                rule = r
                break
        _resolved[point] = rule
    return rule


def _arm_check(rule: FaultRule, point: str) -> bool:
    """Probability/once bookkeeping; True = the action fires now."""
    with _lock:
        if rule.disarmed:
            return False
        if rule.probability < 1.0 and random.random() >= rule.probability:
            return False
        rule.triggered += 1
        if rule.once:
            rule.disarmed = True
        key = (point, rule.action)
        _counts[key] = _counts.get(key, 0) + 1
    return True


def failpoint(point: str, data: Optional[bytes] = None) -> Optional[bytes]:
    """The hook. Inert (no matching armed rule) → returns None having
    cost one dict lookup. Armed:

    - ``latency`` sleeps, returns None;
    - ``error`` raises :class:`FaultInjected`;
    - ``crash`` writes one stderr line and ``os._exit(137)``s;
    - ``torn-write`` with ``data`` returns a random strict prefix of it —
      the caller persists that prefix and then fails, simulating a crash
      mid-write; without ``data`` (a site that has no payload) it
      degrades to ``error``.
    """
    if not _rules:
        return None
    rule = _match(point)
    if rule is None or not _arm_check(rule, point):
        return None
    action = rule.action
    if action == "latency":
        # the injected stall IS the fault under test; only armed
        # latency rules (tests) ever reach this sleep
        # pio: disable=hotpath-blocking
        time.sleep(rule.delay_s or 0.0)
        return None
    if action == "crash":
        # stderr is unbuffered-ish and this is the last observable trace
        # of the injection for crash-consistency tests' parent process
        sys.stderr.write(f"pio-tpu: injected crash at failpoint {point!r}\n")
        sys.stderr.flush()
        os._exit(CRASH_EXIT_CODE)
    if action == "torn-write" and data is not None:
        # the truncated copy is the injected wound; test-only path
        # pio: disable=hotpath-zero-copy
        return data[: random.randrange(0, max(1, len(data)))]
    raise FaultInjected(point, action)


def trigger_counts() -> Dict[Tuple[str, str], int]:
    with _lock:
        return dict(_counts)


def exposition_lines() -> List[str]:
    """Prometheus rendering of the trigger counter, for
    ``MetricsRegistry.add_collector`` on the serving daemons."""
    with _lock:
        items = sorted(_counts.items())
    if not items:
        return []
    lines = [
        "# HELP pio_tpu_fault_triggered_total Armed failpoint triggers",
        "# TYPE pio_tpu_fault_triggered_total counter",
    ]
    for (point, action), n in items:
        lines.append(
            "pio_tpu_fault_triggered_total"
            f'{{point="{point}",action="{action}"}} {n}'
        )
    return lines


def snapshot() -> dict:
    """``GET /faults.json`` payload."""
    with _lock:
        return {
            "enabled": bool(_rules),
            "spec": _spec,
            "rules": [r.to_dict() for r in _rules],
            "triggered": [
                {"point": p, "action": a, "count": n}
                for (p, a), n in sorted(_counts.items())
            ],
        }


# arm from the environment at import: spawned pool workers and forked
# test writers inherit the spec without any plumbing. A bad env spec
# raises here — same fail-fast the CLI gives the flag form.
if knobs.knob_str(ENV_VAR):
    install()
