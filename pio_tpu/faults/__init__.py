"""Fault injection: named failpoints that prove the recovery paths.

The reference's recovery story is "lineage + HBase WAL" (SURVEY.md §2.3,
§5); this rebuild replaced that with local append-only logs, group
commit, and a supervised worker pool — and this package is the
instrument that PROVES those survive faults, in the failpoint tradition
of WAL-centric storage engines.

A *failpoint* is a named hook compiled into a risky code path::

    from pio_tpu import faults
    faults.failpoint("eventlog.flush.before_write")

With no spec installed it is inert — one dict-membership check — so the
hooks stay in production code. A spec (``pio deploy --faults`` /
``PIO_TPU_FAULTS``) arms them, e.g.::

    eventlog.flush.*=error:0.1,storage.sqlite.commit=latency:200ms,worker.serve=crash:once

Grammar mirrors the QoS spec (``point=action[:arg[:modifier]]``, comma
separated; see :func:`parse_faults`). Actions:

- ``error`` — raise :class:`FaultInjected` (classified transient by the
  storage ``retrying()`` wrapper, so low-rate error specs exercise the
  retry layer without surfacing 5xx);
- ``latency:<duration>`` — sleep (SLO suffixes: ``us ms s m h d``);
- ``torn-write`` — at write sites that pass their payload to the
  failpoint, persist only a random prefix of it and fail the call:
  a crash mid-``write()``, the exact wound torn-tail repair heals;
- ``crash`` — ``os._exit(137)``: the process dies as if SIGKILLed,
  buffers unflushed, ``finally`` blocks skipped.

Modifiers: a probability in ``(0, 1]`` (``error:0.1``) or ``once``
(trigger a single time, then disarm). Trigger counts are exported as
``pio_tpu_fault_triggered_total{point,action}`` and the serving daemons
surface :func:`snapshot` on ``GET /faults.json``.
"""

from pio_tpu.faults.registry import (
    CRASH_EXIT_CODE,
    ENV_VAR,
    FaultError,
    FaultInjected,
    FaultRule,
    exposition_lines,
    failpoint,
    install,
    parse_faults,
    snapshot,
    trigger_counts,
    uninstall,
)

__all__ = [
    "CRASH_EXIT_CODE",
    "ENV_VAR",
    "FaultError",
    "FaultInjected",
    "FaultRule",
    "exposition_lines",
    "failpoint",
    "install",
    "parse_faults",
    "snapshot",
    "trigger_counts",
    "uninstall",
]
