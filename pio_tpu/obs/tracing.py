"""Per-request stage tracing: context-manager spans over the monotonic
clock, a ring buffer of recent traces, and per-stage histograms.

One :class:`Tracer` per instrumented path (query serving, event ingest,
training). Usage::

    tracer = Tracer("query", registry=reg, stages=("parse", "execute"))
    with tracer.trace("query") as tr:
        with tr.span("parse"):
            ...
        tr.add_span("queue", measured_elsewhere_s)   # injected timing

Every finished span feeds the ``<name>_stage_seconds{stage=...}``
histogram; every finished trace lands in a bounded ring surfaced as
``GET /traces.json`` (slowest-first), so "where did this query's
milliseconds go" has a first-class answer instead of ad-hoc prints.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

from pio_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    monotonic_s,
)
from pio_tpu.obs.slog import TRACE_CONTEXT


class Trace:
    """One finished (or in-flight) request: ordered spans + metadata."""

    __slots__ = ("trace_id", "kind", "wall_time", "t0", "total_s",
                 "spans", "meta", "error")

    def __init__(self, trace_id: str, kind: str):
        self.trace_id = trace_id
        self.kind = kind
        # display timestamp for /traces.json; durations use t0 below
        self.wall_time = time.time()  # pio: disable=wallclock-duration
        self.t0 = monotonic_s()
        self.total_s: Optional[float] = None
        self.spans: List[Tuple[str, float, float]] = []  # (stage, rel_s, dur)
        self.meta: Dict[str, object] = {}
        self.error = False

    def add_span(self, stage: str, dur_s: float,
                 rel_start_s: Optional[float] = None) -> None:
        if rel_start_s is None:
            rel_start_s = monotonic_s() - self.t0 - dur_s
        self.spans.append((stage, max(rel_start_s, 0.0), dur_s))

    def note(self, **meta) -> None:
        self.meta.update(meta)

    def to_dict(self) -> dict:
        return {
            "id": self.trace_id,
            "kind": self.kind,
            "wallTime": self.wall_time,
            "totalMs": (
                round(self.total_s * 1e3, 3)
                if self.total_s is not None else None
            ),
            "error": self.error,
            "spans": [
                {
                    "stage": stage,
                    "startMs": round(rel * 1e3, 3),
                    "durMs": round(dur * 1e3, 3),
                }
                for stage, rel, dur in self.spans
            ],
            **({"meta": self.meta} if self.meta else {}),
        }


class _TraceHandle:
    """What ``tracer.trace(...)`` yields: span recording for one request."""

    __slots__ = ("_tracer", "_trace")

    def __init__(self, tracer: "Tracer", trace: Trace):
        self._tracer = tracer
        self._trace = trace

    @contextmanager
    def span(self, stage: str):
        t0 = monotonic_s()
        # publish (trace_id, stage) so logs emitted inside the span carry
        # both — slog.JsonLogHandler reads this on every record
        token = TRACE_CONTEXT.set((self._trace.trace_id, stage))
        try:
            yield
        finally:
            TRACE_CONTEXT.reset(token)
            dur = monotonic_s() - t0
            self.add_span(stage, dur, rel_start_s=t0 - self._trace.t0)

    def add_span(self, stage: str, dur_s: float,
                 rel_start_s: Optional[float] = None) -> None:
        """Record a span measured elsewhere (e.g. queue wait computed by
        the micro-batch worker thread)."""
        self._trace.add_span(stage, dur_s, rel_start_s)
        self._tracer._observe(stage, dur_s)

    def note(self, **meta) -> None:
        self._trace.note(**meta)

    def mark_error(self) -> None:
        self._trace.error = True


class Tracer:
    """Stage tracer for one instrumented path."""

    def __init__(self, name: str,
                 registry: Optional[MetricsRegistry] = None,
                 stages: Sequence[str] = (),
                 extra_labels: Optional[Dict[str, str]] = None,
                 ring: int = 128,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        self.name = name
        self._lock = threading.Lock()
        self._ring_cap = ring
        self._ring: List[Trace] = []
        self._pos = 0
        self._n = 0
        self._extra = dict(extra_labels or {})
        self._hist = None
        if registry is not None:
            labelnames = tuple(self._extra) + ("stage",)
            self._hist = registry.histogram(
                f"pio_tpu_{name}_stage_seconds",
                f"Per-stage wall seconds of the {name} path",
                labelnames,
                buckets=buckets,
            )
            # pre-create the declared stage cells so pool-mode binding
            # (registration-order slot layout) sees them at init time
            for stage in stages:
                self._hist.labels(*(tuple(self._extra.values()) + (stage,)))

    def _observe(self, stage: str, dur_s: float) -> None:
        if self._hist is not None:
            self._hist.labels(
                *(tuple(self._extra.values()) + (stage,))
            ).observe(dur_s)

    @contextmanager
    def trace(self, kind: Optional[str] = None, **meta):
        with self._lock:
            self._n += 1
            trace_id = f"{self.name}-{self._n}"
        t = Trace(trace_id, kind or self.name)
        if meta:
            t.meta.update(meta)
        handle = _TraceHandle(self, t)
        # any log line emitted while this trace is open — even outside a
        # named span — correlates to the request via /logs.json?trace_id=
        token = TRACE_CONTEXT.set((trace_id, None))
        try:
            yield handle
        except BaseException:
            t.error = True
            raise
        finally:
            TRACE_CONTEXT.reset(token)
            t.total_s = monotonic_s() - t.t0
            with self._lock:
                if len(self._ring) < self._ring_cap:
                    self._ring.append(t)
                else:
                    self._ring[self._pos] = t
                    self._pos = (self._pos + 1) % self._ring_cap

    # -- inspection --------------------------------------------------------
    @property
    def stage_histogram(self):
        """The ``pio_tpu_<name>_stage_seconds`` histogram (None when the
        tracer was built without a registry)."""
        return self._hist

    @property
    def count(self) -> int:
        return self._n

    def recent(self, n: int = 20, slowest: bool = True) -> List[dict]:
        """The ring's traces as dicts — slowest-first by default (the
        debugging question is "what were the worst recent requests")."""
        with self._lock:
            traces = [t for t in self._ring if t.total_s is not None]
        traces.sort(
            key=(lambda t: t.total_s) if slowest
            else (lambda t: t.wall_time),
            reverse=True,
        )
        return [t.to_dict() for t in traces[:n]]
