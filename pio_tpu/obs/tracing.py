"""Per-request stage tracing: context-manager spans over the monotonic
clock, a ring buffer of recent traces, and per-stage histograms.

One :class:`Tracer` per instrumented path (query serving, event ingest,
training). Usage::

    tracer = Tracer("query", registry=reg, stages=("parse", "execute"))
    with tracer.trace("query") as tr:
        with tr.span("parse"):
            ...
        tr.add_span("queue", measured_elsewhere_s)   # injected timing

Every finished span feeds the ``<name>_stage_seconds{stage=...}``
histogram (attaching the trace id as an OpenMetrics exemplar, so
``/metrics`` joins back to ``/traces.json``); every finished trace lands
in a bounded ring surfaced as ``GET /traces.json`` (slowest-first), so
"where did this query's milliseconds go" has a first-class answer
instead of ad-hoc prints.

Cross-process propagation
-------------------------

A trace crosses process and daemon boundaries via the ``X-Pio-Trace``
header (:data:`TRACE_HEADER`): ``<trace_id>`` or ``<trace_id>/<parent>``
where *parent* names the span in the upstream trace that issued the
call. :func:`parse_trace_header` / :func:`format_trace_header` are the
only parser/formatter pair — servers adopt the inbound id via
``tracer.trace(..., trace_id=..., parent=...)`` so one id names the
whole multi-process waterfall, and echo the header on responses so the
caller learns the id of traces the server minted itself.

Within a process, :data:`ACTIVE_TRACE` carries the open trace handle
through call stacks that never see the server layer (the device scorer,
storage, armed debug locks). :func:`add_active_span` records a span on
whatever trace is active — a no-op when none is — so deep layers
instrument unconditionally without plumbing handles through every
signature.

Naming: span/stage names are dot-scoped ``stage`` or ``stage.substage``
(lowercase ``[a-z0-9_]`` atoms). Top-level stages tile the request
(their durations sum to the end-to-end time); dotted substages attribute
*within* an enclosing stage and are excluded from budget sums (enforced
by the ``span-name`` lint rule).

Slow-trace capture: a second bounded ring keeps complete waterfalls for
requests breaching ``slow_threshold_s`` (an SLO threshold or p99
estimate, re-evaluated per trace via ``slow_threshold_fn``) —
tail-sampling that survives high QPS where the main ring churns in
milliseconds. ``/traces.json?slow=1`` serves it.
"""

from __future__ import annotations

import contextvars
import re
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from pio_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    monotonic_s,
)
from pio_tpu.obs.slog import TRACE_CONTEXT

#: the cross-process trace propagation header. Value: ``<trace_id>`` or
#: ``<trace_id>/<parent_span>``; echoed on responses.
TRACE_HEADER = "X-Pio-Trace"

#: legal trace ids on the wire — generous but bounded (a hostile header
#: must not inject log/exposition syntax or unbounded memory).
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._:\-]{0,127}$")

#: the open trace handle for THIS thread/task; lets deep layers (device
#: scorer, storage, armed debug locks) attach spans without plumbing.
ACTIVE_TRACE: contextvars.ContextVar[Optional["_TraceHandle"]] = \
    contextvars.ContextVar("pio_tpu_active_trace", default=None)


def parse_trace_header(value: Optional[str]
                       ) -> Tuple[Optional[str], Optional[str]]:
    """``(trace_id, parent_span)`` from an ``X-Pio-Trace`` value; both
    ``None`` for an absent or malformed header (propagation is best
    effort — a bad header starts a fresh trace, never a 400)."""
    if not value:
        return None, None
    trace_id, sep, parent = value.strip().partition("/")
    if not _TRACE_ID_RE.match(trace_id):
        return None, None
    if sep and not _TRACE_ID_RE.match(parent):
        parent = None
    return trace_id, (parent or None)


def format_trace_header(trace_id: str, parent: Optional[str] = None) -> str:
    """The ``X-Pio-Trace`` value naming ``trace_id`` (and the calling
    span, when the caller is itself traced)."""
    return f"{trace_id}/{parent}" if parent else trace_id


def active_trace() -> Optional["_TraceHandle"]:
    """The trace handle open on this thread/task, if any."""
    return ACTIVE_TRACE.get()


def add_active_span(stage: str, dur_s: float,
                    rel_start_s: Optional[float] = None) -> None:
    """Record a span on the active trace; silently a no-op without one
    (deep layers call this unconditionally)."""
    handle = ACTIVE_TRACE.get()
    if handle is not None:
        handle.add_span(stage, dur_s, rel_start_s)


class Trace:
    """One finished (or in-flight) request: ordered spans + metadata."""

    __slots__ = ("trace_id", "kind", "wall_time", "t0", "total_s",
                 "spans", "meta", "error", "parent", "links", "worker",
                 "slow")

    def __init__(self, trace_id: str, kind: str):
        self.trace_id = trace_id
        self.kind = kind
        # display timestamp for /traces.json; durations use t0 below
        self.wall_time = time.time()  # pio: disable=wallclock-duration
        self.t0 = monotonic_s()
        self.total_s: Optional[float] = None
        self.spans: List[Tuple[str, float, float]] = []  # (stage, rel_s, dur)
        self.meta: Dict[str, object] = {}
        self.error = False
        self.parent: Optional[str] = None   # upstream span (propagated)
        self.links: List[str] = []          # related trace ids (batch members)
        self.worker: Optional[int] = None   # pool worker index
        self.slow = False                   # retained by the slow ring

    def add_span(self, stage: str, dur_s: float,
                 rel_start_s: Optional[float] = None) -> None:
        if rel_start_s is None:
            rel_start_s = monotonic_s() - self.t0 - dur_s
        self.spans.append((stage, max(rel_start_s, 0.0), dur_s))

    def note(self, **meta) -> None:
        self.meta.update(meta)

    def to_dict(self) -> dict:
        return {
            "id": self.trace_id,
            "kind": self.kind,
            "wallTime": self.wall_time,
            "totalMs": (
                round(self.total_s * 1e3, 3)
                if self.total_s is not None else None
            ),
            "error": self.error,
            "spans": [
                {
                    "stage": stage,
                    "startMs": round(rel * 1e3, 3),
                    "durMs": round(dur * 1e3, 3),
                }
                for stage, rel, dur in self.spans
            ],
            **({"parent": self.parent} if self.parent else {}),
            **({"links": list(self.links)} if self.links else {}),
            **({"worker": self.worker} if self.worker is not None else {}),
            **({"slow": True} if self.slow else {}),
            **({"meta": self.meta} if self.meta else {}),
        }


class _TraceHandle:
    """What ``tracer.trace(...)`` yields: span recording for one request."""

    __slots__ = ("_tracer", "_trace")

    def __init__(self, tracer: "Tracer", trace: Trace):
        self._tracer = tracer
        self._trace = trace

    @property
    def trace_id(self) -> str:
        return self._trace.trace_id

    @property
    def elapsed_s(self) -> float:
        """Seconds since the (possibly rebased) trace start — lets a
        caller place a span it measured with its own clock."""
        return monotonic_s() - self._trace.t0

    @contextmanager
    def span(self, stage: str):
        t0 = monotonic_s()
        # publish (trace_id, stage) so logs emitted inside the span carry
        # both — slog.JsonLogHandler reads this on every record
        token = TRACE_CONTEXT.set((self._trace.trace_id, stage))
        try:
            yield
        finally:
            TRACE_CONTEXT.reset(token)
            dur = monotonic_s() - t0
            self.add_span(stage, dur, rel_start_s=t0 - self._trace.t0)

    def add_span(self, stage: str, dur_s: float,
                 rel_start_s: Optional[float] = None) -> None:
        """Record a span measured elsewhere (e.g. queue wait computed by
        the micro-batch worker thread)."""
        self._trace.add_span(stage, dur_s, rel_start_s)
        self._tracer._observe(stage, dur_s, self._trace.trace_id)

    def rebase(self, earlier_s: float) -> None:
        """Extend the trace window ``earlier_s`` seconds backward —
        accept/admission time spent before the trace could be opened
        belongs to the request, and the waterfall should show it at
        ``startMs=0`` rather than pretend the request began at parse."""
        if earlier_s <= 0:
            return
        t = self._trace
        t.t0 -= earlier_s
        t.wall_time -= earlier_s
        t.spans = [(s, rel + earlier_s, d) for s, rel, d in t.spans]

    def extend_total(self) -> None:
        """Re-stamp ``totalMs`` after post-close spans (the response
        write happens after the handler — and the trace — finishes)."""
        t = self._trace
        t.total_s = monotonic_s() - t.t0
        self._tracer._maybe_slow(t)

    def link(self, *trace_ids: str) -> None:
        """Associate related traces (a batch span links its members)."""
        self._trace.links.extend(trace_ids)

    def note(self, **meta) -> None:
        self._trace.note(**meta)

    def mark_error(self) -> None:
        self._trace.error = True


class Tracer:
    """Stage tracer for one instrumented path."""

    def __init__(self, name: str,
                 registry: Optional[MetricsRegistry] = None,
                 stages: Sequence[str] = (),
                 extra_labels: Optional[Dict[str, str]] = None,
                 ring: int = 128,
                 slow_ring: int = 32,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        self.name = name
        self._lock = threading.Lock()
        self._ring_cap = ring
        self._ring: List[Trace] = []
        self._pos = 0
        self._n = 0
        self._id_prefix = name
        self._slow_cap = slow_ring
        self._slow: List[Trace] = []
        self._slow_pos = 0
        #: returns the current slow threshold in seconds (or None to
        #: disable) — re-evaluated per trace so a p99 estimate tracks
        #: the live distribution. Assign after construction.
        self.slow_threshold_fn: Optional[Callable[[], Optional[float]]] = None
        self._extra = dict(extra_labels or {})
        self._hist = None
        #: stage -> bound histogram cell; every span lands ~4-8 observes
        #: per request on the serving hot path, so per-observe labels()
        #: resolution (tuple build + stringify + registry lookup) costs
        #: more than the bucket update itself
        self._stage_cells: Dict[str, object] = {}
        if registry is not None:
            labelnames = tuple(self._extra) + ("stage",)
            self._hist = registry.histogram(
                f"pio_tpu_{name}_stage_seconds",
                f"Per-stage wall seconds of the {name} path",
                labelnames,
                buckets=buckets,
            )
            # pre-create the declared stage cells so pool-mode binding
            # (registration-order slot layout) sees them at init time
            for stage in stages:
                self._stage_cells[stage] = self._hist.labels(
                    *(tuple(self._extra.values()) + (stage,))
                )

    def set_worker(self, worker: int) -> None:
        """Namespace generated trace ids per pool worker
        (``query-w2-17``) — SO_REUSEPORT workers otherwise mint
        colliding ids, and the supervisor's merged view needs ids to be
        pool-unique."""
        self._worker = worker  # type: ignore[attr-defined]
        self._id_prefix = f"{self.name}-w{worker}"

    def _observe(self, stage: str, dur_s: float,
                 trace_id: Optional[str] = None) -> None:
        if self._hist is None:
            return
        cell = self._stage_cells.get(stage)
        if cell is None:
            # undeclared stage: resolve once, then serve from the cache
            # (benign race — labels() hands every caller the same cell)
            cell = self._hist.labels(
                *(tuple(self._extra.values()) + (stage,))
            )
            self._stage_cells[stage] = cell
        cell.observe(dur_s, exemplar=trace_id)

    def _maybe_slow(self, t: Trace) -> None:
        """Move ``t`` into the slow ring if it breaches the threshold
        (idempotent — ``extend_total`` re-checks after the write span)."""
        fn = self.slow_threshold_fn
        if fn is None or t.slow or t.total_s is None:
            return
        try:
            threshold = fn()
        except Exception:
            return
        if threshold is None or t.total_s < threshold:
            return
        t.slow = True
        with self._lock:
            if len(self._slow) < self._slow_cap:
                self._slow.append(t)
            else:
                self._slow[self._slow_pos] = t
                self._slow_pos = (self._slow_pos + 1) % self._slow_cap

    @contextmanager
    def trace(self, kind: Optional[str] = None,
              trace_id: Optional[str] = None,
              parent: Optional[str] = None,
              links: Optional[Sequence[str]] = None,
              **meta):
        if trace_id is None:
            with self._lock:
                self._n += 1
                trace_id = f"{self._id_prefix}-{self._n}"
        else:
            with self._lock:
                self._n += 1
        t = Trace(trace_id, kind or self.name)
        t.parent = parent
        if links:
            t.links.extend(links)
        t.worker = getattr(self, "_worker", None)
        if meta:
            t.meta.update(meta)
        handle = _TraceHandle(self, t)
        # any log line emitted while this trace is open — even outside a
        # named span — correlates to the request via /logs.json?trace_id=
        token = TRACE_CONTEXT.set((trace_id, None))
        active_token = ACTIVE_TRACE.set(handle)
        try:
            yield handle
        except BaseException:
            t.error = True
            raise
        finally:
            ACTIVE_TRACE.reset(active_token)
            TRACE_CONTEXT.reset(token)
            t.total_s = monotonic_s() - t.t0
            with self._lock:
                if len(self._ring) < self._ring_cap:
                    self._ring.append(t)
                else:
                    self._ring[self._pos] = t
                    self._pos = (self._pos + 1) % self._ring_cap
            self._maybe_slow(t)

    # -- inspection --------------------------------------------------------
    @property
    def stage_histogram(self):
        """The ``pio_tpu_<name>_stage_seconds`` histogram (None when the
        tracer was built without a registry)."""
        return self._hist

    @property
    def count(self) -> int:
        return self._n

    def recent(self, n: int = 20, slowest: bool = True) -> List[dict]:
        """The ring's traces as dicts — slowest-first by default (the
        debugging question is "what were the worst recent requests")."""
        with self._lock:
            traces = [t for t in self._ring if t.total_s is not None]
        traces.sort(
            key=(lambda t: t.total_s) if slowest
            else (lambda t: t.wall_time),
            reverse=True,
        )
        return [t.to_dict() for t in traces[:n]]

    def slow(self, n: int = 20) -> List[dict]:
        """The slow ring (threshold breaches only), slowest-first."""
        with self._lock:
            traces = [t for t in self._slow if t.total_s is not None]
        traces.sort(key=lambda t: t.total_s, reverse=True)
        return [t.to_dict() for t in traces[:n]]

    def find(self, trace_id: str) -> Optional[dict]:
        """Look up one trace by id across both rings (slow ring first —
        it retains longer under churn)."""
        with self._lock:
            candidates = list(self._slow) + list(self._ring)
        for t in candidates:
            if t.trace_id == trace_id:
                return t.to_dict()
        return None
