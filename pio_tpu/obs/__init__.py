"""pio_tpu.obs — dependency-free observability subsystem.

Three pillars (ISSUE 1; the reference exposes JSON request counts only —
SURVEY.md §5 observability row):

- **Metrics registry** (:mod:`pio_tpu.obs.metrics`): Counter, Gauge and
  fixed-bucket Histogram types with labels and proper ``# HELP``/``# TYPE``
  Prometheus text exposition, replacing the bespoke per-server stat
  classes and hand-rolled exposition lines.
- **Stage tracing** (:mod:`pio_tpu.obs.tracing`): a lightweight
  context-manager tracer over the single monotonic clock, with a ring
  buffer of recent traces surfaced as ``GET /traces.json``.
- **Cross-worker aggregation** (:mod:`pio_tpu.obs.shm`): in
  SO_REUSEPORT pool serving each worker mirrors its counters/histogram
  buckets into a per-worker stripe of one mmapped segment, so a scrape
  of ANY worker reports pool-wide totals.

The ops plane on top (ISSUE 2):

- **Structured logs** (:mod:`pio_tpu.obs.slog`): every record rendered
  as one-line JSON carrying the trace id of the enclosing span (the
  tracer publishes a contextvar), a bounded ring behind
  ``GET /logs.json``, and ``pio_tpu_log_messages_total`` volume counters.
- **Health probes** (:mod:`pio_tpu.obs.health`): named liveness
  (``/healthz`` — heartbeats, critical threads) and readiness
  (``/readyz`` — engine deployed, storage reachable, pool stripe
  attached) check registries.
- **SLO engine** (:mod:`pio_tpu.obs.slo`): declared objectives
  (``p99=50ms:99.9``) evaluated against the live counters/histograms as
  multi-window burn rates — ``GET /slo.json`` + ``pio_tpu_slo_*`` gauges.

Plus :mod:`pio_tpu.obs.profile` (the opt-in ``PIO_TPU_PROFILE=dir`` JAX
profiler hook), :mod:`pio_tpu.obs.promparse` (a small text-format
parser shared by tests, bench.py and the dashboard) and
:mod:`pio_tpu.obs.trainwatch` (the training telemetry plane — step
stream, ``/train.json`` progress, run ledger) and
:mod:`pio_tpu.obs.devicewatch` (the device telemetry plane — live HBM
accounting, compile attribution, ``/device.json``).

``monotonic_s`` is THE process-wide monotonic clock for durations —
serving paths used to mix ``time.monotonic()`` and
``time.perf_counter()``; every timing site now goes through this one
source (``perf_counter``: monotonic per the stdlib contract, and the
highest-resolution clock CPython offers for intervals).
"""

from __future__ import annotations

from pio_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    RequestWindow,
    escape_help,
    escape_label_value,
    monotonic_s,
)
from pio_tpu.obs import devicewatch, trainwatch
from pio_tpu.obs.health import Heartbeat, HealthMonitor
from pio_tpu.obs.hotpath import hotpath_payload
from pio_tpu.obs.slo import SLOEngine, SLObjective, parse_duration_s, parse_slo
from pio_tpu.obs.tracing import (
    TRACE_HEADER,
    Trace,
    Tracer,
    active_trace,
    add_active_span,
    format_trace_header,
    parse_trace_header,
)

__all__ = [
    "DEFAULT_LATENCY_BUCKETS",
    "Counter",
    "Gauge",
    "HealthMonitor",
    "Heartbeat",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "RequestWindow",
    "SLOEngine",
    "SLObjective",
    "TRACE_HEADER",
    "Trace",
    "Tracer",
    "active_trace",
    "add_active_span",
    "devicewatch",
    "escape_help",
    "escape_label_value",
    "format_trace_header",
    "hotpath_payload",
    "monotonic_s",
    "parse_trace_header",
    "parse_slo",
    "parse_duration_s",
    "trainwatch",
]
