"""Deep health and readiness probes.

Two distinct questions, per the Kubernetes probe model the upstream
deployment story assumes (serving daemons behind a load balancer):

- **liveness** (``GET /healthz``) — "is this process still making
  progress": supervision-loop heartbeat fresh, critical background
  threads (micro-batch dispatcher, blob GC, ...) alive, group-commit
  lock not wedged. A 503 here means restart me.
- **readiness** (``GET /readyz``) — "can this process serve correctly
  right now": engine deployed and models loaded, storage reachable,
  pool metrics stripe attached. A 503 here means take me out of
  rotation (or, at startup, don't send traffic yet) — restarting won't
  help.

:class:`HealthMonitor` is a named-check registry; each check is a
zero-arg callable returning truthy/falsy, ``(ok, detail)``, or raising
(a raise is a failure carrying the exception text — a broken dependency
must flip the probe, not 500 it). Both probes return the full per-check
report so an operator sees WHICH dependency failed, not just a 503.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from pio_tpu.obs.metrics import monotonic_s


class Heartbeat:
    """Freshness probe for a supervision/event loop: the loop calls
    :meth:`beat` each iteration; :meth:`check` fails once the last beat
    is older than ``max_age_s`` — catching a loop that is WEDGED (stuck
    in a call, deadlocked) even though its thread object is alive."""

    def __init__(self, max_age_s: float = 30.0):
        self.max_age_s = float(max_age_s)
        self._last = monotonic_s()

    def beat(self) -> None:
        self._last = monotonic_s()

    def age_s(self) -> float:
        return monotonic_s() - self._last

    def check(self) -> Tuple[bool, str]:
        age = self.age_s()
        return age <= self.max_age_s, f"last beat {age:.1f}s ago"


def thread_alive(thread_getter: Callable[[], Optional[threading.Thread]]
                 ) -> Callable[[], Tuple[bool, str]]:
    """Liveness check over a critical background thread. Takes a getter
    (not the thread) because restarts/reloads may swap the object."""

    def check() -> Tuple[bool, str]:
        t = thread_getter()
        if t is None:
            return True, "not running (disabled)"
        if t.is_alive():
            return True, f"alive ({t.name})"
        return False, f"thread {t.name!r} is dead"

    return check


def _run_check(fn: Callable) -> Tuple[bool, str]:
    try:
        out = fn()
    except Exception as e:  # a failing dependency flips the probe
        return False, f"{type(e).__name__}: {e}"
    if isinstance(out, tuple):
        ok, detail = out
        return bool(ok), str(detail)
    return bool(out) if out is not None else True, ""


class HealthMonitor:
    """Named liveness + readiness check registry for one service."""

    def __init__(self):
        self._lock = threading.Lock()
        self._liveness: List[Tuple[str, Callable]] = []
        self._readiness: List[Tuple[str, Callable]] = []

    # -- registration ------------------------------------------------------
    def add_liveness(self, name: str, fn: Callable) -> None:
        with self._lock:
            self._liveness.append((name, fn))

    def add_readiness(self, name: str, fn: Callable) -> None:
        with self._lock:
            self._readiness.append((name, fn))

    def add_critical_thread(
        self, name: str,
        thread_getter: Callable[[], Optional[threading.Thread]],
    ) -> None:
        """A background thread whose death means the process can no
        longer make progress (→ liveness failure → restart)."""
        self.add_liveness(name, thread_alive(thread_getter))

    # -- evaluation --------------------------------------------------------
    def _evaluate(self, checks) -> Tuple[bool, Dict[str, dict]]:
        report: Dict[str, dict] = {}
        ok = True
        for name, fn in checks:
            c_ok, detail = _run_check(fn)
            report[name] = {"ok": c_ok}
            if detail:
                report[name]["detail"] = detail
            ok = ok and c_ok
        return ok, report

    def liveness(self) -> Tuple[bool, dict]:
        with self._lock:
            checks = list(self._liveness)
        ok, report = self._evaluate(checks)
        return ok, {"status": "ok" if ok else "unhealthy", "checks": report}

    def readiness(self) -> Tuple[bool, dict]:
        with self._lock:
            checks = list(self._readiness)
        ok, report = self._evaluate(checks)
        return ok, {"status": "ready" if ok else "not ready",
                    "checks": report}
